"""Disk-backed XLA executable cache with AOT warm-start semantics.

TensorFlow's distributed runtime amortized graph construction across
sessions implicitly (the reference inherits that via
MonitoredTrainingSession); under JAX every process restart — a
supervisor recovery, an elastic world-shrink re-entry, a serve bucket
warmup — pays a full retrace + XLA compile on entry, and the AOT
``lower().compile()`` path the FLOPs probes use doesn't even share the
in-process executable cache (``bench.py``'s long-standing caveat). This
module makes the amortization an explicit, observable subsystem:

- **Keying** (:meth:`CompileCache.fingerprint`): sha256 over the lowered
  StableHLO module text (which embeds shapes, in/out shardings, and
  donation aliasing) mixed with an explicit context dict — mesh shape +
  axis names, donation argnums, compute dtype — and the environment
  (jax/jaxlib version, backend platform, device kind, device count).
  Same program twice ⇒ same key; a dtype/mesh/donation change ⇒ a
  different key. Deterministic across processes, so a restarted run
  lands on the entries its predecessor wrote.
- **Entries** are flat files committed via atomic rename with the same
  integrity discipline as the checkpoint sidecars (``ckpt/checkpoint.py``):
  ``<key>.exec`` (pickled ``jax.experimental.serialize_executable``
  payload) → ``<key>.exec.sha256`` (digest sidecar) → ``<key>.hlo.z``
  (zlib StableHLO) → ``<key>.meta.json`` **last** — the meta file is the
  commit point, so a crash mid-store can never publish a partial entry.
- **Fail-open everywhere**: a corrupt payload, a bad sidecar, an
  unsupported backend, a full disk — every cache failure degrades to a
  plain recompile (with a ``compile`` miss event naming the reason),
  never to a crashed or wrong run. When executable serialization is
  unsupported, the entry keeps the lowered StableHLO + cost analysis
  (``source="stablehlo"``) so FLOPs consumers still skip their
  recompile.
- **Bounded**: LRU eviction by ``max_bytes`` over the whole directory,
  applied after each store (per-entry ``last_used`` rides the meta
  file). ``tools/compile_cache_cli.py`` inspects/verifies/prunes the
  same layout offline.
- **Observable**: every lookup emits one ``compile`` JSONL event
  (key, phase, hit, compile_s, source) through the run's
  ``MetricsLogger`` — wired into the schema lint, the
  ``tools/telemetry_report.py`` compile-cost section, and (via the
  Trainer's ``on_event`` hook) the goodput ``compile`` fraction.
"""

from __future__ import annotations

import hashlib
import json
import os
import pickle
import time
import zlib
from typing import Any, Callable, Optional, Tuple

#: event sources (the ``source`` field of ``compile`` JSONL records).
#: memory — this process already holds the live executable (an earlier
#:   seam compiled or deserialized it), reused with zero load cost —
#:   the in-process sharing the AOT path historically lacked;
#: executable — deserialized a cached executable, no XLA compile;
#: stablehlo — entry had module+cost analysis but no executable
#:   (serialization unsupported when it was written), compiled;
#: miss — no entry, compiled and stored;
#: corrupt — entry failed integrity/decode, was dropped, recompiled;
#: error — the cache machinery itself failed, fail-open compile;
#: uncached — no cache configured (emitted by seams that always
#:   report their compiles, e.g. serve warmup).
SOURCES = ("memory", "executable", "stablehlo", "miss", "corrupt",
           "error", "uncached")

#: Process-level fingerprint → live Compiled registry. Two jobs: (1) a
#: same-process re-entry (supervisor restart, elastic re-entry, a
#: second Trainer) reuses the live executable at zero cost; (2) it
#: guarantees a program is deserialized AT MOST ONCE per process —
#: jaxlib's deserialize_and_load corrupts memory when a live executable
#: for the same program already exists in-process (observed on CPU
#: jaxlib 0.4.x: wrong results, then segfault), so the disk path is
#: reserved for the fresh-process warm start it exists for.
_PROCESS_EXECUTABLES: dict = {}

#: Backends where executing an AOT/deserialized executable in place of
#: the jit call path is allowed. DEFAULT: NONE — jaxlib's experimental
#: ``serialize_executable`` deserialize path is memory-unsafe in ways
#: fail-open cannot catch: the tunneled-TPU A/B showed AOT-swapped
#: executables silently corrupting donated state (training drifts, then
#: NaNs), and on CPU (jaxlib 0.4.36) donating checkpoint-restored
#: buffers into a deserialized executable aborts the process with heap
#: corruption (malloc_consolidate/SIGSEGV, ~5/6 of supervisor-resume
#: runs). Everywhere by default the cache runs DEGRADED: execution
#: stays on the plain jit call path, warm start is delegated to jax's
#: own persistent compilation cache (armed under <cache_dir>/xla by
#: :func:`arm_native_cache`), and our entries keep the StableHLO + cost
#: analysis + hit/miss telemetry. Opt in per backend you have verified
#: via DML_COMPILECACHE_EXEC_BACKENDS=cpu,tpu (tests pass
#: ``executable_backends=("cpu",)`` explicitly to exercise the
#: machinery on small donation-free programs, where it is stable).
EXECUTABLE_BACKENDS = tuple(
    b.strip() for b in os.environ.get(
        "DML_COMPILECACHE_EXEC_BACKENDS", "").lower().split(",")
    if b.strip())


def _native_cache_platform_ok() -> bool:
    """True when the process is headed for a non-CPU accelerator, read
    WITHOUT initializing a backend (requested-platforms config/env,
    else PJRT plugin discovery). XLA:CPU is excluded: loading cached
    CPU executables from disk intermittently corrupts the heap on
    jaxlib 0.4.36 (malloc_consolidate/SIGSEGV aborts in ~1/3 of
    supervisor resumes with the native cache armed — same disease as
    the serialize_executable path, see EXECUTABLE_BACKENDS). Force with
    DML_COMPILECACHE_NATIVE_CACHE=1/0."""
    force = os.environ.get("DML_COMPILECACHE_NATIVE_CACHE", "").lower()
    if force in ("1", "true", "yes", "on"):
        return True
    if force in ("0", "false", "no", "off"):
        return False
    try:
        import jax

        plats = (jax.config.jax_platforms
                 or os.environ.get("JAX_PLATFORMS") or "").lower()
    except Exception:
        plats = (os.environ.get("JAX_PLATFORMS") or "").lower()
    tokens = {t.strip() for t in plats.split(",") if t.strip()}
    if tokens:
        return tokens != {"cpu"}
    # Platform auto-select: an accelerator will be picked iff a PJRT
    # plugin is discoverable; sniff without creating a client.
    try:
        import importlib.metadata

        if list(importlib.metadata.entry_points(group="jax_plugins")):
            return True
    except Exception:
        pass
    try:
        import importlib.util

        return importlib.util.find_spec("libtpu") is not None
    except Exception:
        return False


def arm_native_cache(cache_dir: Optional[str]) -> None:
    """Point jax's persistent compilation cache into ``cache_dir/xla``
    (idempotent; respects a cache dir the user already configured; no-op
    when ``cache_dir`` is falsy or the platform is CPU — see
    :func:`_native_cache_platform_ok`). This is the XLA-level warm
    start for backends where the executable-swap path is off — the
    call-path compile itself becomes a disk hit on re-entry.

    MUST run before jax initializes its backends: the client reads
    ``jax_compilation_cache_dir`` at creation, and updating the config
    afterwards is a silent no-op (verified on jax 0.4.37). The CLI and
    bench entry points call this straight after flag parsing; the
    constructor's call only helps processes that build their cache
    before touching devices (tests, spawned workers)."""
    if not cache_dir or not _native_cache_platform_ok():
        return
    try:
        import jax

        if jax.config.jax_compilation_cache_dir:
            return
        jax.config.update("jax_compilation_cache_dir",
                          os.path.join(cache_dir, "xla"))
        # Cache every program: the default 1 s floor would skip the
        # small eval/init programs whose recompiles still cost a
        # restart round trip.
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          0.0)
    except Exception:
        pass


def _avals_of(args):
    """Avals for ``lower``: shape/dtype, keeping the sharding only of
    COMMITTED arrays. An uncommitted array (e.g. the fresh PRNG key fed
    to init) carries an incidental single-device sharding that `lower`
    would treat as an explicit placement and reject against the
    program's mesh-wide out_shardings; the jit call path moves such
    arrays freely, so the aval must too."""
    import jax

    def aval(x):
        sh = getattr(x, "sharding", None)
        if sh is not None and not getattr(x, "committed",
                                          getattr(x, "_committed", True)):
            sh = None
        return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=sh)

    return jax.tree.map(aval, args)


def _flops_of(cost) -> Optional[float]:
    """``flops`` out of an XLA cost analysis that may be a dict (TPU) or
    a list of per-program dicts (CPU backends on current jaxlib)."""
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else None
    if not isinstance(cost, dict):
        return None
    f = cost.get("flops", 0.0)
    try:
        f = float(f)
    except (TypeError, ValueError):
        return None
    return f if f > 0 else None


def _jsonable_cost(cost):
    """Cost analysis as plain JSON (dict of float), or None."""
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else None
    if not isinstance(cost, dict):
        return None
    out = {}
    for k, v in cost.items():
        try:
            out[str(k)] = float(v)
        except (TypeError, ValueError):
            continue
    return out or None


def mesh_context(mesh, donate=(), compute_dtype: Optional[str] = None,
                 **extra) -> dict:
    """The explicit half of the cache key for a compile seam: mesh shape
    + axis names, donation argnums, compute dtype, plus any
    caller-specific discriminators. The StableHLO hash already embeds
    shapes/shardings/donation aliasing — this dict states the intent
    redundantly so key provenance survives lowering-format changes."""
    ctx = {"donate": sorted(int(d) for d in donate)}
    if mesh is not None:
        ctx["mesh_axes"] = list(getattr(mesh, "axis_names", ()))
        ctx["mesh_shape"] = [int(v) for v in
                             dict(getattr(mesh, "shape", {})).values()]
    if compute_dtype:
        ctx["compute_dtype"] = str(compute_dtype)
    ctx.update(extra)
    return ctx


class CompileCache:
    """The disk store. One instance per process/run; all methods are
    fail-open (they catch their own errors and report them through the
    returned event instead of raising into the training loop)."""

    def __init__(self, cache_dir: str, max_bytes: int = 2_000_000_000,
                 logger=None, on_event: Optional[Callable] = None,
                 executable_backends=EXECUTABLE_BACKENDS):
        self.cache_dir = cache_dir
        self.max_bytes = int(max_bytes)
        self.logger = logger
        self.on_event = on_event
        self.executable_backends = tuple(executable_backends)
        self._degraded: Optional[bool] = None  # resolved lazily (jax)
        os.makedirs(cache_dir, exist_ok=True)
        # Best-effort: only effective when the backend is not yet
        # initialized (see arm_native_cache) — the CLI/bench entry
        # points arm earlier for the common path.
        arm_native_cache(cache_dir)

    def degraded(self) -> bool:
        """True when this backend must not execute swapped-in AOT
        executables (see EXECUTABLE_BACKENDS): the cache then keeps its
        keying/telemetry/cost-analysis role, execution stays on the jit
        call path, and the warm start comes from jax's own persistent
        compilation cache, armed under ``<cache_dir>/xla`` on
        accelerator platforms (see :func:`arm_native_cache`)."""
        if self._degraded is None:
            try:
                import jax

                self._degraded = (jax.devices()[0].platform.lower()
                                  not in self.executable_backends)
            except Exception:
                self._degraded = True
        return self._degraded

    @classmethod
    def from_config(cls, cfg, logger=None, on_event=None
                    ) -> Optional["CompileCache"]:
        """Cache per ``TrainConfig`` (None when ``compile_cache_dir`` is
        unset — every seam then compiles exactly as before)."""
        if not getattr(cfg, "compile_cache_dir", None):
            return None
        return cls(cfg.compile_cache_dir,
                   max_bytes=cfg.compile_cache_max_bytes,
                   logger=logger, on_event=on_event)

    # --- keying ---

    def environment(self) -> dict:
        import jax
        import jaxlib

        dev = jax.devices()[0]
        return {
            "jax": jax.__version__,
            "jaxlib": jaxlib.__version__,
            "backend": dev.platform,
            "device_kind": dev.device_kind,
            "n_devices": jax.device_count(),
        }

    def fingerprint(self, hlo_text: str, context: Optional[dict] = None
                    ) -> str:
        """Deterministic cache key: sha256 over the StableHLO module and
        the canonical-JSON (context, environment) pair."""
        h = hashlib.sha256(hlo_text.encode())
        h.update(json.dumps({"context": context or {},
                             "env": self.environment()},
                            sort_keys=True).encode())
        return h.hexdigest()[:32]

    # --- entry layout ---

    def _paths(self, key: str) -> dict:
        base = os.path.join(self.cache_dir, key)
        return {"exec": base + ".exec", "sum": base + ".exec.sha256",
                "hlo": base + ".hlo.z", "meta": base + ".meta.json"}

    @staticmethod
    def _atomic_write(path: str, data, mode: str = "wb") -> None:
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, mode) as f:
            f.write(data)
        os.replace(tmp, path)

    def entries(self):
        """[(key, meta dict)] for every COMMITTED entry (meta present and
        parseable), unsorted. Unreadable metas are skipped, not raised."""
        out = []
        try:
            names = os.listdir(self.cache_dir)
        except OSError:
            return out
        for name in names:
            if not name.endswith(".meta.json") or ".tmp." in name:
                continue
            key = name[:-len(".meta.json")]
            try:
                with open(os.path.join(self.cache_dir, name)) as f:
                    out.append((key, json.load(f)))
            except (OSError, ValueError):
                continue
        return out

    def entry_bytes(self, key: str) -> int:
        return sum(os.path.getsize(p) for p in self._paths(key).values()
                   if os.path.isfile(p))

    def drop(self, key: str) -> None:
        for p in self._paths(key).values():
            try:
                os.remove(p)
            except OSError:
                pass

    # --- store / load ---

    def store(self, key: str, phase: str, exec_blob: Optional[bytes],
              hlo_text: str, cost, compile_s: float,
              context: Optional[dict]) -> None:
        """Commit one entry (exec → sha256 sidecar → hlo → meta LAST) and
        apply the LRU bound. Failures are swallowed: a cache that cannot
        write must not take the run down with it."""
        try:
            sizes = {}
            if exec_blob is not None:
                self._atomic_write(self._paths(key)["exec"], exec_blob)
                self._atomic_write(
                    self._paths(key)["sum"],
                    json.dumps({"algo": "sha256",
                                "digest": hashlib.sha256(
                                    exec_blob).hexdigest(),
                                "bytes": len(exec_blob)}), mode="w")
                sizes["exec_bytes"] = len(exec_blob)
            hlo_z = zlib.compress(hlo_text.encode(), 6)
            self._atomic_write(self._paths(key)["hlo"], hlo_z)
            sizes["hlo_bytes"] = len(hlo_z)
            meta = {
                "key": key, "phase": phase, "created": time.time(),
                "last_used": time.time(), "hits": 0,
                "compile_s": round(compile_s, 4),
                "cost_analysis": _jsonable_cost(cost),
                "has_executable": exec_blob is not None,
                "context": context or {}, **self.environment(), **sizes,
            }
            self._atomic_write(self._paths(key)["meta"],
                               json.dumps(meta), mode="w")
            self._evict()
        except Exception:
            pass

    def _touch(self, key: str, meta: dict) -> None:
        """Best-effort hit-count/recency update (LRU input)."""
        try:
            meta = dict(meta)
            meta["hits"] = int(meta.get("hits") or 0) + 1
            meta["last_used"] = time.time()
            self._atomic_write(self._paths(key)["meta"],
                               json.dumps(meta), mode="w")
        except Exception:
            pass

    def _evict(self) -> None:
        """Drop least-recently-used entries until the directory fits
        ``max_bytes``. Runs after every store; also the CLI's prune."""
        entries = self.entries()
        total = sum(self.entry_bytes(k) for k, _ in entries)
        if total <= self.max_bytes:
            return
        for key, meta in sorted(entries,
                                key=lambda km: km[1].get("last_used", 0)):
            if total <= self.max_bytes:
                break
            total -= self.entry_bytes(key)
            self.drop(key)

    def load_meta(self, key: str) -> Optional[dict]:
        try:
            with open(self._paths(key)["meta"]) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def verify_entry(self, key: str) -> Tuple[bool, str]:
        """(ok, reason) — the integrity walk ``compile_cache_cli verify``
        and the load path share. An entry without an executable (the
        StableHLO-only degraded form) verifies on its meta alone."""
        meta = self.load_meta(key)
        if meta is None:
            return False, "missing/unreadable meta"
        if not meta.get("has_executable"):
            return (os.path.isfile(self._paths(key)["hlo"]),
                    "stablehlo-only entry")
        paths = self._paths(key)
        if not os.path.isfile(paths["exec"]):
            return False, "missing exec payload"
        try:
            with open(paths["sum"]) as f:
                want = json.load(f)
        except (OSError, ValueError) as e:
            return False, f"unreadable sha256 sidecar: {e!r}"
        with open(paths["exec"], "rb") as f:
            blob = f.read()
        if hashlib.sha256(blob).hexdigest() != want.get("digest") \
                or len(blob) != want.get("bytes"):
            return False, (f"checksum mismatch ({len(blob)} bytes vs "
                           f"sidecar {want.get('bytes')})")
        return True, "verified"

    # --- the one-stop compile seam ---

    def obtain(self, jitted, avals, phase: str,
               context: Optional[dict] = None):
        """``(compiled, event)`` for one program: lower, fingerprint,
        and either deserialize the cached executable or AOT-compile and
        store it. ``compile_s`` covers the whole obtain (trace + load or
        compile) — the figure the goodput ``compile`` fraction wants.
        Raises only if the fail-open *compile itself* fails (a genuine
        program error the caller must see)."""
        t0 = time.perf_counter()
        key = None
        try:
            degraded = self.degraded()
            lowered = jitted.lower(*avals)
            hlo_text = lowered.as_text()
            key = self.fingerprint(hlo_text, context)
            mem = None if degraded else _PROCESS_EXECUTABLES.get(key)
            if mem is not None:
                # Same-process re-entry (supervisor restart / second
                # Trainer): the live executable is authoritative —
                # deserializing again would both waste the load and
                # trip jaxlib's duplicate-deserialize corruption (see
                # _PROCESS_EXECUTABLES).
                meta = self.load_meta(key)
                if meta is not None:
                    self._touch(key, meta)
                return mem, self._event(
                    key, phase, hit=True,
                    compile_s=time.perf_counter() - t0, source="memory")
            source = "miss"
            meta = self.load_meta(key)
            if meta is not None:
                ok, reason = self.verify_entry(key)
                if meta.get("has_executable") and ok and not degraded:
                    compiled = self._deserialize(key)
                    if compiled is not None:
                        _PROCESS_EXECUTABLES[key] = compiled
                        self._touch(key, meta)
                        return compiled, self._event(
                            key, phase, hit=True,
                            compile_s=time.perf_counter() - t0,
                            source="executable")
                    source = "corrupt"
                    self.drop(key)
                elif not ok and "stablehlo-only" not in reason:
                    source = "corrupt"
                    self.drop(key)
                else:
                    # Degraded entry: module + cost analysis cached,
                    # executable not serializable on this backend.
                    source = "stablehlo"
                    self._touch(key, meta)
            compiled = lowered.compile()
            compile_s = time.perf_counter() - t0
            if not degraded:
                _PROCESS_EXECUTABLES[key] = compiled
            self.store(key, phase,
                       None if degraded else self._serialize(compiled),
                       hlo_text, self._cost(compiled), compile_s,
                       context)
            return compiled, self._event(key, phase, hit=False,
                                         compile_s=compile_s,
                                         source=source)
        except Exception:
            # Fail-open: any cache-machinery failure falls back to the
            # plain call-path compile in the wrapper; report it.
            return None, self._event(key, phase, hit=False,
                                     compile_s=time.perf_counter() - t0,
                                     source="error")

    def note_degraded(self, jitted, avals, phase: str,
                      context: Optional[dict], elapsed_s: float):
        """Record a degraded-mode first call (the executable that ran
        came from the jit call path, warm-started by jax's native
        persistent cache): fingerprint the program, commit a
        StableHLO + cost-analysis entry on miss, emit the ``compile``
        event. ``elapsed_s`` is the measured first-call time (trace +
        compile-or-native-cache-load + first execution)."""
        try:
            lowered = jitted.lower(*avals)
            hlo_text = lowered.as_text()
            key = self.fingerprint(hlo_text, context)
            meta = self.load_meta(key)
            if meta is not None:
                self._touch(key, meta)
                return self._event(key, phase, hit=True,
                                   compile_s=elapsed_s,
                                   source="stablehlo")
            cost = None
            try:
                # Analysis-only AOT compile, never executed; with the
                # native cache armed it is a disk hit, not a second
                # full compile.
                cost = self._cost(lowered.compile())
            except Exception:
                pass
            self.store(key, phase, None, hlo_text, cost, elapsed_s,
                       context)
            return self._event(key, phase, hit=False,
                               compile_s=elapsed_s, source="miss")
        except Exception:
            return self._event(None, phase, hit=False,
                               compile_s=elapsed_s, source="error")

    def cached_flops(self, jitted, avals,
                     context: Optional[dict] = None,
                     phase: str = "analysis") -> Optional[float]:
        """FLOPs for a program WITHOUT recompiling when the cache has
        seen it: served from the entry's recorded cost analysis on a
        hit; a miss compiles through :meth:`obtain` (storing the entry
        for next time). The cache-native replacement for the AOT
        ``lower().compile().cost_analysis()`` probe."""
        try:
            lowered = jitted.lower(*avals)
            key = self.fingerprint(lowered.as_text(), context)
            meta = self.load_meta(key)
            if meta is not None and meta.get("cost_analysis") is not None:
                self._touch(key, meta)
                self._event(key, phase, hit=True, compile_s=0.0,
                            source="executable"
                            if meta.get("has_executable")
                            else "stablehlo")
                return _flops_of(meta["cost_analysis"])
        except Exception:
            return None
        compiled, _ = self.obtain(jitted, avals, phase, context)
        if compiled is None:
            return None
        return _flops_of(self._cost(compiled))

    # --- serialization helpers ---

    @staticmethod
    def _cost(compiled):
        try:
            return compiled.cost_analysis()
        except Exception:
            return None

    @staticmethod
    def _serialize(compiled) -> Optional[bytes]:
        """Pickle of ``serialize_executable.serialize``'s
        (payload, in_tree, out_tree); None when the backend refuses —
        the entry then degrades to StableHLO + cost analysis."""
        try:
            from jax.experimental import serialize_executable
            return pickle.dumps(serialize_executable.serialize(compiled))
        except Exception:
            return None

    def _deserialize(self, key: str):
        try:
            from jax.experimental import serialize_executable
            with open(self._paths(key)["exec"], "rb") as f:
                payload, in_tree, out_tree = pickle.loads(f.read())
            return serialize_executable.deserialize_and_load(
                payload, in_tree, out_tree)
        except Exception:
            return None

    # --- telemetry ---

    def _event(self, key, phase, hit, compile_s, source) -> dict:
        ev = {"key": key, "phase": phase, "hit": bool(hit),
              "compile_s": round(compile_s, 4), "source": source}
        if self.logger is not None:
            self.logger.log("compile", **ev)
        if self.on_event is not None:
            try:
                self.on_event(ev)
            except Exception:
                pass
        return ev


class CachedFunction:
    """Callable wrapper that routes a jitted function's FIRST call
    through a :class:`CompileCache` and every later call through the
    obtained executable (~0.5 µs/dispatch over the jit fast path,
    measured on CPU — noise against the ≥1 ms step programs cached
    here). Fail-open: any cache failure permanently falls back to the
    wrapped jit callable for this process."""

    def __init__(self, jitted, cache: CompileCache, phase: str,
                 context: Optional[dict] = None):
        self._jitted = jitted
        self._cache = cache
        self.phase = phase
        self.context = context
        self.compiled = None
        self.last_event: Optional[dict] = None
        self._fallback = False

    def __call__(self, *args):
        if self.compiled is not None:
            try:
                return self.compiled(*args)
            except (TypeError, ValueError):
                # A second input signature through the same wrapper
                # (executables are shape-exact): fall back to the jit
                # call path, which traces/compiles per shape as usual.
                # Only the first signature is disk-cached — every
                # framework seam builds one wrapper per fixed-shape
                # program, so this is a safety net, not a design path.
                return self._jitted(*args)
        if self._fallback:
            return self._jitted(*args)
        if self._cache.degraded():
            # Backend not on the executable allowlist: execute via the
            # jit call path (numerics authoritative; jax's native
            # persistent cache provides the warm start on accelerator
            # platforms), keep the fingerprint/telemetry/cost-analysis
            # role.
            t0 = time.perf_counter()
            out = self._jitted(*args)
            self.last_event = self._cache.note_degraded(
                self._jitted, _avals_of(args), self.phase, self.context,
                time.perf_counter() - t0)
            self._fallback = True
            return out
        compiled, ev = self._cache.obtain(self._jitted, _avals_of(args),
                                          self.phase, self.context)
        self.last_event = ev
        if compiled is None:
            self._fallback = True
            return self._jitted(*args)
        self.compiled = compiled
        return compiled(*args)

    def lower(self, *args, **kwargs):
        return self._jitted.lower(*args, **kwargs)

    def cached_flops(self, avals) -> Optional[float]:
        """FLOPs via the cache (no recompile on hits) — preferred by
        ``utils/profiling.compiled_flops``. Serves the already-obtained
        executable's analysis when this wrapper compiled the same
        avals."""
        if self.compiled is not None:
            f = _flops_of(CompileCache._cost(self.compiled))
            if f:
                return f
        return self._cache.cached_flops(self._jitted, avals,
                                        context=self.context,
                                        phase=self.phase)


def wrap(jitted, cache: Optional[CompileCache], phase: str,
         context: Optional[dict] = None):
    """``CachedFunction`` when a cache is configured, the jitted
    function untouched otherwise — so every seam can call this
    unconditionally and the no-cache hot path stays exactly as before."""
    if cache is None:
        return jitted
    return CachedFunction(jitted, cache, phase, context)
