"""Device-time performance attribution: programmatic profiler capture
windows and a zero-fetch device step-time estimator.

The PR-1 telemetry layer (``utils/telemetry.py``) times the HOST loop —
it can say the run spent 95% of wall-clock "training" and still not know
where the device spent that time (the headline bench sat at ~27% MFU for
five rounds with nothing pointing at the other 73%). This module closes
that gap from two directions, both honoring the loop's round-trip budget
(zero extra device fetches — ``tests/test_telemetry.py`` pins it):

- :class:`ProfileWindow` — ``--profile_at_steps N:K`` arms a
  programmatic ``jax.profiler`` capture from global step N for K steps,
  written under ``--profile_dir`` (default ``<log_dir>/devprof``). On
  stop, the captured Chrome trace is parsed HOST-SIDE into a per-lane
  device-time table — top-k ops and compute / collective / infeed
  buckets — and emitted as ``devtime`` JSONL records that
  ``tools/telemetry_report.py`` renders. No trace UI required to answer
  "which op owns the step".
- :class:`DeviceStepEstimator` — an always-on per-boundary estimate of
  the device-side step time, measured as the block-until-ready delta at
  the loop's EXISTING fused metrics fetch (the fetch drains everything
  dispatched since the last boundary, so ``drain_end − window_start``
  bounds the device's busy window; divided by the steps in the window
  it is the per-step device time). ``train`` rows gain
  ``device_step_ms`` + ``drain_wait_ms``: a ``drain_wait_ms`` near the
  full window means the host idled on the device (device-bound — the
  step itself must get faster); near zero means the device idled on the
  host (host-bound — feed it better). Two ``perf_counter`` reads per
  boundary, no device traffic.

Bucket semantics (op names, lowercased): ``collective`` matches the
cross-device primitives (all-reduce / all-gather / reduce-scatter /
all-to-all / collective-permute / send / recv), ``infeed`` matches data
movement (in/outfeed, copies, transfers), everything else is
``compute``. On backends whose profiler emits no per-op device lanes
(CPU: host-side runtime events only) the parser falls back to the host
lanes so the record shape — and the tier-1 tests — stay identical; the
table then attributes runtime phases rather than XLA ops.
"""

from __future__ import annotations

import glob
import gzip
import json
import os
import re
import sys
import time
from typing import List, Optional

#: Device-time buckets, in report order.
DEVTIME_BUCKETS = ("compute", "collective", "infeed")

#: named_scope phases attributed as their own (overlapping) totals, in
#: addition to the exclusive buckets above: the train step wraps its
#: grad and update phases in jax.named_scope("fwd_bwd"/"optimizer")
#: (parallel/step.py), and the scope name survives into the emitted op
#: names / metadata — so `optimizer_ms` is MEASURED attribution of the
#: weight-update tail (the ZeRO-1 / fused-kernel target), not inference.
SCOPE_RE = re.compile(r"optimizer")

_COLLECTIVE_RE = re.compile(
    r"all[-_]?reduce|all[-_]?gather|reduce[-_]?scatter|all[-_]?to[-_]?all"
    r"|collective[-_]?permute|collective|ppermute|psum|\bsend\b|\brecv\b")
_INFEED_RE = re.compile(
    r"infeed|outfeed|\bcopy\b|copy[-_]?start|copy[-_]?done|transfer"
    r"|memcpy|h2d|d2h|host[-_]?to[-_]?device|device[-_]?to[-_]?host")


def classify_op(name: str) -> str:
    """Bucket an op/event name: ``collective`` | ``infeed`` | ``compute``."""
    low = name.lower()
    if _COLLECTIVE_RE.search(low):
        return "collective"
    if _INFEED_RE.search(low):
        return "infeed"
    return "compute"


def parse_profile_at_steps(spec: Optional[str]):
    """``"N:K"`` → ``(start_step, n_steps)``; None/empty → None.

    Validated loudly: a typo'd capture spec silently profiling nothing
    would be the worst kind of observability bug.
    """
    if not spec:
        return None
    parts = spec.split(":")
    try:
        if len(parts) != 2:
            raise ValueError
        start, n = int(parts[0]), int(parts[1])
    except ValueError:
        raise ValueError(
            f"--profile_at_steps must be START:COUNT (e.g. 100:20), got "
            f"{spec!r}")
    if start < 0 or n < 1:
        raise ValueError(
            f"--profile_at_steps needs START >= 0 and COUNT >= 1, got "
            f"{spec!r}")
    return start, n


def parse_trace_doc(doc: dict, top_k: int = 12) -> List[dict]:
    """Chrome-trace dict → per-lane device-time records (no I/O).

    Lane selection prefers the profiler's device lanes (process names
    containing ``/device:``); absent those (CPU backend) it falls back
    to host lanes, then to any lane with complete events. Durations are
    summed per op name within a lane — nested host events double-count
    their parents, which is why device lanes (flat per-op rows) are
    preferred when present.
    """
    events = doc.get("traceEvents") or []
    pid_names = {}
    for e in events:
        if e.get("ph") == "M" and e.get("name") == "process_name":
            pid_names[e.get("pid")] = (e.get("args") or {}).get("name", "")
    xs = [e for e in events
          if e.get("ph") == "X" and e.get("dur") is not None]
    if not xs:
        return []
    pids_with_x = {e.get("pid") for e in xs}
    device_pids = {p for p in pids_with_x
                   if "/device:" in (pid_names.get(p) or "")}
    host_pids = {p for p in pids_with_x
                 if "/host:" in (pid_names.get(p) or "")}
    lanes = device_pids or host_pids or pids_with_x
    out = []
    for pid in sorted(lanes, key=lambda p: (str(pid_names.get(p, "")), p)):
        evs = [e for e in xs if e.get("pid") == pid]
        if not evs:
            continue
        by_op = {}
        optimizer_us = 0.0
        t_lo = min(e["ts"] for e in evs)
        t_hi = max(e["ts"] + e["dur"] for e in evs)
        for e in evs:
            agg = by_op.setdefault(e.get("name") or "?", [0.0, 0])
            agg[0] += e["dur"]          # microseconds
            agg[1] += 1
            # Scope attribution: the named_scope prefix may live in the
            # event name OR in the profiler's metadata args (long_name /
            # tf_op carry the full HLO op_name on XLA device lanes).
            args = e.get("args") or {}
            text = " ".join((e.get("name") or "",
                             str(args.get("name", "")),
                             str(args.get("long_name", "")),
                             str(args.get("tf_op", "")))).lower()
            if SCOPE_RE.search(text):
                optimizer_us += e["dur"]
        buckets = dict.fromkeys(DEVTIME_BUCKETS, 0.0)
        total_us = 0.0
        for name, (dur_us, _calls) in by_op.items():
            buckets[classify_op(name)] += dur_us
            total_us += dur_us
        top = sorted(by_op.items(), key=lambda kv: -kv[1][0])[:top_k]
        out.append({
            "device": pid_names.get(pid) or f"pid:{pid}",
            "total_ms": round(total_us / 1e3, 3),
            "compute_ms": round(buckets["compute"] / 1e3, 3),
            "collective_ms": round(buckets["collective"] / 1e3, 3),
            "infeed_ms": round(buckets["infeed"] / 1e3, 3),
            # OVERLAPPING scope total (a subset of the buckets above,
            # not a fourth one): device time inside the step's
            # jax.named_scope("optimizer") — the weight-update tail.
            "optimizer_ms": round(optimizer_us / 1e3, 3),
            "window_ms": round((t_hi - t_lo) / 1e3, 3),
            "top_ops": [
                {"name": name, "bucket": classify_op(name),
                 "dur_ms": round(dur_us / 1e3, 3), "calls": calls,
                 "frac": round(dur_us / total_us, 4) if total_us else 0.0}
                for name, (dur_us, calls) in top],
        })
    return out


def parse_profile_dir(profile_dir: str, top_k: int = 12) -> List[dict]:
    """Parse the NEWEST capture session under a ``jax.profiler`` output
    dir (``<dir>/plugins/profile/<timestamp>/*.trace.json[.gz]``) into
    per-lane records; ``[]`` when nothing parseable is there."""
    sessions = sorted(glob.glob(
        os.path.join(profile_dir, "plugins", "profile", "*")))
    if not sessions:
        return []
    lanes: List[dict] = []
    paths = (glob.glob(os.path.join(sessions[-1], "*.trace.json.gz"))
             + glob.glob(os.path.join(sessions[-1], "*.trace.json")))
    for path in sorted(paths):
        try:
            if path.endswith(".gz"):
                with gzip.open(path, "rt") as f:
                    doc = json.load(f)
            else:
                with open(path) as f:
                    doc = json.load(f)
            lanes.extend(parse_trace_doc(doc, top_k=top_k))
        except (OSError, ValueError):
            continue
    return lanes


class ProfileWindow:
    """Step-gated ``jax.profiler`` capture + host-side trace parsing.

    The driver calls :meth:`maybe_start` at each dispatch seam (arms at
    the first seam at/after ``start_step``) and :meth:`maybe_stop` at
    each iteration end with the boundary's ``drained`` flag — the stop
    waits for a DRAINED boundary at/after ``start+n_steps`` so the
    captured window closes on quiesced devices instead of truncating
    in-flight dispatches. :meth:`close` (the loop's ``finally``) stops a
    window the run ended inside of. Fail-open throughout: a profiler or
    parse error prints one warning and the training run continues.
    """

    def __init__(self, start_step: int, n_steps: int, out_dir: str,
                 logger=None, top_k: int = 12):
        self.start_step = start_step
        self.n_steps = n_steps
        self.out_dir = out_dir
        self.logger = logger
        self.top_k = top_k
        self.state = "pending"            # pending -> active -> done
        self._armed_at = start_step       # actual arm step once active
        # Per-step optimizer device time from the parsed window (mean
        # over lanes of optimizer_ms / steps-in-window); None until a
        # window completes. Train rows after the window carry it as
        # `optimizer_ms` — measured attribution of the update tail.
        self.optimizer_step_ms: Optional[float] = None

    @classmethod
    def from_config(cls, cfg, logger=None) -> Optional["ProfileWindow"]:
        """Build the capture window the config asked for (None = flag
        off). Composes with ``--profile_dir``: the window writes there
        when set (so the host-loop Chrome trace, the XLA trace, and the
        parsed ``devtime`` table all describe the same run), else under
        ``<log_dir>/devprof``."""
        spec = parse_profile_at_steps(
            getattr(cfg, "profile_at_steps", None))
        if spec is None:
            return None
        out_dir = cfg.profile_dir or os.path.join(cfg.log_dir, "devprof")
        return cls(spec[0], spec[1], out_dir, logger=logger)

    def maybe_start(self, step: int) -> None:
        if self.state != "pending" or step < self.start_step:
            return
        self.state = "active"
        self._armed_at = step
        try:
            import jax
            jax.profiler.start_trace(self.out_dir)
        except Exception as e:              # fail-open
            print(f"[devprof] profiler start failed at step {step}: "
                  f"{e!r}", file=sys.stderr)
            self.state = "done"

    def maybe_stop(self, step: int, drained: bool = True) -> None:
        if self.state != "active" or not drained \
                or step < self.start_step + self.n_steps:
            return
        self._finish(step)

    def close(self, step: int) -> None:
        """End-of-run stop for a window the run finished inside."""
        if self.state == "active":
            self._finish(step)

    def _finish(self, step: int) -> None:
        self.state = "done"
        try:
            import jax
            jax.profiler.stop_trace()
        except Exception as e:
            print(f"[devprof] profiler stop failed at step {step}: {e!r}",
                  file=sys.stderr)
            return
        try:
            lanes = parse_profile_dir(self.out_dir, top_k=self.top_k)
        except Exception as e:
            print(f"[devprof] trace parse failed: {e!r}", file=sys.stderr)
            return
        if not lanes:
            print(f"[devprof] no parseable trace under {self.out_dir}",
                  file=sys.stderr)
            return
        steps = max(1, step - self._armed_at)
        self.optimizer_step_ms = round(
            sum(ln.get("optimizer_ms") or 0.0 for ln in lanes)
            / len(lanes) / steps, 4)
        for lane in lanes:
            if self.logger is not None:
                self.logger.log("devtime", step=step, **lane)
            top = lane["top_ops"][0] if lane["top_ops"] else None
            head = (f"; top op {top['name']} {top['dur_ms']:.1f} ms "
                    f"({100 * top['frac']:.1f}%)") if top else ""
            print(f"[devprof] {lane['device']}: {lane['total_ms']:.1f} ms "
                  f"attributed over steps {self._armed_at}..{step} "
                  f"(compute {lane['compute_ms']:.1f} / collective "
                  f"{lane['collective_ms']:.1f} / infeed "
                  f"{lane['infeed_ms']:.1f}){head}")


class DeviceStepEstimator:
    """Per-boundary device step-time estimate from the fused fetch.

    Protocol mirrors ``DrainMeter`` (utils/profiling.py): ``mark(step)``
    at the end of any iteration that drained (and once after the first
    dispatch returns), then at a metrics boundary wrap the existing
    fused ``device_get`` with two clock reads and call :meth:`boundary`.
    The window ``[mark, drain_end]`` contains every training dispatch
    since the mark plus the drain itself; the device executes that
    window's steps back-to-back (modulo input starvation), so
    ``(drain_end − mark) / steps`` estimates the per-step device time
    and ``drain_end − drain_start`` is the host's blocked share (host
    idle ⇔ device busy). An upper bound when the device starves — the
    profiler window (:class:`ProfileWindow`) adjudicates that case.
    """

    __slots__ = ("_mark",)

    def __init__(self):
        self._mark = None

    def mark(self, step: int, now: Optional[float] = None) -> None:
        self._mark = (step, time.perf_counter() if now is None else now)

    def boundary(self, step: int, drain_start: float, drain_end: float):
        """→ ``(device_step_ms, drain_wait_ms)``; the first is ``None``
        before any mark (schema keys stay present, null-valued)."""
        drain_ms = round(max(drain_end - drain_start, 0.0) * 1e3, 3)
        if self._mark is None:
            return None, drain_ms
        mark_step, mark_t = self._mark
        steps = step - mark_step
        if steps <= 0:
            return None, drain_ms
        return round((drain_end - mark_t) / steps * 1e3, 4), drain_ms
