"""Run-health telemetry: host-loop span tracing, goodput accounting, and
device/HBM health snapshots.

The metrics stream (``utils/logging.py``) records WHAT happened at each
boundary; this layer records WHERE THE WALL-CLOCK WENT and WHETHER THE RUN
IS HEALTHY — the two questions a long multi-host job must answer without a
profiler attached. Three coordinated pieces:

- :class:`SpanTracer`: a ring-buffered context-manager tracer the driver
  wraps around its host-loop phases (compile/first-dispatch, data wait,
  dispatch enqueue, boundary drain, eval, checkpoint, preemption
  allgather). Near-zero overhead when disabled — ``span()`` returns a
  shared no-op context manager, no allocation, no clock read. Finished
  spans export two ways: JSONL ``span`` records through the existing
  ``MetricsLogger`` (:func:`flush_boundary`) and a Chrome trace-event file
  (:meth:`SpanTracer.export_chrome_trace`) loadable in Perfetto alongside
  the XLA trace from ``--profile_dir``.
- Goodput accounting: top-level spans carry a category
  (``compile`` / ``data`` / ``eval`` / ``checkpoint`` / ``sync``);
  :meth:`SpanTracer.goodput` reports the fraction of wall-clock since the
  tracer epoch spent in each, with productive training as the remainder —
  so the categories sum to 1.0 by construction. Host-loop caveat: on the
  async-dispatch paths a host-side data wait can overlap device compute,
  so ``data_frac`` is an upper bound on true device starvation.
- :func:`hbm_stats`: per-process device-memory snapshot via
  ``device.memory_stats()`` (sum of bytes in use / peak / limit over local
  devices) — a host-side runtime call, NOT a device fetch, so logging it
  at boundaries adds no round trip. Backends without memory stats (CPU)
  report ``available=False`` rather than omitting the record.

Training-health scalars (grad norm, param norm, update ratio) are NOT
computed here — they are compiled into the step (``parallel/step.py``,
``health_metrics=True``) and ride the loop's single fused boundary fetch,
honoring the ~100 ms-RTT tunnel constraint documented in ``train/loop.py``.
"""

from __future__ import annotations

import collections
import json
import os
import time
from typing import Optional

# Category order pins the goodput report layout (train first, then the
# overheads in rough size order for a typical run).
GOODPUT_CATEGORIES = ("compile", "data", "eval", "checkpoint", "sync")


class _NullSpan:
    """Shared no-op context manager — the disabled-tracer fast path."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class _Span:
    __slots__ = ("_tracer", "name", "cat", "t0")

    def __init__(self, tracer: "SpanTracer", name: str, cat: Optional[str]):
        self._tracer = tracer
        self.name = name
        self.cat = cat

    def __enter__(self):
        self.t0 = time.perf_counter()
        self._tracer._depth += 1
        return self

    def __exit__(self, *exc):
        t1 = time.perf_counter()
        tr = self._tracer
        tr._depth -= 1
        tr._record(self.name, self.cat, self.t0, t1 - self.t0, tr._depth)
        return False


class SpanTracer:
    """Ring-buffered host-loop span tracer + goodput aggregator.

    ``with tracer.span("eval", cat="eval"): ...`` records one finished
    span. Only DEPTH-0 spans with a category count toward goodput —
    nested sub-spans are trace detail, not wall-clock attribution (a
    category on a nested span would double-count its parent's time).
    The ring keeps the most recent ``max_spans`` finished spans for the
    Chrome export; ``drain()`` hands out (and forgets) the spans finished
    since the last drain so boundary flushes are incremental. Overflow is
    counted (``dropped``), never silent.
    """

    def __init__(self, enabled: bool = True, max_spans: int = 65536):
        self.enabled = enabled
        self.max_spans = max_spans
        self.dropped = 0
        self._depth = 0
        # (name, cat, start_s, dur_s, depth) tuples; _ring feeds the
        # Chrome export, _pending feeds the incremental JSONL flush.
        self._ring = collections.deque(maxlen=max_spans)
        self._pending = collections.deque(maxlen=max_spans)
        self._cat_secs = dict.fromkeys(GOODPUT_CATEGORIES, 0.0)
        self._epoch = time.perf_counter()
        self._wall_epoch = time.time()

    def start(self) -> None:
        """Reset the goodput epoch (call at loop entry, pre-compile)."""
        self._epoch = time.perf_counter()
        self._wall_epoch = time.time()

    def span(self, name: str, cat: Optional[str] = None):
        if not self.enabled:
            return _NULL_SPAN
        return _Span(self, name, cat)

    def _record(self, name, cat, t0, dur, depth) -> None:
        if len(self._ring) == self.max_spans \
                or len(self._pending) == self.max_spans:
            self.dropped += 1
        rec = (name, cat, t0 - self._epoch, dur, depth)
        self._ring.append(rec)
        self._pending.append(rec)
        if depth == 0 and cat is not None:
            self._cat_secs[cat] = self._cat_secs.get(cat, 0.0) + dur

    def add_secs(self, cat: str, secs: float) -> None:
        """Attribute externally-measured seconds to a goodput category
        without a span — the compile cache reports its obtain time
        (trace + executable load-or-compile) here, so startup/restart
        compile cost lands in the `compile` fraction instead of the
        train-as-remainder bucket even when it happens outside any
        categorized span (eval-seam first compiles, warm-start loads).
        Caveat: seconds added while a categorized span is ALSO open are
        counted in both categories; ``goodput()`` clamps the sum to 1.0,
        so the overlap only softens the remainder, never inflates it."""
        if not self.enabled or secs <= 0:
            return
        self._cat_secs[cat] = self._cat_secs.get(cat, 0.0) + secs

    def drain(self) -> list:
        """Spans finished since the last drain (and forget them)."""
        out = list(self._pending)
        self._pending.clear()
        return out

    def goodput(self, now: Optional[float] = None) -> dict:
        """Cumulative goodput breakdown since the epoch.

        ``{total_s, train_frac, <cat>_frac...}`` — ``train_frac`` is the
        unattributed remainder (dispatch enqueue, boundary drain, host
        logging all count as productive: during them the device is
        executing training steps), so the fractions sum to 1.0 exactly.
        """
        total = max((now if now is not None else time.perf_counter())
                    - self._epoch, 1e-9)
        out = {"total_s": round(total, 4)}
        attributed = 0.0
        for cat in sorted(self._cat_secs):
            secs = min(self._cat_secs[cat], total - attributed)
            attributed += secs
            out[f"{cat}_frac"] = round(secs / total, 6)
        out["train_frac"] = round((total - attributed) / total, 6)
        return out

    def export_chrome_trace(self, path: str, pid: int = 0) -> None:
        """Write the retained spans as a Chrome trace-event JSON file.

        Load in Perfetto (ui.perfetto.dev) or chrome://tracing — ``ts``
        is microseconds since the tracer epoch, so the host-loop lane
        lines up with an XLA trace captured over the same run.
        """
        events = [{"name": name, "ph": "X",
                   "ts": round(start * 1e6, 1),
                   "dur": round(dur * 1e6, 1),
                   "pid": pid, "tid": depth,
                   **({"cat": cat} if cat else {})}
                  for name, cat, start, dur, depth in self._ring]
        doc = {"traceEvents": events, "displayTimeUnit": "ms",
               "otherData": {"epoch_unix_s": round(self._wall_epoch, 3),
                             "dropped_spans": self.dropped}}
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        with open(path, "w") as f:
            json.dump(doc, f)


def percentile(values, q: float):
    """Linearly-interpolated percentile (numpy's default method) of an
    UNSORTED sequence; ``None`` on empty input. Kept dependency-free so
    the serving hot path and ``tools/loadgen.py`` share one definition
    without importing numpy for a handful of floats."""
    if not values:
        return None
    vs = sorted(values)
    if len(vs) == 1:
        return vs[0]
    rank = (len(vs) - 1) * (q / 100.0)
    lo = int(rank)
    hi = min(lo + 1, len(vs) - 1)
    frac = rank - lo
    return vs[lo] * (1.0 - frac) + vs[hi] * frac


def latency_summary(seconds, prefix: str = "") -> dict:
    """p50/p95/p99/mean/max of a latency sample, in MILLISECONDS (the
    serving-convention unit; train-side spans stay in seconds). Keys are
    ``{prefix}p50_ms`` etc.; all ``None`` when the sample is empty so
    JSONL records keep their required keys (null-valued, per the schema
    contract in tools/check_jsonl_schema.py)."""
    if not seconds:
        return {f"{prefix}{k}": None
                for k in ("p50_ms", "p95_ms", "p99_ms", "mean_ms", "max_ms")}
    return {
        f"{prefix}p50_ms": round(percentile(seconds, 50) * 1e3, 3),
        f"{prefix}p95_ms": round(percentile(seconds, 95) * 1e3, 3),
        f"{prefix}p99_ms": round(percentile(seconds, 99) * 1e3, 3),
        f"{prefix}mean_ms": round(sum(seconds) / len(seconds) * 1e3, 3),
        f"{prefix}max_ms": round(max(seconds) * 1e3, 3),
    }


def hbm_stats() -> dict:
    """Per-process device-memory snapshot, summed over local devices.

    A host-side runtime query (no device round trip). Fields are 0 with
    ``available=False`` on backends whose ``memory_stats()`` is missing
    or empty (CPU), so the ``hbm`` record is emitted unconditionally and
    downstream tooling need not special-case the backend.
    """
    import jax

    in_use = peak = limit = 0
    ndev = 0
    for d in jax.local_devices():
        try:
            s = d.memory_stats()
        except Exception:
            s = None
        if not s:
            continue
        ndev += 1
        in_use += int(s.get("bytes_in_use", 0))
        peak += int(s.get("peak_bytes_in_use", s.get("bytes_in_use", 0)))
        limit += int(s.get("bytes_limit", 0))
    return {"available": ndev > 0, "devices": ndev,
            "bytes_in_use": in_use, "peak_bytes": peak,
            "bytes_limit": limit}


def flush_boundary(tracer: SpanTracer, logger, step: int,
                   final: bool = False, alerts=None) -> None:
    """Emit the boundary telemetry records through ``MetricsLogger``:
    every span finished since the last flush, the cumulative goodput
    breakdown, and an HBM snapshot. Pure host work — zero device fetches
    (the ~100 ms-RTT tunnel rule).

    ``alerts`` (an :class:`~dml_cnn_cifar10_tpu.utils.alerts.AlertEngine`)
    gets its time-window pass here — the record-driven rules already saw
    every record above via the logger's observer hook; this is where
    absence rules (heartbeat staleness) and rate-window resolutions are
    adjudicated, so alerting runs exactly at the cadence the stream
    already flushes. The engine may run even when the tracer is off —
    `train`/`fault` records still flow without ``--telemetry``."""
    if tracer.enabled:
        for name, cat, start, dur, depth in tracer.drain():
            logger.log("span", step=step, name=name,
                       start_s=round(start, 4), dur_s=round(dur, 4),
                       depth=depth, **({"cat": cat} if cat else {}))
        gp = tracer.goodput()
        if tracer.dropped:
            gp["dropped_spans"] = tracer.dropped
        if final:
            gp["final"] = 1
        logger.log("goodput", step=step, **gp)
        logger.log("hbm", step=step, **hbm_stats())
    if alerts is not None:
        alerts.evaluate(emit=logger.log, step=step)
