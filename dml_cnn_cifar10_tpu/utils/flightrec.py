"""Alert-triggered flight recorder: a bounded in-memory ring of the
last N metrics records per process, snapshotted to an atomic
post-mortem bundle the moment a streaming alert FIRES.

The ring is fed from the existing :meth:`MetricsLogger.add_observer`
hook — the same seam the alert engine rides — so arming it adds zero
instrumentation and zero device fetches (the fetch-parity pin in
``tests/test_telemetry.py`` stays green). The recorder must be attached
BEFORE the alert engine's observer: observers run in attach order, so
the record that trips a rule lands in the ring first, and the engine's
nested ``alert`` emission (observed here as just another record) then
triggers the capture with the full causal prefix already ringed.

Capture semantics map 1:1 onto the alert engine's emission contract
(``utils/alerts.py``): an ``alert`` record exists exactly when a firing
EMITS, so one bundle per firing falls out naturally — suppressed
re-fires inside the rate-limit window emit nothing and capture nothing,
and ``alert_resolved`` is a different kind and never captures.

A bundle is one directory (written to a temp path, then atomically
renamed into ``postmortem_dir``) holding::

    ring.jsonl     the ring at capture time (kind + wallclock + fields)
    alert.json     the triggering alert record + capture wallclock
    config.json    the run's full config tree (when one was given)
    env.json       python/jax/platform versions, pid, selected env vars
    context.json   live process context (active serving version, ...)

Training captures additionally ARM a one-shot ``utils/devprof.py``
window: the trainer's loop pops it at the next dispatch seam
(:meth:`FlightRecorder.pop_devprof_window`) so the bundle gains a
device-time attribution of the steps right after the fault — but only
when no whole-run ``--profile_dir`` capture owns the profiler.
``tools/postmortem.py`` renders a bundle into a human timeline.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
from typing import Callable, Optional

#: Devprof window length (steps) armed after a training capture.
DEVPROF_STEPS = 2


def _jsonable(v):
    """Best-effort plain-JSON coercion for ring/context payloads."""
    try:
        json.dumps(v, allow_nan=False)
        return v
    except (TypeError, ValueError):
        return repr(v)


class FlightRecorder:
    """Ring buffer + alert-triggered atomic bundle writer.

    ``size`` bounds the ring; ``postmortem_dir`` is where bundles land;
    ``config`` (a TrainConfig) and ``context_fn`` (zero-arg callable
    returning live process context, e.g. the serving engine's active
    version) enrich the bundle; ``logger`` receives one ``postmortem``
    JSONL record per capture so the stream itself says a bundle exists.
    """

    def __init__(self, size: int = 256,
                 postmortem_dir: Optional[str] = None,
                 config=None,
                 context_fn: Optional[Callable[[], dict]] = None,
                 logger=None):
        self.size = max(1, int(size))
        self.postmortem_dir = postmortem_dir
        self.config = config
        self.context_fn = context_fn
        self.logger = logger
        self._ring = collections.deque(maxlen=self.size)
        self._lock = threading.Lock()
        self._seq = 0
        self._capturing = False
        self._devprof_bundle: Optional[str] = None
        #: bundle directories written, in capture order (tests + tools).
        self.bundles = []

    @classmethod
    def from_config(cls, cfg, context_fn=None,
                    logger=None) -> Optional["FlightRecorder"]:
        """Armed only when ``--postmortem_dir`` is set — the disarmed
        path costs nothing (no observer, no ring)."""
        pm_dir = getattr(cfg, "postmortem_dir", None)
        if not pm_dir:
            return None
        return cls(size=getattr(cfg, "flightrec_size", 256),
                   postmortem_dir=pm_dir, config=cfg,
                   context_fn=context_fn, logger=logger)

    def observer(self):
        """The ``MetricsLogger.add_observer`` adapter. Attach BEFORE
        the alert engine's observer (see module docstring)."""
        return self.observe

    # -- the ring -------------------------------------------------------

    def observe(self, kind: str, fields: dict) -> None:
        with self._lock:
            if self._capturing:
                # The capture's own `postmortem` emission re-enters
                # here; ring it after the flag clears, never recurse.
                return
            self._ring.append({"kind": kind,
                               "wallclock": round(time.time(), 6),
                               **{k: _jsonable(v)
                                  for k, v in fields.items()}})
            if kind != "alert":
                return
            self._capturing = True
            ring_snapshot = list(self._ring)
            self._seq += 1
            seq = self._seq
        try:
            self._capture(dict(fields), ring_snapshot, seq)
        except Exception as e:  # fail-open: never take down the host
            print(f"[flightrec] capture failed: {e!r}", flush=True)
        finally:
            with self._lock:
                self._capturing = False

    def snapshot(self) -> list:
        with self._lock:
            return list(self._ring)

    # -- capture --------------------------------------------------------

    def _capture(self, alert_fields: dict, ring: list, seq: int) -> None:
        rule = str(alert_fields.get("rule") or "alert")
        safe_rule = "".join(c if c.isalnum() or c in "-_" else "_"
                            for c in rule) or "alert"
        final = os.path.join(self.postmortem_dir,
                             f"{safe_rule}_{seq:03d}")
        tmp = f"{final}.tmp{os.getpid()}"
        os.makedirs(tmp, exist_ok=True)
        with open(os.path.join(tmp, "ring.jsonl"), "w") as f:
            for rec in ring:
                f.write(json.dumps(rec) + "\n")
        with open(os.path.join(tmp, "alert.json"), "w") as f:
            json.dump({**{k: _jsonable(v)
                          for k, v in alert_fields.items()},
                       "captured_wallclock": round(time.time(), 6)},
                      f, indent=2)
        if self.config is not None:
            from dml_cnn_cifar10_tpu.config import config_to_dict
            with open(os.path.join(tmp, "config.json"), "w") as f:
                json.dump(config_to_dict(self.config), f, indent=2)
        with open(os.path.join(tmp, "env.json"), "w") as f:
            json.dump(self._env(), f, indent=2)
        context = {}
        if self.context_fn is not None:
            try:
                context = {k: _jsonable(v)
                           for k, v in (self.context_fn() or {}).items()}
            except Exception as e:
                context = {"error": repr(e)}
        with open(os.path.join(tmp, "context.json"), "w") as f:
            json.dump(context, f, indent=2)
        # Atomic publish: a reader never sees a half-written bundle.
        os.rename(tmp, final)
        self.bundles.append(final)
        # Arm the one-shot devprof window for the NEXT dispatch seam
        # (training only; the serving hosts have no step loop to pop it
        # and simply never do).
        self._devprof_bundle = final
        if self.logger is not None:
            self.logger.log("postmortem", rule=rule, dir=final,
                            records=len(ring))
        print(f"[flightrec] alert {rule!r} captured post-mortem bundle "
              f"-> {final} ({len(ring)} ring record(s))", flush=True)

    @staticmethod
    def _env() -> dict:
        import platform
        import sys
        env = {"python": sys.version.split()[0],
               "platform": platform.platform(),
               "pid": os.getpid(),
               "env": {k: os.environ[k] for k in
                       ("JAX_PLATFORMS", "XLA_FLAGS",
                        "DML_FLEET_WORKER_PLATFORM")
                       if k in os.environ}}
        try:
            import jax
            env["jax"] = jax.__version__
        except Exception:
            pass
        return env

    # -- devprof arming -------------------------------------------------

    def pop_devprof_window(self, step: int, logger=None):
        """One-shot: after a capture, return a ProfileWindow starting
        at ``step`` writing under ``<bundle>/devprof``; None when no
        capture is pending. The trainer pops this at its dispatch seam
        (only when no ``--profile_dir`` run-wide capture owns the
        profiler)."""
        with self._lock:
            bundle = self._devprof_bundle
            self._devprof_bundle = None
        if bundle is None:
            return None
        from dml_cnn_cifar10_tpu.utils.devprof import ProfileWindow
        return ProfileWindow(step, DEVPROF_STEPS,
                             os.path.join(bundle, "devprof"),
                             logger=logger)
