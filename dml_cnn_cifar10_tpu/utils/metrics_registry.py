"""Live metrics: a process-local registry + Prometheus-text export.

Every observability surface before this module was post-hoc: the JSONL
stream, the reports, the trace tools all read files after the run. The
reference stack got live supervision for free from
``tf.train.MonitoredTrainingSession``'s hook machinery; this is the
SPMD-era equivalent — a thread-safe registry of counters / gauges /
histograms that any process type (trainer, serve worker, fleet router)
can expose over HTTP in the standard text exposition format, scrapable
by Prometheus or by ``tools/live_monitor.py`` while the run is live.

Design rules:

- **No new instrumentation.** The numbers already exist — the JSONL
  records carry them. :func:`observe_record` is the one translation
  table from record kinds to metrics, and ``MetricsLogger`` calls it
  for every record it writes (``utils/logging.py``), so every seam
  that logs is already exporting. Direct registry calls exist only
  where a number never enters the stream (per-peer beat staleness in
  ``parallel/cluster.py``, the serving latency histogram in
  ``serve/metrics.py``).
- **Zero device traffic.** Everything here is host-side dict work; the
  ``test_telemetry`` fetch-parity assert pins that arming the registry
  adds no ``jax.device_get`` calls.
- **Process-local.** One registry per process (:func:`default_registry`)
  — the fleet's workers each export their own; aggregation is the
  scraper's job (that is the Prometheus model, and what the live
  monitor does).

Export surfaces: ``GET /metrics`` on the serve server and the fleet
router (next to their ``/healthz``), and :func:`ensure_stats_server` —
the lightweight stats-HTTP thread the trainer starts behind
``--stats_port`` (0 = off; the trainer has no other HTTP surface).

:func:`parse_prometheus_text` is the inverse of :meth:`render` —
shared by the live monitor's scraper and the exposition-format lint in
``tests/test_alerts.py`` (render → parse → same numbers).
"""

from __future__ import annotations

import json
import threading
from typing import Dict, List, Optional, Sequence, Tuple

#: Default histogram buckets (milliseconds-flavored: the one histogram
#: fed today is the serving latency).
DEFAULT_BUCKETS = (1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0,
                   500.0, 1000.0, 2500.0)


def _fmt(v: float) -> str:
    """Prometheus-text float: integers render bare, specials by name."""
    if v != v:
        return "NaN"
    if v in (float("inf"), float("-inf")):
        return "+Inf" if v > 0 else "-Inf"
    if float(v).is_integer() and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


def _label_str(names: Sequence[str], values: Sequence[str]) -> str:
    if not names:
        return ""
    inner = ",".join(
        f'{n}="{str(v).replace(chr(92), chr(92) * 2).replace(chr(34), chr(92) + chr(34))}"'
        for n, v in zip(names, values))
    return "{" + inner + "}"


class _Metric:
    """One named metric family: help text, type, per-label-set values."""

    def __init__(self, name: str, help_text: str, mtype: str,
                 labelnames: Sequence[str] = ()):
        self.name = name
        self.help = help_text
        self.type = mtype
        self.labelnames = tuple(labelnames)
        self._lock = threading.Lock()
        self._values: Dict[Tuple[str, ...], float] = {}

    def _key(self, labels: Dict[str, str]) -> Tuple[str, ...]:
        if set(labels) != set(self.labelnames):
            raise ValueError(
                f"metric {self.name} wants labels {self.labelnames}, "
                f"got {sorted(labels)}")
        return tuple(str(labels[n]) for n in self.labelnames)

    def values(self) -> Dict[Tuple[str, ...], float]:
        with self._lock:
            return dict(self._values)


class Counter(_Metric):
    """Monotone counter. ``inc`` by a non-negative delta."""

    def __init__(self, name, help_text, labelnames=()):
        super().__init__(name, help_text, "counter", labelnames)

    def inc(self, delta: float = 1.0, **labels) -> None:
        if delta < 0:
            return  # counters never go down; a bad delta is dropped
        key = self._key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + delta


class Gauge(_Metric):
    """Point-in-time value. ``set`` wins, ``inc``/``dec`` adjust."""

    def __init__(self, name, help_text, labelnames=()):
        super().__init__(name, help_text, "gauge", labelnames)

    def set(self, value, **labels) -> None:
        if value is None:
            return  # null-valued JSONL fields simply don't update
        key = self._key(labels)
        with self._lock:
            self._values[key] = float(value)

    def inc(self, delta: float = 1.0, **labels) -> None:
        key = self._key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + delta

    def remove(self, **labels) -> None:
        key = self._key(labels)
        with self._lock:
            self._values.pop(key, None)


class Histogram(_Metric):
    """Cumulative-bucket histogram (the Prometheus shape: every bucket
    counts observations ≤ its bound, plus ``+Inf``/sum/count series)."""

    def __init__(self, name, help_text, labelnames=(),
                 buckets: Sequence[float] = DEFAULT_BUCKETS):
        super().__init__(name, help_text, "histogram", labelnames)
        self.buckets = tuple(sorted(buckets))
        self._counts: Dict[Tuple[str, ...], List[int]] = {}
        self._sums: Dict[Tuple[str, ...], float] = {}
        self._totals: Dict[Tuple[str, ...], int] = {}

    def observe(self, value: float, **labels) -> None:
        key = self._key(labels)
        with self._lock:
            counts = self._counts.setdefault(key,
                                             [0] * len(self.buckets))
            for i, bound in enumerate(self.buckets):
                if value <= bound:
                    counts[i] += 1
            self._sums[key] = self._sums.get(key, 0.0) + float(value)
            self._totals[key] = self._totals.get(key, 0) + 1

    def snapshot(self) -> Dict[Tuple[str, ...], dict]:
        with self._lock:
            return {key: {"buckets": list(self._counts[key]),
                          "sum": self._sums[key],
                          "count": self._totals[key]}
                    for key in self._counts}


class MetricsRegistry:
    """Thread-safe named-metric registry; ``render()`` is the
    ``/metrics`` payload. Registration is idempotent by name (the same
    seam may re-register across supervisor restart attempts)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, _Metric] = {}

    def _register(self, cls, name, help_text, labelnames, **kw):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls(name, help_text, labelnames=labelnames, **kw)
                self._metrics[name] = m
            elif not isinstance(m, cls) \
                    or m.labelnames != tuple(labelnames):
                raise ValueError(
                    f"metric {name} re-registered with a different "
                    f"type/labels ({m.type}{m.labelnames})")
            return m

    def counter(self, name, help_text="", labelnames=()) -> Counter:
        return self._register(Counter, name, help_text, labelnames)

    def gauge(self, name, help_text="", labelnames=()) -> Gauge:
        return self._register(Gauge, name, help_text, labelnames)

    def histogram(self, name, help_text="", labelnames=(),
                  buckets=DEFAULT_BUCKETS) -> Histogram:
        return self._register(Histogram, name, help_text, labelnames,
                              buckets=buckets)

    def get(self, name: str) -> Optional[_Metric]:
        with self._lock:
            return self._metrics.get(name)

    def snapshot(self) -> Dict[str, Dict[Tuple[str, ...], float]]:
        """Plain-dict view of every scalar series (histograms excluded)
        — what tests and the live monitor's in-process path read."""
        with self._lock:
            metrics = list(self._metrics.values())
        return {m.name: m.values() for m in metrics
                if not isinstance(m, Histogram)}

    def render(self) -> str:
        """The standard text exposition format (version 0.0.4): HELP +
        TYPE comments, one ``name{labels} value`` line per series."""
        with self._lock:
            metrics = sorted(self._metrics.values(),
                             key=lambda m: m.name)
        lines: List[str] = []
        for m in metrics:
            lines.append(f"# HELP {m.name} {m.help}")
            lines.append(f"# TYPE {m.name} {m.type}")
            if isinstance(m, Histogram):
                for key, snap in sorted(m.snapshot().items()):
                    for bound, n in zip(m.buckets, snap["buckets"]):
                        lines.append(
                            m.name + "_bucket"
                            + _label_str(tuple(m.labelnames) + ("le",),
                                         key + (_fmt(bound),))
                            + f" {n}")
                    lines.append(
                        m.name + "_bucket"
                        + _label_str(tuple(m.labelnames) + ("le",),
                                     key + ("+Inf",))
                        + f" {snap['count']}")
                    lines.append(m.name + "_sum"
                                 + _label_str(m.labelnames, key)
                                 + f" {_fmt(snap['sum'])}")
                    lines.append(m.name + "_count"
                                 + _label_str(m.labelnames, key)
                                 + f" {snap['count']}")
                continue
            for key, value in sorted(m.values().items()):
                lines.append(m.name + _label_str(m.labelnames, key)
                             + f" {_fmt(value)}")
        return "\n".join(lines) + "\n"


def parse_prometheus_text(text: str) -> Dict[str, dict]:
    """Parse the text exposition format back into
    ``{name: {"type": ..., "help": ..., "samples":
    {(("label","value"),...): float}}}`` — the scrape half of the live
    monitor, and the round-trip check the exposition lint runs.
    Raises ``ValueError`` on a malformed line (the lint's teeth)."""
    out: Dict[str, dict] = {}
    for ln, raw in enumerate(text.splitlines(), 1):
        line = raw.strip()
        if not line:
            continue
        if line.startswith("# HELP ") or line.startswith("# TYPE "):
            _, verb, rest = line.split(" ", 2)
            name, _, payload = rest.partition(" ")
            fam = out.setdefault(name, {"type": None, "help": None,
                                        "samples": {}})
            fam["help" if verb == "HELP" else "type"] = payload
            continue
        if line.startswith("#"):
            continue
        # sample line: name{l="v",...} value   (labels optional)
        brace = line.find("{")
        if brace >= 0:
            close = line.rfind("}")
            if close < brace:
                raise ValueError(f"line {ln}: unbalanced braces: {raw!r}")
            name = line[:brace]
            label_body = line[brace + 1:close]
            value_s = line[close + 1:].strip()
            labels = []
            if label_body:
                # Split on commas OUTSIDE quotes, then unescape each
                # label value (the renderer escapes \ and ").
                part = ""
                in_quote = False
                parts = []
                for ch in label_body:
                    if ch == '"' and not part.endswith("\\"):
                        in_quote = not in_quote
                    if ch == "," and not in_quote:
                        parts.append(part)
                        part = ""
                    else:
                        part += ch
                if part:
                    parts.append(part)
                for p in parts:
                    k, eq, v = p.partition("=")
                    if not eq or not (v.startswith('"')
                                      and v.endswith('"')):
                        raise ValueError(
                            f"line {ln}: bad label {p!r} in {raw!r}")
                    labels.append(
                        (k, v[1:-1].replace('\\"', '"')
                            .replace("\\\\", "\\")))
        else:
            name, _, value_s = line.partition(" ")
            labels = []
            value_s = value_s.strip()
        if not name or not value_s:
            raise ValueError(f"line {ln}: malformed sample: {raw!r}")
        try:
            value = float(value_s.replace("+Inf", "inf")
                          .replace("-Inf", "-inf"))
        except ValueError:
            raise ValueError(f"line {ln}: bad value {value_s!r}")
        fam = out.setdefault(name.rstrip(), {"type": None, "help": None,
                                             "samples": {}})
        fam["samples"][tuple(labels)] = value
    return out


# ---------------------------------------------------------------------------
# the process-default registry + the JSONL-kind translation table
# ---------------------------------------------------------------------------

_DEFAULT = MetricsRegistry()


def default_registry() -> MetricsRegistry:
    """The process-local registry every export surface renders."""
    return _DEFAULT


def observe_record(kind: str, fields: dict,
                   registry: Optional[MetricsRegistry] = None) -> None:
    """Translate one JSONL record into registry updates — the single
    table that turns the existing telemetry stream into live metrics.
    Called by ``MetricsLogger.log`` for every record it writes, so any
    seam that logs is already exporting; unknown kinds are ignored.
    Fail-open: a malformed record must not take down the logger."""
    reg = registry if registry is not None else _DEFAULT
    try:
        _observe_record(kind, fields, reg)
    except Exception:
        pass


def _observe_record(kind: str, f: dict, reg: MetricsRegistry) -> None:
    if kind == "train":
        reg.gauge("dml_train_step",
                  "Global training step at the last metrics boundary"
                  ).set(f.get("step"))
        reg.gauge("dml_train_loss", "Training loss at the last boundary"
                  ).set(f.get("loss"))
        reg.gauge("dml_train_images_per_sec",
                  "Drain-anchored training throughput"
                  ).set(f.get("images_per_sec"))
        reg.gauge("dml_device_step_ms",
                  "Estimated device time per training step"
                  ).set(f.get("device_step_ms"))
        reg.gauge("dml_drain_wait_ms",
                  "Host time blocked in the fused boundary fetch"
                  ).set(f.get("drain_wait_ms"))
        reg.counter("dml_train_boundaries_total",
                    "Metrics boundaries flushed").inc()
    elif kind == "goodput":
        g = reg.gauge("dml_goodput_fraction",
                      "Cumulative goodput fraction by category",
                      labelnames=("category",))
        for key, value in f.items():
            if key.endswith("_frac"):
                g.set(value, category=key[:-len("_frac")])
        reg.gauge("dml_goodput_total_seconds",
                  "Wall-clock seconds since the tracer epoch"
                  ).set(f.get("total_s"))
    elif kind == "hbm":
        if f.get("available"):
            reg.gauge("dml_hbm_bytes_in_use",
                      "Device memory in use, summed over local devices"
                      ).set(f.get("bytes_in_use"))
            reg.gauge("dml_hbm_bytes_limit",
                      "Device memory limit, summed over local devices"
                      ).set(f.get("bytes_limit"))
            reg.gauge("dml_hbm_peak_bytes",
                      "Peak device memory, summed over local devices"
                      ).set(f.get("peak_bytes"))
    elif kind == "eval":
        reg.gauge("dml_eval_accuracy", "Last eval accuracy"
                  ).set(f.get("test_accuracy"))
    elif kind == "fault":
        reg.counter("dml_faults_total", "Fault records by class",
                    labelnames=("fault",)
                    ).inc(1, fault=str(f.get("fault")))
    elif kind == "recovery":
        reg.counter("dml_recoveries_total", "Recovery actions by kind",
                    labelnames=("action",)
                    ).inc(1, action=str(f.get("action")))
    elif kind == "compile":
        reg.counter("dml_compile_lookups_total",
                    "Compile-seam lookups by hit/miss",
                    labelnames=("hit",)
                    ).inc(1, hit="true" if f.get("hit") else "false")
        reg.counter("dml_compile_seconds_total",
                    "Seconds spent obtaining compiled programs"
                    ).inc(f.get("compile_s") or 0.0)
    elif kind == "heartbeat":
        reg.gauge("dml_heartbeat_step",
                  "Step carried by this process's latest beat"
                  ).set(f.get("step"))
    elif kind == "serve":
        reg.gauge("dml_serve_qps", "Completed requests/s, last window"
                  ).set(f.get("qps"))
        reg.gauge("dml_serve_p50_ms", "Latency p50, last window"
                  ).set(f.get("p50_ms"))
        reg.gauge("dml_serve_p99_ms", "Latency p99, last window"
                  ).set(f.get("p99_ms"))
        reg.gauge("dml_serve_batch_fill",
                  "Mean batch fill fraction, last window"
                  ).set(f.get("batch_fill"))
        reg.counter("dml_serve_requests_total", "Requests submitted"
                    ).inc(f.get("requests") or 0)
        reg.counter("dml_serve_completed_total", "Requests completed"
                    ).inc(f.get("completed") or 0)
        shed = reg.counter("dml_serve_shed_total",
                           "Requests shed by admission control",
                           labelnames=("reason",))
        shed.inc(f.get("shed_queue") or 0, reason="queue_full")
        shed.inc(f.get("shed_deadline") or 0, reason="deadline")
        reg.counter("dml_serve_cache_hits_total",
                    "Requests answered by the response cache "
                    "(bypassed the batcher)"
                    ).inc(f.get("cache_hit") or 0)
    elif kind == "fleet":
        reg.gauge("dml_fleet_live_replicas",
                  "Replicas in the routing rotation").set(f.get("live"))
        reg.gauge("dml_fleet_replicas",
                  "Replicas known to the router").set(f.get("replicas"))
        reg.counter("dml_fleet_routed_total", "Requests routed"
                    ).inc(f.get("routed") or 0)
        reg.counter("dml_fleet_rerouted_total",
                    "Requests re-routed after a replica failure"
                    ).inc(f.get("rerouted") or 0)
        reg.counter("dml_fleet_evictions_total", "Replica evictions"
                    ).inc(f.get("evictions") or 0)
        reg.counter("dml_fleet_shed_total", "Requests shed by the router"
                    ).inc(f.get("shed") or 0)
    elif kind == "scale":
        reg.counter("dml_fleet_scale_total", "Autoscaler actions",
                    labelnames=("action",)
                    ).inc(1, action=str(f.get("action")))
    elif kind in ("elastic_restart", "elastic_expand"):
        reg.gauge("dml_cluster_world_size",
                  "World size adopted by the last restart decision"
                  ).set(f.get("world_size"))
        reg.gauge("dml_cluster_epoch", "Adopted coordination epoch"
                  ).set(f.get("epoch"))
    elif kind == "alert":
        reg.gauge("dml_alert_active", "1 while the alert rule is firing",
                  labelnames=("rule", "severity")
                  ).set(1, rule=str(f.get("rule")),
                        severity=str(f.get("severity")))
        reg.counter("dml_alerts_total", "Alert firings by rule",
                    labelnames=("rule",)).inc(1, rule=str(f.get("rule")))
    elif kind == "alert_resolved":
        reg.gauge("dml_alert_active", "1 while the alert rule is firing",
                  labelnames=("rule", "severity")
                  ).set(0, rule=str(f.get("rule")),
                        severity=str(f.get("severity")))
    elif kind == "job":
        reg.counter("dml_job_transitions_total",
                    "Runtime job state transitions by type and state",
                    labelnames=("jtype", "state")
                    ).inc(1, jtype=str(f.get("jtype")),
                          state=str(f.get("state")))
    elif kind == "job_done":
        reg.counter("dml_jobs_done_total",
                    "Runtime jobs finished, by type and verdict",
                    labelnames=("jtype", "ok")
                    ).inc(1, jtype=str(f.get("jtype")),
                          ok="true" if f.get("ok") else "false")
        reg.gauge("dml_job_seconds",
                  "Wall seconds of the last finished job of each type",
                  labelnames=("jtype",)
                  ).set(f.get("secs"), jtype=str(f.get("jtype")))
    elif kind == "publish":
        reg.counter("dml_publishes_total",
                    "Checkpoint weights published into the in-process "
                    "serving engine, by swap verdict",
                    labelnames=("swapped",)
                    ).inc(1, swapped="true" if f.get("swapped")
                          else "false")
        reg.gauge("dml_publish_latency_ms",
                  "Latency of the last publish (copy-install swap)"
                  ).set(f.get("latency_ms"))
        reg.gauge("dml_published_step",
                  "Training step of the last published version"
                  ).set(f.get("step"))


# ---------------------------------------------------------------------------
# the stats HTTP thread (--stats_port) — trainer-side export surface
# ---------------------------------------------------------------------------

class StatsServer:
    """``GET /metrics`` (text exposition) + ``GET /healthz`` on a
    daemon accept thread — the trainer's only HTTP surface, so it stays
    deliberately tiny (same stdlib transport as ``serve/server.py``)."""

    def __init__(self, registry: MetricsRegistry, port: int,
                 host: str = ""):
        from http.server import (BaseHTTPRequestHandler,
                                 ThreadingHTTPServer)
        reg = registry

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _reply(self, code, body: bytes, ctype: str) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/metrics":
                    self._reply(200, reg.render().encode(),
                                "text/plain; version=0.0.4")
                elif self.path == "/healthz":
                    self._reply(200, json.dumps({"ok": True}).encode(),
                                "application/json")
                else:
                    self._reply(404, b'{"error": "no route"}',
                                "application/json")

        self._server = ThreadingHTTPServer((host, port), Handler)
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="stats-http",
            daemon=True)
        self._thread.start()

    def close(self) -> None:
        self._server.shutdown()
        self._server.server_close()


_STATS_LOCK = threading.Lock()
_STATS_SERVER: Optional[StatsServer] = None


def ensure_stats_server(port: Optional[int],
                        registry: Optional[MetricsRegistry] = None
                        ) -> Optional[StatsServer]:
    """Start (once per process) the stats HTTP thread when ``port`` is
    truthy; idempotent so supervisor restart attempts re-entering
    ``Trainer.__init__`` reuse the bound socket instead of fighting
    over it. ``0``/``None`` = off (the default). Fail-open: a bind
    failure prints a notice and returns None — live export must never
    kill training."""
    global _STATS_SERVER
    if not port:
        return None
    with _STATS_LOCK:
        if _STATS_SERVER is not None:
            return _STATS_SERVER
        try:
            _STATS_SERVER = StatsServer(
                registry if registry is not None else _DEFAULT, port)
        except OSError as e:
            import sys
            print(f"[stats] could not bind --stats_port {port}: {e}; "
                  f"live metrics export disabled", file=sys.stderr)
            return None
        print(f"[stats] GET /metrics on :{_STATS_SERVER.port}")
        return _STATS_SERVER


def stop_stats_server() -> None:
    """Close and forget the process stats server (tests; a long-lived
    driver embedding several runs in one process)."""
    global _STATS_SERVER
    with _STATS_LOCK:
        if _STATS_SERVER is not None:
            _STATS_SERVER.close()
            _STATS_SERVER = None
