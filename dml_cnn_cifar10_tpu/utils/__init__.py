"""Cross-cutting utilities: structured logging, profiling, telemetry."""

from dml_cnn_cifar10_tpu.utils.logging import MetricsLogger  # noqa: F401
from dml_cnn_cifar10_tpu.utils.profiling import DrainMeter, profile_trace  # noqa: F401
from dml_cnn_cifar10_tpu.utils.telemetry import SpanTracer, hbm_stats  # noqa: F401
