"""Cross-cutting utilities: structured logging, profiling, timing."""

from dml_cnn_cifar10_tpu.utils.logging import MetricsLogger  # noqa: F401
from dml_cnn_cifar10_tpu.utils.profiling import StepTimer, profile_trace  # noqa: F401
