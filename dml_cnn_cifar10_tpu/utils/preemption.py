"""Graceful preemption: SIGTERM/SIGINT → finish the step, checkpoint, exit.

The reference's only fault story is "restart the worker and
MonitoredTrainingSession restores the latest checkpoint"
(``cifar10cnn.py:222``, SURVEY §5 "Failure detection") — fine under async
PS where a dead worker doesn't block the others, but it loses up to
``checkpoint_every`` steps of work. Under synchronous SPMD every preemption
kills the whole job, so the framework adds the missing half: a signal
guard the training loop polls each step. On SIGTERM (the standard
preemption warning on managed TPU/K8s pools) or SIGINT the loop completes
the in-flight step, force-saves a checkpoint, and exits cleanly; the next
start restores and resumes. Works per-process in multi-host runs — each
process saves/exits on its own signal, and restart re-forms the SPMD set.
"""

from __future__ import annotations

import signal
import threading
from typing import Optional


class PreemptionGuard:
    """Context manager: installs SIGTERM/SIGINT handlers that set a flag
    instead of killing the process. Poll ``requested`` from the training
    loop. No-ops (flag stays False, no handlers touched) when not in the
    main thread, where Python forbids ``signal.signal``."""

    SIGNALS = (signal.SIGTERM, signal.SIGINT)

    def __init__(self):
        self.requested = False
        self.signum: Optional[int] = None
        self._saved = {}

    def _handle(self, signum, frame):
        del frame
        self.requested = True
        self.signum = signum

    def __enter__(self) -> "PreemptionGuard":
        if threading.current_thread() is threading.main_thread():
            for s in self.SIGNALS:
                self._saved[s] = signal.signal(s, self._handle)
        return self

    def __exit__(self, *exc) -> None:
        for s, old in self._saved.items():
            signal.signal(s, old)
        self._saved.clear()
        return None
