"""Profiling hooks: step timing + XLA trace capture.

The reference has no profiling at all (SURVEY §5). Here: a cheap steady-state
step timer (excludes compile) feeding images/sec into the metrics stream, and
an optional ``jax.profiler`` trace for TensorBoard/Perfetto.
"""

from __future__ import annotations

import contextlib
import time
from typing import Optional


class StepTimer:
    """Rolling step-time/throughput meter. ``skip`` initial steps are
    excluded so the first-compile stall doesn't pollute the numbers."""

    def __init__(self, batch_size: int, skip: int = 2):
        self.batch_size = batch_size
        self.skip = skip
        self._count = 0
        self._elapsed = 0.0
        self._last: Optional[float] = None

    def tick(self) -> None:
        now = time.perf_counter()
        if self._last is not None:
            self.skip -= 1
            if self.skip < 0:
                self._elapsed += now - self._last
                self._count += 1
        self._last = now

    @property
    def steps_per_sec(self) -> float:
        return self._count / self._elapsed if self._elapsed else 0.0

    @property
    def images_per_sec(self) -> float:
        return self.steps_per_sec * self.batch_size


@contextlib.contextmanager
def profile_trace(log_dir: Optional[str]):
    """Capture an XLA profiler trace into ``log_dir`` when set."""
    if not log_dir:
        yield
        return
    import jax
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
