"""Profiling hooks: throughput metering, FLOPs probes, XLA trace capture.

The reference has no profiling at all (SURVEY §5). Here: the drain-anchored
throughput meter feeding images/sec into the metrics stream, the XLA
cost-analysis FLOPs probes behind the TFLOP/s / MFU metrics, and an optional
``jax.profiler`` trace for TensorBoard/Perfetto. Host-loop phase timing
lives in ``utils/telemetry.py`` (``SpanTracer``), which subsumed the old
``StepTimer`` (a rolling host-interval step timer the trainer never used —
host intervals measure enqueue rate, not execution, exactly the hazard
``DrainMeter`` exists to avoid).
"""

from __future__ import annotations

import contextlib
import time
from typing import Optional


class DrainMeter:
    """Drain-anchored throughput meter.

    Dispatches are async: host loop intervals measure ENQUEUE rate, not
    execution (the ``block_until_ready`` hazard ``bench.py`` documents).
    Every device fetch is a true drain, so the exact training rate is
    (steps between drains) / (wall time between drains) — provided the
    window holds only training dispatches. Protocol: call :meth:`rate`
    right after a boundary's metric fetch, and :meth:`mark` at the END
    of any iteration that drained (metrics fetch, eval sweep, checkpoint
    fetch), so eval/checkpoint work never pollutes the next window.
    """

    def __init__(self, images_per_step: float):
        self.images_per_step = images_per_step
        self._mark: Optional[tuple] = None

    def rate(self, step: int) -> float:
        """images/sec since the previous mark; 0.0 before the first."""
        if self._mark is None:
            return 0.0
        prev_step, prev_t = self._mark
        dt = time.perf_counter() - prev_t
        if dt <= 0 or step <= prev_step:
            return 0.0
        return (step - prev_step) * self.images_per_step / dt

    def mark(self, step: int) -> None:
        self._mark = (step, time.perf_counter())


def abstractify(tree):
    """Pytree of arrays → ``ShapeDtypeStruct``s (sharding preserved) —
    the avals needed to look a compiled executable up via ``lower``."""
    import jax

    return jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype,
                                       sharding=getattr(x, "sharding",
                                                        None)), tree)


def compiled_flops(jitted_fn, abstract_args) -> Optional[float]:
    """FLOPs of one dispatch from XLA's cost analysis.

    A cache-wrapped function (``compilecache.CachedFunction``, or the
    resident-chunk partial's shim) serves the figure from the persistent
    compile cache — the already-obtained executable's analysis or the
    entry's recorded one — with NO recompile. The bare AOT fallback
    ``lower().compile()`` keeps its own executable cache and recompiles
    (hundreds of ms to seconds for a real train step) even when the call
    path already compiled, so the driver runs this on a background
    thread, never inline in the step loop. None when the backend doesn't
    report flops."""
    cached = getattr(jitted_fn, "cached_flops", None)
    if cached is not None:
        try:
            flops = cached(abstract_args)
            if flops and flops > 0:
                return float(flops)
        except Exception:
            pass
    try:
        cost = jitted_fn.lower(*abstract_args).compile().cost_analysis()
        flops = cost.get("flops", 0.0)
        return float(flops) if flops and flops > 0 else None
    except Exception:
        return None


def correct_stack_flops(f: float, depth: int, bf_counted: Optional[float],
                        bf_true: Optional[float]):
    """Fix a step's cost-analysis FLOPs for a lax.scan-ned layer stack →
    ``(corrected_flops, label)``.

    XLA counts a scan body once, so a depth-D stacked model reports
    ~1/D of its stack FLOPs; Pallas kernels are opaque custom calls
    counted as 0. Given one block's standalone measurements —
    ``bf_counted`` (as the step runs it) and ``bf_true``
    (dense-equivalent, fully counted) — swap the counted contribution
    for the true cost at full depth. A scan-once count contains the body
    ~once (``f ≈ overhead + bf_counted``); an unrolled / per-iteration
    count contains it ~``depth`` times (``f ≥ depth·bf_counted``). The
    midpoint ``(1+depth)/2 · bf_counted`` separates the two regimes even
    when non-stack step FLOPs (embed/head/optimizer) exceed one block's
    counted FLOPs — the old fixed ``2·bf_counted`` threshold mislabeled
    such steps per-iteration (round-3 advisor finding). Returns the input
    unchanged with label ``probe_failed`` when the block numbers are
    unusable — the caller must then NOT publish the (known ~1/depth
    wrong) figure as honest.
    """
    if not (depth and depth > 1 and bf_counted and bf_true):
        return f, "probe_failed"
    if f < (1 + depth) / 2 * bf_counted:
        return f - bf_counted + depth * bf_true, f"scan_once_x{depth}"
    return f + depth * (bf_true - bf_counted), "per_iteration"


@contextlib.contextmanager
def profile_trace(log_dir: Optional[str]):
    """Capture an XLA profiler trace into ``log_dir`` when set."""
    if not log_dir:
        yield
        return
    import jax
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
