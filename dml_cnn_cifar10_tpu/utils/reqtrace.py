"""Distributed request tracing for the serving path.

One request, one ``trace_id``, minted at the CLIENT (``tools/loadgen.py``,
or the serve/worker HTTP handler for external callers that send no
header) and propagated through every hop on the ``X-DML-Trace`` wire
header: client → fleet router (one span per placement ATTEMPT, so a
retried-after-worker-kill request shows both placements) → worker HTTP
handler → micro-batcher queue → engine dispatch. Each hop appends one
``rspan`` JSONL record to ITS OWN process stream — ``trace_id`` is the
join key ``tools/trace_aggregate.py`` stitches the cross-process
timeline from, and ``wallclock`` (unix seconds at hop START) is what
places the span on the merged clock without needing heartbeat offsets.

Sampling is HEAD-based: the client decides once per request
(``--trace_sample_rate``), encodes the decision in the header's ``s``
bit, and every downstream hop honors it — no hop re-rolls the dice, so
a sampled trace is always complete. Requests that end up SHED or
RETRIED flip :meth:`TraceContext.force` at the point of failure: the
interesting requests are captured even at sample rate 0, and every span
emitted at-or-after the flip (plus the buffered router attempt spans)
makes it into the stream.

Everything here is host-side bookkeeping on numbers the hops already
have — zero extra device fetches (the fetch-parity pin in
``tests/test_telemetry.py`` stays green with tracing on).
"""

from __future__ import annotations

import os
import random
import time
from typing import Optional

#: The propagation header: ``"<hex trace id>;s=<0|1>"`` where ``s`` is
#: the head-sampling decision (sampled OR forced at send time).
TRACE_HEADER = "X-DML-Trace"


class TraceContext:
    """One request's trace identity + sampling state.

    Shared BY REFERENCE across the threads a request crosses (HTTP
    handler thread, batcher dispatch thread): a downstream hop that
    forces the trace (shed, retry) makes every LATER span emit, which
    is exactly the forced-sample contract.
    """

    __slots__ = ("trace_id", "sampled", "forced")

    def __init__(self, trace_id: str, sampled: bool,
                 forced: bool = False):
        self.trace_id = trace_id
        self.sampled = bool(sampled)
        self.forced = bool(forced)

    @property
    def emit(self) -> bool:
        """Should spans for this trace be written?"""
        return self.sampled or self.forced

    def force(self) -> None:
        """Forced-sample override: the request was shed or retried —
        capture it regardless of the head-sampling decision."""
        self.forced = True

    def header(self) -> str:
        """Wire form for :data:`TRACE_HEADER` on the NEXT hop."""
        return f"{self.trace_id};s={1 if self.emit else 0}"

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (f"TraceContext({self.trace_id}, sampled={self.sampled}, "
                f"forced={self.forced})")


def mint(sample_rate: float = 0.0) -> TraceContext:
    """Client-side: new trace id + the head-sampling roll."""
    rate = max(0.0, min(1.0, float(sample_rate or 0.0)))
    sampled = rate >= 1.0 or (rate > 0.0 and random.random() < rate)
    return TraceContext(os.urandom(8).hex(), sampled)


def parse(header_value: Optional[str],
          sample_rate: float = 0.0) -> TraceContext:
    """Server-side: adopt the caller's trace context from the header,
    or mint one (an external caller without the header becomes the
    trace root at THIS hop). A malformed header also mints — tracing
    must never fail a request."""
    if not header_value:
        return mint(sample_rate)
    trace_id, _, rest = header_value.partition(";")
    trace_id = trace_id.strip()
    if not trace_id:
        return mint(sample_rate)
    sampled = False
    for part in rest.split(";"):
        k, _, v = part.partition("=")
        if k.strip() == "s":
            sampled = v.strip() == "1"
    return TraceContext(trace_id, sampled)


def wallclock_at(perf_t: float) -> float:
    """Unix seconds of a past ``time.perf_counter()`` reading — how the
    hops stamp span STARTS without carrying a second clock around."""
    return time.time() - (time.perf_counter() - perf_t)


def emit_span(logger, ctx: Optional[TraceContext], hop: str,
              dur_s: float, wallclock: float, **fields) -> None:
    """One ``rspan`` record, iff the trace is sampled-or-forced and a
    logger exists. ``dur_s`` is the hop's own latency contribution,
    ``wallclock`` the hop's absolute start time."""
    if logger is None or ctx is None or not ctx.emit:
        return
    logger.log("rspan", trace_id=ctx.trace_id, hop=hop,
               dur_ms=round(max(dur_s, 0.0) * 1e3, 3),
               wallclock=round(wallclock, 6), **fields)
