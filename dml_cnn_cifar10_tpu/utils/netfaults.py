"""Deterministic network-fault state for the coordination transport.

The file-backed coordination store (``parallel/cluster.py``) cannot be
partitioned, delayed, or lossy — the filesystem either works or the
whole sim is dead. The network transport (``parallel/net.py``) can, and
this module is the single source of truth for *which* fault is armed
against *whom*, shared by every seam that must enforce it:

- the :class:`~dml_cnn_cifar10_tpu.parallel.net.CoordServer` consults
  :func:`server_action` per request (the control plane: beats, decision
  files, replica pushes);
- the fleet router consults :func:`is_isolated` before proxying to a
  replica (the data plane: an isolated worker must look connect-dead,
  not merely quiet).

Fault kinds (armed via ``--fault_spec`` entries handled in
``utils/faults.py``, which POSTs them to the server's ``/fault``
endpoint, or directly via :func:`arm` in in-process sims):

- ``net_partition`` — requests from the isolated process ids are HELD:
  the server never responds, exactly like a switch that ate the reply
  packets. The *client-side socket timeout* is the only thing that
  bounds the hang — which is precisely the hardening the
  ``no_net_timeout`` planted regression strips. Heals after
  ``PARTITION_HEAL_S``: requests arriving after the heal are answered,
  held ones never are.
- ``net_delay`` — every request from the isolated ids is answered
  ``DELAY_PER_REQUEST_S`` late for ``DELAY_WINDOW_S``.
- ``net_drop`` — every second request from the isolated ids is
  answered ``503 injected_drop`` for ``DROP_WINDOW_S`` (a deterministic
  "lossy link"; the client's bounded retries must absorb it).
- ``net_dup`` — writes from the isolated ids are applied twice for
  ``DUP_WINDOW_S`` (duplicate delivery; the store's atomic-replace
  semantics must make the dup invisible).

All state is process-local and deterministic: no randomness, no
clock-free scheduling — the chaos campaign's fault *steps* supply the
when, this module supplies the what.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Sequence

#: The network-fault vocabulary (mirrored into faults.FAULT_KINDS).
NET_FAULT_KINDS = ("net_partition", "net_delay", "net_drop", "net_dup")

#: Partition duration: long enough that the isolated side declares its
#: peers dead (peer_dead_after_s is 2.5s in the sims) and runs the full
#: classify → evict → rejoin arc, short enough that the heal lands well
#: inside the rejoin wait budget.
PARTITION_HEAL_S = 6.0

#: Per-request added latency and window of a ``net_delay``.
DELAY_PER_REQUEST_S = 0.25
DELAY_WINDOW_S = 2.0

#: Window of a ``net_drop`` (every 2nd request 503s inside it).
DROP_WINDOW_S = 2.0

#: Window of a ``net_dup`` (writes applied twice inside it).
DUP_WINDOW_S = 2.0

_DURATIONS = {"net_partition": PARTITION_HEAL_S,
              "net_delay": DELAY_WINDOW_S,
              "net_drop": DROP_WINDOW_S,
              "net_dup": DUP_WINDOW_S}

_lock = threading.Lock()
_faults: List[dict] = []


def arm(kind: str, isolate: Sequence[int],
        duration_s: Optional[float] = None,
        now: Optional[float] = None) -> dict:
    """Arm one fault against the ``isolate`` process ids; returns the
    armed record (kind, isolate, duration_s, until). Unknown kinds fail
    loudly — a typo'd drill that silently injects nothing would void
    the test it was written for."""
    if kind not in NET_FAULT_KINDS:
        raise ValueError(f"unknown net fault kind {kind!r} "
                         f"(want one of {NET_FAULT_KINDS})")
    now = time.time() if now is None else now
    duration = _DURATIONS[kind] if duration_s is None else float(duration_s)
    rec = {"kind": kind, "isolate": sorted(int(p) for p in isolate),
           "duration_s": duration, "until": now + duration,
           "armed_at": now, "n": 0}
    with _lock:
        _faults.append(rec)
    return rec


def clear() -> None:
    """Disarm everything (test/sim teardown)."""
    with _lock:
        _faults.clear()


def active(now: Optional[float] = None) -> List[dict]:
    """Currently-armed faults; expired ones are pruned as a side
    effect (held partition connections stay held — the hold loop keys
    on :func:`is_isolated` going false, i.e. on this prune)."""
    now = time.time() if now is None else now
    with _lock:
        _faults[:] = [f for f in _faults if f["until"] > now]
        return list(_faults)


def _match(kind: str, pid: Optional[int],
           now: Optional[float] = None) -> Optional[dict]:
    for f in active(now):
        if f["kind"] != kind:
            continue
        if pid is None or not f["isolate"] or pid in f["isolate"]:
            return f
    return None


def is_isolated(pid: Optional[int],
                now: Optional[float] = None) -> bool:
    """True while a ``net_partition`` covering ``pid`` is active — the
    data-plane check (the fleet router treats an isolated replica as
    connect-dead)."""
    return _match("net_partition", pid, now) is not None


def server_action(pid: Optional[int],
                  now: Optional[float] = None) -> tuple:
    """What the coordination server should do with one request from
    ``pid``: ``("hold",)`` never answer (partition), ``("drop",)``
    answer 503 (every 2nd request inside a drop window), ``("delay",
    secs)`` answer late, ``("dup",)`` apply writes twice, ``("ok",)``
    proceed. Checked once per request, in severity order."""
    if is_isolated(pid, now):
        return ("hold",)
    f = _match("net_drop", pid, now)
    if f is not None:
        with _lock:
            f["n"] += 1
            n = f["n"]
        if n % 2 == 1:
            return ("drop",)
    f = _match("net_delay", pid, now)
    if f is not None:
        return ("delay", DELAY_PER_REQUEST_S)
    if _match("net_dup", pid, now) is not None:
        return ("dup",)
    return ("ok",)


def snapshot() -> Dict[str, list]:
    """Read-only view for telemetry/debugging."""
    return {"active": [dict(f) for f in active()]}
