"""Deterministic bounded exponential backoff.

One tiny pure function shared by every retry loop in the framework —
the run supervisor (``train/supervisor.py``), the multi-host
coordinator bootstrap (``parallel/multihost.py``), and the dataset
downloader all retry with the same shape: ``base * 2^(attempt-1)``
capped at ``cap``. Keeping it pure (no jitter, no clock) makes retry
plans reproducible: the sequence of sleeps for a given budget is a
fixed list a test can pin exactly (``tests/test_cluster.py``).
"""

from __future__ import annotations

from typing import List


def delay_s(base_s: float, cap_s: float, attempt: int) -> float:
    """Backoff before retry ``attempt`` (1-based): ``base * 2^(a-1)``,
    capped at ``cap_s``. ``attempt < 1`` is a contract violation."""
    if attempt < 1:
        raise ValueError(f"attempt must be >= 1, got {attempt}")
    return min(base_s * (2 ** (attempt - 1)), cap_s)


def schedule(base_s: float, cap_s: float, retries: int) -> List[float]:
    """The full deterministic sleep plan for a ``retries``-attempt
    budget — what a run WILL wait, computable before it waits it."""
    return [delay_s(base_s, cap_s, a) for a in range(1, retries + 1)]
