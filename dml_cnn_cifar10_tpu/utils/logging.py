"""Structured metrics logging.

The reference's observability is ``print`` at a 200/500-step cadence plus two
Python lists that are appended and then dropped on the floor
(``cifar10cnn.py:226-241``). This logger keeps the exact console format for
parity and *also* persists every record as JSONL with wall-clock and
throughput, so runs are analyzable after the fact.

Live-metrics seam: every record written here also feeds the
process-local metrics registry (``utils/metrics_registry.py`` — the
``GET /metrics`` export surfaces render it) and any attached observers
(the streaming alert engine, ``utils/alerts.py``). Both are pure host
work on numbers the record already carries — no new instrumentation,
no device fetches — and both are fail-open: a broken observer must
never take down the training loop that logs through it.
"""

from __future__ import annotations

import json
import math
import os
import threading
import time
from typing import Optional

from dml_cnn_cifar10_tpu.utils import metrics_registry


def _finite(v):
    """NaN/Inf → None so every line stays strict JSON (faithful runs with
    the reference's LR-0.1-on-raw-pixels hyperparameters do NaN)."""
    if isinstance(v, float) and not math.isfinite(v):
        return None
    return v


class MetricsLogger:
    def __init__(self, jsonl_path: Optional[str] = None, task_index: int = 0,
                 tensorboard_dir: Optional[str] = None):
        self.task_index = task_index
        # Writers span threads (serve metrics flusher, fleet swap
        # watcher, router handler threads, cluster watchdog); a line
        # must never interleave with another mid-write.
        self._lock = threading.Lock()
        self._file = None
        # Observers see (kind, fields) for every record, called OUTSIDE
        # the write lock: an observer that re-enters log() (the alert
        # engine emitting an `alert` record) must not deadlock. The
        # registry feed is unconditional — a process that never exports
        # pays one dict-dispatch per record.
        self._observers = []
        if jsonl_path:
            os.makedirs(os.path.dirname(jsonl_path) or ".", exist_ok=True)
            self._file = open(jsonl_path, "a", buffering=1)
        self._t0 = time.time()
        # TensorBoard event files — the MonitoredTrainingSession wrote
        # summaries to --log_dir by default (cifar10cnn.py:222); opt-in
        # here because the writer import is heavyweight. Only scalar
        # fields accompanied by a ``step`` are recorded.
        self._tb = None
        if tensorboard_dir:
            # tensorboardX over torch.utils.tensorboard: identical
            # add_scalar/close API without dragging the full torch
            # runtime into a JAX process.
            from tensorboardX import SummaryWriter
            self._tb = SummaryWriter(log_dir=tensorboard_dir)

    def add_observer(self, fn) -> None:
        """Attach ``fn(kind, fields)`` to every subsequent record.
        Idempotent by identity so supervisor restart attempts that
        re-attach the same engine adapter don't double-feed it."""
        if fn not in self._observers:
            self._observers.append(fn)

    def log(self, kind: str, **fields) -> None:
        if self._file is not None:
            rec = {"kind": kind, "t": round(time.time() - self._t0, 4),
                   "task": self.task_index,
                   **{k: _finite(v) for k, v in fields.items()}}
            line = json.dumps(rec, allow_nan=False) + "\n"
            with self._lock:
                if self._file is not None:
                    self._file.write(line)
        if self._tb is not None and "step" in fields:
            step = fields["step"]
            for k, v in fields.items():
                # bool is an int subclass: without the exclusion, flag
                # fields (e.g. hbm available) land as 0/1 scalar charts.
                if k != "step" and isinstance(v, (int, float)) \
                        and not isinstance(v, bool) \
                        and _finite(v) is not None:
                    self._tb.add_scalar(f"{kind}/{k}", v, step)
        # Live-metrics feeds, after the sinks so a slow/broken observer
        # can't lose the persisted record. observe_record is fail-open
        # internally; attached observers get the same protection here.
        metrics_registry.observe_record(kind, fields)
        for fn in self._observers:
            try:
                fn(kind, fields)
            except Exception:
                pass

    def train_print(self, global_step: int, local_step: int,
                    train_accuracy: float) -> None:
        # Byte-for-byte the reference's training line (cifar10cnn.py:234-235).
        print("global_step %s, task:%d_step %d, training accuracy %g"
              % (global_step, self.task_index, local_step, train_accuracy))

    def eval_print(self, test_accuracy: float) -> None:
        # Reference's eval line (cifar10cnn.py:240-241).
        print(" --- Test Accuracy = {:.2f}%.".format(100.0 * test_accuracy))

    def flush(self) -> None:
        """Force both sinks to disk — tensorboardX's event writer is a
        daemon thread (flush_secs=120) that dies unflushed at interpreter
        exit, so the driver flushes at every fit() end."""
        with self._lock:
            if self._file is not None:
                self._file.flush()
        if self._tb is not None:
            self._tb.flush()

    def close(self) -> None:
        with self._lock:
            if self._file is not None:
                self._file.close()
                self._file = None
        if self._tb is not None:
            self._tb.close()
            self._tb = None
