"""Backend-platform selection helpers.

This box's sitecustomize pins ``JAX_PLATFORMS`` to the TPU plugin and
overrides the env var, so forcing the CPU backend requires BOTH the env var
(for code that reads it before jax loads) and ``jax.config.update`` after
import. Used by the test suite, the multichip dry run, and multi-process
worker scripts; importing ``jax`` (without touching devices) is safe here —
the backend only initializes on first use.
"""

from __future__ import annotations

import os
import re
from typing import Optional

_COUNT_RE = re.compile(r"--xla_force_host_platform_device_count=(\d+)")


def force_cpu(virtual_devices: Optional[int] = None) -> None:
    """Pin the CPU backend, optionally with N virtual devices.

    Must be called before anything initializes the XLA backend
    (``jax.devices()``, any computation, ``jax.distributed.initialize``).
    A pre-existing device-count flag with a DIFFERENT value is an error —
    silently keeping it would strand callers on the wrong mesh size.
    """
    if virtual_devices:
        flags = os.environ.get("XLA_FLAGS", "")
        m = _COUNT_RE.search(flags)
        if m is None:
            os.environ["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count="
                f"{virtual_devices}").strip()
        elif int(m.group(1)) != virtual_devices:
            raise RuntimeError(
                f"XLA_FLAGS already pins "
                f"{m.group(1)} host-platform devices; caller asked for "
                f"{virtual_devices}. Unset XLA_FLAGS or reconcile.")
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")
