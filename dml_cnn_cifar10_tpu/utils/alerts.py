"""Streaming SLO/alert engine over the metrics stream.

The JSONL stream records everything; nothing WATCHES it while the run
is live — an operator learns about a goodput collapse or a shed storm
from a post-hoc report. This module closes that gap with a small
declarative rule engine evaluated at the seams that already see every
number: ``MetricsLogger`` feeds each record it writes into
:meth:`AlertEngine.observe` (``utils/logging.py`` observer hook), and
the metrics-boundary flush / serve flusher / fleet control loop call
:meth:`AlertEngine.evaluate` for the time-based rules. No polling
thread, no extra device fetches, no new instrumentation.

Three rule shapes cover the SLO vocabulary:

- **threshold** — ``kind.field OP value`` breached on ``window``
  CONSECUTIVE records (one flaky boundary is noise; N in a row is a
  condition). Derived fields close the gap between raw records and
  operator questions: ``train.drain_frac`` (drain-wait share of the
  estimated device window — near 0 means the run flipped host-bound),
  ``serve.shed_frac``, ``hbm.used_frac``.
- **rate** — ≥ N matching records inside a trailing window of steps
  (deterministic under any wall-clock, the simulation-friendly unit)
  or seconds; optional field match (``fault=nonfinite``).
- **absence** — no record of a kind for ``window`` seconds (armed only
  after the first one: a run that never heartbeats is not stale, it is
  simply not clustered).

Firing emits an ``alert`` JSONL record (rule, severity, window, value,
id — a monotonic ``rule#N`` stamped on the firing, its resolution, and
any remediation it triggers) and recovery a paired ``alert_resolved``
carrying the same id — rate-limited per rule
(``min_interval_s``) so a flapping signal cannot flood the stream: a
suppressed re-fire also suppresses its resolution, keeping the emitted
records strictly paired. Active state is exported live as the
``dml_alert_active`` gauge (via the registry's record observer) and
consumed by the fleet autoscaler as a scale-up input signal.

Built-in defaults (:func:`built_in_rules`) cover the failure modes the
repo's other layers already classify — goodput train-fraction
collapse, drain-wait flipping host-bound, nonfinite/recovery bursts,
heartbeat staleness, shed > 1%, p99 vs ``--serve_slo_ms``, HBM
headroom — and ``--alert_rules`` adds custom rules in a one-line
grammar (:func:`parse_alert_rules`; ``docs/OBSERVABILITY.md``).
"""

from __future__ import annotations

import collections
import dataclasses
import re
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

_OPS = {
    ">": lambda a, b: a > b,
    "<": lambda a, b: a < b,
    ">=": lambda a, b: a >= b,
    "<=": lambda a, b: a <= b,
}


@dataclasses.dataclass
class AlertRule:
    """One declarative rule. ``window_unit`` gives ``window`` meaning:
    ``count`` = consecutive records (threshold), ``steps`` = trailing
    global-step window (rate), ``seconds`` = trailing wall window
    (rate/absence)."""

    name: str
    rule_type: str                     # threshold | rate | absence
    kind: str
    op: str = ">"
    value: float = 0.0
    field: Optional[str] = None        # threshold only
    window: float = 1.0
    window_unit: str = "count"         # count | steps | seconds
    severity: str = "warn"
    match: Dict[str, str] = dataclasses.field(default_factory=dict)

    def window_str(self) -> str:
        w = int(self.window) if float(self.window).is_integer() \
            else self.window
        unit = {"count": "consecutive", "steps": "steps",
                "seconds": "s"}[self.window_unit]
        return f"{w} {unit}" if unit != "s" else f"{w}s"


def built_in_rules(slo_ms: Optional[float] = None,
                   heartbeat_stale_s: float = 15.0) -> List[AlertRule]:
    """The default rule set — every signal is already in the stream.

    The ``serve_p99_slo`` burn rule exists only when an SLO is
    configured (``--serve_slo_ms``); the others are universal and
    silent on healthy runs by construction.
    """
    rules = [
        # Productive-train fraction collapsed: most of the wall-clock
        # is going to compile/data/eval/checkpoint/sync overheads.
        # Two consecutive boundaries: the first boundary after a cold
        # start legitimately reads compile-heavy.
        AlertRule("goodput_train_collapse", "threshold", "goodput",
                  field="train_frac", op="<", value=0.5, window=2,
                  window_unit="count", severity="warn"),
        # drain_frac ~ drain_wait / (device_step * steps): near zero
        # means the device idles on the host (the run flipped
        # host-bound) — the step itself is no longer the bottleneck.
        AlertRule("host_bound_drain", "threshold", "train",
                  field="drain_frac", op="<", value=0.10, window=3,
                  window_unit="count", severity="warn"),
        # A non-finite loss inside the trailing step window. Resolves
        # once training has progressed a clean window past it — the
        # paired alert/alert_resolved the acceptance smoke pins.
        AlertRule("nonfinite_burst", "rate", "fault", op=">=",
                  value=1, window=50, window_unit="steps",
                  severity="page", match={"fault": "nonfinite"}),
        # Recovery churn: the supervisor absorbing restarts faster
        # than the budget was sized for.
        AlertRule("recovery_burst", "rate", "recovery", op=">=",
                  value=3, window=200, window_unit="steps",
                  severity="page"),
        # The cluster layer stopped heartbeating (armed only after
        # the first beat record — non-cluster runs never arm it).
        AlertRule("heartbeat_stale", "absence", "heartbeat",
                  window=heartbeat_stale_s, window_unit="seconds",
                  severity="page"),
        # Admission control actively rejecting > 1% of traffic.
        AlertRule("serve_shed", "threshold", "serve",
                  field="shed_frac", op=">", value=0.01, window=1,
                  window_unit="count", severity="warn"),
        # The router-side twin (fleet window records): shed fraction
        # across the whole fleet — what the controller's own stream
        # sees, and a scale-up input to the autoscaler.
        AlertRule("fleet_shed", "threshold", "fleet",
                  field="shed_frac", op=">", value=0.01, window=1,
                  window_unit="count", severity="warn"),
        # Less than 8% HBM headroom: the next allocation spike OOMs.
        AlertRule("hbm_headroom", "threshold", "hbm",
                  field="used_frac", op=">", value=0.92, window=1,
                  window_unit="count", severity="warn"),
    ]
    if slo_ms is not None:
        rules.append(
            AlertRule("serve_p99_slo", "threshold", "serve",
                      field="p99_ms", op=">", value=float(slo_ms),
                      window=2, window_unit="count", severity="page"))
    return rules


# --- the --alert_rules grammar --------------------------------------------

_THRESHOLD_RE = re.compile(
    r"^(?P<kind>\w+)\.(?P<field>\w+)\s*(?P<op>>=|<=|>|<)\s*"
    r"(?P<value>-?[\d.]+)$")
_RATE_RE = re.compile(
    r"^rate\((?P<kind>\w+)(?:\.(?P<mfield>\w+)=(?P<mvalue>\w+))?\)\s*"
    r"(?P<op>>=|>)\s*(?P<value>[\d.]+)$")
_ABSENT_RE = re.compile(r"^absent\((?P<kind>\w+)\)$")


def parse_alert_rules(spec: Optional[str]) -> List[AlertRule]:
    """Parse the ``--alert_rules`` grammar into rules.

    ``;``-separated entries, each ``name=expr[@window][!severity]``:

    - ``lossy=train.loss>10@3`` — threshold, breached on 3 consecutive
      records (default 1),
    - ``churn=rate(recovery)>=2@300`` — ≥ 2 records in the trailing
      300 STEPS (``@60s`` = 60 seconds; default 100 steps),
    - ``churn2=rate(fault.fault=nonfinite)>=1@50`` — with field match,
    - ``beatless=absent(heartbeat)@20s`` — no record for 20 s
      (seconds required; default 30 s),
    - ``...!page`` — severity suffix (default ``warn``).

    Raises ``ValueError`` with the offending entry on any mismatch — a
    typo'd rule must fail the run at flag-parse time, not silently
    never fire.
    """
    rules: List[AlertRule] = []
    if not spec:
        return rules
    for raw in spec.split(";"):
        entry = raw.strip()
        if not entry:
            continue
        name, eq, rest = entry.partition("=")
        name = name.strip()
        if not eq or not name or not re.fullmatch(r"\w+", name):
            raise ValueError(f"bad alert rule {entry!r}: want "
                             f"name=expr[@window][!severity]")
        severity = "warn"
        if "!" in rest:
            rest, _, severity = rest.rpartition("!")
            severity = severity.strip()
            if not severity:
                raise ValueError(f"bad alert rule {entry!r}: empty "
                                 f"severity after '!'")
        window_s: Optional[str] = None
        if "@" in rest:
            rest, _, window_s = rest.rpartition("@")
            window_s = window_s.strip()
        expr = rest.strip()

        def parse_window(default: float, default_unit: str,
                         require_seconds: bool = False
                         ) -> Tuple[float, str]:
            if window_s is None:
                return default, default_unit
            if window_s.endswith("s") and window_s[:-1]:
                return float(window_s[:-1]), "seconds"
            if require_seconds:
                raise ValueError(
                    f"bad alert rule {entry!r}: absence windows are "
                    f"wall-clock — write @{window_s}s")
            return float(window_s), default_unit

        m = _THRESHOLD_RE.match(expr)
        if m:
            window, unit = parse_window(1, "count")
            if unit == "seconds":
                raise ValueError(
                    f"bad alert rule {entry!r}: threshold windows "
                    f"count consecutive records — drop the 's'")
            rules.append(AlertRule(
                name, "threshold", m.group("kind"),
                field=m.group("field"), op=m.group("op"),
                value=float(m.group("value")), window=window,
                window_unit="count", severity=severity))
            continue
        m = _RATE_RE.match(expr)
        if m:
            window, unit = parse_window(100, "steps")
            match = {}
            if m.group("mfield"):
                match[m.group("mfield")] = m.group("mvalue")
            rules.append(AlertRule(
                name, "rate", m.group("kind"), op=m.group("op"),
                value=float(m.group("value")), window=window,
                window_unit=unit, severity=severity, match=match))
            continue
        m = _ABSENT_RE.match(expr)
        if m:
            window, unit = parse_window(30.0, "seconds",
                                        require_seconds=True)
            rules.append(AlertRule(
                name, "absence", m.group("kind"), window=window,
                window_unit="seconds", severity=severity))
            continue
        raise ValueError(
            f"bad alert rule {entry!r}: expr must be kind.field OP "
            f"value, rate(kind[.field=value]) >= N, or absent(kind)")
    names = [r.name for r in rules]
    dupes = {n for n in names if names.count(n) > 1}
    if dupes:
        raise ValueError(f"duplicate alert rule name(s): "
                         f"{sorted(dupes)}")
    return rules


# --- derived fields --------------------------------------------------------

def _derive(kind: str, fields: dict, state: dict) -> dict:
    """Compute the operator-level fields rules key on from raw record
    fields (non-destructive: returns an augmented copy when needed)."""
    if kind == "train":
        dev = fields.get("device_step_ms")
        drain = fields.get("drain_wait_ms")
        step = fields.get("step")
        prev = state.get("prev_train_step")
        if isinstance(step, (int, float)):
            state["prev_train_step"] = step
        if (isinstance(dev, (int, float)) and dev > 0
                and isinstance(drain, (int, float))
                and isinstance(step, (int, float))
                and isinstance(prev, (int, float)) and step > prev):
            out = dict(fields)
            out["drain_frac"] = min(drain / (dev * (step - prev)), 1.0)
            return out
    elif kind == "serve":
        req = fields.get("requests")
        if isinstance(req, (int, float)) and req > 0:
            out = dict(fields)
            out["shed_frac"] = ((fields.get("shed_queue") or 0)
                                + (fields.get("shed_deadline") or 0)) \
                / req
            return out
    elif kind == "fleet":
        total = (fields.get("routed") or 0) + (fields.get("shed") or 0)
        if total > 0:
            out = dict(fields)
            out["shed_frac"] = (fields.get("shed") or 0) / total
            return out
    elif kind == "hbm":
        limit = fields.get("bytes_limit")
        if fields.get("available") and isinstance(limit, (int, float)) \
                and limit > 0:
            out = dict(fields)
            out["used_frac"] = (fields.get("bytes_in_use") or 0) / limit
            return out
    return fields


class _RuleState:
    __slots__ = ("active", "emitted", "consecutive", "events",
                 "last_seen", "last_emit_t", "value", "since_t",
                 "alert_id")

    def __init__(self):
        self.active = False
        self.emitted = False
        self.consecutive = 0
        self.events: collections.deque = collections.deque()
        self.last_seen: Optional[float] = None   # absence arm time
        self.last_emit_t: Optional[float] = None
        self.value: Optional[float] = None
        self.since_t: Optional[float] = None
        self.alert_id: Optional[str] = None      # last EMITTED firing


class AlertEngine:
    """Evaluate a rule set against the record stream; emit paired,
    rate-limited ``alert`` / ``alert_resolved`` records.

    ``observe`` is called per record (via the ``MetricsLogger``
    observer); ``evaluate`` is called at the metrics-boundary flush /
    serve flusher tick / fleet control tick for the time-based rules.
    Both take an ``emit(kind, **fields)`` callable — normally the
    feeding logger's ``log`` — and an injectable ``now`` for
    deterministic tests. Thread-safe: state mutates under one lock,
    emissions fire after it is released (``emit`` re-enters the logger,
    whose observers re-enter ``observe`` — which ignores alert kinds)."""

    def __init__(self, rules: List[AlertRule],
                 min_interval_s: float = 30.0):
        self.rules = list(rules)
        names = [r.name for r in self.rules]
        dupes = {n for n in names if names.count(n) > 1}
        if dupes:
            raise ValueError(
                f"alert rule name(s) {sorted(dupes)} defined twice "
                f"(a custom --alert_rules entry shadowing a built-in?)")
        self.min_interval_s = min_interval_s
        self._lock = threading.Lock()
        self._states = {r.name: _RuleState() for r in self.rules}
        self._derive_state: dict = {}
        self._max_step: Optional[float] = None
        # Monotonic id sequence: every EMITTED firing gets a unique
        # ``rule#N`` id, stamped on the alert record, its paired
        # resolution, and everything downstream (remediation records,
        # postmortem lineage). Deterministic under replay.
        self._emit_seq = 0
        # Alert→action trigger hooks: each fires once per EMITTED alert
        # firing (never for suppressed re-fires — they add nothing to
        # the pending list — and never for resolutions). The runtime's
        # alert→FineTuneJob control loop and the autopilot policy
        # engine ride this seam. Stored as (fn, wants_meta) — a 3-arg
        # hook also receives {"id", "step", "severity"}.
        self._triggers: List[tuple] = []
        # observer() adapters keyed by the logger they wrap, so a shared
        # logger re-attaching the engine gets the SAME callable back and
        # MetricsLogger.add_observer's identity check keeps it single.
        self._observer_cache: dict = {}

    # -- feeding ---------------------------------------------------------

    def observe(self, kind: str, fields: dict,
                emit: Optional[Callable] = None,
                now: Optional[float] = None) -> None:
        if kind in ("alert", "alert_resolved"):
            return
        now = time.time() if now is None else now
        pending: List[tuple] = []
        with self._lock:
            fields = _derive(kind, fields, self._derive_state)
            step = fields.get("step")
            if isinstance(step, (int, float)):
                self._max_step = step if self._max_step is None \
                    else max(self._max_step, step)
            for rule in self.rules:
                if rule.kind != kind:
                    continue
                st = self._states[rule.name]
                if rule.rule_type == "absence":
                    st.last_seen = now
                    if st.active:
                        self._resolve(rule, st, 0.0, now, pending)
                elif rule.rule_type == "threshold":
                    v = fields.get(rule.field)
                    if not isinstance(v, (int, float)):
                        continue
                    if _OPS[rule.op](v, rule.value):
                        st.consecutive += 1
                        if st.consecutive >= rule.window \
                                and not st.active:
                            self._fire(rule, st, float(v), now, pending)
                        elif st.active:
                            st.value = float(v)
                    else:
                        st.consecutive = 0
                        if st.active:
                            self._resolve(rule, st, float(v), now,
                                          pending)
                elif rule.rule_type == "rate":
                    if any(str(fields.get(k)) != str(v)
                           for k, v in rule.match.items()):
                        continue
                    mark = now if rule.window_unit == "seconds" \
                        else (step if isinstance(step, (int, float))
                              else self._max_step)
                    if mark is None:
                        continue
                    st.events.append(mark)
                    self._prune_rate(rule, st, now)
                    if len(st.events) >= rule.value and not st.active:
                        self._fire(rule, st, float(len(st.events)),
                                   now, pending)
                    elif st.active:
                        st.value = float(len(st.events))
        self._emit_all(pending, emit)

    def evaluate(self, emit: Optional[Callable] = None,
                 now: Optional[float] = None,
                 step: Optional[float] = None) -> None:
        """Time/step-window pass: absence firings, rate resolutions.
        Call at every boundary flush / control-loop tick."""
        now = time.time() if now is None else now
        pending: List[tuple] = []
        with self._lock:
            if isinstance(step, (int, float)):
                self._max_step = step if self._max_step is None \
                    else max(self._max_step, step)
            for rule in self.rules:
                st = self._states[rule.name]
                if rule.rule_type == "absence":
                    if st.last_seen is None:
                        continue   # never armed
                    age = now - st.last_seen
                    if age > rule.window and not st.active:
                        self._fire(rule, st, round(age, 3), now,
                                   pending)
                    elif st.active:
                        st.value = round(age, 3)
                elif rule.rule_type == "rate":
                    self._prune_rate(rule, st, now)
                    if st.active and len(st.events) < rule.value:
                        self._resolve(rule, st,
                                      float(len(st.events)), now,
                                      pending)
        self._emit_all(pending, emit)

    # -- state transitions (lock held) -----------------------------------

    def _prune_rate(self, rule: AlertRule, st: _RuleState,
                    now: float) -> None:
        horizon = (now - rule.window
                   if rule.window_unit == "seconds"
                   else (self._max_step - rule.window
                         if self._max_step is not None else None))
        if horizon is None:
            return
        while st.events and st.events[0] <= horizon:
            st.events.popleft()

    def _fire(self, rule, st, value, now, pending) -> None:
        st.active = True
        st.value = value
        st.since_t = now
        if st.last_emit_t is not None \
                and now - st.last_emit_t < self.min_interval_s:
            # Flap suppression: a re-fire inside the rate-limit window
            # keeps internal state but emits nothing — and marks the
            # cycle unemitted so its resolution stays silent too
            # (emitted records are strictly alert/alert_resolved pairs).
            st.emitted = False
            return
        st.emitted = True
        st.last_emit_t = now
        self._emit_seq += 1
        st.alert_id = f"{rule.name}#{self._emit_seq}"
        pending.append(("alert", rule, value, st.alert_id,
                        self._max_step))

    def _resolve(self, rule, st, value, now, pending) -> None:
        st.active = False
        st.consecutive = 0
        if st.emitted:
            st.emitted = False
            pending.append(("alert_resolved", rule, value, st.alert_id,
                            self._max_step))

    def _emit_all(self, pending, emit) -> None:
        for record_kind, rule, value, alert_id, step in pending:
            if emit is not None:
                emit(record_kind, rule=rule.name, severity=rule.severity,
                     window=rule.window_str(), value=value, id=alert_id)
            if record_kind != "alert":
                continue  # resolutions never trigger actions
            meta = {"id": alert_id, "step": step,
                    "severity": rule.severity}
            for fn, wants_meta in list(self._triggers):
                try:
                    if wants_meta:
                        fn(rule, value, meta)
                    else:
                        fn(rule, value)
                except Exception as e:  # fail-open like logger observers
                    print(f"[alerts] trigger hook failed for "
                          f"{rule.name!r}: {e!r}", flush=True)

    def add_trigger(self, fn: Callable) -> None:
        """Attach ``fn(rule, value)`` — or ``fn(rule, value, meta)``,
        detected by signature, where ``meta`` carries the firing's
        ``id``/``step``/``severity`` — called once per EMITTED ``alert``
        firing (outside the engine lock, after the record is emitted).
        Suppressed re-fires inside the rate-limit window and
        ``alert_resolved`` transitions never call it. Idempotent by
        identity; exceptions are swallowed (an action hook must never
        take down the metrics path)."""
        import inspect
        if any(fn is f for f, _ in self._triggers):
            return
        try:
            params = inspect.signature(fn).parameters.values()
            npos = sum(p.kind in (p.POSITIONAL_ONLY,
                                  p.POSITIONAL_OR_KEYWORD)
                       for p in params)
            wants_meta = npos >= 3 or any(
                p.kind == p.VAR_POSITIONAL for p in params)
        except (TypeError, ValueError):
            wants_meta = False
        self._triggers.append((fn, wants_meta))

    def add_rules(self, rules: List[AlertRule]) -> None:
        """Register additional rules on a live engine (the autopilot
        injects pattern rules its policies need — e.g. a peer-churn
        rate rule with no built-in). Name collisions raise, same as the
        constructor."""
        with self._lock:
            existing = {r.name for r in self.rules}
            for rule in rules:
                if rule.name in existing:
                    raise ValueError(
                        f"alert rule {rule.name!r} already defined")
                existing.add(rule.name)
                self.rules.append(rule)
                self._states[rule.name] = _RuleState()

    # -- consumers --------------------------------------------------------

    def active(self) -> List[dict]:
        """Currently-firing rules (the autoscaler input and the live
        monitor's "active alerts" panel)."""
        with self._lock:
            return [{"rule": r.name, "severity": r.severity,
                     "value": self._states[r.name].value,
                     "since_t": self._states[r.name].since_t,
                     "id": self._states[r.name].alert_id}
                    for r in self.rules if self._states[r.name].active]

    def active_names(self) -> List[str]:
        return [a["rule"] for a in self.active()]

    def observer(self, logger) -> Callable:
        """The ``MetricsLogger.add_observer`` adapter: every record the
        logger writes feeds ``observe``, emissions go back out through
        the same logger. Cached per logger — when the runtime and a
        Trainer share one logger, both attach the SAME callable and the
        logger's identity check keeps the engine fed exactly once."""
        fn = self._observer_cache.get(id(logger))
        if fn is None:
            fn = lambda kind, fields: self.observe(kind, fields,
                                                   emit=logger.log)
            self._observer_cache[id(logger)] = fn
        return fn

    @classmethod
    def from_config(cls, cfg, extra_rules: Optional[str] = None
                    ) -> Optional["AlertEngine"]:
        """Engine for a :class:`~dml_cnn_cifar10_tpu.config.TrainConfig`
        — built-ins (SLO-aware) plus the ``--alert_rules`` grammar.
        None when there is nowhere to emit or export (no JSONL stream,
        no stats port, no custom rules): the disarmed path costs
        nothing."""
        spec = extra_rules if extra_rules is not None \
            else getattr(cfg, "alert_rules", None)
        if not (cfg.metrics_jsonl or getattr(cfg, "stats_port", 0)
                or spec):
            return None
        rules = built_in_rules(slo_ms=cfg.serve.slo_ms)
        rules += parse_alert_rules(spec)
        return cls(rules)
