"""Deterministic fault injection for exercising recovery paths.

The reference's fault story is untestable by construction: the only way
to see MonitoredTrainingSession recover is to kill a real worker
mid-run (SURVEY §5). Here every failure mode the resilience layer
handles can be injected at an exact global step, on CPU, in tier-1 —
``--fault_spec "nan@120,ckpt_corrupt@200,sigterm@300,data_stall@400"``
fires each fault ONCE at the first host-loop seam where the global step
reaches its trigger. The injector's fired-state survives supervisor
restarts (``train/supervisor.py`` builds one injector and threads it
through every attempt), so a recovered run does not re-injure itself
replaying the same steps.

Fault kinds:

- ``nan`` — multiply one parameter leaf by NaN so the *real* forward/
  backward produces a non-finite loss (the detection path is the
  genuine ``check_numerics`` boundary fetch, not a mock).
- ``ckpt_corrupt`` — truncate the newest committed checkpoint on disk
  (a file codec loses its tail; a directory codec loses one member
  file), leaving the checksum sidecar stale — exactly what a crashed
  copy or bit rot looks like to ``restore_checkpoint``. Defers until a
  checkpoint exists.
- ``sigterm`` — deliver SIGTERM to this process, exercising
  ``PreemptionGuard``'s finish-step/checkpoint/exit path.
- ``data_stall`` — raise :class:`DataStallError` at the host-loop seam,
  the stand-in for a wedged input pipeline; the supervisor classifies
  it as a recoverable data failure.

Cluster-resilience kinds (need a :class:`~parallel.cluster.ClusterMonitor`
— i.e. ``--cluster_dir``; docs/RESILIENCE.md multi-host section):

- ``heartbeat_stall`` — stop publishing heartbeats while the process
  keeps training: from outside, indistinguishable from a dead host.
  Peers declare this process lost and restart without it; the eviction
  check fences it cleanly.
- ``host_lost`` — ``os._exit`` with no cleanup, no checkpoint, no
  flushed logs: the crashed/preempted-host case. Peers see the
  heartbeats go stale.
- ``collective_hang`` — block the main thread at the dispatch seam
  while the background publisher keeps beating: the wedged-collective
  case. Peers see a fresh-but-behind straggler; this process's own
  watchdog eventually aborts it (``collective_timeout_s``), turning
  the silent hang into a classified host loss.
- ``host_return`` — the deterministic stand-in for "a host came back
  at step N": block the (surviving) process at the seam until a
  returning host's ``rejoin``-phase beat appears in the store, so the
  2→1→2 elastic scale-UP drill expands at a known step instead of
  racing the returning process's startup. The expand itself then runs
  through the real chief-side rejoin scan (``--elastic_expand``). A
  drill where nobody ever returns fails loudly after a bounded wait.

Every injection logs a ``fault`` JSONL record (``injected: true``) so
recovery tooling can pair injections with the ``recovery`` records they
provoke (``docs/RESILIENCE.md``).
"""

from __future__ import annotations

import dataclasses
import os
import signal
import time
from typing import List, Optional

FAULT_KINDS = ("nan", "ckpt_corrupt", "sigterm", "data_stall",
               "heartbeat_stall", "host_lost", "collective_hang",
               "host_return")

#: Bounded wait for a ``host_return`` drill's returning host: long
#: enough for a cold process start (imports + restore + compile), short
#: enough that a drill where nobody returns fails the run, not the CI
#: budget.
HOST_RETURN_TIMEOUT_S = 300.0

#: Exit code of a ``host_lost`` injection — an abrupt, cleanup-free
#: death (distinct from the watchdog's own abort code so tests can tell
#: the injected corpse from a watchdog-fenced process).
EXIT_HOST_LOST = 77


class InjectedFault(RuntimeError):
    """Base class for failures raised (not merely caused) by injection."""


class DataStallError(InjectedFault):
    """Injected stand-in for a wedged/failed input pipeline."""


@dataclasses.dataclass
class FaultEvent:
    kind: str
    step: int
    fired: bool = False


def parse_fault_spec(spec: str) -> List[FaultEvent]:
    """``"kind@step,kind@step,..."`` → ordered fault events.

    Steps are global training steps; duplicate kinds are allowed (e.g.
    ``nan@100,nan@200`` re-poisons after a recovery). Unknown kinds and
    malformed entries fail loudly at parse time — a typo'd fault plan
    that silently injects nothing would void the test it was written
    for.
    """
    events = []
    for entry in spec.split(","):
        entry = entry.strip()
        if not entry:
            continue
        kind, sep, step_s = entry.partition("@")
        kind = kind.strip()
        if not sep or kind not in FAULT_KINDS:
            raise ValueError(
                f"bad fault spec entry {entry!r}: want kind@step with "
                f"kind in {FAULT_KINDS}")
        try:
            step = int(step_s)
        except ValueError:
            raise ValueError(
                f"bad fault spec entry {entry!r}: step {step_s!r} is "
                f"not an integer") from None
        if step < 0:
            raise ValueError(f"bad fault spec entry {entry!r}: "
                             f"negative step")
        events.append(FaultEvent(kind, step))
    return sorted(events, key=lambda e: (e.step, e.kind))


def poison_state(state):
    """Multiply the first parameter leaf by NaN, preserving structure,
    dtype, and sharding — the subsequent (real) train step then yields a
    non-finite loss through the genuine compute path."""
    import jax
    import jax.numpy as jnp

    leaves, treedef = jax.tree.flatten(state.params)
    if not leaves:
        return state
    leaves[0] = leaves[0] * jnp.asarray(float("nan"), leaves[0].dtype)
    return state._replace(params=jax.tree.unflatten(treedef, leaves))


def corrupt_latest_checkpoint(log_dir: str) -> Optional[str]:
    """Truncate the newest committed checkpoint (file codecs) or one
    member file (directory codecs). Returns the corrupted path, or None
    when no checkpoint exists yet."""
    from dml_cnn_cifar10_tpu.ckpt import checkpoint as ckpt_lib

    path = ckpt_lib.latest_checkpoint(log_dir)
    if path is None:
        return None
    victim = path
    if os.path.isdir(path):
        members = sorted(
            os.path.join(path, n) for n in os.listdir(path)
            if os.path.isfile(os.path.join(path, n))
            and n != "MANIFEST.json")
        # Prefer a DATA member over sidecar/index files (the sharded
        # dir now carries per-shard .sha256 + files.json companions):
        # truncating real payload exercises the integrity walk, not
        # just the metadata parse.
        data = [m for m in members if m.endswith(".msgpack")]
        members = data or members
        if not members:  # nothing but the manifest — truncate that
            members = [os.path.join(path, "MANIFEST.json")]
        victim = members[0]
    size = os.path.getsize(victim)
    with open(victim, "r+b") as f:
        f.truncate(size // 2)
    return path


class FaultInjector:
    """One-shot, step-keyed fault firing at the training loop's host
    seam (``Trainer.fit`` calls :meth:`step_hook` once per dispatch).
    Owned by the supervisor across restarts so fired events stay
    fired."""

    def __init__(self, events: List[FaultEvent]):
        self.events = events

    @classmethod
    def from_spec(cls, spec: Optional[str]) -> Optional["FaultInjector"]:
        if not spec:
            return None
        return cls(parse_fault_spec(spec))

    def pending(self) -> List[FaultEvent]:
        return [e for e in self.events if not e.fired]

    def _log(self, logger, step: int, kind: str, **extra) -> None:
        if logger is not None:
            logger.log("fault", step=step, fault=kind, injected=True,
                       **extra)

    def step_hook(self, step: int, state, log_dir: str, logger=None,
                  cluster=None):
        """Fire every due, unfired event; returns the (possibly
        poisoned) state. ``ckpt_corrupt`` stays pending until a
        checkpoint exists to corrupt. ``data_stall`` raises after
        marking itself fired so a supervised restart does not re-raise
        it. The cluster kinds take the :class:`ClusterMonitor` the
        Trainer threads through (``cluster``) and fail loudly without
        one — a cluster drill that silently no-ops would void its
        test."""
        for ev in self.events:
            if ev.fired or step < ev.step:
                continue
            if ev.kind == "nan":
                ev.fired = True
                state = poison_state(state)
                self._log(logger, step, ev.kind)
            elif ev.kind == "ckpt_corrupt":
                path = corrupt_latest_checkpoint(log_dir)
                if path is None:
                    continue  # no checkpoint yet — stay pending
                ev.fired = True
                self._log(logger, step, ev.kind, path=path)
            elif ev.kind == "sigterm":
                ev.fired = True
                self._log(logger, step, ev.kind)
                os.kill(os.getpid(), signal.SIGTERM)
            elif ev.kind == "data_stall":
                ev.fired = True
                self._log(logger, step, ev.kind)
                raise DataStallError(
                    f"injected data stall at step {step}")
            elif ev.kind == "heartbeat_stall":
                if cluster is None:
                    raise InjectedFault(
                        "heartbeat_stall injection needs --cluster_dir "
                        "(no ClusterMonitor to stall)")
                ev.fired = True
                self._log(logger, step, ev.kind)
                cluster.stall_heartbeats()
            elif ev.kind == "host_lost":
                ev.fired = True
                self._log(logger, step, ev.kind)
                # Abrupt death: no checkpoint, no drain, no atexit. The
                # JSONL line above is line-buffered (already on disk);
                # everything else is deliberately lost.
                os._exit(EXIT_HOST_LOST)
            elif ev.kind == "collective_hang":
                if cluster is None:
                    raise InjectedFault(
                        "collective_hang injection needs --cluster_dir "
                        "(no watchdog to abort the hang)")
                ev.fired = True
                self._log(logger, step, ev.kind)
                # Wedge the main thread while the publisher keeps
                # beating — exactly what a stuck XLA collective looks
                # like. Only the watchdog's collective_timeout_s abort
                # (os._exit) ends this loop.
                while True:
                    time.sleep(0.05)
            elif ev.kind == "host_return":
                if cluster is None:
                    raise InjectedFault(
                        "host_return injection needs --cluster_dir "
                        "(no beat store to watch for the rejoin)")
                ev.fired = True
                self._log(logger, step, ev.kind)
                # Pin "the host returns here": hold this step until a
                # rejoin announcement is visible, so the chief's rejoin
                # scan fires at the very next seam — the 2→1→2 drill
                # expands before it can checkpoint world-shrunk
                # progress past the shared restore point. An expand the
                # chief ALREADY granted (the returning host announced
                # before this step) satisfies the drill too — the beat
                # is consumed by the grant, so waiting for one would
                # hang a run that already did the right thing.
                deadline = time.time() + HOST_RETURN_TIMEOUT_S
                while not cluster.rejoin_candidates():
                    d = cluster.coordinator.read()
                    if d is not None and \
                            getattr(d, "kind", "shrink") == "expand":
                        break
                    if time.time() > deadline:
                        raise InjectedFault(
                            f"host_return@{ev.step}: no rejoin "
                            f"announcement within "
                            f"{HOST_RETURN_TIMEOUT_S:.0f}s — did the "
                            f"returning host start with "
                            f"--elastic_expand?")
                    time.sleep(0.05)
        return state
