"""Deterministic fault injection for exercising recovery paths.

The reference's fault story is untestable by construction: the only way
to see MonitoredTrainingSession recover is to kill a real worker
mid-run (SURVEY §5). Here every failure mode the resilience layer
handles can be injected at an exact global step, on CPU, in tier-1 —
``--fault_spec "nan@120,ckpt_corrupt@200,sigterm@300,data_stall@400"``
fires each fault ONCE at the first host-loop seam where the global step
reaches its trigger. The injector's fired-state survives supervisor
restarts (``train/supervisor.py`` builds one injector and threads it
through every attempt), so a recovered run does not re-injure itself
replaying the same steps.

Fault kinds:

- ``nan`` — multiply one parameter leaf by NaN so the *real* forward/
  backward produces a non-finite loss (the detection path is the
  genuine ``check_numerics`` boundary fetch, not a mock).
- ``ckpt_corrupt`` — truncate the newest committed checkpoint on disk
  (a file codec loses its tail; a directory codec loses one member
  file), leaving the checksum sidecar stale — exactly what a crashed
  copy or bit rot looks like to ``restore_checkpoint``. Defers until a
  checkpoint exists.
- ``sigterm`` — deliver SIGTERM to this process, exercising
  ``PreemptionGuard``'s finish-step/checkpoint/exit path.
- ``data_stall`` — raise :class:`DataStallError` at the host-loop seam,
  the stand-in for a wedged input pipeline; the supervisor classifies
  it as a recoverable data failure.

Cluster-resilience kinds (need a :class:`~parallel.cluster.ClusterMonitor`
— i.e. ``--cluster_dir``; docs/RESILIENCE.md multi-host section):

- ``heartbeat_stall`` — stop publishing heartbeats while the process
  keeps training: from outside, indistinguishable from a dead host.
  Peers declare this process lost and restart without it; the eviction
  check fences it cleanly.
- ``host_lost`` — ``os._exit`` with no cleanup, no checkpoint, no
  flushed logs: the crashed/preempted-host case. Peers see the
  heartbeats go stale.
- ``collective_hang`` — block the main thread at the dispatch seam
  while the background publisher keeps beating: the wedged-collective
  case. Peers see a fresh-but-behind straggler; this process's own
  watchdog eventually aborts it (``collective_timeout_s``), turning
  the silent hang into a classified host loss.
- ``host_return`` — the deterministic stand-in for "a host came back
  at step N": block the (surviving) process at the seam until a
  returning host's ``rejoin``-phase beat appears in the store, so the
  2→1→2 elastic scale-UP drill expands at a known step instead of
  racing the returning process's startup. The expand itself then runs
  through the real chief-side rejoin scan (``--elastic_expand``). A
  drill where nobody ever returns fails loudly after a bounded wait.

Phase-qualified triggers (``kind@phase``) fire inside the RECOVERY
paths instead of at a training step — exactly the seams a fault that
strikes *during* recovery hits:

- ``@restore`` — at the checkpoint-restore seam of a recovery attempt
  (``Trainer.init_or_restore``; the run-start restore of a fresh,
  unfailed run does not count). ``ckpt_corrupt@restore`` corrupts the
  newest checkpoint at the exact moment the restore walk starts.
- ``@decide`` — on the chief, immediately AFTER it commits a restart/
  expand decision and before it restores. ``host_lost@decide`` is the
  chief-killed-mid-decision drill: survivors must finish recovery via
  the next chief re-deciding at a higher epoch.
- ``@adopt`` — on any seat, immediately after it adopts a coordinated
  restart decision (before re-entering restore).

Phase triggers need the run supervisor (``--supervise``) — the seams
live in ``train/supervisor.py``. A schedule can also name several
faults at one trigger (``nan@15,ckpt_corrupt@15``): compound faults
fire in spec order at the same seam.

- ``decision_corrupt`` — corrupt the cluster's restart-decision file
  (``restart_decision.json``): overwrite it with a decodable but bogus
  decision and a MISMATCHED integrity sidecar — what bit rot or a
  half-synced shared filesystem serves to survivors polling for the
  chief's verdict. The hardened ``RestartCoordinator.read`` must
  classify it (``decision_corrupt`` telemetry, read as absent), never
  adopt it. Needs a :class:`ClusterMonitor`.

Network-fault kinds (need the NET coordination transport —
``--cluster_transport net``; they arm ``utils/netfaults.py`` state on
the coordination service via ``POST /fault``, isolating the INJECTING
process, and fail loudly on the file transport — there is no network
to break there):

- ``net_partition`` — this process's link to the coordination service
  eats replies for ``netfaults.PARTITION_HEAL_S``: beats stop landing,
  reads come back empty, a decision cannot be committed. The bounded
  client timeouts turn that into the ordinary ``peer_lost``/eviction
  paths; under ``--elastic_expand`` the process rejoins when the
  partition heals.
- ``net_delay`` — every request answered late for a window (the slow-
  store drill; bounded re-reads, not hangs).
- ``net_drop`` — every second request 503s for a window (lossy link;
  the client's bounded retries absorb it).
- ``net_dup`` — writes applied twice for a window (duplicate delivery;
  atomic-replace commits make it invisible).

:class:`FaultSchedule` is the seeded sampler over this vocabulary the
chaos campaign driver (``tools/chaos.py``) uses: the same seed always
yields the same compound-fault schedule.

Every injection logs a ``fault`` JSONL record (``injected: true``) so
recovery tooling can pair injections with the ``recovery`` records they
provoke (``docs/RESILIENCE.md``).
"""

from __future__ import annotations

import dataclasses
import os
import random
import signal
import time
from typing import List, Optional, Sequence

FAULT_KINDS = ("nan", "ckpt_corrupt", "sigterm", "data_stall",
               "heartbeat_stall", "host_lost", "collective_hang",
               "host_return", "decision_corrupt", "replica_corrupt",
               "replica_stale", "net_partition", "net_delay",
               "net_drop", "net_dup")

#: The network-fault subset (armed server-side via utils/netfaults.py;
#: needs --cluster_transport net).
NET_FAULT_KINDS = ("net_partition", "net_delay", "net_drop", "net_dup")

#: Recovery-path seams a fault may be phase-qualified to
#: (``kind@phase``). The seams are supervisor-owned: ``restore`` fires
#: at the next recovery attempt's checkpoint restore, ``decide`` on the
#: chief right after it commits a coordinated-restart decision,
#: ``adopt`` right after any seat adopts one.
FAULT_PHASES = ("restore", "adopt", "decide")

#: Kinds that make sense at a phase seam (no train state to poison
#: there, and a blocking kind would deadlock the recovery itself).
PHASE_FAULT_KINDS = ("ckpt_corrupt", "sigterm", "data_stall",
                     "host_lost", "heartbeat_stall", "decision_corrupt")

#: Bounded wait for a ``host_return`` drill's returning host: long
#: enough for a cold process start (imports + restore + compile), short
#: enough that a drill where nobody returns fails the run, not the CI
#: budget.
HOST_RETURN_TIMEOUT_S = 300.0

#: Exit code of a ``host_lost`` injection — an abrupt, cleanup-free
#: death (distinct from the watchdog's own abort code so tests can tell
#: the injected corpse from a watchdog-fenced process).
EXIT_HOST_LOST = 77


class InjectedFault(RuntimeError):
    """Base class for failures raised (not merely caused) by injection."""


class DataStallError(InjectedFault):
    """Injected stand-in for a wedged/failed input pipeline."""


@dataclasses.dataclass
class FaultEvent:
    kind: str
    step: Optional[int] = None
    fired: bool = False
    phase: Optional[str] = None

    @property
    def trigger(self) -> str:
        """The ``@``-suffix this event was parsed from."""
        return self.phase if self.phase is not None else str(self.step)


def parse_fault_spec(spec: str) -> List[FaultEvent]:
    """``"kind@trigger,kind@trigger,..."`` → ordered fault events.

    A trigger is a global training step (``nan@120``) or a recovery
    phase from :data:`FAULT_PHASES` (``ckpt_corrupt@restore``).
    Duplicate kinds are allowed (e.g. ``nan@100,nan@200`` re-poisons
    after a recovery), and several faults may share one trigger — a
    compound fault firing in spec order at the same seam. Unknown
    kinds, malformed entries, and phase triggers on kinds outside
    :data:`PHASE_FAULT_KINDS` fail loudly at parse time — a typo'd
    fault plan that silently injects nothing would void the test it was
    written for.
    """
    events = []
    for entry in spec.split(","):
        entry = entry.strip()
        if not entry:
            continue
        kind, sep, trigger = entry.partition("@")
        kind = kind.strip()
        if not sep or kind not in FAULT_KINDS:
            raise ValueError(
                f"bad fault spec entry {entry!r}: want kind@trigger "
                f"with kind in {FAULT_KINDS}")
        trigger = trigger.strip()
        if trigger in FAULT_PHASES:
            if kind not in PHASE_FAULT_KINDS:
                raise ValueError(
                    f"bad fault spec entry {entry!r}: kind {kind!r} "
                    f"cannot be phase-qualified (allowed: "
                    f"{PHASE_FAULT_KINDS})")
            events.append(FaultEvent(kind, phase=trigger))
            continue
        try:
            step = int(trigger)
        except ValueError:
            raise ValueError(
                f"bad fault spec entry {entry!r}: trigger {trigger!r} "
                f"is neither an integer step nor a phase in "
                f"{FAULT_PHASES}") from None
        if step < 0:
            raise ValueError(f"bad fault spec entry {entry!r}: "
                             f"negative step")
        events.append(FaultEvent(kind, step))
    # Step events in step order first; phase events after them in a
    # stable (phase, kind) order — they have no step to slot into.
    return sorted(events, key=lambda e: (
        e.step is None, e.step if e.step is not None else 0,
        e.phase or "", e.kind))


def format_fault_spec(events: Sequence[FaultEvent]) -> str:
    """The ``--fault_spec`` string for ``events`` — the inverse of
    :func:`parse_fault_spec` (chaos shrinking emits reproducers with
    it)."""
    return ",".join(f"{e.kind}@{e.trigger}" for e in events)


def poison_state(state):
    """Multiply the first parameter leaf by NaN, preserving structure,
    dtype, and sharding — the subsequent (real) train step then yields a
    non-finite loss through the genuine compute path."""
    import jax
    import jax.numpy as jnp

    leaves, treedef = jax.tree.flatten(state.params)
    if not leaves:
        return state
    leaves[0] = leaves[0] * jnp.asarray(float("nan"), leaves[0].dtype)
    return state._replace(params=jax.tree.unflatten(treedef, leaves))


def corrupt_latest_checkpoint(log_dir: str) -> Optional[str]:
    """Truncate the newest committed checkpoint (file codecs) or one
    member file (directory codecs). Returns the corrupted path, or None
    when no checkpoint exists yet."""
    from dml_cnn_cifar10_tpu.ckpt import checkpoint as ckpt_lib

    path = ckpt_lib.latest_checkpoint(log_dir)
    if path is None:
        return None
    victim = path
    if os.path.isdir(path):
        members = sorted(
            os.path.join(path, n) for n in os.listdir(path)
            if os.path.isfile(os.path.join(path, n))
            and n != "MANIFEST.json")
        # Prefer a DATA member over sidecar/index files (the sharded
        # dir now carries per-shard .sha256 + files.json companions):
        # truncating real payload exercises the integrity walk, not
        # just the metadata parse.
        data = [m for m in members if m.endswith(".msgpack")]
        members = data or members
        if not members:  # nothing but the manifest — truncate that
            members = [os.path.join(path, "MANIFEST.json")]
        victim = members[0]
    size = os.path.getsize(victim)
    with open(victim, "r+b") as f:
        f.truncate(size // 2)
    return path


def _committed_replica_steps(cluster):
    """``(owner_dir_path, step)`` pairs of every COMMITTED peer replica
    (``INDEX.json`` present) under the cluster's replica store, newest
    step first. Empty when peer redundancy is off or nothing committed
    yet."""
    from dml_cnn_cifar10_tpu.ckpt import peerstore

    root = os.path.join(cluster.cluster_dir, peerstore.REPLICAS_DIRNAME)
    out = []
    if not os.path.isdir(root):
        return out
    for host in sorted(os.listdir(root)):
        hdir = os.path.join(root, host)
        if not os.path.isdir(hdir):
            continue
        for name in os.listdir(hdir):
            sdir = os.path.join(hdir, name)
            if name.endswith(".tmp") or not os.path.isdir(sdir):
                continue
            if not os.path.exists(
                    os.path.join(sdir, peerstore.INDEX)):
                continue
            try:
                step = int(name.split("_", 1)[1])
            except (IndexError, ValueError):
                continue
            out.append((sdir, step))
    out.sort(key=lambda t: (-t[1], t[0]))
    return out


def corrupt_peer_replicas(cluster) -> List[str]:
    """Truncate one payload file inside every owner's NEWEST committed
    peer replica — the replica set the next diskless restore would read.
    The sidecar verify catches the damage (classified
    :class:`~dml_cnn_cifar10_tpu.ckpt.peerstore.ReplicaMiss`) and the
    restore falls back to disk. Returns the corrupted paths (empty when
    nothing is committed yet — the event stays pending, like
    ``ckpt_corrupt``)."""
    victims = []
    seen_hosts = set()
    for sdir, _step in _committed_replica_steps(cluster):
        host = os.path.basename(os.path.dirname(sdir))
        if host in seen_hosts:
            continue  # newest-first: only each owner's newest replica
        seen_hosts.add(host)
        parts = sorted(n for n in os.listdir(sdir)
                       if n.endswith(".msgpack"))
        if not parts:
            continue
        victim = os.path.join(sdir, parts[0])
        size = os.path.getsize(victim)
        with open(victim, "r+b") as f:
            f.truncate(size // 2)
        victims.append(victim)
    return victims


def stale_peer_replicas(cluster) -> List[str]:
    """Delete every owner's NEWEST committed peer replica step dir,
    leaving any older ones — the beats still advertise the deleted step
    (the stores' counters know nothing of the tampering), so a chief
    that decides ``source=peer`` finds only older-or-no replicas and
    the restore classifies a miss → disk fallback. Returns the deleted
    dirs (empty = stay pending)."""
    import shutil

    removed = []
    seen_hosts = set()
    for sdir, _step in _committed_replica_steps(cluster):
        host = os.path.basename(os.path.dirname(sdir))
        if host in seen_hosts:
            continue
        seen_hosts.add(host)
        shutil.rmtree(sdir, ignore_errors=True)
        removed.append(sdir)
    return removed


def corrupt_decision_file(cluster) -> str:
    """Corrupt the cluster's restart-decision file the *nasty* way: a
    decodable but bogus decision (absurd epoch, empty survivor set —
    adopting it would fence every live host) paired with a MISMATCHED
    integrity sidecar. A plain truncation would be caught by the JSON
    parse alone; this shape is only caught by the sidecar check, which
    is exactly the hardening the chaos campaign exists to regress-test
    (a reverted check adopts the bogus decision and the run visibly
    breaks)."""
    import json

    coord = cluster.coordinator
    bogus = {"epoch": cluster.epoch + 997, "world_size": 1,
             "restore_step": 0, "survivors": [], "kind": "shrink"}
    with open(coord.path, "w") as f:
        json.dump(bogus, f)
    with open(coord.sidecar_path, "w") as f:
        json.dump({"algo": "sha256", "digest": "0" * 64}, f)
    return coord.path


#: Default seeded-sampler vocabulary: every (kind, trigger) the chaos
#: campaign may draw for a SUPERVISED single-process run — each entry
#: is recoverable to run completion (sigterm/host_lost on the sole
#: process end the run early by design, so they are cluster-scenario
#: backbone faults, not sampled ones).
CHAOS_VOCABULARY = (
    "nan@step", "ckpt_corrupt@step", "data_stall@step",
    "decision_corrupt@step", "ckpt_corrupt@restore",
    "data_stall@restore", "decision_corrupt@restore",
)

#: Extra vocabulary for the 2-process cluster scenario's SURVIVOR seat
#: (the dead peer carries the backbone ``host_lost``): recovery-phase
#: compound faults on the seat that must keep the run alive.
CHAOS_CLUSTER_VOCABULARY = CHAOS_VOCABULARY + (
    "decision_corrupt@decide", "heartbeat_stall@adopt",
)

#: Vocabulary for the 2→1→2 elastic-expand scenario's surviving chief.
#: Two families are deliberately absent: ``heartbeat_stall@adopt`` (a
#: chief going dark right before re-admitting a joiner starts an
#: evict/rejoin ping-pong with unbounded wall-clock — a liveness
#: property the deadline invariant would punish, not a recovery
#: property this scenario fuzzes), and ``decision_corrupt`` (the
#: drill's harness-respawned seat learns of its eviction FROM the
#: decision file; corrupting it leaves that seat beating in ``train``
#: phase forever and the ``host_return`` hold times out by
#: construction — decision-file fuzzing is the train/cluster
#: scenarios' job).
CHAOS_EXPAND_VOCABULARY = (
    "nan@step", "ckpt_corrupt@step", "data_stall@step",
    "ckpt_corrupt@restore", "data_stall@restore",
)

#: Vocabulary for the 2-process ``peer_recovery`` scenario (peer
#: redundancy ON): the full cluster vocabulary PLUS the replica faults.
#: The replica kinds live ONLY here — they stay pending until a replica
#: is committed, so a scenario with redundancy off would schedule
#: faults that can never fire and trip the scheduled-vs-injected count
#: invariant. Compound double-faults (backbone ``host_lost`` and a
#: drawn ``replica_corrupt``/``replica_stale`` on the survivor) are the
#: point: the diskless restore must degrade to the disk walk cleanly,
#: still bit-identical.
CHAOS_PEER_VOCABULARY = CHAOS_CLUSTER_VOCABULARY + (
    "replica_corrupt@step", "replica_stale@step",
)

#: Vocabulary for the 1-process unified-runtime scenario (``--mode
#: run``: supervised TrainJob + in-process ServeJob on one mesh,
#: docs/RUNTIME.md). Every kind must be recoverable WITHOUT ending the
#: process — the scenario's extra invariant is that the serving side
#: keeps publishing across recoveries, so process-ending kinds
#: (sigterm/host_lost) and the cluster-decision kinds (a 1-process
#: runtime adopts no coordinated decisions) are out.
CHAOS_RUNTIME_VOCABULARY = (
    "nan@step", "ckpt_corrupt@step", "data_stall@step",
    "ckpt_corrupt@restore", "data_stall@restore",
)

#: Vocabulary for the 2-process ``net_partition`` scenario's SERVER
#: seat (the partitioned seat carries the ``net_partition`` backbone):
#: the expand vocabulary — the partitioned peer rejoins through the
#: same elastic-expand arc, so the same exclusions apply — plus the
#: recoverable link faults (delay/drop/dup) on the coordination
#: service's own loopback link. ``net_partition`` itself is NOT
#: sampled: partitioning the seat that HOSTS the coordination service
#: is a liveness torture test (its own held loopback requests), not a
#: recovery property this scenario fuzzes.
CHAOS_NET_VOCABULARY = CHAOS_EXPAND_VOCABULARY + (
    "net_delay@step", "net_drop@step", "net_dup@step",
)


@dataclasses.dataclass
class FaultSchedule:
    """A seeded, reproducible compound-fault schedule.

    ``generate(seed, budget)`` draws ``budget`` faults from a
    vocabulary of ``kind@step`` / ``kind@phase`` templates with a
    :class:`random.Random` seeded stream — same seed, same schedule,
    forever. The chaos campaign (``tools/chaos.py``) runs many of these
    through the CPU sims and shrinks failing ones to minimal
    reproducers.
    """

    seed: int
    events: List[FaultEvent]

    @property
    def spec(self) -> str:
        return format_fault_spec(self.events)

    @classmethod
    def generate(cls, seed: int, budget: int,
                 vocabulary: Sequence[str] = CHAOS_VOCABULARY,
                 min_step: int = 1, max_step: int = 35,
                 ckpt_every: int = 10) -> "FaultSchedule":
        """Sample ``budget`` faults. Step templates get a uniform step
        in ``[min_step, max_step]`` (several faults may land on one
        step — compound faults are the point); phase templates are
        deduplicated (a phase event is one-shot, a duplicate could
        never fire). ``ckpt_corrupt`` steps are drawn only after the
        SECOND checkpoint can exist (``2 * ckpt_every + 1``): corrupting
        the run's only checkpoint right before a recovery needs it is
        unrecoverable by construction — the sampler fuzzes the recovery
        state space, and "your sole backup rotted" has no recovery to
        fuzz (the classified halt covers it)."""
        if budget < 1:
            raise ValueError(f"budget must be >= 1, got {budget}")
        rng = random.Random(seed)
        events: List[FaultEvent] = []
        seen_phase = set()
        for _ in range(budget):
            template = rng.choice(list(vocabulary))
            kind, _, trigger = template.partition("@")
            if trigger == "step":
                lo = max(min_step, 2 * ckpt_every + 1) \
                    if kind == "ckpt_corrupt" else min_step
                events.append(
                    FaultEvent(kind, rng.randint(lo, max(lo, max_step))))
            else:
                if (kind, trigger) in seen_phase:
                    continue
                seen_phase.add((kind, trigger))
                events.append(FaultEvent(kind, phase=trigger))
        # Round-trip through the parser: validates every sampled entry
        # and applies the canonical ordering.
        return cls(seed, parse_fault_spec(format_fault_spec(events)))


class FaultInjector:
    """One-shot, step-keyed fault firing at the training loop's host
    seam (``Trainer.fit`` calls :meth:`step_hook` once per dispatch).
    Owned by the supervisor across restarts so fired events stay
    fired."""

    def __init__(self, events: List[FaultEvent]):
        self.events = events
        # Set by the supervisor once a recoverable failure is being
        # handled: phase-qualified ``@restore`` events only fire at
        # RECOVERY restores, not the run-start restore of a fresh run.
        self.recovering = False
        # Last step seen by step_hook — phase events fire outside the
        # step loop and borrow it for their telemetry.
        self._last_step = 0

    @classmethod
    def from_spec(cls, spec: Optional[str]) -> Optional["FaultInjector"]:
        if not spec:
            return None
        return cls(parse_fault_spec(spec))

    def pending(self) -> List[FaultEvent]:
        return [e for e in self.events if not e.fired]

    def _log(self, logger, step: int, kind: str, **extra) -> None:
        if logger is not None:
            logger.log("fault", step=step, fault=kind, injected=True,
                       **extra)

    def step_hook(self, step: int, state, log_dir: str, logger=None,
                  cluster=None):
        """Fire every due, unfired event; returns the (possibly
        poisoned) state. ``ckpt_corrupt`` stays pending until a
        checkpoint exists to corrupt. ``data_stall`` raises after
        marking itself fired so a supervised restart does not re-raise
        it. The cluster kinds take the :class:`ClusterMonitor` the
        Trainer threads through (``cluster``) and fail loudly without
        one — a cluster drill that silently no-ops would void its
        test."""
        self._last_step = step
        for ev in self.events:
            if ev.phase is not None or ev.fired or step < ev.step:
                continue
            if ev.kind == "nan":
                ev.fired = True
                state = poison_state(state)
                self._log(logger, step, ev.kind)
            elif ev.kind == "ckpt_corrupt":
                path = corrupt_latest_checkpoint(log_dir)
                if path is None:
                    continue  # no checkpoint yet — stay pending
                ev.fired = True
                self._log(logger, step, ev.kind, path=path)
            elif ev.kind == "sigterm":
                ev.fired = True
                self._log(logger, step, ev.kind)
                os.kill(os.getpid(), signal.SIGTERM)
            elif ev.kind == "data_stall":
                ev.fired = True
                self._log(logger, step, ev.kind)
                raise DataStallError(
                    f"injected data stall at step {step}")
            elif ev.kind == "heartbeat_stall":
                if cluster is None:
                    raise InjectedFault(
                        "heartbeat_stall injection needs --cluster_dir "
                        "(no ClusterMonitor to stall)")
                ev.fired = True
                self._log(logger, step, ev.kind)
                cluster.stall_heartbeats()
            elif ev.kind == "host_lost":
                ev.fired = True
                self._log(logger, step, ev.kind)
                # Abrupt death: no checkpoint, no drain, no atexit. The
                # JSONL line above is line-buffered (already on disk);
                # everything else is deliberately lost.
                os._exit(EXIT_HOST_LOST)
            elif ev.kind == "collective_hang":
                if cluster is None:
                    raise InjectedFault(
                        "collective_hang injection needs --cluster_dir "
                        "(no watchdog to abort the hang)")
                ev.fired = True
                self._log(logger, step, ev.kind)
                # Wedge the main thread while the publisher keeps
                # beating — exactly what a stuck XLA collective looks
                # like. Only the watchdog's collective_timeout_s abort
                # (os._exit) ends this loop.
                while True:
                    time.sleep(0.05)
            elif ev.kind == "decision_corrupt":
                if cluster is None:
                    raise InjectedFault(
                        "decision_corrupt injection needs --cluster_dir "
                        "(no restart-decision file to corrupt)")
                ev.fired = True
                path = corrupt_decision_file(cluster)
                self._log(logger, step, ev.kind, path=path)
            elif ev.kind == "replica_corrupt":
                if cluster is None:
                    raise InjectedFault(
                        "replica_corrupt injection needs --cluster_dir "
                        "(no peer-replica store to corrupt)")
                paths = corrupt_peer_replicas(cluster)
                if not paths:
                    continue  # no committed replica yet — stay pending
                ev.fired = True
                self._log(logger, step, ev.kind, path=paths[0])
            elif ev.kind == "replica_stale":
                if cluster is None:
                    raise InjectedFault(
                        "replica_stale injection needs --cluster_dir "
                        "(no peer-replica store to age)")
                paths = stale_peer_replicas(cluster)
                if not paths:
                    continue  # no committed replica yet — stay pending
                ev.fired = True
                self._log(logger, step, ev.kind, path=paths[0])
            elif ev.kind in NET_FAULT_KINDS:
                client = getattr(cluster, "net_client", None) \
                    if cluster is not None else None
                if client is None:
                    raise InjectedFault(
                        f"{ev.kind} injection needs --cluster_transport "
                        f"net (no network between the file store and "
                        f"its directory to break)")
                ev.fired = True
                # Arm ON the coordination service, isolating THIS
                # process — the arm request must land before the fault
                # takes effect, which is why the injecting seat is the
                # isolated one.
                rec = client.post_fault(ev.kind,
                                        isolate=[cluster.process_id])
                self._log(logger, step, ev.kind,
                          isolate=rec.get("isolate"),
                          duration_s=rec.get("duration_s"))
            elif ev.kind == "host_return":
                if cluster is None:
                    raise InjectedFault(
                        "host_return injection needs --cluster_dir "
                        "(no beat store to watch for the rejoin)")
                ev.fired = True
                self._log(logger, step, ev.kind)
                # Pin "the host returns here": hold this step until a
                # rejoin announcement is visible, so the chief's rejoin
                # scan fires at the very next seam — the 2→1→2 drill
                # expands before it can checkpoint world-shrunk
                # progress past the shared restore point. An expand the
                # chief ALREADY granted (the returning host announced
                # before this step) satisfies the drill too — the beat
                # is consumed by the grant, so waiting for one would
                # hang a run that already did the right thing.
                deadline = time.time() + HOST_RETURN_TIMEOUT_S
                while not cluster.rejoin_candidates():
                    d = cluster.coordinator.read()
                    if d is not None and \
                            getattr(d, "kind", "shrink") == "expand":
                        break
                    if time.time() > deadline:
                        raise InjectedFault(
                            f"host_return@{ev.step}: no rejoin "
                            f"announcement within "
                            f"{HOST_RETURN_TIMEOUT_S:.0f}s — did the "
                            f"returning host start with "
                            f"--elastic_expand?")
                    time.sleep(0.05)
        return state

    def phase_hook(self, phase: str, log_dir: str, logger=None,
                   cluster=None) -> None:
        """Fire every unfired event qualified to ``phase`` — the
        recovery-path twin of :meth:`step_hook`, called by the
        supervisor at the ``decide``/``adopt`` seams and by
        ``Trainer.init_or_restore`` at the ``restore`` seam. ``restore``
        events are additionally gated on :attr:`recovering` (every fit
        attempt restores; only recovery restores count as the seam).
        The fault record borrows the last step the step hook saw and
        carries the phase so injections stay pairable with the recovery
        they strike."""
        if phase not in FAULT_PHASES:
            raise ValueError(f"unknown fault phase {phase!r} "
                             f"(want one of {FAULT_PHASES})")
        if phase == "restore" and not self.recovering:
            return
        step = self._last_step
        for ev in self.events:
            if ev.fired or ev.phase != phase:
                continue
            if ev.kind == "ckpt_corrupt":
                # The recovery-phase drill exercises the FALLBACK walk:
                # it fires only when an older candidate exists to fall
                # back to. Corrupting the sole copy makes the run
                # unrecoverable by construction — that is a halt test
                # (covered by the classified all-candidates-failed
                # error), not a recovery drill; stay pending instead.
                from dml_cnn_cifar10_tpu.ckpt import (
                    checkpoint as ckpt_lib)
                if len(ckpt_lib.all_checkpoint_steps(log_dir)) < 2:
                    continue
                path = corrupt_latest_checkpoint(log_dir)
                if path is None:
                    continue
                ev.fired = True
                self._log(logger, step, ev.kind, phase=phase, path=path)
            elif ev.kind == "sigterm":
                ev.fired = True
                self._log(logger, step, ev.kind, phase=phase)
                os.kill(os.getpid(), signal.SIGTERM)
            elif ev.kind == "data_stall":
                ev.fired = True
                self._log(logger, step, ev.kind, phase=phase)
                raise DataStallError(
                    f"injected data stall at recovery phase {phase!r}")
            elif ev.kind == "host_lost":
                ev.fired = True
                self._log(logger, step, ev.kind, phase=phase)
                if logger is not None and hasattr(logger, "flush"):
                    logger.flush()
                os._exit(EXIT_HOST_LOST)
            elif ev.kind == "heartbeat_stall":
                if cluster is None:
                    raise InjectedFault(
                        f"heartbeat_stall@{phase} injection needs "
                        f"--cluster_dir (no ClusterMonitor to stall)")
                ev.fired = True
                self._log(logger, step, ev.kind, phase=phase)
                cluster.stall_heartbeats()
            elif ev.kind == "decision_corrupt":
                if cluster is None:
                    raise InjectedFault(
                        f"decision_corrupt@{phase} injection needs "
                        f"--cluster_dir (no decision file to corrupt)")
                ev.fired = True
                path = corrupt_decision_file(cluster)
                self._log(logger, step, ev.kind, phase=phase, path=path)
