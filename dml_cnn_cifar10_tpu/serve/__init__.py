"""TPU-native serving: dynamic micro-batching inference over the
exported StableHLO artifact (or live params).

The training half of the repo ends at a checkpoint directory and an
``export.py`` artifact; this package is the missing deployment half —
the runtime that turns single-image requests into padded device batches
at a small set of pre-compiled bucket sizes, with admission control,
deadline shedding, and latency/throughput accounting on the existing
JSONL telemetry stream. See ``docs/SERVING.md``.
"""

from dml_cnn_cifar10_tpu.serve.batcher import (MicroBatcher,  # noqa: F401
                                               ShedError, VersionedLogits)
from dml_cnn_cifar10_tpu.serve.engine import ServingEngine  # noqa: F401
from dml_cnn_cifar10_tpu.serve.metrics import ServeMetrics  # noqa: F401
