"""Exact-match response cache: (input digest, serving version) -> the
finished response payload.

CIFAR-sized inference repeats inputs more than it looks like it should
— canaries, health probes, replayed loadgen corpora, duplicate client
retries — and an exact hit costs one SHA-1 over 3 KB of pixels versus a
queue wait plus a device dispatch. Hits bypass the batcher entirely
(no submit, no bucket padding, no shed exposure) and are counted as
``cache_hit`` in the serve windows plus ``dml_serve_cache_hits_total``
in the live registry.

Version safety is structural, not best-effort: the cache binds every
entry generation to ONE serving version and self-flushes the moment a
lookup or store sees a different one — the hot-swap flush. A response
computed by version N can never answer while version M serves, so the
version tag in every response (the ``+int8`` suffix included) stays
truthful even through a float→int8 swap under load.

``--serve_cache_size`` (0 = off) bounds the LRU; eviction is
oldest-use first. One instance is shared by every handler thread —
all mutation under one lock, same discipline as ``ServeMetrics``.
"""

from __future__ import annotations

import hashlib
import threading
from collections import OrderedDict
from typing import Optional


class ResponseCache:
    """Thread-safe exact-match LRU, one generation per serving version."""

    def __init__(self, capacity: int):
        if capacity <= 0:
            raise ValueError("ResponseCache needs capacity >= 1 "
                             "(0 means: don't construct one)")
        self.capacity = int(capacity)
        self._lock = threading.Lock()
        self._entries: OrderedDict = OrderedDict()
        self._version: Optional[str] = None
        self.hits = 0
        self.misses = 0
        self.flushes = 0   # version-change flushes (hot-swaps observed)

    @staticmethod
    def digest(body: bytes) -> bytes:
        return hashlib.sha1(body).digest()

    def _sync_version(self, version: str) -> None:
        # caller holds the lock
        if version != self._version:
            if self._version is not None and self._entries:
                self.flushes += 1
            self._entries.clear()
            self._version = version

    def lookup(self, body: bytes, version: str) -> Optional[dict]:
        """The cached payload for this exact input under the CURRENT
        serving version, or None. Seeing a new version flushes the
        previous generation (the hot-swap flush)."""
        key = self.digest(body)
        with self._lock:
            self._sync_version(str(version))
            payload = self._entries.get(key)
            if payload is None:
                self.misses += 1
                return None
            self._entries.move_to_end(key)
            self.hits += 1
            return payload

    def store(self, body: bytes, version: str, payload: dict) -> None:
        """Cache a finished response under the version that COMPUTED it
        (``VersionedLogits.version``) — if a swap landed between
        dispatch and completion, the generation check just drops it."""
        key = self.digest(body)
        with self._lock:
            self._sync_version(str(version))
            self._entries[key] = payload
            self._entries.move_to_end(key)
            while len(self._entries) > self.capacity:
                self._entries.popitem(last=False)

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)
