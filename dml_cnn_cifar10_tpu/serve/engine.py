"""The device side of serving: one uint8-in/logits-out callable plus
bucket pre-compilation and the checkpoint hot-swap seam.

Two construction paths, one call contract:

- :meth:`ServingEngine.from_artifact` — deserialize the ``export.py``
  StableHLO artifact (weights embedded, symbolic batch dim, raw-uint8
  input with the eval decode compiled in). The input image geometry is
  read back out of the artifact's own avals, so a server needs no
  ``DataConfig`` to validate requests against it.
- :meth:`ServingEngine.from_params` — live params passed as ARGUMENTS
  to one jitted program (:func:`~dml_cnn_cifar10_tpu.export.
  make_variable_serving_fn`). Because the weights are traced inputs,
  not constants, :meth:`try_swap` can install a new checkpoint's params
  as a pytree replacement with NO recompile — the zero-downtime
  hot-swap the serving fleet (``fleet/``) is built on. A batch in
  flight finishes on the old weights; the next batch runs the new ones.

Either way the callable is jitted, so each distinct batch size compiles
exactly once. That is why the batcher quantizes to a fixed bucket set
(:meth:`warmup` pre-compiles them all before traffic): an unquantized
batcher would recompile on every new fill level and the first request at
each level would eat a multi-second compile in its latency.

With a :class:`~dml_cnn_cifar10_tpu.compilecache.CompileCache` armed
(``--compile_cache_dir``), the per-bucket warmup compiles persist across
process restarts: a redeployed/recovered server warm-starts its bucket
programs from the cache (jax's native persistent cache by default;
deserialized executables on opted-in backends), so time-to-ready drops
from one XLA compile per bucket to one disk load per bucket — the cheap
replica spin-up the fleet's autoscaler exploits. Warmup always emits one
``compile`` JSONL event per bucket (key null when uncached) so the
serving section of ``tools/telemetry_report.py`` can price the warmup.

Every response is tagged with the engine's current ``version`` (the
checkpoint step it serves, threaded by the batcher into
:class:`~dml_cnn_cifar10_tpu.serve.batcher.VersionedLogits`), so a
rollout is observable end-to-end: watch the version tags in the
responses flip as the fleet swaps.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Optional, Tuple

import numpy as np


def _variable_spec(variables):
    """Hashable (treedef, ((shape, dtype), ...)) signature of a
    variables pytree — the contract :meth:`ServingEngine.try_swap`
    checks a candidate checkpoint against. Anything the compiled
    program is shape/dtype-sensitive to is in here; values are not."""
    import jax

    leaves, treedef = jax.tree.flatten(variables)
    return treedef, tuple((tuple(np.shape(l)), np.dtype(
        getattr(l, "dtype", type(l))).name) for l in leaves)


def _spec_mismatch(want, got) -> str:
    """Human-readable first divergence between two variable specs."""
    if want[0] != got[0]:
        return "param tree structure differs"
    for i, (a, b) in enumerate(zip(want[1], got[1])):
        if a != b:
            return (f"leaf {i}: have {a[0]}/{a[1]}, "
                    f"candidate {b[0]}/{b[1]}")
    return "specs differ"


class ServingEngine:
    """Uint8 image batches in, numpy logits out, with device timing.

    ``fn`` maps ``uint8 [B, H, W, C] -> logits [B, K]`` (the
    closed-over/artifact path; the live-params path installs a two-arg
    jitted program instead — see :meth:`from_params`). ``image_shape``
    is the per-request ``(H, W, C)`` contract the batcher validates and
    pads against. ``compile_cache``/``logger`` arm the persistent
    warmup path described in the module docstring. ``version`` tags
    every response; ``replica_id`` names this engine in swap telemetry.
    """

    def __init__(self, fn, image_shape: Tuple[int, int, int],
                 source: str = "live", compile_cache=None, logger=None,
                 version: str = "0", replica_id: int = 0):
        self._fn = fn
        self.image_shape = tuple(int(d) for d in image_shape)
        self.source = source
        self.compile_cache = compile_cache
        self.logger = logger
        self.version = str(version)
        self.replica_id = int(replica_id)
        self.swap_count = 0
        # Hot-swap seam state (live-params engines only): the two-arg
        # jitted program, the current variables pytree, and its
        # shape/dtype spec. The lock pairs (variables, version) reads
        # with swap writes; compute happens outside it.
        self._swap_lock = threading.Lock()
        self._jitted_v = None
        self._variables = None
        self._var_spec = None
        # Replicated placement on an externally-owned mesh (the unified
        # runtime's): set by from_params(mesh=...). Every install —
        # initial and swapped — goes through the SAME sharding so the
        # compiled program never sees a placement change. None keeps
        # jax's default single-device placement.
        self._put_sharding = None
        # Alternate serving programs (e.g. the int8 path): name ->
        # (jitted two-arg fn, variable spec). try_swap routes a
        # candidate tree to whichever program its spec matches, and
        # the forward reads the active path name under the same lock
        # as the variables — a float->int8 swap is the same pytree
        # pointer replacement as a float->float one.
        self._alt_programs = {}
        self._active_path = "primary"
        # (path, bucket) -> AOT executable obtained through the cache;
        # forward_timed prefers these, falling back to the jitted fn
        # for sizes the warmup never saw. Swap-safe by construction:
        # the executables are compiled for the variables' AVALS, which
        # try_swap pins per path, so they serve every installed version
        # of that path.
        self._bucket_fns = {}
        #: last warmup's {bucket: event dict} (hit/source/compile_s).
        self.last_warmup: dict = {}

    @classmethod
    def from_artifact(cls, path: Optional[str] = None,
                      blob: Optional[bytes] = None,
                      compile_cache=None, logger=None,
                      version: str = "artifact",
                      replica_id: int = 0) -> "ServingEngine":
        """Engine over a serialized ``export.py`` artifact (file path or
        raw bytes). Self-contained: weights, decode, and input geometry
        all come from the artifact — which also means NOT hot-swappable
        (the weights are baked into the program; :meth:`try_swap`
        rejects)."""
        import jax

        from dml_cnn_cifar10_tpu import export as export_lib

        if (path is None) == (blob is None):
            raise ValueError("pass exactly one of path= or blob=")
        if path is not None:
            with open(path, "rb") as f:
                blob = f.read()
        exported = export_lib.deserialize_exported(blob)
        shape = export_lib.artifact_image_shape(exported)
        return cls(jax.jit(exported.call), shape,
                   source=path or "<artifact bytes>",
                   compile_cache=compile_cache, logger=logger,
                   version=version, replica_id=replica_id)

    @classmethod
    def from_params(cls, model_def, model_cfg, data_cfg, params: Any,
                    model_state: Any = None, compile_cache=None,
                    logger=None, version: str = "0",
                    replica_id: int = 0, mesh=None,
                    quantize: Optional[str] = None,
                    quant_scales=None) -> "ServingEngine":
        """Engine over live params — the same eval forward export.py
        would serialize, with the weights as jit ARGUMENTS so
        :meth:`try_swap` can replace them without a recompile.

        ``mesh`` attaches the engine to an externally-owned mesh (the
        unified runtime's): weights are placed replicated over it, and
        every later :meth:`try_swap` re-places candidates onto the SAME
        sharding — a device-to-device transfer, never a host round-trip
        — so train-sharded publishes and the serving program agree.

        ``quantize="int8"`` builds the quantized construction path
        instead: the float params are converted with ``quant_scales``
        (a ``quant.calibrate.QuantScales``, required) and the engine's
        primary program is the XLA-int8 forward — the version carries
        the ``+int8`` suffix so every response advertises the numeric
        path. The swap contract then accepts QUANTIZED trees."""
        import jax

        from dml_cnn_cifar10_tpu.export import make_variable_serving_fn

        eng = cls(None, (data_cfg.image_height, data_cfg.image_width,
                         data_cfg.num_channels),
                  compile_cache=compile_cache, logger=logger,
                  version=version, replica_id=replica_id)
        if quantize:
            if quantize != "int8":
                raise ValueError(f"unknown quantize mode {quantize!r} "
                                 f"(supported: int8)")
            if quant_scales is None:
                raise ValueError(
                    "quantize='int8' needs quant_scales= (run "
                    "quant.calibrate.calibrate on eval batches first)")
            from dml_cnn_cifar10_tpu.quant import convert as quant_convert
            eng._jitted_v = jax.jit(
                quant_convert.make_quantized_serving_fn(model_cfg,
                                                        data_cfg))
            eng.version = quant_convert.quantized_version(version)
            params = quant_convert.quantize_params(params, quant_scales)
            model_state = None
        else:
            eng._jitted_v = jax.jit(
                make_variable_serving_fn(model_def, model_cfg, data_cfg))
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec
            eng._put_sharding = NamedSharding(mesh, PartitionSpec())
        variables = eng._place((params, model_state
                                if model_def.has_state else None))
        eng._variables = variables
        eng._var_spec = _variable_spec(variables)
        return eng

    def _place(self, tree):
        """Device placement honoring the attached mesh (replicated) or
        jax's default when the engine owns no mesh."""
        import jax

        if self._put_sharding is not None:
            return jax.device_put(tree, self._put_sharding)
        return jax.device_put(tree)

    # --- hot-swap seam ---

    @property
    def swappable(self) -> bool:
        return self._jitted_v is not None

    def attach_program(self, name: str, jitted_fn,
                       template_variables, warm_buckets=None) -> None:
        """Arm an alternate serving program (same ``fn(variables,
        batch_u8) -> logits`` contract as the primary). ``try_swap``
        then routes any candidate whose variable spec matches the
        TEMPLATE's to this program — e.g. a float engine armed with the
        int8 program hot-swaps to a quantized tree the moment one
        passes the publish gate, and back, with no engine rebuild.

        ``warm_buckets`` pre-pays the alternate path's per-bucket
        compiles with the template variables (zero batches), so the
        first post-swap batch doesn't eat an XLA compile mid-traffic.
        """
        import jax

        if not self.swappable:
            raise ValueError("alternate programs need a live-params "
                             "engine (artifact engines are baked)")
        template_variables = self._place(template_variables)
        self._alt_programs[name] = (jitted_fn,
                                    _variable_spec(template_variables))
        for b in sorted(set(int(b) for b in (warm_buckets or ()))):
            zeros = np.zeros((b, *self.image_shape), np.uint8)
            t0 = time.perf_counter()
            jax.block_until_ready(jitted_fn(template_variables, zeros))
            if self.logger is not None:
                self.logger.log(
                    "compile", key=None, phase=f"serve_warmup_{name}",
                    hit=False,
                    compile_s=round(time.perf_counter() - t0, 4),
                    source="uncached")

    def _match_program(self, spec):
        """(path name, jitted fn) whose compiled contract the candidate
        spec satisfies, or None. The construction-time program is
        checked first, then attached alternates."""
        if spec == self._var_spec:
            return "primary", self._jitted_v
        for name, (fn, pspec) in self._alt_programs.items():
            if spec == pspec:
                return name, fn
        return None

    def _active_fn(self):
        if self._active_path == "primary":
            return self._jitted_v
        return self._alt_programs[self._active_path][0]

    def try_swap(self, params: Any, model_state: Any = None,
                 version: str = "?") -> Tuple[bool, str]:
        """Validate + atomically install a new weight set.

        The candidate must match the engine's compiled contract — same
        param tree structure, same leaf shapes and dtypes — because the
        warm bucket executables were compiled for exactly those avals.
        A mismatch (wrong --model, changed width, different dtype...)
        is REJECTED: a clear ``swap_rejected`` JSONL event, return
        ``(False, reason)``, and the old version keeps serving — never
        a mid-batch failure. On success the swap is a pytree pointer
        replacement under the lock: the in-flight batch completes on
        the old weights, the next batch runs the new ones, and every
        response's version tag says which.
        """
        import jax

        t0 = time.perf_counter()
        version = str(version)
        if not self.swappable:
            return False, self._reject(
                version, "engine is artifact-backed (weights baked "
                         "into the program); not swappable")
        candidate = (params, model_state)
        spec = _variable_spec(candidate)
        match = self._match_program(spec)
        if match is None:
            return False, self._reject(
                version, _spec_mismatch(self._var_spec, spec))
        path, _ = match
        # Place on device BEFORE taking the lock: the transfer is the
        # slow part and must not stall a concurrent forward. With an
        # attached mesh this re-places onto the engine's replicated
        # sharding, so a train-sharded publish never changes the
        # compiled program's input placement.
        candidate = self._place(candidate)
        with self._swap_lock:
            from_version = self.version
            self._variables = candidate
            self._active_path = path
            self.version = version
            self.swap_count += 1
        swap_ms = round((time.perf_counter() - t0) * 1e3, 3)
        if self.logger is not None:
            self.logger.log("swap", replica_id=self.replica_id,
                            version=version, from_version=from_version,
                            swap_ms=swap_ms)
        print(f"[serve] hot-swapped params {from_version} -> {version} "
              f"in {swap_ms:.1f} ms (swap #{self.swap_count})")
        return True, "swapped"

    def _reject(self, version: str, reason: str) -> str:
        if self.logger is not None:
            self.logger.log("swap_rejected", replica_id=self.replica_id,
                            version=version, reason=reason)
        print(f"[serve] REJECTED candidate version {version}: {reason} "
              f"(still serving {self.version})")
        return reason

    # --- warmup ---

    def _avals(self, zeros: np.ndarray):
        """Lowering avals for one bucket: (variables?, batch)."""
        import jax

        batch = jax.ShapeDtypeStruct(zeros.shape, zeros.dtype)
        if not self.swappable:
            return (batch,)
        var_avals = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(np.shape(x), x.dtype),
            self._variables)
        return (var_avals, batch)

    def _jitted(self):
        return self._active_fn() if self.swappable else self._fn

    def _warm_bucket(self, b: int) -> None:
        """Obtain bucket ``b``'s executable through the cache (hit =
        deserialized, no XLA compile) or compile it on the call path;
        either way emit one ``compile`` event for the serve log."""
        import jax

        zeros = np.zeros((b, *self.image_shape), np.uint8)
        avals = self._avals(zeros)
        if self.compile_cache is not None \
                and self.compile_cache.degraded():
            # Backend off the executable allowlist: compile on the jit
            # call path (jax's native persistent cache — armed by the
            # CompileCache — makes a restarted server's warmup a disk
            # hit), record the StableHLO entry + event.
            t0 = time.perf_counter()
            self.forward_timed(zeros)
            ev = self.compile_cache.note_degraded(
                self._jitted(), avals, "serve_warmup", {"bucket": b},
                time.perf_counter() - t0)
            self.last_warmup[b] = ev
            return
        if self.compile_cache is not None:
            compiled, ev = self.compile_cache.obtain(
                self._jitted(), avals, "serve_warmup", {"bucket": b})
            if compiled is not None:
                self._bucket_fns[(self._active_path, b)] = compiled
                # One zeros forward through the obtained executable:
                # warms the dispatch/transfer path and proves the
                # deserialized program actually runs before traffic.
                jax.block_until_ready(
                    compiled(self._variables, zeros) if self.swappable
                    else compiled(zeros))
            else:
                # fail-open: the "error" event is already emitted; the
                # plain call-path compile serves this bucket.
                self.forward_timed(zeros)
            self.last_warmup[b] = ev
            return
        t0 = time.perf_counter()
        self.forward_timed(zeros)
        ev = {"key": None, "phase": "serve_warmup", "hit": False,
              "compile_s": round(time.perf_counter() - t0, 4),
              "source": "uncached"}
        if self.logger is not None:
            self.logger.log("compile", **ev)
        self.last_warmup[b] = ev

    def warmup(self, buckets) -> dict:
        """Compile (or cache-load) every bucket size before admitting
        traffic; returns ``{bucket: seconds}`` for the serve log.
        Per-bucket hit/source detail lands in :attr:`last_warmup` and
        as ``compile`` JSONL events."""
        out = {}
        self.last_warmup = {}
        for b in sorted(set(int(b) for b in buckets)):
            t0 = time.perf_counter()
            self._warm_bucket(b)
            out[b] = round(time.perf_counter() - t0, 3)
        return out

    # --- forward ---

    def forward_timed_versioned(self, batch_u8: np.ndarray):
        """``(logits ndarray [B, K], device_seconds, version)`` — the
        version is read under the swap lock TOGETHER with the weights
        that compute this batch, so the tag can never name a version
        other than the one that produced the logits."""
        import jax

        b = int(batch_u8.shape[0])
        if self.swappable:
            with self._swap_lock:
                variables = self._variables
                version = self.version
                path = self._active_path
            fn = self._bucket_fns.get((path, b))
            if fn is None:
                fn = self._jitted_v if path == "primary" \
                    else self._alt_programs[path][0]
            t0 = time.perf_counter()
            out = fn(variables, batch_u8)
            logits = np.asarray(jax.device_get(out))
            return logits, time.perf_counter() - t0, version
        fn = self._bucket_fns.get(("primary", b), self._fn)
        t0 = time.perf_counter()
        logits = np.asarray(jax.device_get(fn(batch_u8)))
        return logits, time.perf_counter() - t0, self.version

    def forward_timed(self, batch_u8: np.ndarray):
        """``(logits ndarray [B, K], device_seconds)`` — the fetch blocks
        until the device result is ready, so the timing covers dispatch +
        execution + transfer (what a request actually waits for)."""
        logits, secs, _ = self.forward_timed_versioned(batch_u8)
        return logits, secs
