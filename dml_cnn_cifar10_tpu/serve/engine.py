"""The device side of serving: one uint8-in/logits-out callable plus
bucket pre-compilation.

Two construction paths, one call contract:

- :meth:`ServingEngine.from_artifact` — deserialize the ``export.py``
  StableHLO artifact (weights embedded, symbolic batch dim, raw-uint8
  input with the eval decode compiled in). The input image geometry is
  read back out of the artifact's own avals, so a server needs no
  ``DataConfig`` to validate requests against it.
- :meth:`ServingEngine.from_params` — wrap live params through
  :func:`~dml_cnn_cifar10_tpu.export.make_serving_fn` (identical
  semantics to what export would serialize; the no-artifact dev loop).

Either way the callable is jitted, so each distinct batch size compiles
exactly once. That is why the batcher quantizes to a fixed bucket set
(:meth:`warmup` pre-compiles them all before traffic): an unquantized
batcher would recompile on every new fill level and the first request at
each level would eat a multi-second compile in its latency.
"""

from __future__ import annotations

import time
from typing import Any, Optional, Tuple

import numpy as np


class ServingEngine:
    """Uint8 image batches in, numpy logits out, with device timing.

    ``fn`` maps ``uint8 [B, H, W, C] -> logits [B, K]``; ``image_shape``
    is the per-request ``(H, W, C)`` contract the batcher validates and
    pads against.
    """

    def __init__(self, fn, image_shape: Tuple[int, int, int],
                 source: str = "live"):
        self._fn = fn
        self.image_shape = tuple(int(d) for d in image_shape)
        self.source = source

    @classmethod
    def from_artifact(cls, path: Optional[str] = None,
                      blob: Optional[bytes] = None) -> "ServingEngine":
        """Engine over a serialized ``export.py`` artifact (file path or
        raw bytes). Self-contained: weights, decode, and input geometry
        all come from the artifact."""
        import jax

        from dml_cnn_cifar10_tpu import export as export_lib

        if (path is None) == (blob is None):
            raise ValueError("pass exactly one of path= or blob=")
        if path is not None:
            with open(path, "rb") as f:
                blob = f.read()
        exported = export_lib.deserialize_exported(blob)
        shape = export_lib.artifact_image_shape(exported)
        return cls(jax.jit(exported.call), shape,
                   source=path or "<artifact bytes>")

    @classmethod
    def from_params(cls, model_def, model_cfg, data_cfg, params: Any,
                    model_state: Any = None) -> "ServingEngine":
        """Engine over live params — the same eval forward export.py
        would serialize, without the serialize/deserialize round trip."""
        import jax

        from dml_cnn_cifar10_tpu.export import make_serving_fn

        fn = jax.jit(make_serving_fn(model_def, model_cfg, data_cfg,
                                     params, model_state))
        return cls(fn, (data_cfg.image_height, data_cfg.image_width,
                        data_cfg.num_channels))

    def warmup(self, buckets) -> dict:
        """Compile every bucket size before admitting traffic (zeros
        input); returns ``{bucket: compile_seconds}`` for the serve log."""
        out = {}
        for b in sorted(set(int(b) for b in buckets)):
            t0 = time.perf_counter()
            self.forward_timed(np.zeros((b, *self.image_shape), np.uint8))
            out[b] = round(time.perf_counter() - t0, 3)
        return out

    def forward_timed(self, batch_u8: np.ndarray):
        """``(logits ndarray [B, K], device_seconds)`` — the fetch blocks
        until the device result is ready, so the timing covers dispatch +
        execution + transfer (what a request actually waits for)."""
        import jax

        t0 = time.perf_counter()
        logits = np.asarray(jax.device_get(self._fn(batch_u8)))
        return logits, time.perf_counter() - t0
