"""The device side of serving: one uint8-in/logits-out callable plus
bucket pre-compilation.

Two construction paths, one call contract:

- :meth:`ServingEngine.from_artifact` — deserialize the ``export.py``
  StableHLO artifact (weights embedded, symbolic batch dim, raw-uint8
  input with the eval decode compiled in). The input image geometry is
  read back out of the artifact's own avals, so a server needs no
  ``DataConfig`` to validate requests against it.
- :meth:`ServingEngine.from_params` — wrap live params through
  :func:`~dml_cnn_cifar10_tpu.export.make_serving_fn` (identical
  semantics to what export would serialize; the no-artifact dev loop).

Either way the callable is jitted, so each distinct batch size compiles
exactly once. That is why the batcher quantizes to a fixed bucket set
(:meth:`warmup` pre-compiles them all before traffic): an unquantized
batcher would recompile on every new fill level and the first request at
each level would eat a multi-second compile in its latency.

With a :class:`~dml_cnn_cifar10_tpu.compilecache.CompileCache` armed
(``--compile_cache_dir``), the per-bucket warmup compiles persist across
process restarts: a redeployed/recovered server warm-starts its bucket
programs from the cache (jax's native persistent cache by default;
deserialized executables on opted-in backends), so time-to-ready drops
from one XLA compile per bucket to one disk load per bucket. Warmup
always emits one ``compile`` JSONL event per bucket (key null when
uncached) so the serving section of ``tools/telemetry_report.py`` can
price the warmup.
"""

from __future__ import annotations

import time
from typing import Any, Optional, Tuple

import numpy as np


class ServingEngine:
    """Uint8 image batches in, numpy logits out, with device timing.

    ``fn`` maps ``uint8 [B, H, W, C] -> logits [B, K]``; ``image_shape``
    is the per-request ``(H, W, C)`` contract the batcher validates and
    pads against. ``compile_cache``/``logger`` arm the persistent
    warmup path described in the module docstring.
    """

    def __init__(self, fn, image_shape: Tuple[int, int, int],
                 source: str = "live", compile_cache=None, logger=None):
        self._fn = fn
        self.image_shape = tuple(int(d) for d in image_shape)
        self.source = source
        self.compile_cache = compile_cache
        self.logger = logger
        # bucket size -> AOT executable obtained through the cache;
        # forward_timed prefers these, falling back to the jitted fn
        # for sizes the warmup never saw.
        self._bucket_fns = {}
        #: last warmup's {bucket: event dict} (hit/source/compile_s).
        self.last_warmup: dict = {}

    @classmethod
    def from_artifact(cls, path: Optional[str] = None,
                      blob: Optional[bytes] = None,
                      compile_cache=None, logger=None) -> "ServingEngine":
        """Engine over a serialized ``export.py`` artifact (file path or
        raw bytes). Self-contained: weights, decode, and input geometry
        all come from the artifact."""
        import jax

        from dml_cnn_cifar10_tpu import export as export_lib

        if (path is None) == (blob is None):
            raise ValueError("pass exactly one of path= or blob=")
        if path is not None:
            with open(path, "rb") as f:
                blob = f.read()
        exported = export_lib.deserialize_exported(blob)
        shape = export_lib.artifact_image_shape(exported)
        return cls(jax.jit(exported.call), shape,
                   source=path or "<artifact bytes>",
                   compile_cache=compile_cache, logger=logger)

    @classmethod
    def from_params(cls, model_def, model_cfg, data_cfg, params: Any,
                    model_state: Any = None, compile_cache=None,
                    logger=None) -> "ServingEngine":
        """Engine over live params — the same eval forward export.py
        would serialize, without the serialize/deserialize round trip."""
        import jax

        from dml_cnn_cifar10_tpu.export import make_serving_fn

        fn = jax.jit(make_serving_fn(model_def, model_cfg, data_cfg,
                                     params, model_state))
        return cls(fn, (data_cfg.image_height, data_cfg.image_width,
                        data_cfg.num_channels),
                   compile_cache=compile_cache, logger=logger)

    def _warm_bucket(self, b: int) -> None:
        """Obtain bucket ``b``'s executable through the cache (hit =
        deserialized, no XLA compile) or compile it on the call path;
        either way emit one ``compile`` event for the serve log."""
        import jax

        zeros = np.zeros((b, *self.image_shape), np.uint8)
        if self.compile_cache is not None \
                and self.compile_cache.degraded():
            # Backend off the executable allowlist: compile on the jit
            # call path (jax's native persistent cache — armed by the
            # CompileCache — makes a restarted server's warmup a disk
            # hit), record the StableHLO entry + event.
            t0 = time.perf_counter()
            self.forward_timed(zeros)
            ev = self.compile_cache.note_degraded(
                self._fn,
                (jax.ShapeDtypeStruct(zeros.shape, zeros.dtype),),
                "serve_warmup", {"bucket": b},
                time.perf_counter() - t0)
            self.last_warmup[b] = ev
            return
        if self.compile_cache is not None:
            compiled, ev = self.compile_cache.obtain(
                self._fn, (jax.ShapeDtypeStruct(zeros.shape, zeros.dtype),),
                "serve_warmup", {"bucket": b})
            if compiled is not None:
                self._bucket_fns[b] = compiled
                # One zeros forward through the obtained executable:
                # warms the dispatch/transfer path and proves the
                # deserialized program actually runs before traffic.
                jax.block_until_ready(compiled(zeros))
            else:
                # fail-open: the "error" event is already emitted; the
                # plain call-path compile serves this bucket.
                self.forward_timed(zeros)
            self.last_warmup[b] = ev
            return
        t0 = time.perf_counter()
        self.forward_timed(zeros)
        ev = {"key": None, "phase": "serve_warmup", "hit": False,
              "compile_s": round(time.perf_counter() - t0, 4),
              "source": "uncached"}
        if self.logger is not None:
            self.logger.log("compile", **ev)
        self.last_warmup[b] = ev

    def warmup(self, buckets) -> dict:
        """Compile (or cache-load) every bucket size before admitting
        traffic; returns ``{bucket: seconds}`` for the serve log.
        Per-bucket hit/source detail lands in :attr:`last_warmup` and
        as ``compile`` JSONL events."""
        out = {}
        self.last_warmup = {}
        for b in sorted(set(int(b) for b in buckets)):
            t0 = time.perf_counter()
            self._warm_bucket(b)
            out[b] = round(time.perf_counter() - t0, 3)
        return out

    def forward_timed(self, batch_u8: np.ndarray):
        """``(logits ndarray [B, K], device_seconds)`` — the fetch blocks
        until the device result is ready, so the timing covers dispatch +
        execution + transfer (what a request actually waits for)."""
        import jax

        fn = self._bucket_fns.get(int(batch_u8.shape[0]), self._fn)
        t0 = time.perf_counter()
        logits = np.asarray(jax.device_get(fn(batch_u8)))
        return logits, time.perf_counter() - t0
