"""The ``--mode serve`` runtime: engine + batcher behind a stdlib HTTP
front end, with periodic telemetry flushes.

Deliberately minimal transport — ``http.server.ThreadingHTTPServer`` is
in the standard library, one thread per connection, and every request
thread just parks on a batcher future (the real concurrency limit is
the bucket size, not the thread count). The endpoints:

- ``POST /predict`` — body is one raw image: exactly ``H*W*C`` bytes of
  uint8 (the CIFAR on-disk pixel layout, row-major HWC). Response JSON:
  ``{"class": argmax, "logits": [...]}``. 503 with a reason on shed.
- ``GET /stats`` — cumulative :class:`ServeMetrics` snapshot as JSON.
- ``GET /healthz`` — liveness + the engine's input contract.
- ``GET /metrics`` — the process-local registry in Prometheus text
  exposition (``utils/metrics_registry.py``): live qps/latency/shed
  gauges + counters fed by the same ``serve`` window records the JSONL
  stream carries, plus the serving latency histogram.

Artifact resolution for :func:`main_serve`: an explicit
``serve.artifact_path`` must exist (fail loudly — a typo'd path
silently falling back to fresh weights would serve garbage); otherwise
the default export location ``<log_dir>/model.jaxexport`` is used when
present, else the latest checkpoint is restored and served live.
"""

from __future__ import annotations

import json
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from dml_cnn_cifar10_tpu.serve.batcher import MicroBatcher, ShedError
from dml_cnn_cifar10_tpu.serve.engine import ServingEngine
from dml_cnn_cifar10_tpu.serve.metrics import ServeMetrics
from dml_cnn_cifar10_tpu.utils import reqtrace


def _make_handler(batcher: MicroBatcher, metrics: ServeMetrics,
                  replica_id: int = 0, hop: str = "server",
                  logger=None, sample_rate: float = 0.0, cache=None):
    image_bytes = 1
    for d in batcher.engine.image_shape:
        image_bytes *= d
    started_at = time.time()

    class Handler(BaseHTTPRequestHandler):
        def _reply(self, code: int, payload: dict) -> None:
            body = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _reply_text(self, code: int, text: str) -> None:
            body = text.encode()
            self.send_response(code)
            self.send_header("Content-Type",
                             "text/plain; version=0.0.4")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):  # access log -> metrics, not stderr
            pass

        def do_GET(self):
            if self.path == "/metrics":
                from dml_cnn_cifar10_tpu.utils.metrics_registry import \
                    default_registry
                self._reply_text(200, default_registry().render())
            elif self.path == "/healthz":
                # Everything a fleet router (or a human with curl)
                # needs to judge this worker without submitting
                # inference traffic: identity, the weights version it
                # serves, current backpressure, and age.
                self._reply(200, {
                    "ok": True,
                    "replica_id": replica_id,
                    "version": getattr(batcher.engine, "version", None),
                    "queue_depth": batcher.queue_depth(),
                    "uptime_s": round(time.time() - started_at, 3),
                    "image_shape": batcher.engine.image_shape,
                    "buckets": batcher.buckets})
            elif self.path == "/stats":
                self._reply(200, metrics.cumulative())
            else:
                self._reply(404, {"error": f"no route {self.path}"})

        def do_POST(self):
            import numpy as np
            if self.path != "/predict":
                self._reply(404, {"error": f"no route {self.path}"})
                return
            n = int(self.headers.get("Content-Length", 0))
            body = self.rfile.read(n)
            if len(body) != image_bytes:
                self._reply(400, {
                    "error": f"expected {image_bytes} raw uint8 bytes "
                             f"(HWC {batcher.engine.image_shape}), "
                             f"got {len(body)}"})
                return
            # Response cache probe BEFORE the batcher: an exact hit
            # under the current serving version answers immediately
            # (no queue, no device). The cache self-flushes on any
            # version change, so a hot-swap can never serve stale.
            if cache is not None:
                hit = cache.lookup(
                    body, getattr(batcher.engine, "version", ""))
                if hit is not None:
                    metrics.record_cache_hit()
                    self._reply(200, hit)
                    return
            image = np.frombuffer(body, np.uint8).reshape(
                batcher.engine.image_shape)
            # Adopt the caller's trace context (or become the trace
            # root for header-less external callers). The context is
            # shared by reference with the batcher dispatch thread, so
            # a deadline shed there forces this hop's span too.
            ctx = reqtrace.parse(self.headers.get(reqtrace.TRACE_HEADER),
                                 sample_rate)
            # Tenant tier (X-Tier header; 0 = premium, higher = more
            # sheddable): under autopilot tier-shedding, best-effort
            # tiers get an immediate 503 while tier-0 keeps flowing.
            try:
                tier = int(self.headers.get("X-Tier", 0))
            except ValueError:
                tier = 0
            t0 = time.perf_counter()
            try:
                logits = batcher.submit(image, trace=ctx,
                                        tier=tier).result()
            except ShedError as e:
                reqtrace.emit_span(logger, ctx, hop,
                                   time.perf_counter() - t0,
                                   reqtrace.wallclock_at(t0),
                                   status=503, shed=e.reason,
                                   replica_id=replica_id)
                self._reply(503, {"shed": e.reason})
                return
            payload = {"class": int(logits.argmax()),
                       "logits": [float(v) for v in logits]}
            version = getattr(logits, "version", None)
            if version is not None:
                # The weights version that computed THIS response —
                # what makes a hot-swap rollout observable end-to-end.
                payload["version"] = version
                if cache is not None:
                    # Keyed to the version that COMPUTED it; if a swap
                    # landed meanwhile the generation check drops it.
                    cache.store(body, version, payload)
            reqtrace.emit_span(logger, ctx, hop,
                               time.perf_counter() - t0,
                               reqtrace.wallclock_at(t0),
                               status=200, version=version,
                               replica_id=replica_id)
            self._reply(200, payload)

    return Handler


class _MetricsFlusher(threading.Thread):
    """Periodic ``serve`` window records while the server runs — and,
    when an alert engine is attached, its time-window evaluation tick
    (the serving analogue of the trainer's metrics-boundary flush)."""

    def __init__(self, metrics: ServeMetrics, logger, every_s: float,
                 alerts=None):
        super().__init__(name="serve-metrics", daemon=True)
        self._metrics = metrics
        self._logger = logger
        self._every = every_s
        self._alerts = alerts
        self._stop = threading.Event()

    def run(self):
        while not self._stop.wait(self._every):
            self._metrics.emit(self._logger)
            if self._alerts is not None:
                self._alerts.evaluate(emit=self._logger.log)

    def stop(self):
        self._stop.set()


def resolve_engine(cfg, task_index: int = 0, logger=None,
                   replica_id: int = 0) -> ServingEngine:
    """Artifact if configured/present, else live params from the latest
    checkpoint (the same EMA-preferring selection as ``--mode export``).
    ``--compile_cache_dir`` arms the persistent bucket-warmup cache
    (compilecache/): a restarted server deserializes its bucket
    executables instead of recompiling them. Live-params engines are
    versioned with the restored checkpoint step (hot-swappable)."""
    from dml_cnn_cifar10_tpu.compilecache import CompileCache

    cache = CompileCache.from_config(cfg, logger=logger)
    serve_cfg = cfg.serve
    if serve_cfg.quantize == "int8":
        # Quantized serving wants live params (calibration needs the
        # float weights); a float artifact can't be quantized post-hoc.
        if serve_cfg.artifact_path:
            raise SystemExit(
                "--serve_quantize int8 quantizes live checkpoint "
                "params; it cannot combine with --serve_artifact "
                "(export a quantized artifact with --mode export "
                "--serve_quantize int8 and serve that instead)")
        import jax

        # import from the module path: the package re-exports a
        # `calibrate` FUNCTION that shadows the module name
        from dml_cnn_cifar10_tpu.quant.calibrate import (
            calibrate as quant_calibrate, calibration_sets)
        from dml_cnn_cifar10_tpu.train.loop import Trainer
        trainer = Trainer(cfg, task_index=task_index)
        state = trainer.init_or_restore()
        params = state.opt.get("ema", state.params)
        calib, _, _ = calibration_sets(
            cfg.data, 64, serve_cfg.quant_calib_batches, holdout=0)
        scales = quant_calibrate(
            params, calib, cfg.model, cfg.data, batch_size=64,
            num_batches=serve_cfg.quant_calib_batches, logger=logger)
        return ServingEngine.from_params(
            trainer.model_def, cfg.model, cfg.data, params,
            compile_cache=cache, logger=logger,
            version=str(int(jax.device_get(state.step))),
            replica_id=replica_id, quantize="int8", quant_scales=scales)
    if serve_cfg.artifact_path:
        if not os.path.exists(serve_cfg.artifact_path):
            raise SystemExit(
                f"--serve_artifact {serve_cfg.artifact_path} does not "
                f"exist (refusing to fall back to fresh weights)")
        return ServingEngine.from_artifact(serve_cfg.artifact_path,
                                           compile_cache=cache,
                                           logger=logger,
                                           replica_id=replica_id)
    default_artifact = os.path.join(cfg.log_dir, "model.jaxexport")
    if os.path.exists(default_artifact):
        return ServingEngine.from_artifact(default_artifact,
                                           compile_cache=cache,
                                           logger=logger,
                                           replica_id=replica_id)

    import jax

    from dml_cnn_cifar10_tpu.train.loop import Trainer
    trainer = Trainer(cfg, task_index=task_index)
    state = trainer.init_or_restore()
    params = state.opt.get("ema", state.params)
    mstate = state.opt.get("ema_mstate", state.model_state) \
        if trainer.model_def.has_state else None
    return ServingEngine.from_params(
        trainer.model_def, cfg.model, cfg.data, params, mstate,
        compile_cache=cache, logger=logger,
        version=str(int(jax.device_get(state.step))),
        replica_id=replica_id)


def main_serve(cfg, task_index: int = 0,
               ready_event: Optional[threading.Event] = None,
               stop_event: Optional[threading.Event] = None) -> int:
    """Blocking serve loop with graceful SIGTERM/SIGINT drain.

    ``ready_event`` is set once the HTTP socket is listening and all
    buckets are compiled — the hook tests and ``tools/loadgen.py
    --target`` use it to avoid racing the warmup. ``stop_event``
    requests the same graceful shutdown programmatically (tests, and
    any caller not on the main thread, where the signal guard is a
    no-op).

    Shutdown sequence (the managed-pool preemption contract, reusing
    :class:`~dml_cnn_cifar10_tpu.utils.preemption.PreemptionGuard`):
    stop accepting connections, let already-queued batches finish for
    at most ``serve.drain_deadline_s``, shed the remainder, flush the
    final ``serve_done`` metrics record, exit 0.
    """
    from dml_cnn_cifar10_tpu.utils.logging import MetricsLogger
    from dml_cnn_cifar10_tpu.utils.preemption import PreemptionGuard

    serve_cfg = cfg.serve
    # Logger before the engine: bucket warmups emit `compile` JSONL
    # events through it (per-bucket hit/compile_s — the serving
    # section of tools/telemetry_report.py totals them).
    logger = MetricsLogger(jsonl_path=cfg.metrics_jsonl,
                           task_index=task_index)
    # Streaming alerts over the serve windows (shed > 1%, p99 vs
    # --serve_slo_ms, plus any --alert_rules): the engine watches the
    # records this logger writes; the flusher below gives it the
    # periodic time-window tick.
    from dml_cnn_cifar10_tpu.utils import alerts as alerts_lib
    from dml_cnn_cifar10_tpu.utils.flightrec import FlightRecorder
    # Flight recorder BEFORE the alert observer: observers run in
    # attach order, so the record that trips a rule is ringed before
    # the nested `alert` emission triggers the capture. The engine
    # does not exist yet — the context_fn reads it through a holder
    # filled in below.
    holder: dict = {}
    flightrec = FlightRecorder.from_config(
        cfg, context_fn=lambda: {
            "active_version": getattr(holder.get("engine"), "version",
                                      None),
            "replica_id": task_index},
        logger=logger)
    if flightrec is not None:
        logger.add_observer(flightrec.observer())
    alert_engine = alerts_lib.AlertEngine.from_config(cfg)
    if alert_engine is not None:
        logger.add_observer(alert_engine.observer(logger))
    engine = resolve_engine(cfg, task_index, logger=logger)
    holder["engine"] = engine
    metrics = ServeMetrics()
    batcher = MicroBatcher(
        engine, buckets=serve_cfg.buckets,
        max_queue_depth=serve_cfg.max_queue_depth,
        batch_window_s=serve_cfg.batch_window_ms / 1e3,
        default_deadline_s=None if serve_cfg.deadline_ms is None
        else serve_cfg.deadline_ms / 1e3,
        metrics=metrics, logger=logger)
    print(f"[serve] engine={engine.source} image_shape="
          f"{engine.image_shape} buckets={batcher.buckets} "
          f"compile_s={batcher.compile_secs}")

    from dml_cnn_cifar10_tpu.serve.cache import ResponseCache
    response_cache = ResponseCache(serve_cfg.cache_size) \
        if serve_cfg.cache_size > 0 else None
    server = ThreadingHTTPServer(
        ("", serve_cfg.port),
        _make_handler(batcher, metrics, replica_id=task_index,
                      hop="server", logger=logger,
                      sample_rate=serve_cfg.trace_sample_rate,
                      cache=response_cache))
    flusher = _MetricsFlusher(metrics, logger, serve_cfg.metrics_every_s,
                              alerts=alert_engine)
    flusher.start()
    # The accept loop runs on its own thread so the main thread can
    # park on the shutdown signals (signal handlers only fire on the
    # main thread — the exact reason PreemptionGuard exists).
    accept = threading.Thread(target=server.serve_forever,
                              name="serve-accept", daemon=True)
    drained = True
    try:
        with PreemptionGuard() as guard:
            accept.start()
            print(f"[serve] listening on :{server.server_address[1]} "
                  f"(POST /predict, GET /stats, GET /healthz)")
            if ready_event is not None:
                ready_event.set()
            try:
                while not guard.requested and (
                        stop_event is None or not stop_event.is_set()):
                    time.sleep(0.1)
                why = (f"signal {guard.signum}" if guard.requested
                       else "stop requested")
            except KeyboardInterrupt:
                why = "keyboard interrupt"
            print(f"[serve] {why}: draining in-flight batches "
                  f"(deadline {serve_cfg.drain_deadline_s:.1f}s)")
            server.shutdown()          # stop accepting; accept loop exits
            accept.join()
            drained = batcher.drain(timeout=serve_cfg.drain_deadline_s)
    finally:
        # In-flight handler threads have resolved futures by now (result
        # or ShedError), so the close's thread-join is bounded.
        server.server_close()
        flusher.stop()
        if batcher._worker.is_alive():   # drain never ran (startup crash)
            batcher.close()
        metrics.emit(logger, final=True)
        logger.flush()
        logger.close()
    print(f"[serve] exiting cleanly "
          f"({'drained' if drained else 'drain deadline hit; backlog shed'})")
    return 0
