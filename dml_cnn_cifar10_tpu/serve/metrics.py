"""Serving telemetry: latency percentiles, batch-fill, and shed
accounting on the existing JSONL stream.

The training side answers "where did the wall-clock go?" with goodput
fractions; the serving side's analogue questions are "what did a request
wait for?" (queue vs device) and "is the batcher earning its keep?"
(batch-fill fraction) and "is admission control shedding instead of
collapsing?" (shed counts). One :class:`ServeMetrics` instance is shared
by the batcher's worker thread and every client thread, so all mutation
is under one lock; :meth:`emit` writes ``serve`` window records and a
final ``serve_done`` cumulative record through the same
``MetricsLogger`` the trainer uses — ``tools/check_jsonl_schema.py``
lints them and ``tools/telemetry_report.py`` summarizes them alongside
training runs (schema: ``docs/SERVING.md``).
"""

from __future__ import annotations

import threading
import time
from typing import Optional

from dml_cnn_cifar10_tpu.utils.metrics_registry import default_registry
from dml_cnn_cifar10_tpu.utils.telemetry import latency_summary, percentile


class _Window:
    """One accumulation window's raw samples (no derived stats)."""

    __slots__ = ("submitted", "completed", "shed_queue", "shed_deadline",
                 "cache_hits", "latencies", "queue_waits", "device_secs",
                 "fills", "batches", "t0")

    def __init__(self):
        self.submitted = 0
        self.completed = 0
        self.shed_queue = 0
        self.shed_deadline = 0
        self.cache_hits = 0
        self.latencies = []       # submit -> result, seconds
        self.queue_waits = []     # submit -> dispatch start, seconds
        self.device_secs = []     # per batch
        self.fills = []           # real_rows / bucket per batch
        self.batches = 0
        self.t0 = time.perf_counter()


class ServeMetrics:
    """Thread-safe serving counters with windowed + cumulative views."""

    def __init__(self):
        self._lock = threading.Lock()
        self._win = _Window()
        self._total = _Window()

    # --- recording (called from client + worker threads) ---

    def record_submit(self) -> None:
        with self._lock:
            self._win.submitted += 1
            self._total.submitted += 1

    def record_cache_hit(self) -> None:
        """A request answered from the response cache — it bypassed the
        batcher, so it appears in ``cache_hit`` ONLY (not in
        requests/completed, which count batcher traffic)."""
        with self._lock:
            for w in (self._win, self._total):
                w.cache_hits += 1

    def record_shed(self, reason: str) -> None:
        field = "shed_queue" if reason == "queue_full" else "shed_deadline"
        with self._lock:
            for w in (self._win, self._total):
                setattr(w, field, getattr(w, field) + 1)

    def record_batch(self, bucket: int, n_real: int,
                     device_s: float) -> None:
        with self._lock:
            for w in (self._win, self._total):
                w.batches += 1
                w.device_secs.append(device_s)
                w.fills.append(n_real / bucket)

    def record_done(self, latency_s: float, queue_wait_s: float) -> None:
        with self._lock:
            for w in (self._win, self._total):
                w.completed += 1
                w.latencies.append(latency_s)
                w.queue_waits.append(queue_wait_s)
        # Live-export histogram (GET /metrics): the windowed JSONL
        # records carry percentiles only — a Prometheus consumer wants
        # the raw distribution. Host-side dict work per completion.
        default_registry().histogram(
            "dml_serve_latency_ms",
            "End-to-end request latency (submit -> result)"
        ).observe(latency_s * 1e3)

    # --- reporting ---

    @staticmethod
    def _snapshot(w: _Window, now: float) -> dict:
        span = max(now - w.t0, 1e-9)
        lat = latency_summary(w.latencies)
        qw50 = percentile(w.queue_waits, 50)
        dev50 = percentile(w.device_secs, 50)
        dev99 = percentile(w.device_secs, 99)
        return {
            "requests": w.submitted,
            "completed": w.completed,
            "shed_queue": w.shed_queue,
            "shed_deadline": w.shed_deadline,
            "cache_hit": w.cache_hits,
            "qps": round(w.completed / span, 2),
            "p50_ms": lat["p50_ms"],
            "p95_ms": lat["p95_ms"],
            "p99_ms": lat["p99_ms"],
            "max_ms": lat["max_ms"],
            "queue_wait_p50_ms":
                None if qw50 is None else round(qw50 * 1e3, 3),
            "device_p50_ms":
                None if dev50 is None else round(dev50 * 1e3, 3),
            "device_p99_ms":
                None if dev99 is None else round(dev99 * 1e3, 3),
            "batches": w.batches,
            "batch_fill":
                round(sum(w.fills) / len(w.fills), 4) if w.fills else None,
            "window_s": round(span, 3),
        }

    def recent_device_ms(self) -> Optional[float]:
        """Median per-batch DEVICE milliseconds over the recent batches
        (current window, falling back to run lifetime) — the serving
        analogue of the trainer's ``device_step_ms``, advertised in
        fleet heartbeats so the router/autoscaler can tell a slow
        device from a deep queue. ``None`` before the first batch."""
        with self._lock:
            vals = (self._win.device_secs or self._total.device_secs)[-64:]
        p = percentile(vals, 50)
        return None if p is None else round(p * 1e3, 3)

    def window(self, reset: bool = True) -> dict:
        """Stats since the last window reset (the periodic serve record)."""
        with self._lock:
            out = self._snapshot(self._win, time.perf_counter())
            if reset:
                self._win = _Window()
        return out

    def cumulative(self) -> dict:
        """Run-lifetime stats (the ``serve_done`` / report payload)."""
        with self._lock:
            out = self._snapshot(self._total, time.perf_counter())
        total = (out["completed"] + out["shed_queue"]
                 + out["shed_deadline"])
        out["shed_fraction"] = round(
            (out["shed_queue"] + out["shed_deadline"]) / total, 4) \
            if total else 0.0
        return out

    def emit(self, logger, final: bool = False) -> None:
        """Write one ``serve`` window record (and, when ``final``, the
        cumulative ``serve_done``) through ``MetricsLogger``."""
        if logger is None:
            return
        # wallclock: serve-only streams have no heartbeat records, so
        # these windows are the clock-alignment anchor that lets
        # tools/trace_aggregate.py place this stream on the merged
        # timeline.
        logger.log("serve", **self.window(reset=True),
                   wallclock=time.time())
        if final:
            done = self.cumulative()
            done["total_s"] = done.pop("window_s")
            logger.log("serve_done", **done, wallclock=time.time())
