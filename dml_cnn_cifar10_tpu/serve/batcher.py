"""Dynamic micro-batcher: single-image requests → padded bucket batches.

The serving problem on an accelerator is the mismatch between the
request arrival unit (one image) and the efficient execution unit (a
large batch): dispatching batch-1 forwards wastes the MXU, but waiting
to fill a big batch wastes latency. The classic answer — TF-Serving's
dynamic batching, here rebuilt JAX-native — is a short coalescing
window over a thread-safe queue:

- Clients :meth:`MicroBatcher.submit` one image and get a
  ``concurrent.futures.Future`` of its logits row.
- A single worker thread dequeues a batch: it takes the first waiting
  request, then keeps collecting until either the largest bucket is
  full or ``batch_window_s`` has elapsed — so under load batches are
  full (no added latency), and when idle a lone request waits at most
  one window.
- The batch is padded up to the SMALLEST PRE-COMPILED BUCKET that fits
  (e.g. 1/8/32/128). Buckets exist because the engine jit-compiles per
  concrete shape: without quantization every new fill level would eat a
  fresh XLA compile mid-traffic. Pad lanes are zeros; rows are computed
  independently by the eval forward, and only the first ``n_real`` rows
  are scattered back to futures, so padding can never leak into a real
  response (pinned by ``tests/test_serve.py``).

Overload policy is shed, don't collapse: admission control bounds the
queue (``submit`` raises :class:`ShedError` when it is full — the
client gets an immediate reject instead of unbounded latency), and each
request may carry a deadline — requests whose deadline passed while
queued fail with :class:`ShedError` at dispatch time rather than
occupying device lanes nobody is waiting for.
"""

from __future__ import annotations

import os
import queue
import threading
import time
from concurrent.futures import Future
from typing import Optional, Sequence

import numpy as np

from dml_cnn_cifar10_tpu.serve.engine import ServingEngine
from dml_cnn_cifar10_tpu.serve.metrics import ServeMetrics
from dml_cnn_cifar10_tpu.utils import reqtrace


class ShedError(RuntimeError):
    """Request shed by admission control (``queue_full``), deadline
    expiry (``deadline``), or server shutdown (``shutdown``)."""

    def __init__(self, reason: str):
        super().__init__(f"request shed: {reason}")
        self.reason = reason


class VersionedLogits(np.ndarray):
    """A logits row tagged with the model ``version`` that computed it.

    Still a plain ndarray for every numeric purpose (tests and clients
    that ignore versions keep working); the tag is what lets the HTTP
    front end put ``"version"`` in each response, making a checkpoint
    hot-swap observable end-to-end (docs/SERVING.md fleet section)."""

    version: Optional[str] = None


def _versioned_row(row, version) -> VersionedLogits:
    out = np.array(row).view(VersionedLogits)
    out.version = version
    return out


class _Request:
    __slots__ = ("image", "future", "t_enqueue", "deadline", "trace",
                 "tier")

    def __init__(self, image, future, t_enqueue, deadline, trace=None,
                 tier=0):
        self.image = image
        self.future = future
        self.t_enqueue = t_enqueue
        self.deadline = deadline
        self.trace = trace
        self.tier = tier


class MicroBatcher:
    """Thread-safe coalescing request queue in front of a
    :class:`ServingEngine`.

    ``buckets`` must be ascending positive batch sizes; the largest is
    the max batch per dispatch. ``batch_window_s`` is the maximum extra
    latency coalescing may add to the request at the head of a batch.
    ``default_deadline_s`` (None = no deadline) applies to submits that
    don't carry their own.
    """

    def __init__(self, engine: ServingEngine,
                 buckets: Sequence[int] = (1, 8, 32, 128),
                 max_queue_depth: int = 256,
                 batch_window_s: float = 0.002,
                 default_deadline_s: Optional[float] = None,
                 metrics: Optional[ServeMetrics] = None,
                 warmup: bool = True,
                 logger=None):
        bs = [int(b) for b in buckets]
        if not bs or any(b <= 0 for b in bs) or sorted(set(bs)) != bs:
            raise ValueError(
                f"buckets must be ascending positive ints, got {buckets}")
        self.engine = engine
        self.buckets = tuple(bs)
        self.batch_window_s = float(batch_window_s)
        self.default_deadline_s = default_deadline_s
        self.metrics = metrics if metrics is not None else ServeMetrics()
        self.logger = logger
        self._q: "queue.Queue[_Request]" = queue.Queue(
            maxsize=int(max_queue_depth))
        self._stop = threading.Event()
        # Tier-by-tenant load shedding (the autopilot's scale_up_shed
        # action flips this): None = admit every tier.
        self._shed_tier: Optional[int] = None
        if warmup:
            self.compile_secs = engine.warmup(self.buckets)
        else:
            self.compile_secs = {}
        self._worker = threading.Thread(target=self._run,
                                        name="microbatcher", daemon=True)
        self._worker.start()

    # --- client side ---

    def submit(self, image: np.ndarray,
               deadline_s: Optional[float] = None,
               trace: Optional[reqtrace.TraceContext] = None,
               tier: int = 0) -> Future:
        """Enqueue one ``uint8 [H, W, C]`` image; returns a Future of
        its ``[K]`` logits row. Raises :class:`ShedError` immediately
        when the queue is at depth (admission control), the request's
        ``tier`` is being shed (:meth:`set_shed_tier`), or the server
        is stopping. ``trace`` is the request's trace context; sheds
        force it so the interesting requests appear even at sample
        rate 0. ``tier`` 0 is the premium tenant class; higher tiers
        are more sheddable."""
        image = np.asarray(image)
        if image.shape != self.engine.image_shape \
                or image.dtype != np.uint8:
            raise ValueError(
                f"expected uint8 image of shape {self.engine.image_shape}, "
                f"got {image.dtype} {image.shape}")
        if self._stop.is_set():
            raise ShedError("shutdown")
        now = time.perf_counter()
        shed_at = self._shed_tier
        if shed_at is not None and int(tier) >= shed_at:
            self.metrics.record_shed("tier")
            if trace is not None:
                trace.force()
                reqtrace.emit_span(self.logger, trace, "batcher", 0.0,
                                   reqtrace.wallclock_at(now),
                                   shed="tier")
            raise ShedError("tier")
        dl = deadline_s if deadline_s is not None else self.default_deadline_s
        req = _Request(image, Future(), now,
                       None if dl is None else now + dl, trace,
                       tier=int(tier))
        try:
            self._q.put_nowait(req)
        except queue.Full:
            self.metrics.record_shed("queue_full")
            if trace is not None:
                trace.force()
                reqtrace.emit_span(self.logger, trace, "batcher", 0.0,
                                   reqtrace.wallclock_at(now),
                                   shed="queue_full")
            raise ShedError("queue_full") from None
        self.metrics.record_submit()
        return req.future

    def set_shed_tier(self, tier: Optional[int]) -> None:
        """Tier-by-tenant load shedding: admission-reject every request
        whose ``tier`` is >= ``tier`` (so ``1`` sheds all best-effort
        traffic while tier-0 premium requests keep flowing). ``None``
        disables. The autopilot's ``scale_up_shed`` action is the
        canonical caller; thread-safe (a single attribute write)."""
        self._shed_tier = None if tier is None else int(tier)

    def shed_tier(self) -> Optional[int]:
        """The active shed threshold, or None when every tier admits."""
        return self._shed_tier

    def queue_depth(self) -> int:
        """Requests currently waiting (approximate — the queue is live).
        Published in fleet heartbeats and ``/healthz`` so the router and
        autoscaler can see backpressure without submitting traffic."""
        return self._q.qsize()

    def close(self, drain: bool = True) -> None:
        """Stop admitting; by default let the worker drain what is
        already queued, otherwise fail queued requests with
        ``ShedError("shutdown")``."""
        self._stop.set()
        if not drain:
            while True:
                try:
                    req = self._q.get_nowait()
                except queue.Empty:
                    break
                self.metrics.record_shed("shutdown")
                req.future.set_exception(ShedError("shutdown"))
        self._worker.join()

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Graceful-shutdown close: stop admitting, let already-queued
        batches finish for at most ``timeout`` seconds, then shed
        whatever is still waiting. Returns True when everything queued
        completed inside the deadline. The queue hand-off is race-free:
        each request is popped by exactly one side (worker dispatch or
        the shed sweep), so no future resolves twice."""
        self._stop.set()
        self._worker.join(timeout)
        if not self._worker.is_alive():
            return True
        self.close(drain=False)
        return False

    def __enter__(self) -> "MicroBatcher":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # --- worker side ---

    def _pick_bucket(self, n: int) -> int:
        for b in self.buckets:
            if b >= n:
                return b
        return self.buckets[-1]

    def _collect(self):
        """One batch's worth of requests: first request (blocking poll),
        then coalesce until the largest bucket fills or the window
        closes."""
        try:
            first = self._q.get(timeout=0.05)
        except queue.Empty:
            return []
        batch = [first]
        t_close = time.perf_counter() + self.batch_window_s
        while len(batch) < self.buckets[-1]:
            remaining = t_close - time.perf_counter()
            if remaining <= 0:
                # Past the window, still take whatever is already queued
                # (free fill, no extra wait).
                try:
                    batch.append(self._q.get_nowait())
                    continue
                except queue.Empty:
                    break
            try:
                batch.append(self._q.get(timeout=remaining))
            except queue.Empty:
                break
        return batch

    def _dispatch(self, batch) -> None:
        t_start = time.perf_counter()
        live = []
        for r in batch:
            if r.deadline is not None and t_start > r.deadline:
                self.metrics.record_shed("deadline")
                if r.trace is not None:
                    r.trace.force()
                    reqtrace.emit_span(
                        self.logger, r.trace, "batcher",
                        t_start - r.t_enqueue,
                        reqtrace.wallclock_at(r.t_enqueue),
                        shed="deadline")
                r.future.set_exception(ShedError("deadline"))
            else:
                live.append(r)
        if not live:
            return
        bucket = self._pick_bucket(len(live))
        padded = np.zeros((bucket, *self.engine.image_shape), np.uint8)
        for i, r in enumerate(live):
            padded[i] = r.image
        try:
            # Engines expose the versioned forward so each response can
            # carry the exact weights version that computed it (hot-swap
            # observability); plain engines/stubs fall back to the
            # 2-tuple contract with their static version attribute.
            fwd = getattr(self.engine, "forward_timed_versioned", None)
            if fwd is not None:
                logits, device_s, version = fwd(padded)
            else:
                logits, device_s = self.engine.forward_timed(padded)
                version = getattr(self.engine, "version", None)
        except Exception as e:                    # pragma: no cover
            # A device failure must not strand clients on futures that
            # never resolve.
            for r in live:
                r.future.set_exception(e)
            return
        self.metrics.record_batch(bucket, len(live), device_s)
        t_done = time.perf_counter()
        emitting = [r for r in live
                    if r.trace is not None and r.trace.emit]
        if emitting and self.logger is not None:
            # One batch span causally linked (via batch_id) to its N
            # member spans: the coalescing penalty each member paid in
            # the queue is visible per request, while the batch span
            # carries the shared device context once.
            batch_id = os.urandom(4).hex()
            reqtrace.emit_span(
                self.logger,
                reqtrace.TraceContext(batch_id, True), "batch",
                t_done - t_start, reqtrace.wallclock_at(t_start),
                n=len(live), bucket=bucket,
                device_ms=round(device_s * 1e3, 3), version=version)
            for r in emitting:
                reqtrace.emit_span(
                    self.logger, r.trace, "batcher",
                    t_start - r.t_enqueue,
                    reqtrace.wallclock_at(r.t_enqueue),
                    batch_id=batch_id, version=version)
                reqtrace.emit_span(
                    self.logger, r.trace, "engine", device_s,
                    reqtrace.wallclock_at(t_start),
                    batch_id=batch_id, version=version)
        for i, r in enumerate(live):
            self.metrics.record_done(t_done - r.t_enqueue,
                                     t_start - r.t_enqueue)
            r.future.set_result(_versioned_row(logits[i], version))

    def _run(self) -> None:
        while True:
            batch = self._collect()
            if batch:
                self._dispatch(batch)
            elif self._stop.is_set():
                return
