"""Core layer primitives: init schemes + conv/pool/dense on XLA.

Parity notes (all against ``/root/reference/cifar10cnn.py``):
- ``truncated_normal_init`` == ``tf.truncated_normal_initializer(stddev=0.05)``
  (``:97-98``): normal samples truncated to ±2σ (resampled, not clipped),
  NOT variance-rescaled — ``jax.random.truncated_normal`` has exactly these
  semantics.
- ``bias_init`` == ``tf.constant_initializer(0.1)`` (``:100-101``).
- ``conv2d`` == ``tf.nn.conv2d(..., strides=[1,1,1,1], padding='SAME')``
  (``:107,118``) in NHWC/HWIO layout.
- ``max_pool`` == ``tf.nn.max_pool(ksize=[1,3,3,1], strides=[1,2,2,1],
  'SAME')`` (``:113,123``): overlapping 3×3/2 windows, -inf padding.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax


def truncated_normal_init(key, shape, stddev: float = 0.05,
                          dtype=jnp.float32) -> jax.Array:
    """Truncated-normal (±2σ) init, TF-compatible (no rescaling)."""
    return stddev * jax.random.truncated_normal(key, -2.0, 2.0, shape,
                                                dtype=dtype)


def bias_init(shape, value: float = 0.1, dtype=jnp.float32) -> jax.Array:
    return jnp.full(shape, value, dtype=dtype)


def conv2d(x: jax.Array, kernel: jax.Array, stride: int = 1,
           padding: str = "SAME") -> jax.Array:
    """NHWC conv with HWIO kernel → NHWC out (MXU-friendly layout on TPU)."""
    return lax.conv_general_dilated(
        x, kernel,
        window_strides=(stride, stride),
        padding=padding,
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )


def max_pool(x: jax.Array, window: int = 3, stride: int = 2,
             padding: str = "SAME") -> jax.Array:
    """Max pool over NHWC spatial dims via ``lax.reduce_window``."""
    return lax.reduce_window(
        x,
        -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else jnp.iinfo(x.dtype).min,
        lax.max,
        window_dimensions=(1, window, window, 1),
        window_strides=(1, stride, stride, 1),
        padding=padding,
    )


def dense(x: jax.Array, w: jax.Array, b: jax.Array) -> jax.Array:
    """x @ w + b — a single MXU matmul; keep inputs 2-D [B, D]."""
    return jnp.dot(x, w) + b


def pooled_hw(h: int, w: int, n_pools: int, window: int = 3,
              stride: int = 2) -> Tuple[int, int]:
    """Spatial dims after ``n_pools`` SAME-padded stride-2 pools (ceil div)."""
    for _ in range(n_pools):
        h = -(-h // stride)
        w = -(-w // stride)
    return h, w
