"""Core layer primitives: init schemes + conv/pool/dense on XLA.

Parity notes (all against ``/root/reference/cifar10cnn.py``):
- ``truncated_normal_init`` == ``tf.truncated_normal_initializer(stddev=0.05)``
  (``:97-98``): normal samples truncated to ±2σ (resampled, not clipped),
  NOT variance-rescaled — ``jax.random.truncated_normal`` has exactly these
  semantics.
- ``bias_init`` == ``tf.constant_initializer(0.1)`` (``:100-101``).
- ``conv2d`` == ``tf.nn.conv2d(..., strides=[1,1,1,1], padding='SAME')``
  (``:107,118``) in NHWC/HWIO layout.
- ``max_pool`` == ``tf.nn.max_pool(ksize=[1,3,3,1], strides=[1,2,2,1],
  'SAME')`` (``:113,123``): overlapping 3×3/2 windows, -inf padding.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax


def truncated_normal_init(key, shape, stddev: float = 0.05,
                          dtype=jnp.float32) -> jax.Array:
    """Truncated-normal (±2σ) init, TF-compatible (no rescaling)."""
    return stddev * jax.random.truncated_normal(key, -2.0, 2.0, shape,
                                                dtype=dtype)


def bias_init(shape, value: float = 0.1, dtype=jnp.float32) -> jax.Array:
    return jnp.full(shape, value, dtype=dtype)


def conv2d(x: jax.Array, kernel: jax.Array, stride: int = 1,
           padding: str = "SAME") -> jax.Array:
    """NHWC conv with HWIO kernel → NHWC out (MXU-friendly layout on TPU)."""
    return lax.conv_general_dilated(
        x, kernel,
        window_strides=(stride, stride),
        padding=padding,
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )


def max_pool(x: jax.Array, window: int = 3, stride: int = 2,
             padding: str = "SAME") -> jax.Array:
    """Max pool over NHWC spatial dims via ``lax.reduce_window``.

    Backward is XLA's select-and-scatter. Round-3 note (BASELINE.md
    ResNet-50 profile): that op is ~5% of the bf16 224² train step, and a
    hand-written 9-shift compare-mask-pad VJP was implemented and
    MEASURED WORSE (-27% step time — the f32 grad accumulator makes 9
    full passes over the 112² activation grid, far more HBM traffic than
    the generic scatter). The default stays; the experiment is recorded
    so it isn't retried blind.
    """
    return lax.reduce_window(
        x,
        -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating)
        else jnp.iinfo(x.dtype).min,
        lax.max,
        window_dimensions=(1, window, window, 1),
        window_strides=(1, stride, stride, 1),
        padding=padding,
    )


def dense(x: jax.Array, w: jax.Array, b: jax.Array) -> jax.Array:
    """x @ w + b — a single MXU matmul; keep inputs 2-D [B, D]."""
    return jnp.dot(x, w) + b


def he_normal_init(key, shape, dtype=jnp.float32) -> jax.Array:
    """He/Kaiming fan-in normal init for conv (HWIO) / dense (IO) weights.

    Used by the ResNet/ViT configs (no reference counterpart — the reference
    model predates normalized init, SURVEY §7 step 6).
    """
    fan_in = int(np.prod(shape[:-1]))
    return jax.random.normal(key, shape, dtype) * jnp.sqrt(2.0 / fan_in)


def batch_norm(
    x: jax.Array,
    params,
    state,
    train: bool,
    momentum: float = 0.9,
    eps: float = 1e-5,
    axis_name=None,
):
    """BatchNorm over NHWC (stats on N,H,W) with running-stat state.

    Cross-replica semantics (SURVEY §2.3): under ``jit`` auto-partitioning
    the batch axis is sharded over ``data`` and the ``jnp.mean`` below is a
    *global* mean — XLA compiles the cross-replica reduction in. Under the
    explicit ``shard_map`` step the batch the kernel sees is the local
    shard, so ``axis_name`` triggers a literal ``lax.pmean`` of the
    sufficient statistics (E[x], E[x²]) — the hand-written form of the same
    collective.

    Returns ``(y, new_state)``; ``new_state`` equals ``state`` in eval.
    The STATISTICS (mean/var, running stats) are computed in f32
    regardless of compute dtype — bf16 batch stats lose too much
    precision — but the per-element normalize runs in ``x.dtype``
    (round 3: BN's epilogue is memory-bound and the f32 upcast doubled
    its HBM traffic; see BASELINE.md's ResNet-50 profile). Output dtype
    == input dtype in train and eval.
    """
    axes = tuple(range(x.ndim - 1))
    if train:
        xf = x.astype(jnp.float32)
        mean = jnp.mean(xf, axes)
        mean_sq = jnp.mean(jnp.square(xf), axes)
        if axis_name is not None:
            mean = lax.pmean(mean, axis_name)
            mean_sq = lax.pmean(mean_sq, axis_name)
        # Clamp: E[x²]−E[x]² can go (slightly) negative from f32
        # cancellation when mean² >> var (e.g. raw 0..255 faithful-mode
        # pixels), and rsqrt would NaN.
        var = jnp.maximum(mean_sq - jnp.square(mean), 0.0)
        new_state = {
            "mean": momentum * state["mean"] + (1.0 - momentum) * mean,
            "var": momentum * state["var"] + (1.0 - momentum) * var,
        }
    else:
        mean, var = state["mean"], state["var"]
        new_state = state
    inv = lax.rsqrt(var + eps) * params["scale"].astype(jnp.float32)
    # Normalize in the COMPUTE dtype: the statistics stay f32 (above —
    # bf16 batch stats lose too much precision) but the per-element
    # normalize chain runs at the activation width. BN's epilogue is
    # memory-bound, so in bf16 this halves its HBM traffic; for f32
    # activations the casts are no-ops and the math is unchanged.
    cdt = x.dtype
    y = (x - mean.astype(cdt)) * inv.astype(cdt) \
        + params["offset"].astype(cdt)
    return y, new_state


def bn_init(width: int, dtype=jnp.float32):
    """Params for one BatchNorm layer. The running-stat state pytree is
    derived structurally from the params (``resnet.init_state``) — one
    source of truth for its shape/dtype."""
    return {"scale": jnp.ones((width,), dtype),
            "offset": jnp.zeros((width,), dtype)}


def pooled_hw(h: int, w: int, n_pools: int, window: int = 3,
              stride: int = 2) -> Tuple[int, int]:
    """Spatial dims after ``n_pools`` SAME-padded stride-2 pools (ceil div)."""
    for _ in range(n_pools):
        h = -(-h // stride)
        w = -(-w // stride)
    return h, w
