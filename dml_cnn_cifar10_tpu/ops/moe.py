"""Mixture-of-Experts MLP — expert parallelism over the ``model`` mesh axis.

No reference counterpart (SURVEY §2.3: expert parallelism absent), built
TPU-first as the framework's ``ep`` capability:

- **Switch-style top-1 / GShard-style top-2 routing** with a **static
  capacity**: every shape is known at trace time (tokens = B*S, capacity =
  ceil(T/E · factor · k)), so the whole layer is dense einsums XLA can
  tile onto the MXU — no dynamic gather/scatter, no data-dependent shapes
  (the TPU-idiomatic formulation from the Switch/GShard line of work).
- **Dispatch/combine as one-hot einsum contractions**: routing becomes
  ``[T,E,C]`` tensors contracted against tokens. With the expert-major
  weights (``w1 [E,D,H]``, ``w2 [E,H,D]``) sharded over ``model`` on the
  leading expert dim (parallel/shardings.py), GSPMD compiles the dispatch
  contraction into the all-to-all over ICI — expert parallelism falls out
  of the sharding annotation, exactly like tp/sp elsewhere in this repo.
- **Load-balancing aux loss** (Switch eq. 4): E · Σ_e f_e·p_e, where f_e is
  the routed-token fraction and p_e the mean router probability. Scaled by
  the caller (``ModelConfig.moe_aux_coef``).

Tokens that overflow an expert's capacity are dropped (combine weight 0);
with the residual connection around the layer they pass through unchanged.
"""

from __future__ import annotations

import math
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp

Params = Dict[str, Any]


def init_moe_params(key: jax.Array, dim: int, hidden: int, num_experts: int,
                    dtype=jnp.float32) -> Params:
    """Expert-major MoE MLP params: gate [D,E], w1 [E,D,H], w2 [E,H,D]."""
    kg, k1, k2 = jax.random.split(key, 3)
    scale1 = math.sqrt(2.0 / dim)
    scale2 = math.sqrt(2.0 / hidden)
    return {
        "gate": {"kernel": 0.02 * jax.random.normal(kg, (dim, num_experts),
                                                    dtype)},
        "w1": scale1 * jax.random.normal(k1, (num_experts, dim, hidden),
                                         dtype),
        "b1": jnp.zeros((num_experts, hidden), dtype),
        "w2": scale2 * jax.random.normal(k2, (num_experts, hidden, dim),
                                         dtype),
        "b2": jnp.zeros((num_experts, dim), dtype),
    }


def moe_mlp(x: jax.Array, params: Params, capacity_factor: float,
            top_k: int = 1, dispatch: str = "einsum"
            ) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """Top-k MoE MLP: ``[B,S,D] -> ([B,S,D], router stats dict)``.

    ``top_k=1`` is Switch routing (output scaled by the router prob p1);
    ``top_k=2`` is GShard routing (two experts per token, combine weights
    p_i renormalized over the chosen pair). All shapes static; the expert
    dim of every einsum below is the sharded (``model``) axis under
    expert parallelism. First-choice assignments take queue priority over
    second choices, so under capacity pressure a token loses its backup
    expert before anyone loses their primary.

    ``dispatch`` selects the dispatch/combine formulation — identical
    semantics (tests pin them bit-comparable), different cost shape:

    - ``"einsum"`` (default): [T,E,C] one-hot contractions — all-MXU,
      no scatter/gather, but O(T·E·C·D) flops; at capacity ≈ T/E·f the
      dispatch pair costs O(T²·f·D), dwarfing the expert MLPs at long T
      (measured 6:1 at T=16k, D=192 — BASELINE.md round 5).
    - ``"scatter"``: tokens scatter-add into the [E,C,D] expert buffer
      by (expert, queue-slot) index and gather back — O(T·D) data
      movement, no quadratic term; rides XLA's TPU scatter/gather.

    The stats dict carries the router's health for the metrics stream
    (round-4 verdict #1 — no capability without a number):

    - ``aux_loss``  — load-balance loss (differentiable; the ONLY entry
      gradients flow through — the caller scales it into the train loss);
    - ``dropped_frac`` — fraction of the T*k expert assignments that
      overflowed a capacity queue this batch (those tokens ride the
      residual unchanged);
    - ``expert_load`` — [E] fraction of first-choice assignments routed
      to each expert (uniform = 1/E; a collapsed router shows a spike).
    """
    b, s, d = x.shape
    e = params["w1"].shape[0]
    if not 1 <= top_k <= e:
        raise ValueError(f"top_k={top_k} must be in [1, num_experts={e}]")
    t = b * s
    capacity = max(1, math.ceil(t / e * capacity_factor * top_k))

    tokens = x.reshape(t, d)
    gate_logits = tokens.astype(jnp.float32) @ \
        params["gate"]["kernel"].astype(jnp.float32)          # [T,E]
    probs = jax.nn.softmax(gate_logits, axis=-1)

    # Rank the k chosen experts per token (sequential masked argmax —
    # k is tiny and static, so this unrolls into k dense passes).
    masked = probs
    ranks = []                                                # [(1h, prob)]
    for _ in range(top_k):
        idx = jnp.argmax(masked, axis=-1)                     # [T]
        oh = jax.nn.one_hot(idx, e, dtype=jnp.float32)        # [T,E]
        ranks.append((oh, jnp.sum(masked * oh, axis=-1)))     # prob at idx
        masked = masked * (1.0 - oh)
    # Switch keeps the raw p1 scale; GShard renormalizes over the pair.
    renorm = sum(p for _, p in ranks) if top_k > 1 else \
        jnp.ones((t,), jnp.float32)

    cdt = x.dtype
    if dispatch == "scatter":
        # Per-token (expert, queue-slot) coordinates — same queue
        # semantics as the one-hot path (cumsum order = token order,
        # prior ranks' FULL counts offset later ranks' slots).
        offset = jnp.zeros((e,), jnp.int32)
        coords = []                         # [(expert, slot, keep, w)]
        for oh, prob in ranks:
            ohi = oh.astype(jnp.int32)
            idx = jnp.argmax(ohi, axis=-1)                     # [T]
            pos = jnp.cumsum(ohi, axis=0) - 1 + offset[None, :]
            pos_i = jnp.take_along_axis(pos, idx[:, None], 1)[:, 0]
            keep_i = pos_i < capacity
            coords.append((idx, jnp.clip(pos_i, 0, capacity - 1),
                           keep_i, prob / jnp.maximum(renorm, 1e-9)))
            offset = offset + jnp.sum(ohi, axis=0)
        xe = jnp.zeros((e, capacity, d), cdt)
        for idx, slot, keep_i, _ in coords:
            # Kept slots are unique; dropped tokens clip onto slot C-1,
            # so they contribute ZERO via the mask and .add (not .set)
            # keeps collisions harmless.
            # Round-5 negative result: replacing this scatter-add with a
            # stable-argsort + [E,C] masked GATHER build measured 2.5x
            # faster in a standalone layer microbench (8.6 -> 3.4 ms
            # fwd+bwd at T=16k) but end-to-end vit_moe throughput was
            # parity-to-worse (6,406 vs 6,677 img/s) — the full step is
            # bound elsewhere once the einsum dispatch is gone. Kept the
            # simpler form; don't retry without a step-level profile
            # showing this op on top.
            xe = xe.at[idx, slot].add(
                tokens * keep_i[:, None].astype(cdt))
        h = jax.nn.gelu(jnp.einsum("ecd,edh->ech", xe, params["w1"])
                        + params["b1"][:, None, :])
        ye = jnp.einsum("ech,ehd->ecd", h, params["w2"]) \
            + params["b2"][:, None, :]                         # [E,C,D]
        y = jnp.zeros((t, d), cdt)
        kept_total = jnp.zeros((), jnp.float32)
        for idx, slot, keep_i, w in coords:
            y = y + ye[idx, slot] * (w * keep_i)[:, None].astype(cdt)
            kept_total = kept_total + jnp.sum(keep_i)
        dropped = 1.0 - kept_total / float(t * top_k)
    elif dispatch == "einsum":
        disp = jnp.zeros((t, e, capacity), jnp.float32)
        combine = jnp.zeros((t, e, capacity), jnp.float32)
        offset = jnp.zeros((e,), jnp.float32)  # queue slots of prior ranks
        for oh, prob in ranks:
            position = (jnp.cumsum(oh, axis=0) - 1.0 + offset[None, :]) * oh
            keep = (oh > 0) & (position < capacity)
            pos_1h = jax.nn.one_hot(position.astype(jnp.int32), capacity,
                                    dtype=jnp.float32) * keep[..., None]
            disp = disp + pos_1h
            combine = combine + pos_1h * (prob / jnp.maximum(renorm, 1e-9)
                                          )[:, None, None]
            offset = offset + jnp.sum(oh, axis=0)

        xe = jnp.einsum("tec,td->ecd", disp.astype(cdt), tokens)  # [E,C,D]
        h = jax.nn.gelu(jnp.einsum("ecd,edh->ech", xe, params["w1"])
                        + params["b1"][:, None, :])
        ye = jnp.einsum("ech,ehd->ecd", h, params["w2"]) \
            + params["b2"][:, None, :]                             # [E,C,D]
        y = jnp.einsum("tec,ecd->td", combine.astype(cdt), ye)     # [T,D]
        dropped = 1.0 - jnp.sum(disp) / float(t * top_k)
    else:
        raise ValueError(
            f"dispatch must be 'einsum' or 'scatter', got {dispatch!r}")

    # Load-balance loss on FIRST choices (Switch eq. 4 / GShard l_aux):
    # E * sum_e f_e * p_e.
    f = jnp.mean(ranks[0][0], axis=0)                          # [E]
    p = jnp.mean(probs, axis=0)                                # [E]
    aux = e * jnp.sum(f * p)
    stats = {
        "aux_loss": aux,
        "dropped_frac": jax.lax.stop_gradient(
            dropped.astype(jnp.float32)),
        "expert_load": jax.lax.stop_gradient(f),
    }
    return y.reshape(b, s, d), stats
