"""Mixture-of-Experts MLP — expert parallelism over the ``model`` mesh axis.

No reference counterpart (SURVEY §2.3: expert parallelism absent), built
TPU-first as the framework's ``ep`` capability:

- **Switch-style top-1 routing** with a **static capacity**: every shape is
  known at trace time (tokens = B*S, capacity = ceil(T/E · factor)), so the
  whole layer is dense einsums XLA can tile onto the MXU — no dynamic
  gather/scatter, no data-dependent shapes (the TPU-idiomatic formulation
  from the Switch/GShard line of work).
- **Dispatch/combine as one-hot einsum contractions**: routing becomes
  ``[T,E,C]`` tensors contracted against tokens. With the expert-major
  weights (``w1 [E,D,H]``, ``w2 [E,H,D]``) sharded over ``model`` on the
  leading expert dim (parallel/shardings.py), GSPMD compiles the dispatch
  contraction into the all-to-all over ICI — expert parallelism falls out
  of the sharding annotation, exactly like tp/sp elsewhere in this repo.
- **Load-balancing aux loss** (Switch eq. 4): E · Σ_e f_e·p_e, where f_e is
  the routed-token fraction and p_e the mean router probability. Scaled by
  the caller (``ModelConfig.moe_aux_coef``).

Tokens that overflow an expert's capacity are dropped (combine weight 0);
with the residual connection around the layer they pass through unchanged.
"""

from __future__ import annotations

import math
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp

Params = Dict[str, Any]


def init_moe_params(key: jax.Array, dim: int, hidden: int, num_experts: int,
                    dtype=jnp.float32) -> Params:
    """Expert-major MoE MLP params: gate [D,E], w1 [E,D,H], w2 [E,H,D]."""
    kg, k1, k2 = jax.random.split(key, 3)
    scale1 = math.sqrt(2.0 / dim)
    scale2 = math.sqrt(2.0 / hidden)
    return {
        "gate": {"kernel": 0.02 * jax.random.normal(kg, (dim, num_experts),
                                                    dtype)},
        "w1": scale1 * jax.random.normal(k1, (num_experts, dim, hidden),
                                         dtype),
        "b1": jnp.zeros((num_experts, hidden), dtype),
        "w2": scale2 * jax.random.normal(k2, (num_experts, hidden, dim),
                                         dtype),
        "b2": jnp.zeros((num_experts, dim), dtype),
    }


def moe_mlp(x: jax.Array, params: Params, capacity_factor: float
            ) -> Tuple[jax.Array, jax.Array]:
    """Top-1 MoE MLP: ``[B,S,D] -> ([B,S,D], aux_loss scalar)``.

    All shapes static; the expert dim of every einsum below is the sharded
    (``model``) axis under expert parallelism.
    """
    b, s, d = x.shape
    e = params["w1"].shape[0]
    t = b * s
    capacity = max(1, math.ceil(t / e * capacity_factor))

    tokens = x.reshape(t, d)
    gate_logits = tokens.astype(jnp.float32) @ \
        params["gate"]["kernel"].astype(jnp.float32)          # [T,E]
    probs = jax.nn.softmax(gate_logits, axis=-1)
    expert_idx = jnp.argmax(probs, axis=-1)                   # [T]
    expert_prob = jnp.max(probs, axis=-1)                     # [T]
    expert_1h = jax.nn.one_hot(expert_idx, e, dtype=jnp.float32)  # [T,E]

    # Position of each token within its expert's queue (first-come order);
    # tokens beyond capacity are dropped.
    position = jnp.cumsum(expert_1h, axis=0) * expert_1h - 1.0    # [T,E]
    keep = (position >= 0) & (position < capacity)
    pos_1h = jax.nn.one_hot(position.astype(jnp.int32), capacity,
                            dtype=jnp.float32) * keep[..., None]
    dispatch = pos_1h                                          # [T,E,C]
    combine = dispatch * expert_prob[:, None, None]            # [T,E,C]

    cdt = x.dtype
    xe = jnp.einsum("tec,td->ecd", dispatch.astype(cdt), tokens)  # [E,C,D]
    h = jax.nn.gelu(jnp.einsum("ecd,edh->ech", xe, params["w1"])
                    + params["b1"][:, None, :])
    ye = jnp.einsum("ech,ehd->ecd", h, params["w2"]) \
        + params["b2"][:, None, :]                             # [E,C,D]
    y = jnp.einsum("tec,ecd->td", combine.astype(cdt), ye)     # [T,D]

    # Switch load-balance loss: E * sum_e f_e * p_e (scalar, f32).
    f = jnp.mean(expert_1h, axis=0)                            # [E]
    p = jnp.mean(probs, axis=0)                                # [E]
    aux = e * jnp.sum(f * p)
    return y.reshape(b, s, d), aux
