"""Device-side input preprocessing (cast / crop / normalize) as XLA ops.

The reference does all decode work on host CPU threads
(``cifar10cnn.py:54-70``: reader → transpose → cast → crop inside the
queue-runner graph). On TPU the roles invert: the tiny reference CNN is
~1 ms of MXU work per step, so a host that also casts to float32 and crops
cannot keep up (measured: host-decoded pipeline tops out ~2 orders of
magnitude below device compute). The TPU-native split is **host does IO
and shuffling of raw uint8 bytes; the device does the math** — uint8 H2D
is 4x less PCIe/ICI traffic than float32, and the cast/crop/normalize fuse
into the training step for free.

Used by the chunked training path (``parallel/step.py:make_train_chunk``
with ``data_cfg=``); augmented (random crop/flip) training keeps the host
path, deterministic center-crop pipelines (faithful parity + bench) take
this one.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from dml_cnn_cifar10_tpu.config import DataConfig


def device_preprocess(images_u8: jax.Array, cfg: DataConfig) -> jax.Array:
    """uint8 ``[..., H, W, C]`` full-size images → float32
    ``[..., crop_h, crop_w, C]``, center-cropped and normalized per
    ``cfg.normalize`` — the device-side mirror of the host pipeline's
    ``_finish`` (deterministic path)."""
    if cfg.random_crop or cfg.random_flip:
        raise ValueError(
            "device_preprocess is the deterministic path; random crop/flip "
            "run on the host pipeline")
    x = images_u8.astype(jnp.float32)
    h, w = x.shape[-3], x.shape[-2]
    if cfg.crop_height > h or cfg.crop_width > w:
        # Pad-if-smaller, same as the host records.center_crop (parity with
        # tf.image.resize_image_with_crop_or_pad).
        ph, pw = max(cfg.crop_height - h, 0), max(cfg.crop_width - w, 0)
        pad = ([(0, 0)] * (x.ndim - 3)
               + [(ph // 2, ph - ph // 2), (pw // 2, pw - pw // 2), (0, 0)])
        x = jnp.pad(x, pad)
        h, w = x.shape[-3], x.shape[-2]
    oh, ow = (h - cfg.crop_height) // 2, (w - cfg.crop_width) // 2
    x = x[..., oh:oh + cfg.crop_height, ow:ow + cfg.crop_width, :]
    if cfg.normalize == "scale":
        x = x / 255.0
    elif cfg.normalize == "standardize":
        axes = tuple(range(x.ndim - 3, x.ndim))
        mean = jnp.mean(x, axis=axes, keepdims=True)
        std = jnp.std(x, axis=axes, keepdims=True)
        # tf.image.per_image_standardization's min stddev guard
        n = cfg.crop_height * cfg.crop_width * x.shape[-1]
        x = (x - mean) / jnp.maximum(std, 1.0 / jnp.sqrt(float(n)))
    elif cfg.normalize != "none":
        raise ValueError(f"unknown normalize mode {cfg.normalize!r}")
    return x
