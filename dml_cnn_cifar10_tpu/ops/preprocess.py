"""Device-side input preprocessing (cast / crop / augment / normalize).

The reference does all decode work on host CPU threads
(``cifar10cnn.py:54-70``: reader → transpose → cast → crop inside the
queue-runner graph). On TPU the roles invert: the tiny reference CNN is
~1 ms of MXU work per step, so a host that also casts to float32 and crops
cannot keep up (measured: host-decoded pipeline tops out ~2 orders of
magnitude below device compute). The TPU-native split is **host does IO
and shuffling of raw uint8 bytes; the device does the math** — uint8 H2D
is 4x less PCIe/ICI traffic than float32, and the cast/crop/normalize fuse
into the training step for free.

Used by the chunked training path (``parallel/step.py:make_train_chunk``
with ``data_cfg=``). Deterministic center-crop pipelines (faithful parity
+ bench) need no key; augmented configs (``random_crop``/``random_flip``,
fixed mode — any ``cfg.augmented`` field) pass a PRNG ``key`` and the
augmentation runs on device too: per-image random crop windows as one-hot
selection matmuls (MXU work, flips folded in), brightness/contrast as
per-image affine maps, all fused into the step.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from dml_cnn_cifar10_tpu.config import DataConfig


def device_preprocess(images_u8: jax.Array, cfg: DataConfig,
                      key: Optional[jax.Array] = None) -> jax.Array:
    """uint8 ``[..., H, W, C]`` full-size images → float32
    ``[..., crop_h, crop_w, C]``, cropped/augmented and normalized per
    ``cfg`` — the device-side mirror of the host pipeline's ``_finish``.
    Any randomized augmentation (``cfg.augmented``) requires ``key``."""
    if cfg.augmented and key is None:
        raise ValueError(
            "random crop/flip/brightness/contrast on device need a PRNG "
            "key; pass key= or use the host pipeline")
    x = images_u8.astype(jnp.float32)
    if cfg.augmented:
        kc, kf, kb, kn = jax.random.split(key, 4)
    if cfg.random_crop:
        # Flip folds into the crop's column-selection matmul for free.
        x = _random_crop(x, cfg, kc,
                         flip_key=kf if cfg.random_flip else None)
    else:
        x = _center_crop(x, cfg)
        if cfg.random_flip:
            x = _random_flip(x, kf)
    if cfg.random_brightness:
        x = _random_brightness(x, cfg.random_brightness, kb)
    if cfg.random_contrast:
        x = _random_contrast(x, cfg.random_contrast, kn)
    return _normalize(x, cfg)


def _center_crop(x: jax.Array, cfg: DataConfig) -> jax.Array:
    h, w = x.shape[-3], x.shape[-2]
    if cfg.crop_height > h or cfg.crop_width > w:
        # Pad-if-smaller, same as the host records.center_crop (parity with
        # tf.image.resize_image_with_crop_or_pad).
        ph, pw = max(cfg.crop_height - h, 0), max(cfg.crop_width - w, 0)
        pad = ([(0, 0)] * (x.ndim - 3)
               + [(ph // 2, ph - ph // 2), (pw // 2, pw - pw // 2), (0, 0)])
        x = jnp.pad(x, pad)
        h, w = x.shape[-3], x.shape[-2]
    oh, ow = (h - cfg.crop_height) // 2, (w - cfg.crop_width) // 2
    return x[..., oh:oh + cfg.crop_height, ow:ow + cfg.crop_width, :]


def _random_crop(x: jax.Array, cfg: DataConfig, key: jax.Array,
                 flip_key: Optional[jax.Array] = None) -> jax.Array:
    """Per-image random window (the augmentation the reference's comment
    at ``cifar10cnn.py:67`` intended), with optional fused horizontal
    flip.

    TPU-native formulation: the per-image row/column selections are
    one-hot matrices and the crop is two batched matmuls — MXU work
    instead of per-image gathers (measured ~9x faster than
    ``vmap(dynamic_slice)`` and exact, since each output element is
    1·input). A flipped image's crop is the same column matmul with the
    column indices mirrored, so flip costs nothing extra.
    """
    lead = x.shape[:-3]
    h, w, c = x.shape[-3:]
    ch, cw = cfg.crop_height, cfg.crop_width
    flat = x.reshape((-1, h, w, c))
    n = flat.shape[0]
    kt, kl = jax.random.split(key)
    tops = jax.random.randint(kt, (n,), 0, h - ch + 1)
    lefts = jax.random.randint(kl, (n,), 0, w - cw + 1)
    rows = tops[:, None] + jnp.arange(ch)[None, :]            # [N, ch]
    cols = lefts[:, None] + jnp.arange(cw)[None, :]           # [N, cw]
    if flip_key is not None:
        flip = jax.random.bernoulli(flip_key, 0.5, (n,))
        cols = jnp.where(flip[:, None],
                         (w - 1 - lefts)[:, None] - jnp.arange(cw)[None, :],
                         cols)
    rsel = jax.nn.one_hot(rows, h, dtype=flat.dtype)          # [N, ch, H]
    csel = jax.nn.one_hot(cols, w, dtype=flat.dtype)          # [N, cw, W]
    out = jnp.einsum("nrh,nhwc->nrwc", rsel, flat)
    out = jnp.einsum("nkw,nrwc->nrkc", csel, out)
    return out.reshape(lead + (ch, cw, c))


def _random_flip(x: jax.Array, key: jax.Array) -> jax.Array:
    """Per-image horizontal flip with p=0.5 (mirrors records.random_flip)."""
    lead = x.shape[:-3]
    h, w, c = x.shape[-3:]
    flat = x.reshape((-1, h, w, c))
    flip = jax.random.bernoulli(key, 0.5, (flat.shape[0],))
    out = jnp.where(flip[:, None, None, None], flat[:, :, ::-1, :], flat)
    return out.reshape(lead + (h, w, c))


def _random_brightness(x: jax.Array, max_delta: float,
                       key: jax.Array) -> jax.Array:
    """Per-image additive brightness (mirrors records.random_brightness)."""
    lead = x.shape[:-3]
    n = int(np.prod(lead)) if lead else 1
    deltas = jax.random.uniform(key, (n,), minval=-max_delta,
                                maxval=max_delta)
    return x + deltas.reshape(lead + (1, 1, 1))


def _random_contrast(x: jax.Array, max_dev: float,
                     key: jax.Array) -> jax.Array:
    """Per-image contrast about the per-channel mean (mirrors
    records.random_contrast)."""
    lead = x.shape[:-3]
    n = int(np.prod(lead)) if lead else 1
    f = jax.random.uniform(key, (n,), minval=1.0 - max_dev,
                           maxval=1.0 + max_dev).reshape(lead + (1, 1, 1))
    mean = jnp.mean(x, axis=(-3, -2), keepdims=True)
    return (x - mean) * f + mean


def _normalize(x: jax.Array, cfg: DataConfig) -> jax.Array:
    if cfg.normalize == "scale":
        return x / 255.0
    if cfg.normalize == "standardize":
        axes = tuple(range(x.ndim - 3, x.ndim))
        mean = jnp.mean(x, axis=axes, keepdims=True)
        std = jnp.std(x, axis=axes, keepdims=True)
        # tf.image.per_image_standardization's min stddev guard
        n = cfg.crop_height * cfg.crop_width * x.shape[-1]
        return (x - mean) / jnp.maximum(std, 1.0 / jnp.sqrt(float(n)))
    if cfg.normalize != "none":
        raise ValueError(f"unknown normalize mode {cfg.normalize!r}")
    return x
