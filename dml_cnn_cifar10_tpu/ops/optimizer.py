"""Fused single-pass SGD(+momentum, +weight-decay) optimizer kernel.

The ``tree_map`` chain in ``train/optim.py``'s SGD branch materializes
three elementwise passes over every parameter byte: the decayed gradient
(``g + wd*p``), the momentum trace (``mu*m + g'``), and the apply
(``p - lr*m'``) — each a separate HBM read-modify-write when XLA does
not fuse across the tree_map boundaries. At the weight-update tail of a
small-step workload (the reference CNN is ~1 ms of MXU work; SGD+momentum
touches every param byte ~3x) this is pure bandwidth waste. This module
applies the whole update in ONE pass over the bytes:

- **Pallas TPU kernel** (:func:`_pallas_leaf`): the leaf is flattened,
  padded to the f32 tile (8x128), and a grid of VMEM blocks computes
  ``m' = mu*m + (g + wd*p); p' = p - lr*m'`` reading p/g/m once and
  writing p'/m' once. Engaged when the backend is TPU and the update is
  not under a GSPMD-sharded (zero1) layout — a ``pallas_call`` is an
  opaque custom call the partitioner cannot split, so sharded updates
  keep the XLA expression form below (which GSPMD partitions and fuses
  into one loop over the local shard — the same single-pass property).
- **XLA fallback** (:func:`_xla_leaf`): the identical f32 elementwise
  expression, in the identical order, as one fused XLA loop — selected
  on every non-TPU platform so CPU tier-1 runs the exact same math.

Equivalence (PARITY.md "Update-path equivalence", pinned by
``tests/test_zero1.py``): the XLA fallback is BIT-IDENTICAL to the
legacy tree_map chain (same elementwise expression — asserted in the
compiled train step); the Pallas kernel agrees with the fallback within
a few f32 ULPs (pinned ≤ 5e-7 absolute) — the expressions are
identical, but XLA may contract multiply-add pairs into FMAs where the
kernel/interpreter rounds each op separately. No reductions anywhere,
so the bound is per-element and does not grow with model size. Non-f32
leaves (none in the default configs — params are f32 even under bf16
compute) take the fallback unconditionally: the kernel is written for
the f32 tile.
"""

from __future__ import annotations

import functools
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp

#: f32 VMEM tile: (sublanes, lanes). Leaves pad to a whole number of
#: tiles; the grid walks blocks of ``_BLOCK_ROWS`` sublane rows.
_LANES = 128
_SUBLANES = 8
_BLOCK_ROWS = 512  # 512 x 128 x 4 B = 256 KiB per ref; 5 refs < 2 MiB VMEM


def _use_pallas(optimizer_sharding: str) -> bool:
    """Platform selection: the Pallas lowering only on a real TPU and
    only for the replicated (non-GSPMD-sharded) update layout."""
    return (jax.default_backend() == "tpu"
            and optimizer_sharding != "zero1")


def _xla_leaf(p, g, m, lr, momentum: float, weight_decay: float):
    """One leaf, fallback form: the same expression (and order) as the
    kernel — XLA fuses the chain into a single loop over the bytes."""
    if weight_decay:
        g = g + weight_decay * p
    if m is not None:
        m = momentum * m + g
        g = m
    return p - lr * g.astype(p.dtype), m


def _sgd_kernel(lr_ref, p_ref, g_ref, m_ref, out_p_ref, out_m_ref, *,
                momentum: float, weight_decay: float):
    """Momentum-variant kernel body: one read of p/g/m, one write of
    p'/m' — the whole update in a single pass over the block."""
    p = p_ref[...]
    g = g_ref[...]
    if weight_decay:
        g = g + weight_decay * p
    m_new = momentum * m_ref[...] + g
    out_m_ref[...] = m_new
    out_p_ref[...] = p - lr_ref[0] * m_new


def _sgd_kernel_plain(lr_ref, p_ref, g_ref, out_p_ref, *,
                      weight_decay: float):
    """Momentum-free variant (the reference's plain SGD)."""
    p = p_ref[...]
    g = g_ref[...]
    if weight_decay:
        g = g + weight_decay * p
    out_p_ref[...] = p - lr_ref[0] * g


def _pad_rows(flat):
    """Flat [n] f32 → [rows, 128] with rows a multiple of the sublane
    tile (zero-padded; the pad lanes compute garbage that is sliced
    away)."""
    n = flat.shape[0]
    tile = _SUBLANES * _LANES
    padded = -(-n // tile) * tile
    if padded != n:
        flat = jnp.pad(flat, (0, padded - n))
    return flat.reshape(padded // _LANES, _LANES)


def _pallas_leaf(p, g, m, lr, momentum: float, weight_decay: float,
                 interpret: bool):
    """One leaf through the Pallas kernel: flatten → pad to tiles →
    grid over row blocks → slice the pad back off."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    shape = p.shape
    n = p.size
    p2 = _pad_rows(p.reshape(-1))
    g2 = _pad_rows(g.reshape(-1))
    rows = p2.shape[0]
    block_rows = min(_BLOCK_ROWS, rows)
    grid = (-(-rows // block_rows),)
    lr1 = jnp.reshape(lr.astype(jnp.float32), (1,))

    def row_block(i):
        return (i, 0)

    lr_spec = pl.BlockSpec(memory_space=pltpu.SMEM)
    blk = pl.BlockSpec((block_rows, _LANES), row_block)
    out_shape = jax.ShapeDtypeStruct((rows, _LANES), jnp.float32)
    if m is not None:
        m2 = _pad_rows(m.reshape(-1))
        new_p, new_m = pl.pallas_call(
            functools.partial(_sgd_kernel, momentum=momentum,
                              weight_decay=weight_decay),
            grid=grid,
            in_specs=[lr_spec, blk, blk, blk],
            out_specs=[blk, blk],
            out_shape=[out_shape, out_shape],
            interpret=interpret,
        )(lr1, p2, g2, m2)
        return (new_p.reshape(-1)[:n].reshape(shape),
                new_m.reshape(-1)[:n].reshape(shape))
    new_p = pl.pallas_call(
        functools.partial(_sgd_kernel_plain, weight_decay=weight_decay),
        grid=grid,
        in_specs=[lr_spec, blk, blk],
        out_specs=blk,
        out_shape=out_shape,
        interpret=interpret,
    )(lr1, p2, g2)
    return new_p.reshape(-1)[:n].reshape(shape), None


def fused_sgd_update(params: Any, grads: Any, momentum_tree: Optional[Any],
                     lr, momentum: float, weight_decay: float,
                     optimizer_sharding: str = "none",
                     use_pallas: Optional[bool] = None,
                     interpret: Optional[bool] = None
                     ) -> Tuple[Any, Optional[Any]]:
    """``(new_params, new_momentum_tree)`` — the whole SGD update in one
    pass per leaf. ``momentum_tree=None`` means plain SGD (no trace kept).

    ``use_pallas=None`` resolves by platform (:func:`_use_pallas`);
    ``interpret=None`` resolves to interpreter mode off-TPU (the
    kernel-parity tests force ``use_pallas=True`` on CPU and run the
    interpreter). Only f32 leaves enter the kernel; anything else takes
    the identical-math XLA expression.
    """
    if use_pallas is None:
        use_pallas = _use_pallas(optimizer_sharding)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    lr = jnp.asarray(lr, jnp.float32)

    def one(p, g, m):
        if (use_pallas and p.dtype == jnp.float32
                and g.dtype == jnp.float32
                and (m is None or m.dtype == jnp.float32)):
            return _pallas_leaf(p, g, m, lr, momentum, weight_decay,
                                interpret)
        return _xla_leaf(p, g, m, lr, momentum, weight_decay)

    if momentum_tree is None:
        return jax.tree.map(lambda p, g: one(p, g, None)[0],
                            params, grads), None
    out = jax.tree.map(one, params, grads, momentum_tree)
    # Structural transpose (treedef-driven, like optim.py's adafactor
    # unzip): params-of-pairs → pair-of-params-trees.
    new_params, new_mom = jax.tree_util.tree_transpose(
        jax.tree.structure(params), jax.tree.structure((0, 0)), out)
    return new_params, new_mom
