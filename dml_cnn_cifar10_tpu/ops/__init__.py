"""XLA compute primitives.

The reference leans on TF's C++ op kernels — conv2d, max_pool, matmul,
bias_add, relu, softmax-CE, argmax (``cifar10cnn.py:107-145,154,173``). On
TPU the native layer is XLA: these wrappers lower to
``lax.conv_general_dilated`` / ``lax.reduce_window`` / ``jnp.dot`` so the
MXU sees large fused matmul/conv ops, with Pallas kernels
(:mod:`~dml_cnn_cifar10_tpu.ops.pallas`) for the ops XLA doesn't schedule
well (flash attention for the ViT config).
"""

from dml_cnn_cifar10_tpu.ops.layers import (  # noqa: F401
    bias_init,
    conv2d,
    dense,
    max_pool,
    truncated_normal_init,
)
