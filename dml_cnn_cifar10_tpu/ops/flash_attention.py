"""Blocked online-softmax attention — the Pallas TPU kernels, forward AND
backward.

The long-sequence attention path (SURVEY §5 "long-context"; BASELINE.json
ViT config "attention via Pallas"). The S×S score matrix never
materializes in HBM in either direction:

- forward: walk K/V blocks per Q block keeping the FlashAttention running
  statistics (row max ``m``, normalizer ``l``, unnormalized accumulator
  ``acc``) in VMEM scratch; emit the output and, for autodiff, the row
  logsumexp ``lse = m + log l``.
- backward (the FlashAttention-2 recompute form): two kernels that rebuild
  each score block from Q/K and the saved ``lse`` (so ``p = exp(s − lse)``
  is the exact softmax probability without storing it), using the
  ``D = rowsum(dO ∘ O)`` identity for the softmax Jacobian:
  * dQ kernel — grid (b·h, q_blocks, k_blocks): accumulates
    ``dQ_i = Σ_j dS_ij K_j · scale`` in VMEM scratch;
  * dK/dV kernel — grid (b·h, k_blocks, q_blocks): accumulates
    ``dV_j = Σ_i P_ijᵀ dO_i`` and ``dK_j = Σ_i dS_ijᵀ Q_i · scale``.

``flash_attention`` carries a ``jax.custom_vjp`` wiring the three kernels
together, so the whole long-context stack (ViT blocks, Ulysses all-to-all
attention, ring attention's per-block engine) differentiates. The
reference trains every op it exposes (``minimize`` builds the backward for
the whole graph, ``cifar10cnn.py:163``); this gives the flash path the
same property.

``causal=True`` applies a lower-triangular mask inside the kernels and
*skips* score blocks strictly above the diagonal (``@pl.when`` on the
block indices — on TPU the grid runs sequentially per core, so a skipped
block really is ~free), recovering the ~2× FLOP saving causal attention
allows in both directions.

Grid = (batch·heads, outer_blocks, inner_blocks), inner fastest-varying.
On TPU the grid is executed sequentially per core, so VMEM scratch carries
running state across the inner iterations of one outer block;
``@pl.when(inner == 0)`` resets it and the last inner iteration writes the
finished tile. Scores and all accumulators are f32 (VPU/MXU accumulate
dtype) regardless of input dtype.

On non-TPU backends the same kernels run under the Pallas interpreter
(tests exercise them on CPU); ``ops.attention.dispatch_attention`` routes
short sequences to the fused XLA path where materializing S×S is faster.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

NEG_INF = -1e30  # not -inf: exp(-inf - -inf) would NaN the first block

# ---------------------------------------------------------------------------
# Layout helpers. Per-row statistics (m, l, lse, delta) live in [rows, 128]
# f32 tiles with only lane column 0 meaningful: (8, 128) is the minimum f32
# TPU tile, and keeping stats sublane-oriented means the kernels read
# ``ref[:, :1]`` — a [rows, 1] slice that broadcasts against [rows, cols]
# score blocks with no lane→sublane transpose.
# ---------------------------------------------------------------------------


def _resolve(q, scale, block_q, block_k, interpret):
    """Fill in the static kernel parameters from the input shapes."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    s = q.shape[1]
    # Auto block size (None): re-tuned on a v5e each round. Round 2 found
    # 512 beats 128 from S>=2048; the round-3 sweep (with the backward
    # kernels and fetch-free clamps in play) found 1024 beats 512 across
    # the whole fwd+bwd training path — 1.64x at S=2048 (7.5 vs 12.3 ms),
    # 1.28x at S=16384 (129.6 vs 165.6 ms), causal 107->76 ms — while
    # 2048 exceeds the 16 MB scoped-VMEM limit. 1024 is taken only at
    # head_dim <= 64 (the ladder's geometry; bigger heads double the
    # block buffers and the fwd acc scratch, re-approaching the VMEM
    # ceiling 2048 hit). 128 still wins below S=2048.
    # Round-5 negative results on the W=1024-causal gap (7.48x measured
    # vs the 8x round-3 target; 8.24x is the block-1024 granularity
    # ceiling), trace-timed fwd+bwd at S=16384 [B=4,H=8,D=64] bf16 vs
    # 15.39 ms for symmetric 1024 — do NOT retry without new geometry:
    # - asymmetric folds: bq=512/bk=1024 16.40 ms, bq=1024/bk=512
    #   19.86 ms, bq=bk=512 16.34 ms. The band-union FLOPs are identical
    #   at every one of these granularities (the 1024-wide band spans
    #   the same columns regardless of how blocks tile it), so finer
    #   blocks only add grid ticks and narrower MXU dots.
    # - in-tile K-half gating (two 512-wide sub-dots per 1024 tile, each
    #   under pl.when on its half's band-liveness): 18.95 ms (+23%).
    #   At W=block geometry the band crosses BOTH halves of nearly every
    #   live block, so the split skips almost no work and pays the
    #   doubled mask/softmax-update chain on every tick.
    # 7.48x stands as the honest number: 91% of what block granularity
    # admits, and every finer-granularity route measured is a loss.
    d = q.shape[-1]
    auto_block = (1024 if d <= 64 else 512) if s >= 2048 else 128
    block_q = auto_block if block_q is None else block_q
    block_k = auto_block if block_k is None else block_k
    return float(scale), block_q, block_k, interpret


def _static_kv_start(kv_start):
    """``kv_start`` parameterizes the Python-level schedule and mask
    construction, so it MUST be a static int — a traced value would
    reach ``_fold_schedule``'s lru_cache (TypeError) or silently bake
    wrong masks. The ring passes ``±S_local`` from static shapes; any
    traced value is a caller bug worth a clear message."""
    if isinstance(kv_start, jax.core.Tracer):
        raise TypeError(
            "kv_start must be a static Python int (it selects the block "
            "schedule and mask offsets at trace time); got a traced "
            "value. Pass shard offsets from static shapes, e.g. "
            "q.shape[1].")
    return int(kv_start)


def _to_bh(x, block):
    """[B, S, H, D] → [B·H, S_padded, D], S padded to a ``block`` multiple."""
    b, s, h, d = x.shape
    x = jnp.transpose(x, (0, 2, 1, 3)).reshape(b * h, s, d)
    pad = (-s) % block
    if pad:
        x = jnp.pad(x, ((0, 0), (0, pad), (0, 0)))
    return x


def _from_bh(x, b, s, h):
    """[B·H, S_padded, ...] → [B, S, H, ...]."""
    x = x[:, :s]
    x = x.reshape(b, h, s, *x.shape[2:])
    return jnp.swapaxes(x, 1, 2)


def _stat_to_tile(x, block):
    """[B, S, H] f32 stat → [B·H, S_padded, 128] tile (lane col 0)."""
    b, s, h = x.shape
    t = jnp.transpose(x, (0, 2, 1)).reshape(b * h, s)
    pad = (-s) % block
    if pad:
        t = jnp.pad(t, ((0, 0), (0, pad)))
    return jnp.pad(t[:, :, None], ((0, 0), (0, 0), (0, 127)))


# ---------------------------------------------------------------------------
# Forward kernels.
# ---------------------------------------------------------------------------


def _score_mask(shape, *, kv_len, q_len, row0, col0, causal,
                qseg=None, kseg=None, window=None,
                kv_aligned=False, q_aligned=False, col_shift=0):
    """The shared validity mask for one [bq, bk] score block: padded K/V
    columns off; optionally causal (col ≤ row in global coordinates);
    optionally same-segment only (packed sequences); optionally a
    sliding window (band |row − col| < window; with causal only the
    lower half remains — Mistral-style local attention). Padded Q rows
    (row ≥ q_len) are *exempt* from the segment and window masks so
    every padded row keeps l > 0 — their lse stays finite, and their
    gradient contributions vanish anyway because dO is zero-padded.

    ``kv_aligned``/``q_aligned`` are compile-time facts from the caller
    (sequence length divides the block size): they elide the padded-col
    bound and the pad-row exemption entirely — the masked variants'
    whole chain runs fused on the VPU, so dropping terms buys real
    per-tick time on the aligned (common, benchmarked) geometry.

    ``col0`` is the LOCAL column base (block offset into the K/V array
    — the padded-column bound keys on it), while ``col_shift`` is the
    ring-window global displacement (``kv_start``) that only the
    positional (causal/window) comparisons see: a visiting ring shard's
    columns sit ``±S_local`` away in global coordinates, but its array
    padding is at its own local tail (round-4 review finding)."""
    col = None
    mask = None
    if not kv_aligned:
        col_local = col0 + lax.broadcasted_iota(jnp.int32, shape, 1)
        mask = col_local < kv_len
        col = col_local + col_shift
    if causal or window is not None:
        if col is None:
            col = (col0 + col_shift
                   + lax.broadcasted_iota(jnp.int32, shape, 1))
        row = row0 + lax.broadcasted_iota(jnp.int32, shape, 0)
    pad_row = None
    if not q_aligned and (window is not None or qseg is not None):
        if causal or window is not None:
            pad_row = row >= q_len
        else:
            pad_row = (row0 + lax.broadcasted_iota(jnp.int32, shape, 0)
                       >= q_len)

    def _and(m, term):
        return term if m is None else m & term

    if causal:
        mask = _and(mask, col <= row)
    if window is not None:
        band = col > row - window
        if not causal:
            band = band & (col < row + window)
        mask = _and(mask, band if pad_row is None else (band | pad_row))
    if qseg is not None:
        same = qseg == kseg
        mask = _and(mask, same if pad_row is None else (same | pad_row))
    return mask


def _flash_update(q_ref, k_ref, v_ref, m_scr, l_scr, acc_scr, *,
                  scale: float, kv_len: int, q_len: int, block_q: int,
                  block_k: int, causal: bool, window=None, kv_start=0,
                  qseg_ref=None, kseg_ref=None, coords=None):
    """One K/V-block update of the running (m, l, acc) — shared by the
    plain, lse-emitting, and stats-emitting kernels.

    ``coords``: ``(ib, kb, init)`` for the folded (live-blocks-only)
    schedule — block coordinates come from the prefetched schedule and
    every tick is live; ``None`` for the rectangular grid, where they
    derive from the program ids and dead band blocks are skipped."""
    if coords is None:
        ib = pl.program_id(1)
        kb = pl.program_id(2)
        init = kb == 0
        first_tick = (pl.program_id(0) == 0) & (ib == 0) & init
    else:
        ib, kb, init = coords
        first_tick = (pl.program_id(0) == 0) & (pl.program_id(1) == 0)

    @pl.when(first_tick)
    def _zero_all():
        # Once per launch: VMEM scratch starts as garbage that could be
        # NaN/Inf, which the alpha=0 rescale below cannot kill (0·NaN).
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    @pl.when(init)
    def _init():
        # Per-row init only resets the row max (column 0 is all the
        # kernels read). l/acc keep the PREVIOUS row's values: the first
        # live tick has alpha = exp(NEG_INF − m_cur) = 0, which zeroes
        # the stale state for free. Rows that never go live keep m ==
        # NEG_INF and finalize through the _dead_rows guard, so their
        # stale l/acc are never observable.
        m_scr[:, :1] = jnp.full_like(m_scr[:, :1], NEG_INF)

    def _update():
        q = q_ref[0]                      # [bq, d]
        k = k_ref[0]                      # [bk, d]
        v = v_ref[0]                      # [bk, d]

        s = lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
        mask = _score_mask(
            s.shape, kv_len=kv_len, q_len=q_len, row0=ib * block_q,
            col0=kb * block_k, col_shift=kv_start, causal=causal,
            window=window,
            qseg=None if qseg_ref is None else qseg_ref[0][:, :1],
            kseg=None if kseg_ref is None else kseg_ref[0, :1],
            kv_aligned=kv_len % block_k == 0,
            q_aligned=q_len % block_q == 0)
        if mask is not None:
            s = jnp.where(mask, s, NEG_INF)

        m_prev = m_scr[:, :1]                                   # [bq, 1]
        m_cur = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_cur)
        # Dead rows (EVERY key masked so far) keep m_cur == NEG_INF, so
        # exp(s - m_cur) = exp(0) = 1 for their masked entries and l/acc
        # accumulate garbage (masked entries in live-max rows underflow
        # to exactly 0, so only dead rows are affected). Rather than a
        # per-tick select on p, the finalizers detect dead rows by
        # ``m == NEG_INF`` and emit zeros + a LARGE lse — see _dead_rows.
        p = jnp.exp(s - m_cur)                                  # [bq, bk]
        l_scr[:, :1] = (l_scr[:, :1] * alpha
                        + jnp.sum(p, axis=-1, keepdims=True))
        acc_scr[:] = acc_scr[:] * alpha + lax.dot_general(
            p, v.astype(jnp.float32), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[:, :1] = m_cur

    if coords is not None:
        # Folded schedule: every tick IS a live block by construction
        # (or a dead placeholder whose element mask kills everything and
        # whose row finalizes to zeros via _dead_rows).
        _update()
        return
    live = _band_live(ib * block_q, block_q, kv_start + kb * block_k,
                      block_k, causal, window)
    if live is not None:
        @pl.when(live)
        def _live():
            _update()
    else:
        _update()


def _unpack(refs, n_out, has_segments, n_base=3):
    """Split a kernel's positional refs into (base inputs…, qseg, kseg),
    outs, scratch. ``n_base`` is the count of always-present inputs (3 for
    the forward kernels: q/k/v; 6 for the backward: +do/lse/delta); the
    two segment-id refs are only present when asked for, so the
    non-segmented path pays zero extra bandwidth."""
    n_in = n_base + (2 if has_segments else 0)
    ins, outs, scratch = refs[:n_in], refs[n_in:n_in + n_out], \
        refs[n_in + n_out:]
    if not has_segments:
        ins = ins + (None, None)
    return ins, outs, scratch


def _safe_l(l_col):
    """Divide-by-zero guard for the normalizer: fully-dead rows (every
    block skipped — window/cross-length geometries) keep l == 0 and the
    plain division would emit NaN that poisons the backward."""
    return jnp.maximum(l_col, 1e-30)


def _dead_rows(m_col):
    """Dead-row predicate at finalize time: a row with NO live key ever
    (blocks skipped by the schedule, or visited but fully masked —
    segment/window geometries) still has ``m == NEG_INF``; any live
    score is many orders of magnitude above NEG_INF/2. Visited-but-dead
    rows accumulate garbage (``exp(NEG_INF − NEG_INF) = 1`` per masked
    entry ⇒ l = #keys, acc = Σ V), so the finalizers must zero their
    output and publish a LARGE lse — otherwise the backward's
    ``p = exp(s − lse)`` becomes 1/#keys and leaks gradient into dK/dV
    (round-3 advisor finding, extended to the visited-block case)."""
    return m_col <= NEG_INF * 0.5


def _fold_coords(refs, folded):
    """Split off the prefetched schedule ref (folded mode) and derive
    ``(remaining_refs, coords, last)``: coords feed ``_flash_update``,
    ``last`` gates the finalizer. Rect mode reads the program ids."""
    if not folded:
        return refs, None, pl.program_id(2) == pl.num_programs(2) - 1
    info_ref, refs = refs[0], refs[1:]
    t = pl.program_id(1)
    coords = (info_ref[0, t], info_ref[1, t], info_ref[2, t] == 1)
    return refs, coords, info_ref[3, t] == 1


def _flash_kernel(*refs, has_segments: bool = False, folded: bool = False,
                  **kw):
    refs, coords, last = _fold_coords(refs, folded)
    (q_ref, k_ref, v_ref, qseg_ref, kseg_ref), (o_ref,), \
        (m_scr, l_scr, acc_scr) = _unpack(refs, 1, has_segments)
    _flash_update(q_ref, k_ref, v_ref, m_scr, l_scr, acc_scr,
                  qseg_ref=qseg_ref, kseg_ref=kseg_ref, coords=coords, **kw)

    @pl.when(last)
    def _finalize():
        o = acc_scr[:] / _safe_l(l_scr[:, :1])
        o_ref[0] = jnp.where(_dead_rows(m_scr[:, :1]), 0.0,
                             o).astype(o_ref.dtype)


def _flash_fwd_kernel(*refs, has_segments: bool = False,
                      folded: bool = False, **kw):
    """Forward that additionally emits the row logsumexp — the single
    statistic the FlashAttention-2 backward needs."""
    refs, coords, last = _fold_coords(refs, folded)
    (q_ref, k_ref, v_ref, qseg_ref, kseg_ref), (o_ref, lse_ref), \
        (m_scr, l_scr, acc_scr) = _unpack(refs, 2, has_segments)
    _flash_update(q_ref, k_ref, v_ref, m_scr, l_scr, acc_scr,
                  qseg_ref=qseg_ref, kseg_ref=kseg_ref, coords=coords, **kw)

    @pl.when(last)
    def _finalize():
        o = acc_scr[:] / _safe_l(l_scr[:, :1])
        o_ref[0] = jnp.where(_dead_rows(m_scr[:, :1]), 0.0,
                             o).astype(o_ref.dtype)
        # The stat computes on column 0 ONLY (a [bq, 1] log instead of a
        # full-tile one — the [bq, 128] log was ~45 % of a short row's
        # finalize cost) and broadcast-stores across the tile; only
        # col 0 is ever read back. Dead rows publish a LARGE lse so the
        # backward's p = exp(s − lse) is exactly 0 (see _dead_rows).
        m_col = m_scr[:, :1]
        lse_col = jnp.where(_dead_rows(m_col), 1e30,
                            m_col + jnp.log(_safe_l(l_scr[:, :1])))
        lse_ref[0] = jnp.broadcast_to(lse_col, lse_ref.shape[1:])


def _flash_stats_kernel(*refs, has_segments: bool = False,
                        folded: bool = False, **kw):
    """Like ``_flash_kernel`` but emits the raw running state — f32
    UNNORMALIZED accumulator plus row max ``m`` and normalizer ``l`` —
    the partial-softmax interface the ring-attention merge rule needs
    (parallel/ring_attention.py). Emitting ``acc_scr`` directly keeps the
    partial in f32 regardless of input dtype (normalizing to the input
    dtype and re-multiplying by ``l`` would quantize every ring step's
    partial)."""
    refs, coords, last = _fold_coords(refs, folded)
    (q_ref, k_ref, v_ref, qseg_ref, kseg_ref), (acc_ref, m_ref, l_ref), \
        (m_scr, l_scr, acc_scr) = _unpack(refs, 3, has_segments)
    _flash_update(q_ref, k_ref, v_ref, m_scr, l_scr, acc_scr,
                  qseg_ref=qseg_ref, kseg_ref=kseg_ref, coords=coords, **kw)

    @pl.when(last)
    def _finalize():
        acc_ref[0] = acc_scr[:]
        # Only m_scr[:, :1] is ever written (the per-row init); lanes
        # 1..127 are launch-lifetime VMEM garbage — broadcast the col-0
        # stat so the published tile has no uninitialized values (a NaN
        # scanner or a future full-tile consumer would otherwise see
        # garbage; round-4 advisor). l_scr's lanes 1..127 were zeroed by
        # _zero_all and never touched again, so l publishes clean as-is.
        m_ref[0] = jnp.broadcast_to(m_scr[:, :1], m_ref.shape[1:])
        l_ref[0] = l_scr[:]


def _seg_tile(seg, block):
    """[B, S] int32 → [B, S_padded, 128] Q-side tile (lane col 0; pad
    value irrelevant — padded rows are mask-exempt)."""
    b, s = seg.shape
    pad = (-s) % block
    if pad:
        seg = jnp.pad(seg, ((0, 0), (0, pad)), constant_values=-1)
    return jnp.pad(seg[:, :, None], ((0, 0), (0, 0), (0, 127)))


def _seg_lane(seg, block):
    """[B, S] int32 → [B, 8, S_padded] K-side lane layout (padded cols
    are already killed by the kv_len mask). The middle dim exists purely
    for TPU tiling: a (1, bk) block of a [B, S] array has a sublane dim
    of 1, which Mosaic rejects for B > 1 (must be divisible by 8 or the
    full dim); an 8-row broadcast makes the block (1, 8, bk) — legal,
    and only row 0 is ever read."""
    pad = (-seg.shape[1]) % block
    if pad:
        seg = jnp.pad(seg, ((0, 0), (0, pad)), constant_values=-1)
    return jnp.broadcast_to(seg[:, None, :],
                            (seg.shape[0], 8, seg.shape[1]))


import numpy as _np


@functools.lru_cache(maxsize=256)
def _fold_schedule(nq, nk, bq, bk, causal, window, major="q", kv_start=0):
    """The folded (live-blocks-only) grid schedule → int32 ``[4, T]``
    rows ``(outer_block, inner_block, is_first, is_last)`` — or ``None``
    when nothing can be skipped (full attention runs the plain
    rectangular grid: no SMEM prefetch needed).

    Instead of walking the full ``outer × inner`` rectangle and
    ``pl.when``-skipping dead band blocks (which still pay per-grid-step
    overhead — round-3 measured dead ticks at ~0.4 µs each, ~45 % of the
    W=1024 forward), the grid's second dimension enumerates ONLY the
    blocks that intersect the causal/window band, flattened row-major:
    ~half the ticks for causal, ``O(W/block)`` per row for a window.
    Block coordinates ride a scalar-prefetch array (SMEM), the standard
    TPU sparse-schedule technique. ``major='q'`` orders by q block
    (forward + dQ kernels), ``'k'`` by k block (dK/dV kernel). An outer
    block with NO live inner block (cross-length geometries) gets one
    placeholder tick — its element mask kills every score, so the row
    finalizes as dead (zero output, LARGE lse). ``kv_start`` shifts
    the K/V columns' global coordinates (ring window steps attend a
    neighbor shard whose columns sit ``±S_local`` away)."""
    if not causal and window is None:
        return None
    ticks = []
    n_outer, n_inner = (nq, nk) if major == "q" else (nk, nq)
    for r in range(n_outer):
        cols = []
        for c in range(n_inner):
            i, j = (r, c) if major == "q" else (c, r)
            if bool(_band_live(i * bq, bq, kv_start + j * bk, bk, causal,
                               window)):
                cols.append(c)
        if not cols:
            cols = [0]
        for n, c in enumerate(cols):
            ticks.append((r, c, 1 if n == 0 else 0,
                          1 if n == len(cols) - 1 else 0))
    return _np.asarray(ticks, _np.int32).T.copy()


def _band_live(row0, rows, col0, cols, causal, window):
    """Block-liveness predicate for a [rows, cols] score block whose
    top-left is global (row0, col0): does the block intersect the valid
    causal/window band? None when nothing can be skipped. ONE definition
    for all three kernels (fwd, dQ, dK/dV) so the skip logic cannot
    drift from ``_score_mask``'s element mask."""
    live = None
    if causal:
        live = col0 <= row0 + rows - 1
    if window is not None:
        lo = col0 + cols - 1 > row0 - window
        live = lo if live is None else live & lo
        if not causal:
            live = live & (col0 < row0 + rows - 1 + window)
    return live


def _norm_segments(segment_ids):
    """``None`` | ``[B, S]`` (self-attention) | ``(q_seg, kv_seg)``
    (cross/sharded attention — ring blocks see different shards) →
    ``(q_seg, kv_seg)`` int32 or ``(None, None)``."""
    if segment_ids is None:
        return None, None
    if isinstance(segment_ids, (tuple, list)):
        q_seg, kv_seg = segment_ids
        return q_seg.astype(jnp.int32), kv_seg.astype(jnp.int32)
    seg = segment_ids.astype(jnp.int32)
    return seg, seg


def _index_maps(folded: bool, h: int, q_major: bool = True):
    """The four pallas index maps (q-side, kv-side, and their segment-id
    variants) for one kernel pass — ONE definition so the folded/rect and
    q-major/k-major variants cannot drift (round-4 review finding).

    Folded grids read block coordinates from the prefetched schedule:
    row 0 of the schedule is the OUTER (accumulator) block, row 1 the
    inner — which is (q, k) for the q-major passes (forward, dQ) and
    (k, q) for the k-major dK/dV pass. Rect grids read the grid indices
    directly, whose order is (outer, inner) the same way. Segment maps
    fold the head out of the batch·head grid axis (ids are per batch)."""
    qrow, krow = (0, 1) if q_major else (1, 0)
    if folded:
        qi = lambda g, t, info: (g, info[qrow, t], 0)         # noqa: E731
        kj = lambda g, t, info: (g, info[krow, t], 0)         # noqa: E731
        qi_seg = lambda g, t, info: (g // h, info[qrow, t], 0)  # noqa: E731
        kj_seg = lambda g, t, info: (g // h, 0, info[krow, t])  # noqa: E731
    elif q_major:
        qi = lambda g, i, j: (g, i, 0)                        # noqa: E731
        kj = lambda g, i, j: (g, j, 0)                        # noqa: E731
        qi_seg = lambda g, i, j: (g // h, i, 0)               # noqa: E731
        kj_seg = lambda g, i, j: (g // h, 0, j)               # noqa: E731
    else:
        qi = lambda g, j, i: (g, i, 0)                        # noqa: E731
        kj = lambda g, j, i: (g, j, 0)                        # noqa: E731
        qi_seg = lambda g, j, i: (g // h, i, 0)               # noqa: E731
        kj_seg = lambda g, j, i: (g // h, 0, j)               # noqa: E731
    return qi, kj, qi_seg, kj_seg


def _fwd_call(q, k, v, scale, block_q, block_k, interpret, causal,
              mode: str, segment_ids=None, window=None, kv_start=0):
    """Shared forward pallas_call builder.

    mode: "out" → out; "lse" → (out, lse [B,S,H]);
    "stats" → (acc, m, l) — the ring merge interface.
    ``segment_ids`` [B, S] int32 restricts attention to equal-id pairs
    (packed sequences).
    """
    from jax.experimental.pallas import tpu as pltpu

    b, s, h, d = q.shape
    kv_len = k.shape[1]
    bq, bk = min(block_q, s), min(block_k, kv_len)

    qb = _to_bh(q, bq)
    kb_ = _to_bh(k, bk)
    vb = _to_bh(v, bk)
    spq, spk = qb.shape[1], kb_.shape[1]
    nq, nk = spq // bq, spk // bk
    has_seg = segment_ids is not None
    sched = _fold_schedule(nq, nk, bq, bk, causal, window, "q",
                           kv_start=kv_start)
    folded = sched is not None

    kw = dict(scale=scale, kv_len=kv_len, q_len=s, block_q=bq, block_k=bk,
              causal=causal, window=window, kv_start=kv_start,
              has_segments=has_seg, folded=folded)
    qi, kj, qi_seg, kj_seg = _index_maps(folded, h)
    in_specs = [
        pl.BlockSpec((1, bq, d), qi),
        pl.BlockSpec((1, bk, d), kj),
        pl.BlockSpec((1, bk, d), kj),
    ]
    inputs = [qb, kb_, vb]
    if has_seg:
        q_seg, kv_seg = _norm_segments(segment_ids)
        # Segment ids are per (batch, position) — the index maps fold the
        # head out of the grid's batch·head axis.
        in_specs += [
            pl.BlockSpec((1, bq, 128), qi_seg),
            pl.BlockSpec((1, 8, bk), kj_seg),
        ]
        inputs += [_seg_tile(q_seg, bq), _seg_lane(kv_seg, bk)]

    o_spec = pl.BlockSpec((1, bq, d), qi)
    stat_spec = pl.BlockSpec((1, bq, 128), qi)
    stat_shape = jax.ShapeDtypeStruct((b * h, spq, 128), jnp.float32)
    if mode == "out":
        kernel, out_shape, out_specs = (
            _flash_kernel, jax.ShapeDtypeStruct(qb.shape, q.dtype), o_spec)
    elif mode == "lse":
        kernel = _flash_fwd_kernel
        out_shape = [jax.ShapeDtypeStruct(qb.shape, q.dtype), stat_shape]
        out_specs = [o_spec, stat_spec]
    else:
        kernel = _flash_stats_kernel
        out_shape = [jax.ShapeDtypeStruct(qb.shape, jnp.float32),
                     stat_shape, stat_shape]
        out_specs = [o_spec, stat_spec, stat_spec]

    scratch = [
        pltpu.VMEM((bq, 128), jnp.float32),   # m (col 0 used)
        pltpu.VMEM((bq, 128), jnp.float32),   # l (col 0 used)
        pltpu.VMEM((bq, d), jnp.float32),     # acc
    ]
    # LOAD-BEARING: every grid below (incl. the b*h axis) must execute
    # SEQUENTIALLY on one core — _flash_update zeroes l/acc only at the
    # very first tick of the launch and relies on the alpha =
    # exp(NEG_INF − m) = 0 rescale to clear stale scratch between rows
    # (0·NaN = NaN would break that for unzeroed scratch). That holds
    # for Pallas-TPU's default 'arbitrary' dimension semantics; if
    # dimension_semantics is ever added here, the b*h axis must NOT be
    # marked 'parallel' unless _zero_all becomes per-row (round-4
    # advisor).
    if folded:
        res = pl.pallas_call(
            functools.partial(kernel, **kw),
            out_shape=out_shape,
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=1,
                grid=(b * h, sched.shape[1]),
                in_specs=in_specs,
                out_specs=out_specs,
                scratch_shapes=scratch),
            interpret=interpret,
        )(jnp.asarray(sched), *inputs)
    else:
        res = pl.pallas_call(
            functools.partial(kernel, **kw),
            out_shape=out_shape,
            grid=(b * h, nq, nk),
            in_specs=in_specs,
            out_specs=out_specs,
            scratch_shapes=scratch,
            interpret=interpret,
        )(*inputs)

    if mode == "out":
        return _from_bh(res, b, s, h)
    if mode == "lse":
        o, lse = res
        return _from_bh(o, b, s, h), _from_bh(lse[:, :, 0], b, s, h)
    acc, m, l = res
    # Stats live in lane column 0 of their [bq, 128] tiles.
    return (_from_bh(acc, b, s, h), _from_bh(m[:, :, 0], b, s, h),
            _from_bh(l[:, :, 0], b, s, h))


# ---------------------------------------------------------------------------
# Backward kernels (FlashAttention-2 recompute form).
# ---------------------------------------------------------------------------


def _bwd_block(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
               qseg_ref, kseg_ref, *, scale, kv_len, q_len, row0, col0,
               causal, window=None, col_shift=0):
    """Rebuild one score block and its softmax-Jacobian products:
    returns ``(p, ds, do_f32)`` with ``p = exp(s − lse)`` the exact
    softmax probabilities and ``ds = p ∘ (dp − delta) · scale``."""
    q = q_ref[0]
    k = k_ref[0]
    v = v_ref[0]
    do = do_ref[0].astype(jnp.float32)
    lse = lse_ref[0][:, :1]               # [bq, 1]
    delta = delta_ref[0][:, :1]           # [bq, 1]

    s = lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                        preferred_element_type=jnp.float32) * scale
    mask = _score_mask(
        s.shape, kv_len=kv_len, q_len=q_len, row0=row0, col0=col0,
        col_shift=col_shift, causal=causal, window=window,
        qseg=None if qseg_ref is None else qseg_ref[0][:, :1],
        kseg=None if kseg_ref is None else kseg_ref[0, :1],
        kv_aligned=kv_len % s.shape[1] == 0,
        q_aligned=q_len % s.shape[0] == 0)
    if mask is not None:
        s = jnp.where(mask, s, NEG_INF)

    p = jnp.exp(s - lse)                  # [bq, bk], true probabilities
    dp = lax.dot_general(do, v.astype(jnp.float32),
                         (((1,), (1,)), ((), ())),
                         preferred_element_type=jnp.float32)
    ds = p * (dp - delta) * scale
    return p, ds, do


def _flash_bwd_dq_kernel(*refs, scale, kv_len, q_len, block_q, block_k,
                         causal, window=None, kv_start=0,
                         has_segments=False, folded=False):
    """Grid (b·h, q_blocks, k_blocks) — or the folded q-major live-block
    enumeration: dQ_i = Σ_j dS_ij K_j (scale folded into dS)."""
    refs, coords, last = _fold_coords(refs, folded)
    (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, qseg_ref,
     kseg_ref), (dq_ref,), (dq_scr,) = _unpack(refs, 1, has_segments,
                                               n_base=6)
    if coords is None:
        ib, jb = pl.program_id(1), pl.program_id(2)
        init = jb == 0
    else:
        ib, jb, init = coords

    @pl.when(init)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    def _compute():
        _, ds, _ = _bwd_block(q_ref, k_ref, v_ref, do_ref, lse_ref,
                              delta_ref, qseg_ref, kseg_ref, scale=scale,
                              kv_len=kv_len, q_len=q_len,
                              row0=ib * block_q,
                              col0=jb * block_k, col_shift=kv_start,
                              causal=causal, window=window)
        dq_scr[:] += lax.dot_general(
            ds, k_ref[0].astype(jnp.float32), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if folded:
        _compute()
    else:
        live = _band_live(ib * block_q, block_q,
                          kv_start + jb * block_k, block_k,
                          causal, window)
        if live is not None:
            @pl.when(live)
            def _live():
                _compute()
        else:
            _compute()

    @pl.when(last)
    def _finalize():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(*refs, scale, kv_len, q_len, block_q, block_k,
                          causal, window=None, kv_start=0,
                          has_segments=False, folded=False):
    """Grid (b·h, k_blocks, q_blocks) — or the folded k-major live-block
    enumeration: dV_j = Σ_i P_ijᵀ dO_i and dK_j = Σ_i dS_ijᵀ Q_i (scale
    folded into dS). Padded Q rows contribute exactly zero because their
    dO rows are zero-padded."""
    refs, coords, last = _fold_coords(refs, folded)
    (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, qseg_ref,
     kseg_ref), (dk_ref, dv_ref), (dk_scr, dv_scr) = _unpack(
        refs, 2, has_segments, n_base=6)
    if coords is None:
        jb, ib = pl.program_id(1), pl.program_id(2)
        init = ib == 0
    else:
        jb, ib, init = coords

    @pl.when(init)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    def _compute():
        p, ds, do = _bwd_block(q_ref, k_ref, v_ref, do_ref, lse_ref,
                               delta_ref, qseg_ref, kseg_ref, scale=scale,
                               kv_len=kv_len, q_len=q_len,
                               row0=ib * block_q,
                               col0=jb * block_k, col_shift=kv_start,
                               causal=causal, window=window)
        dv_scr[:] += lax.dot_general(p, do, (((0,), (0,)), ((), ())),
                                     preferred_element_type=jnp.float32)
        dk_scr[:] += lax.dot_general(
            ds, q_ref[0].astype(jnp.float32), (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if folded:
        _compute()
    else:
        # Same band, transposed view: the block is live iff its row range
        # intersects the k block's attended-row band — which is exactly
        # the q-major predicate with the same coordinates.
        live = _band_live(ib * block_q, block_q,
                          kv_start + jb * block_k, block_k,
                          causal, window)
        if live is not None:
            @pl.when(live)
            def _live():
                _compute()
        else:
            _compute()

    @pl.when(last)
    def _finalize():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def flash_attention_bwd(q, k, v, do, lse, delta, scale=None,
                        block_q=None, block_k=None, interpret=None,
                        causal: bool = False, out_dtype=None,
                        segment_ids=None, window=None, kv_start: int = 0):
    """The flash backward as a standalone op: ``(dq, dk, dv)`` from saved
    forward state. ``lse``/``delta`` are [B, S, H] f32 — the row logsumexp
    from the forward and ``rowsum(dO ∘ O)``. Exposed (not just wired into
    the custom_vjp) because ring attention's backward reuses it per ring
    step with the *global* lse/delta (parallel/ring_attention.py).

    ``out_dtype`` overrides the gradient dtype (default: match each
    input's). The ring backward passes f32 so its per-step partials are
    never quantized before the cross-step accumulation — matching its jnp
    twin engine."""
    from jax.experimental.pallas import tpu as pltpu

    scale, block_q, block_k, interpret = _resolve(
        q, scale, block_q, block_k, interpret)
    kv_start = _static_kv_start(kv_start)
    b, s, h, d = q.shape
    kv_len = k.shape[1]
    bq, bk = min(block_q, s), min(block_k, kv_len)
    dq_dt = q.dtype if out_dtype is None else out_dtype
    dk_dt = k.dtype if out_dtype is None else out_dtype
    dv_dt = v.dtype if out_dtype is None else out_dtype

    qb, dob = _to_bh(q, bq), _to_bh(do, bq)
    kb_, vb = _to_bh(k, bk), _to_bh(v, bk)
    lse_t = _stat_to_tile(lse.astype(jnp.float32), bq)
    delta_t = _stat_to_tile(delta.astype(jnp.float32), bq)
    spq, spk = qb.shape[1], kb_.shape[1]
    nq, nk = spq // bq, spk // bk

    has_seg = segment_ids is not None
    sched_q = _fold_schedule(nq, nk, bq, bk, causal, window, "q",
                             kv_start=kv_start)
    folded = sched_q is not None
    kw = dict(scale=scale, kv_len=kv_len, q_len=s, block_q=bq, block_k=bk,
              causal=causal, window=window, kv_start=kv_start,
              has_segments=has_seg, folded=folded)

    # dQ pass: q-major — outer/inner = (q block i, k block j).
    qi, kj, qi_seg, kj_seg = _index_maps(folded, h)
    q_spec_i = pl.BlockSpec((1, bq, d), qi)
    kv_spec_j = pl.BlockSpec((1, bk, d), kj)
    stat_spec_i = pl.BlockSpec((1, bq, 128), qi)

    in_specs = [q_spec_i, kv_spec_j, kv_spec_j, q_spec_i, stat_spec_i,
                stat_spec_i]
    inputs = [qb, kb_, vb, dob, lse_t, delta_t]
    if has_seg:
        q_seg, kv_seg = _norm_segments(segment_ids)
        in_specs += [
            pl.BlockSpec((1, bq, 128), qi_seg),
            pl.BlockSpec((1, 8, bk), kj_seg),
        ]
        inputs += [_seg_tile(q_seg, bq), _seg_lane(kv_seg, bk)]

    dq_scratch = [pltpu.VMEM((bq, d), jnp.float32)]
    dq_shape = jax.ShapeDtypeStruct(qb.shape, dq_dt)
    if folded:
        dq = pl.pallas_call(
            functools.partial(_flash_bwd_dq_kernel, **kw),
            out_shape=dq_shape,
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=1,
                grid=(b * h, sched_q.shape[1]),
                in_specs=in_specs,
                out_specs=q_spec_i,
                scratch_shapes=dq_scratch),
            interpret=interpret,
        )(jnp.asarray(sched_q), *inputs)
    else:
        dq = pl.pallas_call(
            functools.partial(_flash_bwd_dq_kernel, **kw),
            out_shape=dq_shape,
            grid=(b * h, nq, nk),
            in_specs=in_specs,
            out_specs=q_spec_i,
            scratch_shapes=dq_scratch,
            interpret=interpret,
        )(*inputs)

    # dK/dV pass: k-major — outer/inner = (k block j, q block i).
    qi2, kj2, qi2_seg, kj2_seg = _index_maps(folded, h, q_major=False)
    q_spec = pl.BlockSpec((1, bq, d), qi2)
    kv_spec = pl.BlockSpec((1, bk, d), kj2)
    stat_spec = pl.BlockSpec((1, bq, 128), qi2)
    in_specs2 = [q_spec, kv_spec, kv_spec, q_spec, stat_spec, stat_spec]
    if has_seg:
        in_specs2 += [
            pl.BlockSpec((1, bq, 128), qi2_seg),
            pl.BlockSpec((1, 8, bk), kj2_seg),
        ]
    dkv_shapes = [jax.ShapeDtypeStruct(kb_.shape, dk_dt),
                  jax.ShapeDtypeStruct(vb.shape, dv_dt)]
    dkv_scratch = [pltpu.VMEM((bk, d), jnp.float32),
                   pltpu.VMEM((bk, d), jnp.float32)]
    if folded:
        sched_k = _fold_schedule(nq, nk, bq, bk, causal, window, "k",
                                 kv_start=kv_start)
        dk, dv = pl.pallas_call(
            functools.partial(_flash_bwd_dkv_kernel, **kw),
            out_shape=dkv_shapes,
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=1,
                grid=(b * h, sched_k.shape[1]),
                in_specs=in_specs2,
                out_specs=[kv_spec, kv_spec],
                scratch_shapes=dkv_scratch),
            interpret=interpret,
        )(jnp.asarray(sched_k), *inputs)
    else:
        dk, dv = pl.pallas_call(
            functools.partial(_flash_bwd_dkv_kernel, **kw),
            out_shape=dkv_shapes,
            grid=(b * h, nk, nq),
            in_specs=in_specs2,
            out_specs=[kv_spec, kv_spec],
            scratch_shapes=dkv_scratch,
            interpret=interpret,
        )(*inputs)

    return (_from_bh(dq, b, s, h), _from_bh(dk, b, kv_len, h),
            _from_bh(dv, b, kv_len, h))


def attention_delta(o, do):
    """``D = rowsum(dO ∘ O)`` [B, S, H] f32 — the softmax-Jacobian row
    term. Plain XLA: an elementwise multiply-reduce fuses fine."""
    return jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)


# ---------------------------------------------------------------------------
# custom_vjp wiring + public API.
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8, 9))
def _flash(q, k, v, segment_ids, scale, block_q, block_k, interpret,
           causal, window):
    return _fwd_call(q, k, v, scale, block_q, block_k, interpret, causal,
                     mode="out", segment_ids=segment_ids, window=window)


def _flash_fwd_rule(q, k, v, segment_ids, scale, block_q, block_k,
                    interpret, causal, window):
    out, lse = _fwd_call(q, k, v, scale, block_q, block_k, interpret,
                         causal, mode="lse", segment_ids=segment_ids,
                         window=window)
    return out, (q, k, v, segment_ids, out, lse)


def _flash_bwd_rule(scale, block_q, block_k, interpret, causal, window,
                    res, do):
    import numpy as np

    q, k, v, segment_ids, out, lse = res
    delta = attention_delta(out, do)
    dq, dk, dv = flash_attention_bwd(q, k, v, do, lse, delta, scale=scale,
                                     block_q=block_q, block_k=block_k,
                                     interpret=interpret, causal=causal,
                                     segment_ids=segment_ids,
                                     window=window)
    # Integer segment ids carry no gradient: float0 cotangent (None stays
    # None — it's an empty pytree; tuples map per-leaf).
    dseg = jax.tree.map(
        lambda s: np.zeros(s.shape, jax.dtypes.float0), segment_ids)
    return dq, dk, dv, dseg


_flash.defvjp(_flash_fwd_rule, _flash_bwd_rule)


@functools.partial(jax.jit,
                   static_argnames=("scale", "block_q", "block_k",
                                    "interpret", "causal", "window"))
def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                    scale: float | None = None,
                    block_q: int | None = None,
                    block_k: int | None = None,
                    interpret: bool | None = None,
                    causal: bool = False,
                    segment_ids: jax.Array | None = None,
                    window: int | None = None) -> jax.Array:
    """FlashAttention over [B, S, H, D] tensors → [B, S, H, D].

    Contract-identical to :func:`ops.attention.xla_attention` (including
    under ``jax.grad`` — the custom_vjp runs the Pallas backward kernels);
    tests assert numerical agreement of both values and gradients.
    Sequence lengths that aren't multiples of the block sizes are
    zero-padded and masked inside the kernels. ``causal=True`` masks above
    the diagonal and skips fully-masked blocks. ``segment_ids`` [B, S]
    int32 restricts attention to same-segment pairs (packed sequences) in
    both directions; combine with ``causal`` for packed causal LM
    batches. A ``(q_seg [B, Sq], kv_seg [B, Skv])`` pair serves
    cross-shard callers (the ring walks K/V shards whose ids differ from
    the local Q shard's). ``window=W`` restricts attention to the band
    ``|row − col| < W`` (with ``causal`` only the lower half —
    sliding-window/local attention); out-of-band blocks are skipped
    fetch-free, so cost scales with W·S instead of S².
    """
    scale, block_q, block_k, interpret = _resolve(
        q, scale, block_q, block_k, interpret)
    if window is not None and window < 1:
        raise ValueError(f"window must be >= 1, got {window}")
    return _flash(q, k, v, segment_ids, scale, block_q, block_k, interpret,
                  causal, window)


@functools.partial(jax.jit,
                   static_argnames=("scale", "block_q", "block_k",
                                    "interpret", "causal", "window",
                                    "kv_start"))
def flash_attention_fwd_lse(q: jax.Array, k: jax.Array, v: jax.Array,
                            scale: float | None = None,
                            block_q: int | None = None,
                            block_k: int | None = None,
                            interpret: bool | None = None,
                            causal: bool = False,
                            segment_ids: jax.Array | None = None,
                            window: int | None = None,
                            kv_start: int = 0):
    """Forward with residual: ``(out [B,S,H,D], lse [B,S,H] f32)``.

    The save-for-backward interface: ``lse`` is the row logsumexp, the
    one statistic :func:`flash_attention_bwd` needs alongside O and dO —
    for any caller that manages its own residuals instead of going
    through :func:`flash_attention`'s custom_vjp. (Ring attention derives
    its residual lse from the merged stats inside its own forward scan —
    parallel/ring_attention.py — and pairs it with
    :func:`flash_attention_bwd` in its backward ring.)
    """
    scale, block_q, block_k, interpret = _resolve(
        q, scale, block_q, block_k, interpret)
    return _fwd_call(q, k, v, scale, block_q, block_k, interpret, causal,
                     mode="lse", segment_ids=segment_ids, window=window,
                     kv_start=_static_kv_start(kv_start))


@functools.partial(jax.jit,
                   static_argnames=("scale", "block_q", "block_k",
                                    "interpret", "causal", "window",
                                    "kv_start"))
def flash_attention_stats(q: jax.Array, k: jax.Array, v: jax.Array,
                          scale: float | None = None,
                          block_q: int | None = None,
                          block_k: int | None = None,
                          interpret: bool | None = None,
                          causal: bool = False,
                          segment_ids: jax.Array | None = None,
                          window: int | None = None,
                          kv_start: int = 0):
    """FlashAttention's raw partial-softmax state:
    ``(acc [B,S,H,D] f32 UNNORMALIZED accumulator, m [B,S,H] f32 row max,
    l [B,S,H] f32 normalizer)``; the normalized output is ``acc / l``.

    This is the partial-attention interface: partials over different K/V
    shards merge with the standard flash rule in full f32 — exactly what
    the ring-attention body needs to run its local block on the MXU via
    Pallas (:func:`parallel.ring_attention.ring_attention`).
    """
    scale, block_q, block_k, interpret = _resolve(
        q, scale, block_q, block_k, interpret)
    return _fwd_call(q, k, v, scale, block_q, block_k, interpret, causal,
                     mode="stats", segment_ids=segment_ids, window=window,
                     kv_start=_static_kv_start(kv_start))
