"""Blocked online-softmax attention — the Pallas TPU kernels, forward AND
backward.

The long-sequence attention path (SURVEY §5 "long-context"; BASELINE.json
ViT config "attention via Pallas"). The S×S score matrix never
materializes in HBM in either direction:

- forward: walk K/V blocks per Q block keeping the FlashAttention running
  statistics (row max ``m``, normalizer ``l``, unnormalized accumulator
  ``acc``) in VMEM scratch; emit the output and, for autodiff, the row
  logsumexp ``lse = m + log l``.
- backward (the FlashAttention-2 recompute form): two kernels that rebuild
  each score block from Q/K and the saved ``lse`` (so ``p = exp(s − lse)``
  is the exact softmax probability without storing it), using the
  ``D = rowsum(dO ∘ O)`` identity for the softmax Jacobian:
  * dQ kernel — grid (b·h, q_blocks, k_blocks): accumulates
    ``dQ_i = Σ_j dS_ij K_j · scale`` in VMEM scratch;
  * dK/dV kernel — grid (b·h, k_blocks, q_blocks): accumulates
    ``dV_j = Σ_i P_ijᵀ dO_i`` and ``dK_j = Σ_i dS_ijᵀ Q_i · scale``.

``flash_attention`` carries a ``jax.custom_vjp`` wiring the three kernels
together, so the whole long-context stack (ViT blocks, Ulysses all-to-all
attention, ring attention's per-block engine) differentiates. The
reference trains every op it exposes (``minimize`` builds the backward for
the whole graph, ``cifar10cnn.py:163``); this gives the flash path the
same property.

``causal=True`` applies a lower-triangular mask inside the kernels and
*skips* score blocks strictly above the diagonal (``@pl.when`` on the
block indices — on TPU the grid runs sequentially per core, so a skipped
block really is ~free), recovering the ~2× FLOP saving causal attention
allows in both directions.

Grid = (batch·heads, outer_blocks, inner_blocks), inner fastest-varying.
On TPU the grid is executed sequentially per core, so VMEM scratch carries
running state across the inner iterations of one outer block;
``@pl.when(inner == 0)`` resets it and the last inner iteration writes the
finished tile. Scores and all accumulators are f32 (VPU/MXU accumulate
dtype) regardless of input dtype.

On non-TPU backends the same kernels run under the Pallas interpreter
(tests exercise them on CPU); ``ops.attention.dispatch_attention`` routes
short sequences to the fused XLA path where materializing S×S is faster.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

NEG_INF = -1e30  # not -inf: exp(-inf - -inf) would NaN the first block

# ---------------------------------------------------------------------------
# Layout helpers. Per-row statistics (m, l, lse, delta) live in [rows, 128]
# f32 tiles with only lane column 0 meaningful: (8, 128) is the minimum f32
# TPU tile, and keeping stats sublane-oriented means the kernels read
# ``ref[:, :1]`` — a [rows, 1] slice that broadcasts against [rows, cols]
# score blocks with no lane→sublane transpose.
# ---------------------------------------------------------------------------


def _resolve(q, scale, block_q, block_k, interpret):
    """Fill in the static kernel parameters from the input shapes."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    s = q.shape[1]
    # Auto block size (None): re-tuned on a v5e each round. Round 2 found
    # 512 beats 128 from S>=2048; the round-3 sweep (with the backward
    # kernels and fetch-free clamps in play) found 1024 beats 512 across
    # the whole fwd+bwd training path — 1.64x at S=2048 (7.5 vs 12.3 ms),
    # 1.28x at S=16384 (129.6 vs 165.6 ms), causal 107->76 ms — while
    # 2048 exceeds the 16 MB scoped-VMEM limit. 1024 is taken only at
    # head_dim <= 64 (the ladder's geometry; bigger heads double the
    # block buffers and the fwd acc scratch, re-approaching the VMEM
    # ceiling 2048 hit). 128 still wins below S=2048.
    d = q.shape[-1]
    auto_block = (1024 if d <= 64 else 512) if s >= 2048 else 128
    block_q = auto_block if block_q is None else block_q
    block_k = auto_block if block_k is None else block_k
    return float(scale), block_q, block_k, interpret


def _to_bh(x, block):
    """[B, S, H, D] → [B·H, S_padded, D], S padded to a ``block`` multiple."""
    b, s, h, d = x.shape
    x = jnp.transpose(x, (0, 2, 1, 3)).reshape(b * h, s, d)
    pad = (-s) % block
    if pad:
        x = jnp.pad(x, ((0, 0), (0, pad), (0, 0)))
    return x


def _from_bh(x, b, s, h):
    """[B·H, S_padded, ...] → [B, S, H, ...]."""
    x = x[:, :s]
    x = x.reshape(b, h, s, *x.shape[2:])
    return jnp.swapaxes(x, 1, 2)


def _stat_to_tile(x, block):
    """[B, S, H] f32 stat → [B·H, S_padded, 128] tile (lane col 0)."""
    b, s, h = x.shape
    t = jnp.transpose(x, (0, 2, 1)).reshape(b * h, s)
    pad = (-s) % block
    if pad:
        t = jnp.pad(t, ((0, 0), (0, pad)))
    return jnp.pad(t[:, :, None], ((0, 0), (0, 0), (0, 127)))


# ---------------------------------------------------------------------------
# Forward kernels.
# ---------------------------------------------------------------------------


def _score_mask(shape, *, kv_len, q_len, row0, col0, causal,
                qseg=None, kseg=None, window=None):
    """The shared validity mask for one [bq, bk] score block: padded K/V
    columns off; optionally causal (col ≤ row in global coordinates);
    optionally same-segment only (packed sequences); optionally a
    sliding window (band |row − col| < window; with causal only the
    lower half remains — Mistral-style local attention). Padded Q rows
    (row ≥ q_len) are *exempt* from the segment and window masks so
    every padded row keeps l > 0 — their lse stays finite, and their
    gradient contributions vanish anyway because dO is zero-padded."""
    col = col0 + lax.broadcasted_iota(jnp.int32, shape, 1)
    mask = col < kv_len
    row = row0 + lax.broadcasted_iota(jnp.int32, shape, 0)
    pad_row = row >= q_len
    if causal:
        mask = mask & (col <= row)
    if window is not None:
        band = col > row - window
        if not causal:
            band = band & (col < row + window)
        mask = mask & (band | pad_row)
    if qseg is not None:
        mask = mask & ((qseg == kseg) | pad_row)
    return mask


def _flash_update(q_ref, k_ref, v_ref, m_scr, l_scr, acc_scr, *,
                  scale: float, kv_len: int, q_len: int, block_q: int,
                  block_k: int, causal: bool, window=None,
                  qseg_ref=None, kseg_ref=None):
    """One K/V-block update of the running (m, l, acc) — shared by the
    plain, lse-emitting, and stats-emitting kernels."""
    ib = pl.program_id(1)
    kb = pl.program_id(2)

    @pl.when(kb == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    def _update():
        q = q_ref[0]                      # [bq, d]
        k = k_ref[0]                      # [bk, d]
        v = v_ref[0]                      # [bk, d]

        s = lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
        mask = _score_mask(
            s.shape, kv_len=kv_len, q_len=q_len, row0=ib * block_q,
            col0=kb * block_k, causal=causal, window=window,
            qseg=None if qseg_ref is None else qseg_ref[0][:, :1],
            kseg=None if kseg_ref is None else kseg_ref[0, :1])
        s = jnp.where(mask, s, NEG_INF)

        m_prev = m_scr[:, :1]                                   # [bq, 1]
        m_cur = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_cur)
        p = jnp.exp(s - m_cur)                                  # [bq, bk]
        l_scr[:, :1] = (l_scr[:, :1] * alpha
                        + jnp.sum(p, axis=-1, keepdims=True))
        acc_scr[:] = acc_scr[:] * alpha + lax.dot_general(
            p, v.astype(jnp.float32), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[:, :1] = m_cur

    live = _band_live(ib * block_q, block_q, kb * block_k, block_k,
                      causal, window)
    if live is not None:
        @pl.when(live)
        def _live():
            _update()
    else:
        _update()


def _unpack(refs, n_out, has_segments, n_base=3):
    """Split a kernel's positional refs into (base inputs…, qseg, kseg),
    outs, scratch. ``n_base`` is the count of always-present inputs (3 for
    the forward kernels: q/k/v; 6 for the backward: +do/lse/delta); the
    two segment-id refs are only present when asked for, so the
    non-segmented path pays zero extra bandwidth."""
    n_in = n_base + (2 if has_segments else 0)
    ins, outs, scratch = refs[:n_in], refs[n_in:n_in + n_out], \
        refs[n_in + n_out:]
    if not has_segments:
        ins = ins + (None, None)
    return ins, outs, scratch


def _safe_l(l_col):
    """Guard against fully-dead rows (every block skipped — possible when
    a window/cross-length geometry leaves a row with no keys): l stays 0
    there, and the plain division would emit NaN that poisons the
    backward. Any live element contributes exp(0)=1, so l >= 1 whenever
    a row has keys; dead rows divide by 1 and output exact zeros."""
    return jnp.maximum(l_col, 1e-30)


def _flash_kernel(*refs, has_segments: bool = False, **kw):
    (q_ref, k_ref, v_ref, qseg_ref, kseg_ref), (o_ref,), \
        (m_scr, l_scr, acc_scr) = _unpack(refs, 1, has_segments)
    _flash_update(q_ref, k_ref, v_ref, m_scr, l_scr, acc_scr,
                  qseg_ref=qseg_ref, kseg_ref=kseg_ref, **kw)

    @pl.when(pl.program_id(2) == pl.num_programs(2) - 1)
    def _finalize():
        o_ref[0] = (acc_scr[:] / _safe_l(l_scr[:, :1])).astype(o_ref.dtype)


def _flash_fwd_kernel(*refs, has_segments: bool = False, **kw):
    """Forward that additionally emits the row logsumexp — the single
    statistic the FlashAttention-2 backward needs."""
    (q_ref, k_ref, v_ref, qseg_ref, kseg_ref), (o_ref, lse_ref), \
        (m_scr, l_scr, acc_scr) = _unpack(refs, 2, has_segments)
    _flash_update(q_ref, k_ref, v_ref, m_scr, l_scr, acc_scr,
                  qseg_ref=qseg_ref, kseg_ref=kseg_ref, **kw)

    @pl.when(pl.program_id(2) == pl.num_programs(2) - 1)
    def _finalize():
        o_ref[0] = (acc_scr[:] / _safe_l(l_scr[:, :1])).astype(o_ref.dtype)
        # Lane cols 1..127 hold -inf-ish garbage (NEG_INF + log 0); only
        # col 0 is ever read back. Fully-dead rows (l == 0) publish a
        # LARGE lse so the backward's p = exp(s − lse) is exactly 0 —
        # their arbitrary outputs must not leak gradient into other
        # rows' dK/dV accumulators.
        lse = jnp.where(l_scr[:] > 0.0, m_scr[:] + jnp.log(_safe_l(l_scr[:])),
                        1e30)
        lse_ref[0] = lse


def _flash_stats_kernel(*refs, has_segments: bool = False, **kw):
    """Like ``_flash_kernel`` but emits the raw running state — f32
    UNNORMALIZED accumulator plus row max ``m`` and normalizer ``l`` —
    the partial-softmax interface the ring-attention merge rule needs
    (parallel/ring_attention.py). Emitting ``acc_scr`` directly keeps the
    partial in f32 regardless of input dtype (normalizing to the input
    dtype and re-multiplying by ``l`` would quantize every ring step's
    partial)."""
    (q_ref, k_ref, v_ref, qseg_ref, kseg_ref), (acc_ref, m_ref, l_ref), \
        (m_scr, l_scr, acc_scr) = _unpack(refs, 3, has_segments)
    _flash_update(q_ref, k_ref, v_ref, m_scr, l_scr, acc_scr,
                  qseg_ref=qseg_ref, kseg_ref=kseg_ref, **kw)

    @pl.when(pl.program_id(2) == pl.num_programs(2) - 1)
    def _finalize():
        acc_ref[0] = acc_scr[:]
        m_ref[0] = m_scr[:]
        l_ref[0] = l_scr[:]


def _seg_tile(seg, block):
    """[B, S] int32 → [B, S_padded, 128] Q-side tile (lane col 0; pad
    value irrelevant — padded rows are mask-exempt)."""
    b, s = seg.shape
    pad = (-s) % block
    if pad:
        seg = jnp.pad(seg, ((0, 0), (0, pad)), constant_values=-1)
    return jnp.pad(seg[:, :, None], ((0, 0), (0, 0), (0, 127)))


def _seg_lane(seg, block):
    """[B, S] int32 → [B, 8, S_padded] K-side lane layout (padded cols
    are already killed by the kv_len mask). The middle dim exists purely
    for TPU tiling: a (1, bk) block of a [B, S] array has a sublane dim
    of 1, which Mosaic rejects for B > 1 (must be divisible by 8 or the
    full dim); an 8-row broadcast makes the block (1, 8, bk) — legal,
    and only row 0 is ever read."""
    pad = (-seg.shape[1]) % block
    if pad:
        seg = jnp.pad(seg, ((0, 0), (0, pad)), constant_values=-1)
    return jnp.broadcast_to(seg[:, None, :],
                            (seg.shape[0], 8, seg.shape[1]))


def _kv_clamp(causal, bq, bk, window=None, nk=None):
    """K/V block-index map component for (…, q_block i, k_block j) grids.

    Causal/windowed grids never read blocks outside the live band (the
    kernels guard compute with ``pl.when``), but Pallas still issues the
    operand DMA for every grid step — UNLESS the block index repeats, in
    which case the pipeline skips the re-fetch. Clamping the index into
    the live band makes every dead iteration a repeat of a live one:
    skipped ticks become fetch-free, which is most of the saving at long
    S (BASELINE.md measured the unclamped causal skip at only
    1.1–1.33× vs 1.4–1.55× clamped)."""
    if not causal and window is None:
        return lambda i, j: j

    def clamp(i, j):
        out = j
        if causal:
            out = jnp.minimum(out, (i * bq + bq - 1) // bk)
        elif window is not None:
            out = jnp.minimum(out, (i * bq + bq - 1 + window - 1) // bk)
        if window is not None:
            out = jnp.maximum(out, (i * bq - window + 1) // bk)
        # Bound into the K/V block range: q_len > kv_len leaves some
        # q blocks with no live K/V block at all, and an unbounded clamp
        # would index past the array on those fully-dead rows.
        return jnp.clip(out, 0, nk - 1)

    return clamp


def _band_live(row0, rows, col0, cols, causal, window):
    """Block-liveness predicate for a [rows, cols] score block whose
    top-left is global (row0, col0): does the block intersect the valid
    causal/window band? None when nothing can be skipped. ONE definition
    for all three kernels (fwd, dQ, dK/dV) so the skip logic cannot
    drift from ``_score_mask``'s element mask."""
    live = None
    if causal:
        live = col0 <= row0 + rows - 1
    if window is not None:
        lo = col0 + cols - 1 > row0 - window
        live = lo if live is None else live & lo
        if not causal:
            live = live & (col0 < row0 + rows - 1 + window)
    return live


def _norm_segments(segment_ids):
    """``None`` | ``[B, S]`` (self-attention) | ``(q_seg, kv_seg)``
    (cross/sharded attention — ring blocks see different shards) →
    ``(q_seg, kv_seg)`` int32 or ``(None, None)``."""
    if segment_ids is None:
        return None, None
    if isinstance(segment_ids, (tuple, list)):
        q_seg, kv_seg = segment_ids
        return q_seg.astype(jnp.int32), kv_seg.astype(jnp.int32)
    seg = segment_ids.astype(jnp.int32)
    return seg, seg


def _fwd_call(q, k, v, scale, block_q, block_k, interpret, causal,
              mode: str, segment_ids=None, window=None):
    """Shared forward pallas_call builder.

    mode: "out" → out; "lse" → (out, lse [B,S,H]);
    "stats" → (acc, m, l) — the ring merge interface.
    ``segment_ids`` [B, S] int32 restricts attention to equal-id pairs
    (packed sequences).
    """
    from jax.experimental.pallas import tpu as pltpu

    b, s, h, d = q.shape
    kv_len = k.shape[1]
    bq, bk = min(block_q, s), min(block_k, kv_len)

    qb = _to_bh(q, bq)
    kb_ = _to_bh(k, bk)
    vb = _to_bh(v, bk)
    spq, spk = qb.shape[1], kb_.shape[1]
    nq, nk = spq // bq, spk // bk
    has_seg = segment_ids is not None

    kw = dict(scale=scale, kv_len=kv_len, q_len=s, block_q=bq, block_k=bk,
              causal=causal, window=window, has_segments=has_seg)
    kvc = _kv_clamp(causal, bq, bk, window=window, nk=nk)
    in_specs = [
        pl.BlockSpec((1, bq, d), lambda g, i, j: (g, i, 0)),
        pl.BlockSpec((1, bk, d), lambda g, i, j: (g, kvc(i, j), 0)),
        pl.BlockSpec((1, bk, d), lambda g, i, j: (g, kvc(i, j), 0)),
    ]
    inputs = [qb, kb_, vb]
    if has_seg:
        q_seg, kv_seg = _norm_segments(segment_ids)
        # Segment ids are per (batch, position) — the index maps fold the
        # head out of the grid's batch·head axis.
        in_specs += [
            pl.BlockSpec((1, bq, 128), lambda g, i, j: (g // h, i, 0)),
            pl.BlockSpec((1, 8, bk),
                         lambda g, i, j: (g // h, 0, kvc(i, j))),
        ]
        inputs += [_seg_tile(q_seg, bq), _seg_lane(kv_seg, bk)]

    o_spec = pl.BlockSpec((1, bq, d), lambda g, i, j: (g, i, 0))
    stat_spec = pl.BlockSpec((1, bq, 128), lambda g, i, j: (g, i, 0))
    stat_shape = jax.ShapeDtypeStruct((b * h, spq, 128), jnp.float32)
    if mode == "out":
        kernel, out_shape, out_specs = (
            _flash_kernel, jax.ShapeDtypeStruct(qb.shape, q.dtype), o_spec)
    elif mode == "lse":
        kernel = _flash_fwd_kernel
        out_shape = [jax.ShapeDtypeStruct(qb.shape, q.dtype), stat_shape]
        out_specs = [o_spec, stat_spec]
    else:
        kernel = _flash_stats_kernel
        out_shape = [jax.ShapeDtypeStruct(qb.shape, jnp.float32),
                     stat_shape, stat_shape]
        out_specs = [o_spec, stat_spec, stat_spec]

    res = pl.pallas_call(
        functools.partial(kernel, **kw),
        out_shape=out_shape,
        grid=(b * h, nq, nk),
        in_specs=in_specs,
        out_specs=out_specs,
        scratch_shapes=[
            pltpu.VMEM((bq, 128), jnp.float32),   # m (col 0 used)
            pltpu.VMEM((bq, 128), jnp.float32),   # l (col 0 used)
            pltpu.VMEM((bq, d), jnp.float32),     # acc
        ],
        interpret=interpret,
    )(*inputs)

    if mode == "out":
        return _from_bh(res, b, s, h)
    if mode == "lse":
        o, lse = res
        return _from_bh(o, b, s, h), _from_bh(lse[:, :, 0], b, s, h)
    acc, m, l = res
    # Stats live in lane column 0 of their [bq, 128] tiles.
    return (_from_bh(acc, b, s, h), _from_bh(m[:, :, 0], b, s, h),
            _from_bh(l[:, :, 0], b, s, h))


# ---------------------------------------------------------------------------
# Backward kernels (FlashAttention-2 recompute form).
# ---------------------------------------------------------------------------


def _bwd_block(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
               qseg_ref, kseg_ref, *, scale, kv_len, q_len, row0, col0,
               causal, window=None):
    """Rebuild one score block and its softmax-Jacobian products:
    returns ``(p, ds, do_f32)`` with ``p = exp(s − lse)`` the exact
    softmax probabilities and ``ds = p ∘ (dp − delta) · scale``."""
    q = q_ref[0]
    k = k_ref[0]
    v = v_ref[0]
    do = do_ref[0].astype(jnp.float32)
    lse = lse_ref[0][:, :1]               # [bq, 1]
    delta = delta_ref[0][:, :1]           # [bq, 1]

    s = lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                        preferred_element_type=jnp.float32) * scale
    mask = _score_mask(
        s.shape, kv_len=kv_len, q_len=q_len, row0=row0, col0=col0,
        causal=causal, window=window,
        qseg=None if qseg_ref is None else qseg_ref[0][:, :1],
        kseg=None if kseg_ref is None else kseg_ref[0, :1])
    s = jnp.where(mask, s, NEG_INF)

    p = jnp.exp(s - lse)                  # [bq, bk], true probabilities
    dp = lax.dot_general(do, v.astype(jnp.float32),
                         (((1,), (1,)), ((), ())),
                         preferred_element_type=jnp.float32)
    ds = p * (dp - delta) * scale
    return p, ds, do


def _flash_bwd_dq_kernel(*refs, scale, kv_len, q_len, block_q, block_k,
                         causal, window=None, has_segments=False):
    """Grid (b·h, q_blocks, k_blocks): dQ_i = Σ_j dS_ij K_j (scale folded
    into dS)."""
    (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, qseg_ref,
     kseg_ref), (dq_ref,), (dq_scr,) = _unpack(refs, 1, has_segments,
                                               n_base=6)
    ib, jb = pl.program_id(1), pl.program_id(2)

    @pl.when(jb == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    def _compute():
        _, ds, _ = _bwd_block(q_ref, k_ref, v_ref, do_ref, lse_ref,
                              delta_ref, qseg_ref, kseg_ref, scale=scale,
                              kv_len=kv_len, q_len=q_len,
                              row0=ib * block_q, col0=jb * block_k,
                              causal=causal, window=window)
        dq_scr[:] += lax.dot_general(
            ds, k_ref[0].astype(jnp.float32), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    live = _band_live(ib * block_q, block_q, jb * block_k, block_k,
                      causal, window)
    if live is not None:
        @pl.when(live)
        def _live():
            _compute()
    else:
        _compute()

    @pl.when(jb == pl.num_programs(2) - 1)
    def _finalize():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(*refs, scale, kv_len, q_len, block_q, block_k,
                          causal, window=None, has_segments=False):
    """Grid (b·h, k_blocks, q_blocks): dV_j = Σ_i P_ijᵀ dO_i and
    dK_j = Σ_i dS_ijᵀ Q_i (scale folded into dS). Padded Q rows contribute
    exactly zero because their dO rows are zero-padded."""
    (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, qseg_ref,
     kseg_ref), (dk_ref, dv_ref), (dk_scr, dv_scr) = _unpack(
        refs, 2, has_segments, n_base=6)
    jb, ib = pl.program_id(1), pl.program_id(2)

    @pl.when(ib == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    def _compute():
        p, ds, do = _bwd_block(q_ref, k_ref, v_ref, do_ref, lse_ref,
                               delta_ref, qseg_ref, kseg_ref, scale=scale,
                               kv_len=kv_len, q_len=q_len,
                               row0=ib * block_q, col0=jb * block_k,
                               causal=causal, window=window)
        dv_scr[:] += lax.dot_general(p, do, (((0,), (0,)), ((), ())),
                                     preferred_element_type=jnp.float32)
        dk_scr[:] += lax.dot_general(
            ds, q_ref[0].astype(jnp.float32), (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    # Same band, transposed view: the block is live iff its row range
    # intersects the k block's attended-row band — which is exactly the
    # q-major predicate with the same coordinates.
    live = _band_live(ib * block_q, block_q, jb * block_k, block_k,
                      causal, window)
    if live is not None:
        @pl.when(live)
        def _live():
            _compute()
    else:
        _compute()

    @pl.when(ib == pl.num_programs(2) - 1)
    def _finalize():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def flash_attention_bwd(q, k, v, do, lse, delta, scale=None,
                        block_q=None, block_k=None, interpret=None,
                        causal: bool = False, out_dtype=None,
                        segment_ids=None, window=None):
    """The flash backward as a standalone op: ``(dq, dk, dv)`` from saved
    forward state. ``lse``/``delta`` are [B, S, H] f32 — the row logsumexp
    from the forward and ``rowsum(dO ∘ O)``. Exposed (not just wired into
    the custom_vjp) because ring attention's backward reuses it per ring
    step with the *global* lse/delta (parallel/ring_attention.py).

    ``out_dtype`` overrides the gradient dtype (default: match each
    input's). The ring backward passes f32 so its per-step partials are
    never quantized before the cross-step accumulation — matching its jnp
    twin engine."""
    from jax.experimental.pallas import tpu as pltpu

    scale, block_q, block_k, interpret = _resolve(
        q, scale, block_q, block_k, interpret)
    b, s, h, d = q.shape
    kv_len = k.shape[1]
    bq, bk = min(block_q, s), min(block_k, kv_len)
    dq_dt = q.dtype if out_dtype is None else out_dtype
    dk_dt = k.dtype if out_dtype is None else out_dtype
    dv_dt = v.dtype if out_dtype is None else out_dtype

    qb, dob = _to_bh(q, bq), _to_bh(do, bq)
    kb_, vb = _to_bh(k, bk), _to_bh(v, bk)
    lse_t = _stat_to_tile(lse.astype(jnp.float32), bq)
    delta_t = _stat_to_tile(delta.astype(jnp.float32), bq)
    spq, spk = qb.shape[1], kb_.shape[1]
    nq, nk = spq // bq, spk // bk

    has_seg = segment_ids is not None
    kw = dict(scale=scale, kv_len=kv_len, q_len=s, block_q=bq, block_k=bk,
              causal=causal, window=window, has_segments=has_seg)
    kvc = _kv_clamp(causal, bq, bk, window=window, nk=nk)
    q_spec_i = pl.BlockSpec((1, bq, d), lambda g, i, j: (g, i, 0))
    kv_spec_j = pl.BlockSpec((1, bk, d), lambda g, i, j: (g, kvc(i, j), 0))
    stat_spec_i = pl.BlockSpec((1, bq, 128), lambda g, i, j: (g, i, 0))

    in_specs = [q_spec_i, kv_spec_j, kv_spec_j, q_spec_i, stat_spec_i,
                stat_spec_i]
    inputs = [qb, kb_, vb, dob, lse_t, delta_t]
    if has_seg:
        q_seg, kv_seg = _norm_segments(segment_ids)
        in_specs += [
            pl.BlockSpec((1, bq, 128), lambda g, i, j: (g // h, i, 0)),
            pl.BlockSpec((1, 8, bk),
                         lambda g, i, j: (g // h, 0, kvc(i, j))),
        ]
        inputs += [_seg_tile(q_seg, bq), _seg_lane(kv_seg, bk)]

    dq = pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel, **kw),
        out_shape=jax.ShapeDtypeStruct(qb.shape, dq_dt),
        grid=(b * h, nq, nk),
        in_specs=in_specs,
        out_specs=q_spec_i,
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        interpret=interpret,
    )(*inputs)

    # dK/dV grid: k blocks outer, q blocks inner (fastest). Causal live
    # region is i >= ceil((j·bk − bq + 1)/bq) = (j·bk)//bq; clamping the
    # q-side maps into it makes the dead head of each j-row fetch-free
    # (same repeat-index trick as the forward).
    if causal or window is not None:
        def qc(j, i):
            # Bounded into [0, nq-1]: with kv_len > q_len the trailing k
            # rows have NO live q block at all, and an unbounded clamp
            # would index past the q array on those fully-dead j-rows.
            out = i
            if causal:
                out = jnp.maximum(out, (j * bk) // bq)
            elif window is not None:
                out = jnp.maximum(
                    out, jnp.maximum(0, (j * bk - window + 1) // bq))
            if window is not None:
                out = jnp.minimum(
                    out, (j * bk + bk - 1 + window - 1) // bq)
            return jnp.clip(out, 0, nq - 1)
    else:
        def qc(j, i):
            return i
    q_spec = pl.BlockSpec((1, bq, d), lambda g, j, i: (g, qc(j, i), 0))
    kv_spec = pl.BlockSpec((1, bk, d), lambda g, j, i: (g, j, 0))
    stat_spec = pl.BlockSpec((1, bq, 128),
                             lambda g, j, i: (g, qc(j, i), 0))
    in_specs2 = [q_spec, kv_spec, kv_spec, q_spec, stat_spec, stat_spec]
    if has_seg:
        in_specs2 += [
            pl.BlockSpec((1, bq, 128),
                         lambda g, j, i: (g // h, qc(j, i), 0)),
            pl.BlockSpec((1, 8, bk), lambda g, j, i: (g // h, 0, j)),
        ]
    dk, dv = pl.pallas_call(
        functools.partial(_flash_bwd_dkv_kernel, **kw),
        out_shape=[jax.ShapeDtypeStruct(kb_.shape, dk_dt),
                   jax.ShapeDtypeStruct(vb.shape, dv_dt)],
        grid=(b * h, nk, nq),
        in_specs=in_specs2,
        out_specs=[kv_spec, kv_spec],
        scratch_shapes=[pltpu.VMEM((bk, d), jnp.float32),
                        pltpu.VMEM((bk, d), jnp.float32)],
        interpret=interpret,
    )(*inputs)

    return (_from_bh(dq, b, s, h), _from_bh(dk, b, kv_len, h),
            _from_bh(dv, b, kv_len, h))


def attention_delta(o, do):
    """``D = rowsum(dO ∘ O)`` [B, S, H] f32 — the softmax-Jacobian row
    term. Plain XLA: an elementwise multiply-reduce fuses fine."""
    return jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)


# ---------------------------------------------------------------------------
# custom_vjp wiring + public API.
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8, 9))
def _flash(q, k, v, segment_ids, scale, block_q, block_k, interpret,
           causal, window):
    return _fwd_call(q, k, v, scale, block_q, block_k, interpret, causal,
                     mode="out", segment_ids=segment_ids, window=window)


def _flash_fwd_rule(q, k, v, segment_ids, scale, block_q, block_k,
                    interpret, causal, window):
    out, lse = _fwd_call(q, k, v, scale, block_q, block_k, interpret,
                         causal, mode="lse", segment_ids=segment_ids,
                         window=window)
    return out, (q, k, v, segment_ids, out, lse)


def _flash_bwd_rule(scale, block_q, block_k, interpret, causal, window,
                    res, do):
    import numpy as np

    q, k, v, segment_ids, out, lse = res
    delta = attention_delta(out, do)
    dq, dk, dv = flash_attention_bwd(q, k, v, do, lse, delta, scale=scale,
                                     block_q=block_q, block_k=block_k,
                                     interpret=interpret, causal=causal,
                                     segment_ids=segment_ids,
                                     window=window)
    # Integer segment ids carry no gradient: float0 cotangent (None stays
    # None — it's an empty pytree; tuples map per-leaf).
    dseg = jax.tree.map(
        lambda s: np.zeros(s.shape, jax.dtypes.float0), segment_ids)
    return dq, dk, dv, dseg


_flash.defvjp(_flash_fwd_rule, _flash_bwd_rule)


@functools.partial(jax.jit,
                   static_argnames=("scale", "block_q", "block_k",
                                    "interpret", "causal", "window"))
def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                    scale: float | None = None,
                    block_q: int | None = None,
                    block_k: int | None = None,
                    interpret: bool | None = None,
                    causal: bool = False,
                    segment_ids: jax.Array | None = None,
                    window: int | None = None) -> jax.Array:
    """FlashAttention over [B, S, H, D] tensors → [B, S, H, D].

    Contract-identical to :func:`ops.attention.xla_attention` (including
    under ``jax.grad`` — the custom_vjp runs the Pallas backward kernels);
    tests assert numerical agreement of both values and gradients.
    Sequence lengths that aren't multiples of the block sizes are
    zero-padded and masked inside the kernels. ``causal=True`` masks above
    the diagonal and skips fully-masked blocks. ``segment_ids`` [B, S]
    int32 restricts attention to same-segment pairs (packed sequences) in
    both directions; combine with ``causal`` for packed causal LM
    batches. A ``(q_seg [B, Sq], kv_seg [B, Skv])`` pair serves
    cross-shard callers (the ring walks K/V shards whose ids differ from
    the local Q shard's). ``window=W`` restricts attention to the band
    ``|row − col| < W`` (with ``causal`` only the lower half —
    sliding-window/local attention); out-of-band blocks are skipped
    fetch-free, so cost scales with W·S instead of S².
    """
    scale, block_q, block_k, interpret = _resolve(
        q, scale, block_q, block_k, interpret)
    if window is not None and window < 1:
        raise ValueError(f"window must be >= 1, got {window}")
    return _flash(q, k, v, segment_ids, scale, block_q, block_k, interpret,
                  causal, window)


@functools.partial(jax.jit,
                   static_argnames=("scale", "block_q", "block_k",
                                    "interpret", "causal", "window"))
def flash_attention_fwd_lse(q: jax.Array, k: jax.Array, v: jax.Array,
                            scale: float | None = None,
                            block_q: int | None = None,
                            block_k: int | None = None,
                            interpret: bool | None = None,
                            causal: bool = False,
                            segment_ids: jax.Array | None = None,
                            window: int | None = None):
    """Forward with residual: ``(out [B,S,H,D], lse [B,S,H] f32)``.

    The save-for-backward interface: ``lse`` is the row logsumexp, the
    one statistic :func:`flash_attention_bwd` needs alongside O and dO —
    for any caller that manages its own residuals instead of going
    through :func:`flash_attention`'s custom_vjp. (Ring attention derives
    its residual lse from the merged stats inside its own forward scan —
    parallel/ring_attention.py — and pairs it with
    :func:`flash_attention_bwd` in its backward ring.)
    """
    scale, block_q, block_k, interpret = _resolve(
        q, scale, block_q, block_k, interpret)
    return _fwd_call(q, k, v, scale, block_q, block_k, interpret, causal,
                     mode="lse", segment_ids=segment_ids, window=window)


@functools.partial(jax.jit,
                   static_argnames=("scale", "block_q", "block_k",
                                    "interpret", "causal", "window"))
def flash_attention_stats(q: jax.Array, k: jax.Array, v: jax.Array,
                          scale: float | None = None,
                          block_q: int | None = None,
                          block_k: int | None = None,
                          interpret: bool | None = None,
                          causal: bool = False,
                          segment_ids: jax.Array | None = None,
                          window: int | None = None):
    """FlashAttention's raw partial-softmax state:
    ``(acc [B,S,H,D] f32 UNNORMALIZED accumulator, m [B,S,H] f32 row max,
    l [B,S,H] f32 normalizer)``; the normalized output is ``acc / l``.

    This is the partial-attention interface: partials over different K/V
    shards merge with the standard flash rule in full f32 — exactly what
    the ring-attention body needs to run its local block on the MXU via
    Pallas (:func:`parallel.ring_attention.ring_attention`).
    """
    scale, block_q, block_k, interpret = _resolve(
        q, scale, block_q, block_k, interpret)
    return _fwd_call(q, k, v, scale, block_q, block_k, interpret, causal,
                     mode="stats", segment_ids=segment_ids, window=window)
