"""Blocked online-softmax attention — the Pallas TPU kernel.

The long-sequence attention path (SURVEY §5 "long-context"; BASELINE.json
ViT config "attention via Pallas"). The S×S score matrix never
materializes in HBM: the kernel walks K/V blocks for each Q block keeping
the FlashAttention running statistics (row max ``m``, normalizer ``l``,
unnormalized accumulator ``acc``) in VMEM scratch.

Grid = (batch·heads, q_blocks, k_blocks), k fastest-varying. On TPU the
grid is executed sequentially per core, so VMEM scratch carries ``m/l/acc``
across the k iterations of one q block; ``@pl.when(kb == 0)`` resets them
and the last k iteration writes the normalized output tile. Scores and the
accumulator are f32 (VPU/MXU accumulate dtype) regardless of input dtype.

On non-TPU backends the same kernel runs under the Pallas interpreter
(tests exercise it on CPU); ``ops.attention.dispatch_attention`` routes
short sequences to the fused XLA path where materializing S×S is faster.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

NEG_INF = -1e30  # not -inf: exp(-inf - -inf) would NaN the first block


def _flash_update(q_ref, k_ref, v_ref, m_scr, l_scr, acc_scr, *,
                  scale: float, kv_len: int, block_k: int):
    """One K/V-block update of the running (m, l, acc) — shared by the
    plain and stats-emitting kernels."""
    kb = pl.program_id(2)

    @pl.when(kb == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    q = q_ref[0]                      # [bq, d]
    k = k_ref[0]                      # [bk, d]
    v = v_ref[0]                      # [bk, d]

    s = lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                        preferred_element_type=jnp.float32) * scale
    col = kb * block_k + lax.broadcasted_iota(jnp.int32, s.shape, 1)
    s = jnp.where(col < kv_len, s, NEG_INF)   # mask padded K/V rows

    m_prev = m_scr[:, :1]                                   # [bq, 1]
    m_cur = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
    alpha = jnp.exp(m_prev - m_cur)
    p = jnp.exp(s - m_cur)                                  # [bq, bk]
    l_scr[:, :1] = l_scr[:, :1] * alpha + jnp.sum(p, axis=-1, keepdims=True)
    acc_scr[:] = acc_scr[:] * alpha + lax.dot_general(
        p, v.astype(jnp.float32), (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    m_scr[:, :1] = m_cur


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr, *,
                  scale: float, kv_len: int, block_k: int):
    _flash_update(q_ref, k_ref, v_ref, m_scr, l_scr, acc_scr, scale=scale,
                  kv_len=kv_len, block_k=block_k)

    @pl.when(pl.program_id(2) == pl.num_programs(2) - 1)
    def _finalize():
        o_ref[0] = (acc_scr[:] / l_scr[:, :1]).astype(o_ref.dtype)


def _flash_stats_kernel(q_ref, k_ref, v_ref, acc_ref, m_ref, l_ref,
                        m_scr, l_scr, acc_scr, *,
                        scale: float, kv_len: int, block_k: int):
    """Like ``_flash_kernel`` but emits the raw running state — f32
    UNNORMALIZED accumulator plus row max ``m`` and normalizer ``l`` —
    the partial-softmax interface the ring-attention merge rule needs
    (parallel/ring_attention.py). Emitting ``acc_scr`` directly keeps the
    partial in f32 regardless of input dtype (normalizing to the input
    dtype and re-multiplying by ``l`` would quantize every ring step's
    partial)."""
    _flash_update(q_ref, k_ref, v_ref, m_scr, l_scr, acc_scr, scale=scale,
                  kv_len=kv_len, block_k=block_k)

    @pl.when(pl.program_id(2) == pl.num_programs(2) - 1)
    def _finalize():
        acc_ref[0] = acc_scr[:]
        m_ref[0] = m_scr[:]
        l_ref[0] = l_scr[:]


def _flash_call(q, k, v, scale, block_q, block_k, interpret,
                with_stats: bool):
    if scale is None:
        scale = q.shape[-1] ** -0.5
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    b, s, h, d = q.shape
    # Auto block size (None): measured on a v5e (BASELINE.md round 2),
    # 512x512 blocks are 1.6-4.3x faster than 128x128 from S=2048 up
    # (5.0 vs 8.0 ms at S=2048; 65 vs 281 ms at S=16384) while 128 wins
    # slightly below (4.2 vs 4.5 ms at S=512) — fewer grid steps amortize
    # the per-block softmax/rescale overhead once the sequence is long.
    auto_block = 512 if s >= 2048 else 128
    block_q = auto_block if block_q is None else block_q
    block_k = auto_block if block_k is None else block_k
    bq, bk = min(block_q, s), min(block_k, s)

    import math
    pad_to = math.lcm(bq, bk)  # q and k grids must both cover the padded S

    def to_bh(x):  # [B,S,H,D] → [B*H, S_padded, D]
        x = jnp.transpose(x, (0, 2, 1, 3)).reshape(b * h, s, d)
        pad = (-s) % pad_to
        if pad:
            x = jnp.pad(x, ((0, 0), (0, pad), (0, 0)))
        return x

    qb, kb_, vb = to_bh(q), to_bh(k), to_bh(v)
    sp = qb.shape[1]
    nq, nk = sp // bq, sp // bk

    from jax.experimental.pallas import tpu as pltpu
    o_spec = pl.BlockSpec((1, bq, d), lambda g, i, j: (g, i, 0))
    stat_spec = pl.BlockSpec((1, bq, 128), lambda g, i, j: (g, i, 0))
    stat_shape = jax.ShapeDtypeStruct((b * h, sp, 128), jnp.float32)
    kernel = _flash_stats_kernel if with_stats else _flash_kernel
    res = pl.pallas_call(
        functools.partial(kernel, scale=scale, kv_len=s, block_k=bk),
        out_shape=([jax.ShapeDtypeStruct(qb.shape, jnp.float32), stat_shape,
                    stat_shape] if with_stats
                   else jax.ShapeDtypeStruct(qb.shape, q.dtype)),
        grid=(b * h, nq, nk),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda g, i, j: (g, i, 0)),
            pl.BlockSpec((1, bk, d), lambda g, i, j: (g, j, 0)),
            pl.BlockSpec((1, bk, d), lambda g, i, j: (g, j, 0)),
        ],
        out_specs=([o_spec, stat_spec, stat_spec] if with_stats else o_spec),
        scratch_shapes=[
            pltpu.VMEM((bq, 128), jnp.float32),   # m (col 0 used)
            pltpu.VMEM((bq, 128), jnp.float32),   # l (col 0 used)
            pltpu.VMEM((bq, d), jnp.float32),     # acc
        ],
        interpret=interpret,
    )(qb, kb_, vb)

    def from_bh(x):  # [B*H, Sp, ...] → [B, S, H, ...]
        x = x[:, :s]
        x = x.reshape(b, h, s, *x.shape[2:])
        return jnp.swapaxes(x, 1, 2)

    if not with_stats:
        return from_bh(res)
    acc, m, l = res
    # Stats live in lane column 0 of their [bq, 128] tiles.
    return from_bh(acc), from_bh(m[:, :, 0]), from_bh(l[:, :, 0])


@functools.partial(jax.jit,
                   static_argnames=("scale", "block_q", "block_k",
                                    "interpret"))
def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                    scale: float | None = None,
                    block_q: int | None = None,
                    block_k: int | None = None,
                    interpret: bool | None = None) -> jax.Array:
    """FlashAttention over [B, S, H, D] tensors → [B, S, H, D].

    Contract-identical to :func:`ops.attention.xla_attention`; tests assert
    numerical agreement. Sequence lengths that aren't multiples of the
    block sizes are zero-padded and masked inside the kernel.
    """
    return _flash_call(q, k, v, scale, block_q, block_k, interpret,
                       with_stats=False)


@functools.partial(jax.jit,
                   static_argnames=("scale", "block_q", "block_k",
                                    "interpret"))
def flash_attention_stats(q: jax.Array, k: jax.Array, v: jax.Array,
                          scale: float | None = None,
                          block_q: int | None = None,
                          block_k: int | None = None,
                          interpret: bool | None = None):
    """FlashAttention's raw partial-softmax state:
    ``(acc [B,S,H,D] f32 UNNORMALIZED accumulator, m [B,S,H] f32 row max,
    l [B,S,H] f32 normalizer)``; the normalized output is ``acc / l``.

    This is the partial-attention interface: partials over different K/V
    shards merge with the standard flash rule in full f32 — exactly what
    the ring-attention body needs to run its local block on the MXU via
    Pallas (:func:`parallel.ring_attention.ring_attention`).
    """
    return _flash_call(q, k, v, scale, block_q, block_k, interpret,
                       with_stats=True)
