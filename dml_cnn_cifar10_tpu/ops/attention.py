"""Multi-head attention ops.

No reference counterpart (the reference model is attention-free,
``cifar10cnn.py:94-147``, SURVEY §2.3); this backs the ViT-Tiny ladder
config (BASELINE.json) and the long-context machinery
(:mod:`~dml_cnn_cifar10_tpu.parallel.ring_attention`).

Two implementations with one contract::

    attention(q, k, v) -> out          # [B, S, H, D] each

- :func:`xla_attention` — the reference path: one fused
  softmax(QKᵀ/√d)V in pure lax; XLA fuses it well at short sequence
  lengths (ViT on CIFAR is 37 tokens — materializing S×S is optimal there).
- :func:`flash_attention` (ops/flash_attention.py) — blocked online-softmax
  Pallas kernel for long sequences where the S×S score matrix must never
  hit HBM.

``dispatch_attention`` picks per config + backend.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


NEG_INF = -1e30  # finite: exp(-inf - -inf) would NaN a fully-masked row


def mask_scores(scores: jax.Array, q_len: int, kv_len: int,
                causal: bool = False,
                segment_ids: jax.Array | None = None,
                window: int | None = None,
                kv_start: int = 0) -> jax.Array:
    """Apply the shared attention-validity mask to dense ``[..., Sq, Sk]``
    scores (jnp counterpart of the flash kernels' ``_score_mask``): causal
    keeps col ≤ row; segment_ids [B, S] keep same-segment pairs only
    (``scores`` must then be [B, H, Sq, Sk]). One definition, used by the
    XLA reference path and the ring's jnp block engines, so the masking
    semantics can't drift between the parity-tested implementations.
    ``kv_start`` offsets the columns' global coordinates (ring window
    blocks attend a neighbor shard sitting ``±S_local`` away)."""
    if window is not None and window < 1:
        # Same contract as the flash path: a non-positive window would
        # silently mask EVERY score and softmax would emit uniform
        # garbage.
        raise ValueError(f"window must be >= 1, got {window}")
    row = jnp.arange(q_len)[:, None]
    col = kv_start + jnp.arange(kv_len)[None, :]
    if causal:
        scores = jnp.where(col <= row, scores, NEG_INF)
    if window is not None:
        band = col > row - window
        if not causal:
            band = band & (col < row + window)
        scores = jnp.where(band, scores, NEG_INF)
    if segment_ids is not None:
        if isinstance(segment_ids, (tuple, list)):
            q_seg, kv_seg = segment_ids
        else:
            q_seg = kv_seg = segment_ids
        same = (q_seg[:, :, None] == kv_seg[:, None, :])
        scores = jnp.where(same[:, None, :, :], scores, NEG_INF)
    return scores


def xla_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                  scale: float | None = None,
                  causal: bool = False,
                  segment_ids: jax.Array | None = None,
                  window: int | None = None) -> jax.Array:
    """softmax(q kᵀ · scale) v over [B, S, H, D] tensors.

    Computed in float32 regardless of input dtype (softmax in bf16 loses
    mass at S large); output is cast back to q.dtype. ``causal=True``
    masks scores above the diagonal (the flash kernel's contract-identical
    reference for parity tests).

    Rows with NO live key (possible under window/cross-length/segment
    geometries) emit exact zeros, matching the flash kernels' ``_safe_l``
    behavior — a plain softmax over all-NEG_INF scores would instead emit
    a uniform average of V (round-3 advisor finding).
    """
    if scale is None:
        scale = q.shape[-1] ** -0.5
    qf = q.astype(jnp.float32)
    kf = k.astype(jnp.float32)
    scores = jnp.einsum("bqhd,bkhd->bhqk", qf, kf) * scale
    scores = mask_scores(scores, q.shape[1], k.shape[1], causal=causal,
                         segment_ids=segment_ids, window=window)
    probs = jax.nn.softmax(scores, axis=-1)
    # A fully-masked row's max is exactly NEG_INF (real scores are many
    # orders of magnitude above it); zero such rows like the flash path.
    live = jnp.max(scores, axis=-1, keepdims=True) > NEG_INF * 0.5
    probs = jnp.where(live, probs, 0.0)
    out = jnp.einsum("bhqk,bkhd->bqhd", probs, v.astype(jnp.float32))
    return out.astype(q.dtype)


def dispatch_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                       use_pallas: bool = False,
                       scale: float | None = None,
                       causal: bool = False,
                       segment_ids: jax.Array | None = None,
                       window: int | None = None) -> jax.Array:
    """Pick the attention impl: Pallas flash kernel when asked for and the
    sequence is long enough to benefit; XLA fused attention otherwise.
    Both paths differentiate (the flash path via its custom_vjp backward
    kernels) and both honor ``causal``."""
    seq = q.shape[1]
    if use_pallas and seq >= 128:
        from dml_cnn_cifar10_tpu.ops import flash_attention as fa
        return fa.flash_attention(q, k, v, scale=scale, causal=causal,
                                  segment_ids=segment_ids, window=window)
    return xla_attention(q, k, v, scale=scale, causal=causal,
                         segment_ids=segment_ids, window=window)
