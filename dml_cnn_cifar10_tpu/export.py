"""Serving export: the trained forward pass as a portable XLA artifact.

The reference has no deployment story at all — its only output is the
checkpoint directory (``cifar10cnn.py:222``); serving would mean rebuilding
the whole TF graph. The TPU-native answer is :mod:`jax.export`: serialize
the jitted eval forward (params captured as constants) to StableHLO bytes
that any later process — including one without this framework installed —
can deserialize and call on TPU or CPU.

The artifact is self-contained (weights embedded), has a symbolic batch
dimension (any batch size at call time), and takes RAW uint8 full-size
images — the device decode (cast/crop/normalize,
:func:`~dml_cnn_cifar10_tpu.ops.preprocess.device_preprocess`) is compiled
into it, so the serving input contract matches the on-disk CIFAR records,
not the training-time float layout.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import export as jax_export

from dml_cnn_cifar10_tpu.config import DataConfig, ModelConfig
from dml_cnn_cifar10_tpu.models.registry import ModelDef


def make_serving_fn(model_def: ModelDef, model_cfg: ModelConfig,
                    data_cfg: DataConfig, params: Any,
                    model_state: Any = None):
    """``fn(images_u8 [B, H, W, C]) -> logits [B, K]`` — eval-mode forward
    with weights closed over and the eval decode fused in front."""
    from dml_cnn_cifar10_tpu.ops.preprocess import device_preprocess

    eval_cfg = data_cfg.without_augmentation()

    def fn(images_u8):
        images = device_preprocess(images_u8, eval_cfg)
        if model_def.has_state:
            logits, _ = model_def.apply(params, model_state, images,
                                        model_cfg, train=False)
        elif model_def.has_aux:
            logits, _ = model_def.apply(params, images, model_cfg,
                                        train=False)
        else:
            logits = model_def.apply(params, images, model_cfg,
                                     train=False)
        return logits

    return fn


def make_variable_serving_fn(model_def: ModelDef, model_cfg: ModelConfig,
                             data_cfg: DataConfig):
    """``fn((params, model_state), images_u8) -> logits`` — the same
    eval forward as :func:`make_serving_fn` with the weights passed as
    ARGUMENTS instead of closed over. One jit of this function serves
    every checkpoint of the same model config: swapping weights is a
    pytree replacement with no recompile, which is what makes the
    serving fleet's checkpoint hot-swap zero-downtime
    (``serve/engine.py::ServingEngine.try_swap``)."""
    from dml_cnn_cifar10_tpu.ops.preprocess import device_preprocess

    eval_cfg = data_cfg.without_augmentation()

    def fn(variables, images_u8):
        params, model_state = variables
        images = device_preprocess(images_u8, eval_cfg)
        if model_def.has_state:
            logits, _ = model_def.apply(params, model_state, images,
                                        model_cfg, train=False)
        elif model_def.has_aux:
            logits, _ = model_def.apply(params, images, model_cfg,
                                        train=False)
        else:
            logits = model_def.apply(params, images, model_cfg,
                                     train=False)
        return logits

    return fn


def export_forward(model_def: ModelDef, model_cfg: ModelConfig,
                   data_cfg: DataConfig, params: Any,
                   model_state: Any = None,
                   platforms: Optional[list] = None) -> bytes:
    """Serialize the serving forward to StableHLO bytes.

    ``platforms`` defaults to ``["tpu", "cpu"]`` so one artifact serves
    both the pod and a CPU canary. The batch dim is symbolic ("b"): the
    deserialized callable accepts any batch size.
    """
    # Device arrays would serialize a sharding; fetch to host first so the
    # artifact is placement-free. fetch_to_host handles sharded /
    # non-fully-addressable state (collective on multi-host meshes — every
    # process must call export_forward together).
    from dml_cnn_cifar10_tpu.ckpt.checkpoint import fetch_to_host

    params = jax.tree.map(np.asarray, fetch_to_host(params))
    if model_state is not None:
        model_state = jax.tree.map(np.asarray, fetch_to_host(model_state))
    fn = make_serving_fn(model_def, model_cfg, data_cfg, params, model_state)
    spec = jax.ShapeDtypeStruct(
        (jax_export.symbolic_shape("b")[0], data_cfg.image_height,
         data_cfg.image_width, data_cfg.num_channels), jnp.uint8)
    exp = jax_export.export(
        jax.jit(fn), platforms=platforms or ["tpu", "cpu"])(spec)
    return exp.serialize()


def export_quantized_forward(model_cfg: ModelConfig, data_cfg: DataConfig,
                             params: Any, quant_scales,
                             platforms: Optional[list] = None) -> bytes:
    """:func:`export_forward`'s int8 sibling: quantize the float params
    with ``quant_scales`` (``quant.calibrate.QuantScales``) and
    serialize the XLA-int8 forward with the int8 weights + f32 scales
    baked in as constants. Same symbolic batch dim, same raw-uint8
    input contract — a deserialized artifact is served exactly like a
    float one, it just computes on the int8 path."""
    from dml_cnn_cifar10_tpu.ckpt.checkpoint import fetch_to_host
    from dml_cnn_cifar10_tpu.quant import convert as quant_convert

    params = jax.tree.map(np.asarray, fetch_to_host(params))
    qtree = quant_convert.quantize_params(params, quant_scales)
    vfn = quant_convert.make_quantized_serving_fn(model_cfg, data_cfg)

    def fn(images_u8):
        return vfn((qtree, None), images_u8)

    spec = jax.ShapeDtypeStruct(
        (jax_export.symbolic_shape("b")[0], data_cfg.image_height,
         data_cfg.image_width, data_cfg.num_channels), jnp.uint8)
    exp = jax_export.export(
        jax.jit(fn), platforms=platforms or ["tpu", "cpu"])(spec)
    return exp.serialize()


def save_exported(path: str, blob: bytes) -> None:
    """Atomic write (tmp + rename, the checkpoint module's convention) so
    a crash mid-write can't leave a truncated artifact for a server to
    trip over."""
    import os

    tmp = f"{path}.tmp"
    with open(tmp, "wb") as f:
        f.write(blob)
    os.replace(tmp, path)


def deserialize_exported(blob: bytes):
    """The raw :class:`jax.export.Exported` — callable plus avals. The
    serving engine reads the input contract back out of the artifact
    itself (:func:`artifact_image_shape`) instead of requiring the
    original ``DataConfig`` at deploy time."""
    return jax_export.deserialize(blob)


def artifact_image_shape(exported) -> tuple:
    """Per-request ``(H, W, C)`` from the artifact's input aval (the
    leading batch dim is symbolic and excluded)."""
    shape = exported.in_avals[0].shape
    return tuple(int(d) for d in shape[1:])


def load_exported_bytes(blob: bytes):
    """Deserialize an exported artifact; returns the jit-callable
    ``fn(images_u8) -> logits``."""
    return jax.jit(deserialize_exported(blob).call)


def load_exported(path: str):
    """:func:`load_exported_bytes` from a file."""
    with open(path, "rb") as f:
        return load_exported_bytes(f.read())
