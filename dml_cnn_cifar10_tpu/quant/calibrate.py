"""Calibration: observe the float model, produce symmetric int8 scales.

Weights need no data — their ranges are known exactly, and they get
PER-CHANNEL scales (one per output channel, the last axis of both HWIO
conv kernels and IO dense kernels) because per-layer weight ranges vary
by an order of magnitude across channels and a single per-tensor scale
would waste most of the int8 grid on the widest channel.

Activations DO need data: their ranges depend on what flows through the
net, so :func:`calibrate` runs N batches of the eval stream through a
"tapped" float forward (the exact :mod:`models/cnn` eval graph with the
five layer-boundary tensors observed) and keeps a running absolute max
per tap. Symmetric quantization throughout: ``scale = amax / 127``,
zero-point 0 — ReLU networks lose one sign bit on activations but
symmetric scales keep the int8 matmul a plain ``dot_general`` with no
zero-point correction terms, which is what XLA fuses best.

Every calibrated tensor is logged as one ``calibration`` JSONL record
(``tools/check_jsonl_schema.py`` lints them; the quantization section
of ``tools/telemetry_report.py`` summarizes them), so a quantized
rollout's scale provenance is in the same stream as its publish gate.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Tuple

import numpy as np

# Layer-boundary activation taps of the reference CNN, in forward
# order: the tensor QUANTIZED as input to conv1/conv2/full1/full2/full3
# respectively (convert.ACT_FOR_LAYER maps layers to taps).
ACT_TAPS = ("in", "pool1", "flat", "fc1", "fc2")

# Guard against a dead tensor (all-zero channel / activation): a zero
# scale would divide by zero at quantize time. The guard value keeps
# the quantized tensor all-zero, which is exactly right for dead input.
EPS = 1e-8


@dataclasses.dataclass
class QuantScales:
    """The calibration product :func:`quant.convert.quantize_params`
    consumes: per-output-channel weight scales and per-tensor
    activation scales, both ``amax / 127``."""

    weight: Dict[str, np.ndarray]   # layer -> f32 [out_channels]
    act: Dict[str, float]           # tap (ACT_TAPS) -> f32 scalar
    calib_batches: int = 0


def weight_scales(params) -> Dict[str, np.ndarray]:
    """Per-output-channel symmetric scales for every ``kernel`` leaf.

    Works straight off the float param tree (no data needed): for each
    layer's kernel, the absolute max over all axes but the last —
    channels live on the last axis in both HWIO and IO layouts."""
    out = {}
    for layer, leaves in params.items():
        k = np.asarray(leaves["kernel"], np.float32)
        amax = np.abs(k.reshape(-1, k.shape[-1])).max(axis=0)
        out[layer] = np.maximum(amax, EPS).astype(np.float32) / 127.0
    return out


def _tapped_forward(model_cfg, data_cfg):
    """The float eval forward with the five boundary tensors observed:
    ``fn(params, images_u8) -> (logits, {tap: batch_amax})``. Must stay
    line-for-line parallel with ``models/cnn.apply`` + the serving
    decode (``export.make_variable_serving_fn``) — the scales are only
    valid for the graph they were measured on."""
    import jax
    import jax.numpy as jnp

    from dml_cnn_cifar10_tpu.ops import layers as L
    from dml_cnn_cifar10_tpu.ops.preprocess import device_preprocess

    eval_cfg = data_cfg.without_augmentation()

    def fn(params, images_u8):
        p = jax.tree.map(lambda a: a.astype(jnp.float32), params)
        x = device_preprocess(images_u8, eval_cfg)
        taps = {"in": x}
        x = jax.nn.relu(L.conv2d(x, p["conv1"]["kernel"])
                        + p["conv1"]["bias"])
        x = L.max_pool(x)
        taps["pool1"] = x
        x = jax.nn.relu(L.conv2d(x, p["conv2"]["kernel"])
                        + p["conv2"]["bias"])
        x = L.max_pool(x)
        x = x.reshape(x.shape[0], -1)
        taps["flat"] = x
        x = jax.nn.relu(L.dense(x, p["full1"]["kernel"],
                                p["full1"]["bias"]))
        taps["fc1"] = x
        x = jax.nn.relu(L.dense(x, p["full2"]["kernel"],
                                p["full2"]["bias"]))
        taps["fc2"] = x
        logits = L.dense(x, p["full3"]["kernel"], p["full3"]["bias"])
        if model_cfg.logit_relu:
            logits = jax.nn.relu(logits)
        return logits, {t: jnp.max(jnp.abs(v)) for t, v in taps.items()}

    return fn


def calibrate(params, images_u8: np.ndarray, model_cfg, data_cfg,
              batch_size: int = 64, num_batches: Optional[int] = None,
              logger=None) -> QuantScales:
    """Weight scales + activation scales from ``num_batches`` batches of
    raw uint8 eval images (the serving input contract — the eval decode
    is part of the tapped graph). Emits one ``calibration`` record per
    tensor through ``logger`` when given.
    """
    import jax

    if model_cfg.name != "cnn":
        raise ValueError(
            f"int8 quantization supports the reference CNN only "
            f"(got model {model_cfg.name!r})")
    images_u8 = np.asarray(images_u8)
    if images_u8.dtype != np.uint8 or images_u8.ndim != 4:
        raise ValueError("calibration images must be raw uint8 "
                         "[N, H, W, C] (the serving input contract)")
    n_avail = max(images_u8.shape[0] // batch_size, 1)
    batches = min(num_batches, n_avail) if num_batches else n_avail
    fn = jax.jit(_tapped_forward(model_cfg, data_cfg))
    amax = {t: 0.0 for t in ACT_TAPS}
    for i in range(batches):
        chunk = images_u8[i * batch_size:(i + 1) * batch_size]
        if chunk.shape[0] < batch_size:   # short tail on tiny sets
            reps = -(-batch_size // chunk.shape[0])
            chunk = np.concatenate([chunk] * reps)[:batch_size]
        _, taps = fn(params, chunk)
        for t in ACT_TAPS:
            amax[t] = max(amax[t], float(taps[t]))
    scales = QuantScales(
        weight=weight_scales(params),
        act={t: max(amax[t], EPS) / 127.0 for t in ACT_TAPS},
        calib_batches=batches)
    if logger is not None:
        for layer, s in sorted(scales.weight.items()):
            logger.log("calibration", tensor=f"{layer}/kernel",
                       amax=round(float(s.max() * 127.0), 8),
                       scale=round(float(s.max()), 8),
                       channels=int(s.shape[0]), batches=batches)
        for tap in ACT_TAPS:
            logger.log("calibration", tensor=f"act/{tap}",
                       amax=round(amax[tap], 8),
                       scale=round(scales.act[tap], 8),
                       channels=0, batches=batches)
    return scales


def calibration_sets(data_cfg, batch_size: int, calib_batches: int,
                     holdout: int = 256, seed: int = 0
                     ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """(calib_images, holdout_images, holdout_labels), raw uint8, drawn
    disjointly from the EVAL split: the first ``calib_batches *
    batch_size`` records calibrate, the next ``holdout`` records are
    the held-out set the publish gate scores float-vs-int8 top-1 on —
    a scale must never be graded on the data that produced it."""
    from dml_cnn_cifar10_tpu.data.pipeline import input_pipeline

    it = input_pipeline(data_cfg, batch_size, train=False, seed=seed)
    n_cal = min(calib_batches * batch_size, max(it.n - 1, 1))
    calib = it.images[:n_cal]
    hold = slice(n_cal, n_cal + holdout)
    hold_images, hold_labels = it.images[hold], it.labels[hold]
    if hold_images.shape[0] == 0:   # tiny synthetic sets: fall back to
        hold_images, hold_labels = calib, it.labels[:n_cal]  # calib set
    return calib, hold_images, np.asarray(hold_labels)
