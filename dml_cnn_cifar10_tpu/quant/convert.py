"""Conversion + the quantized forward + the accuracy-delta publish gate.

The quantized tree is a plain pytree — int8 ``w_q`` leaves, f32
``w_scale``/``bias``/``act_scale`` leaves — passed to ONE jitted
program as arguments, exactly like the float engine's live-params path.
That buys the whole hot-swap seam for free: ``ServingEngine.try_swap``
validates candidates by variable spec, and a quantized tree's spec is
structurally distinct from a float tree's, so the engine can hold both
programs and route a candidate to whichever program it matches.

The forward runs on XLA's NATIVE int8: activations are quantized at
layer boundaries with the calibrated per-tensor scales, the matmuls and
convs execute as ``int8 × int8 → int32`` (``preferred_element_type``),
and the dequant is one fused multiply by ``act_scale * w_scale[c]``
before the f32 bias/ReLU epilogue. No Pallas — int8 ``dot_general`` /
``conv_general_dilated`` lower natively on both TPU and CPU, which is
why the accuracy gate is testable in tier-1.

The gate (:func:`accuracy_gate` / :func:`gate_and_swap`) is the pinned
deployment contract: quantized top-1 on the calibration HOLDOUT must be
within ``--quant_max_delta`` of float top-1, or the candidate is
rejected with a ``quant_rejected`` record and the previous version
keeps serving bit-identically. Version strings carry a ``+int8`` suffix
so every response advertises which numeric path computed it.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Optional

import numpy as np

from dml_cnn_cifar10_tpu.quant.calibrate import (ACT_TAPS, QuantScales,
                                                 calibrate)

VERSION_SUFFIX = "+int8"

# Which calibrated activation tap feeds which layer (forward order).
ACT_FOR_LAYER = {"conv1": "in", "conv2": "pool1", "full1": "flat",
                 "full2": "fc1", "full3": "fc2"}


def quantized_version(version: str) -> str:
    """``"123" -> "123+int8"`` (idempotent)."""
    version = str(version)
    return version if version.endswith(VERSION_SUFFIX) \
        else version + VERSION_SUFFIX


def is_quantized_version(version) -> bool:
    return str(version).endswith(VERSION_SUFFIX)


def quantize_params(params, scales: QuantScales) -> Dict[str, Any]:
    """Float param tree + scales -> the quantized tree the serving fn
    takes: per layer ``{w_q int8, w_scale f32[out], bias f32}`` plus
    the per-tensor activation scales as leaves (so a swap replaces the
    scales WITH the weights they were calibrated for)."""
    q: Dict[str, Any] = {}
    for layer in sorted(ACT_FOR_LAYER):
        w = np.asarray(params[layer]["kernel"], np.float32)
        s = np.asarray(scales.weight[layer], np.float32)
        q[layer] = {
            "w_q": np.clip(np.rint(w / s), -127, 127).astype(np.int8),
            "w_scale": s,
            "bias": np.asarray(params[layer]["bias"], np.float32),
        }
    q["act_scale"] = {t: np.float32(scales.act[t]) for t in ACT_TAPS}
    return q


def dequantize_params(qtree) -> Dict[str, Any]:
    """Back to a float tree (``w_q * w_scale``): each dequantized
    weight is within ``scale/2`` of the original float weight — the
    roundtrip bound tests pin."""
    return {layer: {
        "kernel": (np.asarray(qtree[layer]["w_q"], np.float32)
                   * np.asarray(qtree[layer]["w_scale"], np.float32)),
        "bias": np.asarray(qtree[layer]["bias"], np.float32),
    } for layer in sorted(ACT_FOR_LAYER)}


def _quantize_act(x, scale):
    import jax.numpy as jnp

    return jnp.clip(jnp.round(x / scale), -127, 127).astype(jnp.int8)


def _qconv(x, layer, act_scale):
    """Quantize input -> int8 conv (int32 accum) -> fused dequant ->
    f32 bias + ReLU."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    xq = _quantize_act(x, act_scale)
    y = lax.conv_general_dilated(
        xq, layer["w_q"], window_strides=(1, 1), padding="SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        preferred_element_type=jnp.int32)
    y = y.astype(jnp.float32) * (act_scale * layer["w_scale"])
    return jax.nn.relu(y + layer["bias"])


def _qdense(x, layer, act_scale):
    """int8 matmul (int32 accum) with fused per-channel dequant + bias;
    the caller owns the activation (the last layer has none)."""
    import jax.numpy as jnp
    from jax import lax

    xq = _quantize_act(x, act_scale)
    y = lax.dot_general(xq, layer["w_q"], (((1,), (0,)), ((), ())),
                        preferred_element_type=jnp.int32)
    return y.astype(jnp.float32) * (act_scale * layer["w_scale"]) \
        + layer["bias"]


def make_quantized_serving_fn(model_cfg, data_cfg):
    """``fn((qtree, None), images_u8) -> f32 logits`` — the int8 mirror
    of ``export.make_variable_serving_fn``: same two-arg contract (so
    one jit serves every quantized checkpoint of this config), same
    fused eval decode in front, reference-CNN graph only."""
    import jax
    import jax.numpy as jnp

    from dml_cnn_cifar10_tpu.ops import layers as L
    from dml_cnn_cifar10_tpu.ops.preprocess import device_preprocess

    if model_cfg.name != "cnn":
        raise ValueError(
            f"int8 serving supports the reference CNN only "
            f"(got model {model_cfg.name!r})")
    eval_cfg = data_cfg.without_augmentation()

    def fn(variables, images_u8):
        qtree, _ = variables
        a = qtree["act_scale"]
        x = device_preprocess(images_u8, eval_cfg)
        x = _qconv(x, qtree["conv1"], a["in"])
        x = L.max_pool(x)
        x = _qconv(x, qtree["conv2"], a["pool1"])
        x = L.max_pool(x)
        x = x.reshape(x.shape[0], -1)
        x = jax.nn.relu(_qdense(x, qtree["full1"], a["flat"]))
        x = jax.nn.relu(_qdense(x, qtree["full2"], a["fc1"]))
        logits = _qdense(x, qtree["full3"], a["fc2"])
        if model_cfg.logit_relu:
            logits = jax.nn.relu(logits)
        return logits.astype(jnp.float32)

    return fn


# --- the gate ---


def top1(logits: np.ndarray, labels: np.ndarray) -> float:
    return float(np.mean(np.argmax(logits, axis=-1)
                         == np.asarray(labels)))


def batched_logits(fn: Callable[[np.ndarray], Any],
                   images_u8: np.ndarray,
                   batch_size: int = 64) -> np.ndarray:
    """Run ``fn`` (images -> logits) over the set in fixed-size chunks,
    padding the tail — one compiled batch shape, no tail recompile."""
    outs = []
    n = images_u8.shape[0]
    for i in range(0, n, batch_size):
        chunk = images_u8[i:i + batch_size]
        pad = batch_size - chunk.shape[0]
        if pad:
            chunk = np.concatenate(
                [chunk, np.zeros((pad,) + chunk.shape[1:], chunk.dtype)])
        out = np.asarray(fn(chunk))
        outs.append(out[:batch_size - pad] if pad else out)
    return np.concatenate(outs) if outs else np.zeros((0,))


def accuracy_gate(float_logits: np.ndarray, quant_logits: np.ndarray,
                  labels: np.ndarray, max_delta: float) -> dict:
    """The pinned contract: ``float_top1 - quant_top1 <= max_delta``
    (an int8 candidate BETTER than float always passes)."""
    f_acc, q_acc = top1(float_logits, labels), top1(quant_logits, labels)
    delta = round(f_acc - q_acc, 6)
    return {"ok": delta <= max_delta,
            "float_top1": round(f_acc, 6), "quant_top1": round(q_acc, 6),
            "delta": delta, "max_delta": float(max_delta),
            "n": int(np.asarray(labels).shape[0])}


@dataclasses.dataclass
class QuantContext:
    """Everything a serving process needs to re-quantize and gate each
    published float checkpoint: config, the jitted float/int8 programs
    (built once — recalibration swaps data through them, never
    recompiles), the disjoint calib/holdout sets, and the contract."""

    model_cfg: Any
    data_cfg: Any
    calib_images: np.ndarray
    holdout_images: np.ndarray
    holdout_labels: np.ndarray
    float_fn: Callable        # jitted fn((params, state), images_u8)
    quant_fn: Callable        # jitted fn((qtree, None), images_u8)
    calib_batch_size: int = 64
    calib_batches: int = 4
    max_delta: float = 0.005

    @classmethod
    def build(cls, model_def, model_cfg, data_cfg, serve_cfg,
              calib_batch_size: int = 64, holdout: int = 256,
              seed: int = 0) -> "QuantContext":
        """From configs: draw the calib/holdout split off the eval
        stream and jit both programs."""
        import jax

        from dml_cnn_cifar10_tpu.export import make_variable_serving_fn
        from dml_cnn_cifar10_tpu.quant.calibrate import calibration_sets

        calib, hold_x, hold_y = calibration_sets(
            data_cfg, calib_batch_size, serve_cfg.quant_calib_batches,
            holdout=holdout, seed=seed)
        return cls(
            model_cfg=model_cfg, data_cfg=data_cfg, calib_images=calib,
            holdout_images=hold_x, holdout_labels=hold_y,
            float_fn=jax.jit(make_variable_serving_fn(
                model_def, model_cfg, data_cfg)),
            quant_fn=jax.jit(make_quantized_serving_fn(
                model_cfg, data_cfg)),
            calib_batch_size=calib_batch_size,
            calib_batches=serve_cfg.quant_calib_batches,
            max_delta=serve_cfg.quant_max_delta)

    def quantize(self, params, logger=None):
        """Calibrate (fresh scales for THESE weights) + convert."""
        scales = calibrate(params, self.calib_images, self.model_cfg,
                           self.data_cfg, batch_size=self.calib_batch_size,
                           num_batches=self.calib_batches, logger=logger)
        return quantize_params(params, scales)

    def gate(self, params, qtree) -> dict:
        """Score float vs int8 top-1 on the holdout."""
        bs = self.calib_batch_size
        f_logits = batched_logits(
            lambda x: self.float_fn((params, None), x),
            self.holdout_images, bs)
        q_logits = batched_logits(
            lambda x: self.quant_fn((qtree, None), x),
            self.holdout_images, bs)
        return accuracy_gate(f_logits, q_logits, self.holdout_labels,
                             self.max_delta)


def gate_and_swap(engine, ctx: QuantContext, params, version: str,
                  logger=None, max_delta: Optional[float] = None):
    """The quantized publish-adoption path (fleet worker + tests):
    recalibrate for the candidate weights, run the gate on the holdout,
    and only on pass hand the int8 tree to ``engine.try_swap``. A
    failing candidate emits ``quant_rejected`` and changes NOTHING —
    the engine keeps serving its current version bit-identically.

    Returns ``(swapped, reason)`` like ``try_swap``.
    """
    qversion = quantized_version(version)
    qtree = ctx.quantize(params, logger=logger)
    verdict = ctx.gate(params, qtree)
    if max_delta is not None:        # caller override (tests, drills)
        verdict["max_delta"] = float(max_delta)
        verdict["ok"] = verdict["delta"] <= max_delta
    if not verdict["ok"]:
        reason = (f"accuracy delta {verdict['delta']:+.4f} exceeds "
                  f"max_delta {verdict['max_delta']:.4f} "
                  f"(float {verdict['float_top1']:.4f} vs "
                  f"int8 {verdict['quant_top1']:.4f})")
        if logger is not None:
            logger.log("quant_rejected", replica_id=engine.replica_id,
                       version=qversion,
                       float_top1=verdict["float_top1"],
                       quant_top1=verdict["quant_top1"],
                       delta=verdict["delta"],
                       max_delta=verdict["max_delta"], reason=reason)
        print(f"[quant] REJECTED candidate {qversion}: {reason} "
              f"(still serving {engine.version})")
        return False, reason
    return engine.try_swap(qtree, None, version=qversion)
