"""Post-training int8 quantization for the serving path.

Two halves, mirroring every PTQ deployment stack since the original
TensorFlow system paper treated 8-bit inference as the standard
CNN-classifier serving path:

- :mod:`~dml_cnn_cifar10_tpu.quant.calibrate` — observe the float
  model: per-channel weight ranges plus activation ranges over N
  batches of the eval stream, reduced to symmetric int8 scales
  (``calibration`` JSONL records).
- :mod:`~dml_cnn_cifar10_tpu.quant.convert` — act on the scales:
  quantize the param tree (int8 weights + f32 scale leaves), run the
  quantized forward on XLA's native int8 ``dot_general``/conv, and
  enforce the accuracy-delta publish gate (``quant_rejected`` JSONL
  on failure; the float path keeps serving).

The serving integration (engine construction, fleet hot-swap, export)
lives in ``serve/``/``fleet/``/``export.py`` — this package owns only
the quantization math and the gate. docs/QUANT.md is the contract.
"""

from dml_cnn_cifar10_tpu.quant.calibrate import (  # noqa: F401
    ACT_TAPS, QuantScales, calibrate, calibration_sets, weight_scales)
from dml_cnn_cifar10_tpu.quant.convert import (  # noqa: F401
    VERSION_SUFFIX, QuantContext, accuracy_gate, batched_logits,
    dequantize_params, gate_and_swap, is_quantized_version,
    make_quantized_serving_fn, quantize_params, quantized_version, top1)
