"""Checkpoint / restore."""

from dml_cnn_cifar10_tpu.ckpt.checkpoint import (  # noqa: F401
    CheckpointManager,
    all_checkpoint_steps,
    latest_checkpoint,
    load_data_state,
    restore_checkpoint,
    save_checkpoint,
    save_data_state,
    verify_checkpoint,
    write_checksum,
)
