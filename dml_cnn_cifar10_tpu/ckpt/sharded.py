"""Sharded (per-process) checkpointing — the pod-scale save path.

The msgpack/orbax codecs gather the FULL state to every host first
(``fetch_to_host`` is a ``process_allgather`` for non-addressable leaves)
and the chief writes all of it: O(model) network + host memory per save
on every process. That is the faithful analog of the reference's
single-Saver design (``cifar10cnn.py:222``), but it is exactly what does
NOT scale to a pod running ZeRO-3/tensor-parallel state. This codec is
the SPMD-native alternative:

- **Save** is collective-free in the data plane: every process fetches
  only its OWN addressable shards (``replica_id == 0`` dedups replicated
  copies so each unique slice is written exactly once, cluster-wide) and
  writes ``shard_<p>.msgpack`` into ``ckpt_<step>.sharded/``. O(state/N)
  bytes per process, no allgather.
- One control-plane barrier, then the chief writes ``MANIFEST.json`` —
  the commit point. A crash before the manifest leaves no valid
  checkpoint (restore requires it); a crash after has all shards by
  construction.
- **Restore** reads the manifest + every shard file, assembles the
  global arrays on host, and re-shards onto the target mesh — which
  makes it elastic across process counts and mesh shapes for free (the
  shard files record *index ranges*, not device ids).

Like the reference's checkpoint dir, ``--log_dir`` must be a filesystem
every process can reach (multi-host restore reads all shard files; on a
pod that means NFS/GCS-fuse — same assumption MonitoredTrainingSession
made).
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Tuple

import jax
import numpy as np

from flax import serialization

MANIFEST = "MANIFEST.json"


def _key_str(key_path) -> str:
    """One canonical keypath→string encoding for save AND restore."""
    parts = []
    for k in key_path:
        if hasattr(k, "key"):
            parts.append(str(k.key))
        elif hasattr(k, "idx"):
            parts.append(str(k.idx))
        else:
            parts.append(str(k))
    return "/".join(parts)


def _leaf_paths(tree: Any) -> List[Tuple[str, Any]]:
    return [(_key_str(kp), leaf)
            for kp, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]]


def _norm_index(index, shape) -> List[List[int]]:
    """Slice tuple → [[start, stop], ...] (length == ndim)."""
    out = []
    for sl, dim in zip(index, shape):
        start, stop, step = sl.indices(dim)
        if step != 1:
            raise ValueError(f"non-unit shard stride {step}")
        out.append([start, stop])
    return out


def collect_local_shards(state: Any) -> Dict[str, list]:
    """Device→host fetch of THIS process's unique shards.

    Runs synchronously at the save point (the arrays must be read before
    the next donated step reuses their buffers); the file write can then
    happen on a background thread. Non-``jax.Array`` leaves (host
    numpy after a restore round trip) are owned by process 0.
    """
    payload: Dict[str, list] = {}
    pidx = jax.process_index()
    for path, leaf in _leaf_paths(state):
        entries = []
        if isinstance(leaf, jax.Array) and hasattr(leaf, "addressable_shards"):
            for shard in leaf.addressable_shards:
                if shard.replica_id != 0:
                    continue  # replicated copy; some device owns it
                entries.append({
                    "index": _norm_index(shard.index, leaf.shape),
                    "data": np.asarray(shard.data),
                })
        elif pidx == 0:
            arr = np.asarray(leaf)
            entries.append({
                "index": [[0, d] for d in arr.shape],
                "data": arr,
            })
        if entries:
            payload[path] = entries
    return payload


def write_shard_file(ckpt_path: str, payload: Dict[str, list]) -> str:
    """Atomically write this process's ``shard_<p>.msgpack``."""
    os.makedirs(ckpt_path, exist_ok=True)
    fname = os.path.join(ckpt_path, f"shard_{jax.process_index()}.msgpack")
    data = serialization.msgpack_serialize(payload)
    tmp = fname + ".tmp"
    with open(tmp, "wb") as f:
        f.write(data)
    os.replace(tmp, fname)
    return fname


def write_manifest(ckpt_path: str, state: Any) -> None:
    """Chief-only commit marker: global shapes/dtypes + shard-file set.

    ``shard_files`` is the EXACT file list restore may read: a crashed
    (uncommitted) save can leave stale ``shard_*.msgpack`` from a larger
    process count in the same dir, and an elastic restart that re-reaches
    the step would otherwise commit a manifest whose restore sees too many
    files. Enumerating the files in the commit record makes stale
    leftovers inert."""
    meta = {
        "process_count": jax.process_count(),
        "shard_files": [f"shard_{p}.msgpack"
                        for p in range(jax.process_count())],
        "leaves": {
            # .shape/.dtype are metadata — safe even on non-addressable
            # multi-host arrays (np.asarray would NOT be). Plain host
            # scalars fall back to numpy's view of them.
            path: {"shape": list(getattr(leaf, "shape", np.shape(leaf))),
                   "dtype": str(getattr(leaf, "dtype", None)
                                or np.asarray(leaf).dtype)}
            for path, leaf in _leaf_paths(state)
        },
    }
    tmp = os.path.join(ckpt_path, MANIFEST + ".tmp")
    with open(tmp, "w") as f:
        json.dump(meta, f)
    os.replace(tmp, os.path.join(ckpt_path, MANIFEST))


def save_sharded(ckpt_path: str, state: Any) -> None:
    """Full synchronous save: collect + write + barrier + manifest."""
    payload = collect_local_shards(state)
    finish_sharded_save(ckpt_path, payload, state)


def finish_sharded_save(ckpt_path: str, payload: Dict[str, list],
                        state: Any) -> None:
    """Write phase (background-thread safe single-process; multi-host
    runs it synchronously — the barrier is a collective)."""
    write_shard_file(ckpt_path, payload)
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils
        # All shard files durable before the manifest commits.
        multihost_utils.sync_global_devices(
            f"sharded_ckpt:{os.path.basename(ckpt_path)}")
    if jax.process_index() == 0:
        write_manifest(ckpt_path, state)


def restore_sharded(ckpt_path: str, target: Any) -> Any:
    """Assemble global host arrays from all shard files onto ``target``'s
    STRUCTURE (its values are never read — device or host arrays both
    fine). Elastic: any process count / mesh may restore."""
    with open(os.path.join(ckpt_path, MANIFEST)) as f:
        meta = json.load(f)
    shards: Dict[str, list] = {}
    # Read ONLY the files the manifest committed (older manifests without
    # the list fall back to the glob + count check): stale shard files
    # from a crashed save at a different process count must not poison a
    # validly committed checkpoint.
    files = meta.get("shard_files")
    if files is None:
        files = sorted(f for f in os.listdir(ckpt_path)
                       if f.startswith("shard_") and f.endswith(".msgpack"))
        expect = meta["process_count"]
        if len(files) != expect:
            raise ValueError(
                f"sharded checkpoint {ckpt_path} has {len(files)} shard "
                f"files but was written by {expect} processes — incomplete "
                f"save or unreachable filesystem (every process must see "
                f"--log_dir)")
    missing = [f for f in files
               if not os.path.exists(os.path.join(ckpt_path, f))]
    if missing:
        raise ValueError(
            f"sharded checkpoint {ckpt_path} is missing manifest-listed "
            f"shard files {missing} — incomplete save or unreachable "
            f"filesystem (every process must see --log_dir)")
    for fname in files:
        with open(os.path.join(ckpt_path, fname), "rb") as f:
            part = serialization.msgpack_restore(f.read())
        for path, entries in part.items():
            shards.setdefault(path, []).extend(
                entries.values() if isinstance(entries, dict) else entries)

    def build(path: str) -> np.ndarray:
        info = meta["leaves"].get(path)
        if info is None or path not in shards:
            raise ValueError(
                f"leaf {path!r} missing from sharded checkpoint "
                f"{ckpt_path} (config mismatch with the run that wrote "
                f"it?)")
        full = np.empty(tuple(info["shape"]), dtype=np.dtype(info["dtype"]))
        # Boolean coverage mask: catches holes AND overlaps. Summing
        # element counts would let a duplicated entry mask a hole —
        # filled == size while some elements hold np.empty garbage.
        seen = np.zeros(full.shape, dtype=bool)
        for e in shards[path]:
            idx = tuple(slice(int(s), int(t)) for s, t in
                        np.asarray(e["index"], dtype=np.int64))
            if seen[idx].any():
                raise ValueError(
                    f"leaf {path!r} has overlapping shard entries at "
                    f"{e['index']} in {ckpt_path} — corrupt or hand-merged "
                    f"shard files")
            full[idx] = e["data"]
            seen[idx] = True
        if not seen.all():
            raise ValueError(
                f"leaf {path!r} only {int(seen.sum())}/{full.size} "
                f"elements covered by shard files in {ckpt_path}")
        return full

    target_paths = {path for path, _ in _leaf_paths(target)}
    extra = sorted(set(meta["leaves"]) - target_paths)
    if extra:
        # Mirror the msgpack path's config-mismatch contract: a
        # checkpoint carrying leaves the target lacks (written with
        # --ema_decay/--momentum/... the resume run dropped) must fail
        # loudly, not silently resume half-matched.
        raise ValueError(
            f"sharded checkpoint {ckpt_path} carries leaves the current "
            f"config does not: {extra[:5]}{'...' if len(extra) > 5 else ''}"
            f" — it was written with a different --model/--optimizer/"
            f"--ema_decay/--async_staleness configuration")
    rebuilt = {path: build(path) for path in sorted(target_paths)}
    return jax.tree_util.tree_map_with_path(
        lambda kp, leaf: rebuilt[_key_str(kp)], target)
