"""Sharded (per-process) checkpointing — the pod-scale save path.

The msgpack/orbax codecs gather the FULL state to every host first
(``fetch_to_host`` is a ``process_allgather`` for non-addressable leaves)
and the chief writes all of it: O(model) network + host memory per save
on every process. That is the faithful analog of the reference's
single-Saver design (``cifar10cnn.py:222``), but it is exactly what does
NOT scale to a pod running ZeRO-3/tensor-parallel state. This codec is
the SPMD-native alternative:

- **Save** is collective-free in the data plane: every process fetches
  only its OWN addressable shards (``replica_id == 0`` dedups replicated
  copies so each unique slice is written exactly once, cluster-wide) and
  writes its shard file set into ``ckpt_<step>.sharded/``. O(state/N)
  bytes per process, no allgather. The local payload is split across up
  to ``shard_io_threads`` part files written CONCURRENTLY by a bounded
  thread pool, so one host's save is bounded by disk/NIC bandwidth, not
  one serialize+write thread. Each data file commits (atomic rename)
  and then its ``.sha256`` integrity sidecar commits after it; finally a
  per-process ``shard_<p>.files.json`` index commits the file list.
- One control-plane barrier, then the chief writes ``MANIFEST.json`` —
  the commit point — with ``shard_files`` naming EVERY data file of
  every process (gathered from the per-process index files on the
  shared filesystem). A crash before the manifest leaves no valid
  checkpoint (restore requires it); a crash after has all shards by
  construction.
- **Restore** reads the manifest's shard files CONCURRENTLY (same
  bounded pool), verifies each against its per-shard sha256 sidecar
  before assembly (a corrupt shard raises the classified ``ValueError``
  so ``restore_checkpoint``'s newest→oldest walk falls back, exactly
  like the top-level sidecars from PR 3), assembles the global arrays
  on host, and re-shards onto the target mesh — elastic across process
  counts and mesh shapes for free (the shard files record *index
  ranges*, not device ids). Every shard read/write emits a ``shard_io``
  telemetry record (bytes, secs, verify result) so resume time is
  observable per shard.

Like the reference's checkpoint dir, ``--log_dir`` must be a filesystem
every process can reach (multi-host restore reads all shard files; on a
pod that means NFS/GCS-fuse — same assumption MonitoredTrainingSession
made).
"""

from __future__ import annotations

import concurrent.futures
import hashlib
import json
import os
import sys
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import numpy as np

from flax import serialization

MANIFEST = "MANIFEST.json"

#: Default bound for the per-shard save/restore thread pool
#: (``--shard_io_threads``). 1 degrades to fully serial IO.
DEFAULT_SHARD_IO_THREADS = 4

#: on_event callback type: called as ``on_event("shard_io", **fields)``
#: for every shard read/write (and for the legacy-manifest fallback).
OnEvent = Callable[..., None]


def _emit(on_event: Optional[OnEvent], **fields) -> None:
    if on_event is not None:
        on_event("shard_io", **fields)


def _key_str(key_path) -> str:
    """One canonical keypath→string encoding for save AND restore."""
    parts = []
    for k in key_path:
        if hasattr(k, "key"):
            parts.append(str(k.key))
        elif hasattr(k, "idx"):
            parts.append(str(k.idx))
        else:
            parts.append(str(k))
    return "/".join(parts)


def _leaf_paths(tree: Any) -> List[Tuple[str, Any]]:
    return [(_key_str(kp), leaf)
            for kp, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]]


def _norm_index(index, shape) -> List[List[int]]:
    """Slice tuple → [[start, stop], ...] (length == ndim)."""
    out = []
    for sl, dim in zip(index, shape):
        start, stop, step = sl.indices(dim)
        if step != 1:
            raise ValueError(f"non-unit shard stride {step}")
        out.append([start, stop])
    return out


def collect_local_shards(state: Any) -> Dict[str, list]:
    """Device→host fetch of THIS process's unique shards.

    Runs synchronously at the save point (the arrays must be read before
    the next donated step reuses their buffers); the file write can then
    happen on a background thread. Non-``jax.Array`` leaves (host
    numpy after a restore round trip) are owned by process 0.
    """
    payload: Dict[str, list] = {}
    pidx = jax.process_index()
    for path, leaf in _leaf_paths(state):
        entries = []
        if isinstance(leaf, jax.Array) and hasattr(leaf, "addressable_shards"):
            for shard in leaf.addressable_shards:
                if shard.replica_id != 0:
                    continue  # replicated copy; some device owns it
                entries.append({
                    "index": _norm_index(shard.index, leaf.shape),
                    "data": np.asarray(shard.data),
                })
        elif pidx == 0:
            arr = np.asarray(leaf)
            entries.append({
                "index": [[0, d] for d in arr.shape],
                "data": arr,
            })
        if entries:
            payload[path] = entries
    return payload


def _split_payload(payload: Dict[str, list],
                   parts: int) -> List[Dict[str, list]]:
    """Partition the payload's leaf paths into up to ``parts`` groups,
    greedily balanced by byte size (each path's entries stay together so
    assembly semantics never change). Deterministic: sorted paths,
    largest-first into the lightest bin."""
    if parts <= 1 or len(payload) <= 1:
        return [payload]
    parts = min(parts, len(payload))
    sized = sorted(
        ((sum(e["data"].nbytes for e in entries), path)
         for path, entries in payload.items()),
        reverse=True)
    bins: List[Dict[str, list]] = [{} for _ in range(parts)]
    loads = [0] * parts
    for nbytes, path in sized:
        i = loads.index(min(loads))
        bins[i][path] = payload[path]
        loads[i] += nbytes
    return [b for b in bins if b]


def shard_checksum_path(fname: str) -> str:
    return fname + ".sha256"


def _write_one_shard(ckpt_path: str, fname: str, part: Dict[str, list],
                     on_event: Optional[OnEvent],
                     source: str = "disk") -> Tuple[str, int, float]:
    """Serialize + atomically write one shard data file, then commit its
    sha256 sidecar AFTER the data file lands (same ordering contract as
    the top-level checkpoint sidecars)."""
    t0 = time.perf_counter()
    data = serialization.msgpack_serialize(part)
    full = os.path.join(ckpt_path, fname)
    tmp = full + f".tmp{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(data)
    os.replace(tmp, full)
    sc = shard_checksum_path(full)
    tmp = sc + f".tmp{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump({"algo": "sha256",
                   "digest": hashlib.sha256(data).hexdigest(),
                   "bytes": len(data)}, f)
    os.replace(tmp, sc)
    secs = time.perf_counter() - t0
    _emit(on_event, op="save", shard=fname, bytes=len(data),
          secs=round(secs, 6), verify=None, source=source)
    return fname, len(data), secs


def write_shard_files(ckpt_path: str, payload: Dict[str, list],
                      threads: Optional[int] = None,
                      on_event: Optional[OnEvent] = None) -> List[str]:
    """Write this process's shard file set (split across up to
    ``threads`` part files, written concurrently), each with its sha256
    sidecar, then commit ``shard_<p>.files.json`` naming the set. A
    single-part payload keeps the legacy ``shard_<p>.msgpack`` name."""
    threads = DEFAULT_SHARD_IO_THREADS if threads is None else max(1, threads)
    os.makedirs(ckpt_path, exist_ok=True)
    pidx = jax.process_index()
    parts = _split_payload(payload, threads)
    if len(parts) == 1:
        names = [f"shard_{pidx}.msgpack"]
    else:
        names = [f"shard_{pidx}_{j}.msgpack" for j in range(len(parts))]
    if len(parts) == 1:
        _write_one_shard(ckpt_path, names[0], parts[0], on_event)
    else:
        with concurrent.futures.ThreadPoolExecutor(
                max_workers=threads,
                thread_name_prefix="shard-io") as pool:
            list(pool.map(
                lambda np_: _write_one_shard(ckpt_path, np_[0], np_[1],
                                             on_event),
                zip(names, parts)))
    index = os.path.join(ckpt_path, f"shard_{pidx}.files.json")
    tmp = index + f".tmp{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump({"files": names}, f)
    os.replace(tmp, index)
    return names


def write_shard_file(ckpt_path: str, payload: Dict[str, list]) -> str:
    """Back-compat single-file write (serial, one part)."""
    write_shard_files(ckpt_path, payload, threads=1)
    return os.path.join(ckpt_path,
                        f"shard_{jax.process_index()}.msgpack")


def write_manifest(ckpt_path: str, state: Any) -> None:
    """Chief-only commit marker: global shapes/dtypes + shard-file set.

    ``shard_files`` is the EXACT file list restore may read: a crashed
    (uncommitted) save can leave stale ``shard_*.msgpack`` from a larger
    process count — or from a crashed save at the SAME process count —
    in the same dir, and enumerating the committed files in the commit
    record makes stale leftovers inert. The list is gathered from every
    process's ``shard_<p>.files.json`` index (all durable before the
    pre-manifest barrier released this chief)."""
    shard_files: List[str] = []
    for p in range(jax.process_count()):
        index = os.path.join(ckpt_path, f"shard_{p}.files.json")
        try:
            with open(index) as f:
                shard_files.extend(json.load(f)["files"])
        except (OSError, ValueError, KeyError) as e:
            raise ValueError(
                f"sharded save of {ckpt_path} incomplete: process {p}'s "
                f"shard index {index} is missing/unreadable ({e!r}) — "
                f"unreachable filesystem? (every process must see "
                f"--log_dir)")
    meta = {
        "process_count": jax.process_count(),
        "shard_files": shard_files,
        "leaves": {
            # .shape/.dtype are metadata — safe even on non-addressable
            # multi-host arrays (np.asarray would NOT be). Plain host
            # scalars fall back to numpy's view of them.
            path: {"shape": list(getattr(leaf, "shape", np.shape(leaf))),
                   "dtype": str(getattr(leaf, "dtype", None)
                                or np.asarray(leaf).dtype)}
            for path, leaf in _leaf_paths(state)
        },
    }
    tmp = os.path.join(ckpt_path, MANIFEST + ".tmp")
    with open(tmp, "w") as f:
        json.dump(meta, f)
    os.replace(tmp, os.path.join(ckpt_path, MANIFEST))


def save_sharded(ckpt_path: str, state: Any,
                 threads: Optional[int] = None,
                 on_event: Optional[OnEvent] = None) -> None:
    """Full synchronous save: collect + write + barrier + manifest."""
    payload = collect_local_shards(state)
    finish_sharded_save(ckpt_path, payload, state, threads=threads,
                        on_event=on_event)


def finish_sharded_save(ckpt_path: str, payload: Dict[str, list],
                        state: Any, threads: Optional[int] = None,
                        on_event: Optional[OnEvent] = None) -> None:
    """Write phase (background-thread safe single-process; multi-host
    runs it synchronously — the barrier is a collective)."""
    write_shard_files(ckpt_path, payload, threads=threads,
                      on_event=on_event)
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils
        # All shard files durable before the manifest commits.
        multihost_utils.sync_global_devices(
            f"sharded_ckpt:{os.path.basename(ckpt_path)}")
    if jax.process_index() == 0:
        write_manifest(ckpt_path, state)


def _read_one_shard(ckpt_path: str, fname: str,
                    on_event: Optional[OnEvent],
                    source: str = "disk") -> Dict[str, Any]:
    """Read + integrity-verify + unpack one shard file. A present
    sidecar must match exactly (digest AND byte count); a missing
    sidecar passes (pre-per-shard-integrity checkpoints stay
    restorable); an unreadable sidecar fails like a mismatch. Failures
    raise ``ValueError`` so the newest→oldest restore walk falls back
    instead of crashing the run."""
    t0 = time.perf_counter()
    with open(os.path.join(ckpt_path, fname), "rb") as f:
        data = f.read()
    verify = None
    sc = shard_checksum_path(os.path.join(ckpt_path, fname))
    if os.path.isfile(sc):
        try:
            with open(sc) as f:
                want = json.load(f)
            verify = (hashlib.sha256(data).hexdigest() == want["digest"]
                      and len(data) == want["bytes"])
        except (OSError, ValueError, KeyError):
            verify = False
        if not verify:
            _emit(on_event, op="restore", shard=fname, bytes=len(data),
                  secs=round(time.perf_counter() - t0, 6), verify=False,
                  source=source)
            raise ValueError(
                f"shard file {fname} in {ckpt_path} failed sha256 "
                f"integrity verification (corrupt/truncated shard or "
                f"sidecar)")
    part = serialization.msgpack_restore(data)
    _emit(on_event, op="restore", shard=fname, bytes=len(data),
          secs=round(time.perf_counter() - t0, 6), verify=verify,
          source=source)
    return part


def restore_sharded(ckpt_path: str, target: Any,
                    threads: Optional[int] = None,
                    on_event: Optional[OnEvent] = None) -> Any:
    """Assemble global host arrays from all shard files onto ``target``'s
    STRUCTURE (its values are never read — device or host arrays both
    fine). Elastic: any process count / mesh may restore. Shard files
    are read, verified, and unpacked CONCURRENTLY on a bounded pool of
    ``threads`` (``--shard_io_threads``); the result is deterministic —
    shards merge in manifest order regardless of IO completion order —
    so concurrent restore is bit-identical to serial restore."""
    threads = DEFAULT_SHARD_IO_THREADS if threads is None else max(1, threads)
    with open(os.path.join(ckpt_path, MANIFEST)) as f:
        meta = json.load(f)
    shards: Dict[str, list] = {}
    # Read ONLY the files the manifest committed (older manifests without
    # the list fall back to the glob + count check): stale shard files
    # from a crashed save at a different process count must not poison a
    # validly committed checkpoint.
    files = meta.get("shard_files")
    if files is None:
        files = sorted(f for f in os.listdir(ckpt_path)
                       if f.startswith("shard_") and f.endswith(".msgpack"))
        expect = meta["process_count"]
        if len(files) != expect:
            raise ValueError(
                f"sharded checkpoint {ckpt_path} has {len(files)} shard "
                f"files but was written by {expect} processes — incomplete "
                f"save or unreachable filesystem (every process must see "
                f"--log_dir)")
        # The glob CANNOT tell a valid set from stale shards a crashed
        # save at the SAME process count left behind (count matches,
        # bytes may be half-written). Be loud about the weaker
        # guarantee; new saves always commit `shard_files`.
        print(f"[ckpt] WARNING: sharded checkpoint {ckpt_path} has a "
              f"legacy manifest without `shard_files`; restoring via "
              f"filename glob, which cannot distinguish stale shards "
              f"from a crashed same-process-count save. Re-save to "
              f"upgrade the manifest.", file=sys.stderr)
        _emit(on_event, op="legacy_glob", shard=ckpt_path, bytes=None,
              secs=None, verify=None, source="disk")
    missing = [f for f in files
               if not os.path.exists(os.path.join(ckpt_path, f))]
    if missing:
        raise ValueError(
            f"sharded checkpoint {ckpt_path} is missing manifest-listed "
            f"shard files {missing} — incomplete save or unreachable "
            f"filesystem (every process must see --log_dir)")
    if threads > 1 and len(files) > 1:
        with concurrent.futures.ThreadPoolExecutor(
                max_workers=threads,
                thread_name_prefix="shard-io") as pool:
            # map() preserves submission order: shards merge in manifest
            # order no matter which read finishes first.
            parts = list(pool.map(
                lambda fn: _read_one_shard(ckpt_path, fn, on_event),
                files))
    else:
        parts = [_read_one_shard(ckpt_path, fn, on_event) for fn in files]
    for part in parts:
        for path, entries in part.items():
            shards.setdefault(path, []).extend(
                entries.values() if isinstance(entries, dict) else entries)

    def build(path: str) -> np.ndarray:
        info = meta["leaves"].get(path)
        if info is None or path not in shards:
            raise ValueError(
                f"leaf {path!r} missing from sharded checkpoint "
                f"{ckpt_path} (config mismatch with the run that wrote "
                f"it?)")
        full = np.empty(tuple(info["shape"]), dtype=np.dtype(info["dtype"]))
        # Boolean coverage mask: catches holes AND overlaps. Summing
        # element counts would let a duplicated entry mask a hole —
        # filled == size while some elements hold np.empty garbage.
        seen = np.zeros(full.shape, dtype=bool)
        for e in shards[path]:
            idx = tuple(slice(int(s), int(t)) for s, t in
                        np.asarray(e["index"], dtype=np.int64))
            if seen[idx].any():
                raise ValueError(
                    f"leaf {path!r} has overlapping shard entries at "
                    f"{e['index']} in {ckpt_path} — corrupt or hand-merged "
                    f"shard files")
            full[idx] = e["data"]
            seen[idx] = True
        if not seen.all():
            raise ValueError(
                f"leaf {path!r} only {int(seen.sum())}/{full.size} "
                f"elements covered by shard files in {ckpt_path}")
        return full

    target_paths = {path for path, _ in _leaf_paths(target)}
    extra = sorted(set(meta["leaves"]) - target_paths)
    if extra:
        # Mirror the msgpack path's config-mismatch contract: a
        # checkpoint carrying leaves the target lacks (written with
        # --ema_decay/--momentum/... the resume run dropped) must fail
        # loudly, not silently resume half-matched.
        raise ValueError(
            f"sharded checkpoint {ckpt_path} carries leaves the current "
            f"config does not: {extra[:5]}{'...' if len(extra) > 5 else ''}"
            f" — it was written with a different --model/--optimizer/"
            f"--ema_decay/--async_staleness configuration")
    rebuilt = {path: build(path) for path in sorted(target_paths)}
    return jax.tree_util.tree_map_with_path(
        lambda kp, leaf: rebuilt[_key_str(kp)], target)
