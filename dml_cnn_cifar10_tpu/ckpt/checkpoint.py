"""Pytree checkpointing: save/restore the full training state.

Replaces the MonitoredTrainingSession saver the reference relies on —
``checkpoint_dir=FLAGS.log_dir`` makes the chief save periodically and any
restart restore the latest checkpoint and resume at the saved global step
(``cifar10cnn.py:222``, SURVEY §3.5). Same contract here:

- ``CheckpointManager.maybe_save(state)`` — periodic, chief-only
  (process 0), atomic (tmp + rename), bounded retention.
- ``restore_checkpoint(dir, target)`` — returns the restored state or the
  target untouched when no checkpoint exists, so startup is always
  "restore-if-present" exactly like MTS.

Formats: ``msgpack`` (default — flax msgpack bytes of the state pytree,
one file) or ``orbax`` (an ``orbax.checkpoint`` PyTree directory, the
JAX-ecosystem standard — interoperable with external orbax tooling).
Arrays are fetched to host first, so checkpoints of sharded/replicated
device arrays just work in either format, and ``restore_checkpoint``
auto-detects the format per checkpoint so a run can switch formats
mid-flight. A ``checkpoint`` index file names the latest step, mirroring
TF's ``checkpoint`` protofile convention.
"""

from __future__ import annotations

import hashlib
import json
import os
import re
import sys
import time
from typing import Any, Optional, Tuple

import jax

from flax import serialization

_CKPT_RE = re.compile(r"ckpt_(\d+)\.(msgpack|orbax|sharded)$")

FORMATS = ("msgpack", "orbax", "sharded")


def _ckpt_path(ckpt_dir: str, step: int, fmt: str = "msgpack") -> str:
    return os.path.join(ckpt_dir, f"ckpt_{step}.{fmt}")


# ---------------------------------------------------------------------------
# Integrity sidecars: a sha256 checksum committed AFTER the checkpoint
# bytes land, verified before any restore attempt. The sidecar records
# the exact file list digested at commit time, so stale extra files in a
# .sharded dir (a crashed larger-cluster save — already tolerated by
# restore_sharded's manifest contract) don't fail verification, while a
# truncated/bit-flipped/vanished member does.
# ---------------------------------------------------------------------------

def checksum_path(path: str) -> str:
    return path + ".sha256"


def _checkpoint_files(path: str):
    """Relative paths of the files a checkpoint comprises, sorted."""
    if not os.path.isdir(path):
        return [os.path.basename(path)]
    out = []
    for root, dirs, files in os.walk(path):
        dirs.sort()
        for name in files:
            out.append(os.path.relpath(os.path.join(root, name), path))
    return sorted(out)


def _digest_files(path: str, rel_files) -> Tuple[str, int]:
    """(hex sha256, total bytes) over ``rel_files`` of ``path`` — each
    file's relative name is mixed into the digest so renames don't pass."""
    base = path if os.path.isdir(path) else os.path.dirname(path)
    h = hashlib.sha256()
    total = 0
    for rel in rel_files:
        h.update(rel.encode())
        with open(os.path.join(base, rel), "rb") as f:
            while True:
                chunk = f.read(1 << 20)
                if not chunk:
                    break
                h.update(chunk)
                total += len(chunk)
    return h.hexdigest(), total


def write_checksum(path: str) -> str:
    """Commit the integrity sidecar for an already-committed checkpoint
    (atomic, like the checkpoint itself)."""
    files = _checkpoint_files(path)
    digest, nbytes = _digest_files(path, files)
    sc = checksum_path(path)
    tmp = sc + ".tmp"
    with open(tmp, "w") as f:
        json.dump({"algo": "sha256", "digest": digest, "bytes": nbytes,
                   "files": files}, f)
    os.replace(tmp, sc)
    return sc


def verify_checkpoint(path: str) -> Tuple[bool, str]:
    """(ok, reason). Missing sidecar passes (pre-integrity checkpoints
    stay restorable — the decode itself still guards them); a present
    sidecar must match exactly: every listed file present with the
    committed combined digest."""
    sc = checksum_path(path)
    if not os.path.isfile(sc):
        return True, "no checksum sidecar (pre-integrity checkpoint)"
    try:
        with open(sc) as f:
            want = json.load(f)
    except (OSError, ValueError) as e:
        return False, f"unreadable checksum sidecar: {e!r}"
    base = path if os.path.isdir(path) else os.path.dirname(path)
    rel_files = want.get("files") or []
    missing = [r for r in rel_files
               if not os.path.isfile(os.path.join(base, r))]
    if missing:
        return False, f"missing checkpoint files {missing}"
    try:
        digest, nbytes = _digest_files(path, rel_files)
    except OSError as e:
        return False, f"unreadable checkpoint file: {e!r}"
    if digest != want.get("digest"):
        return False, (f"checksum mismatch (have {nbytes} bytes, "
                       f"sidecar recorded {want.get('bytes')})")
    return True, "verified"


def fetch_to_host(state: Any) -> Any:
    """Device→host fetch that is safe for sharded state.

    Tensor-parallel leaves on a multi-host mesh are not fully addressable;
    ``process_allgather`` reassembles the global value (a collective — EVERY
    process must call this, even when only the chief writes; see
    ``CheckpointManager.maybe_save``). Fully-addressable leaves (single-host
    or replicated) take the plain ``device_get`` path.
    """
    def to_host(x):
        if isinstance(x, jax.Array) and not x.is_fully_addressable:
            from jax.experimental import multihost_utils
            return multihost_utils.process_allgather(x, tiled=True)
        return jax.device_get(x)

    return jax.tree.map(to_host, state)


def _logger_on_event(logger):
    """shard_io telemetry bridge: ckpt/sharded.py emits per-shard IO
    events through this into the MetricsLogger-shaped sink (None stays
    None — the emit helper no-ops)."""
    if logger is None:
        return None
    return lambda kind, **fields: logger.log(kind, **fields)


def save_checkpoint(ckpt_dir: str, state: Any, step: int,
                    keep: int = 3, fmt: str = "msgpack",
                    logger=None, shard_io_threads: Optional[int] = None
                    ) -> str:
    """Fetch (collective-safe) + atomically write ``ckpt_<step>.<fmt>``.

    ``fmt='sharded'`` skips the full-state gather entirely: every
    process writes only its own shards (O(state/N) bytes, no
    allgather, shard files written concurrently on up to
    ``shard_io_threads`` threads) — call it from ALL processes (see
    ckpt/sharded.py).
    """
    if fmt == "sharded":
        from dml_cnn_cifar10_tpu.ckpt import sharded as sharded_lib
        os.makedirs(ckpt_dir, exist_ok=True)
        path = _ckpt_path(ckpt_dir, step, fmt)
        sharded_lib.save_sharded(path, state, threads=shard_io_threads,
                                 on_event=_logger_on_event(logger))
        if jax.process_index() == 0:
            _finalize_checkpoint(ckpt_dir, path, keep, logger=logger)
        return path
    return _write_checkpoint(ckpt_dir, fetch_to_host(state), step, keep,
                             fmt=fmt, logger=logger)


def _check_orbax_single_process(fmt: str) -> None:
    """orbax Checkpointer.save is itself a collective (it runs
    sync_global_processes barriers on ALL hosts), which the chief-only
    write design here would deadlock. The msgpack codec has no such
    constraint. Checked at the write site so BOTH entry points —
    CheckpointManager and a direct save_checkpoint(..., fmt='orbax') —
    are covered."""
    if fmt == "orbax" and jax.process_count() > 1:
        raise ValueError(
            "ckpt_format='orbax' is single-process only under the "
            "chief-only checkpoint design; multi-host runs need "
            "ckpt_format='msgpack'")


def _write_checkpoint(ckpt_dir: str, host_state: Any, step: int,
                      keep: int, fmt: str = "msgpack",
                      logger=None) -> str:
    """Write an already-on-host state; prune to ``keep`` newest."""
    if fmt not in FORMATS:
        raise ValueError(f"unknown checkpoint format {fmt!r}; "
                         f"have {FORMATS}")
    _check_orbax_single_process(fmt)
    os.makedirs(ckpt_dir, exist_ok=True)
    path = _ckpt_path(ckpt_dir, step, fmt)
    if fmt == "orbax":
        import orbax.checkpoint as ocp

        # State dict first: orbax round-trips plain nested dicts; the
        # NamedTuple/typed structure is re-imposed on restore via
        # flax.serialization. Orbax's own save is tmp-dir + rename, so
        # atomicity matches the msgpack path.
        ocp.PyTreeCheckpointer().save(
            os.path.abspath(path),
            serialization.to_state_dict(host_state),
            force=True)
    else:
        data = serialization.to_bytes(host_state)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, path)
    _finalize_checkpoint(ckpt_dir, path, keep, logger=logger)
    return path


def _finalize_checkpoint(ckpt_dir: str, path: str, keep: int,
                         logger=None) -> None:
    """Commit the integrity sidecar, point the ``checkpoint`` index at
    ``path``, prune to ``keep`` (checksum + data-state sidecars ride
    along). A prune failure (disk full, permissions) is logged as a
    ``ckpt_prune_error`` event instead of silently accumulating
    checkpoints until the disk fills for real."""
    write_checksum(path)
    with open(os.path.join(ckpt_dir, "checkpoint"), "w") as f:
        f.write(os.path.basename(path) + "\n")
    for old_step, old_fmt in sorted(_checkpoints(ckpt_dir))[:-keep]:
        old = _ckpt_path(ckpt_dir, old_step, old_fmt)
        try:
            if os.path.isdir(old):
                import shutil
                shutil.rmtree(old)
            else:
                os.remove(old)
            for sidecar in (checksum_path(old),
                            os.path.join(ckpt_dir,
                                         f"data_state_{old_step}.json")):
                if os.path.isfile(sidecar):
                    os.remove(sidecar)
        except OSError as e:
            print(f"[ckpt] retention prune of {old} failed: {e!r} — "
                  f"old checkpoints are accumulating", file=sys.stderr)
            if logger is not None:
                logger.log("ckpt_prune_error", step=old_step, path=old,
                           error=repr(e))


def save_data_state(ckpt_dir: str, step: int, counts: dict) -> None:
    """Sidecar for exact-resume data order: the cumulative number of
    batches each stream has CONSUMED by ``step`` (identical on every
    process under SPMD lockstep — the chief writes it next to its
    checkpoint). Atomic like the checkpoint itself."""
    import json

    os.makedirs(ckpt_dir, exist_ok=True)
    path = os.path.join(ckpt_dir, f"data_state_{step}.json")
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(counts, f)
    os.replace(tmp, path)


def load_data_state(ckpt_dir: str, step: int):
    """Counts written by :func:`save_data_state`, or None."""
    import json

    path = os.path.join(ckpt_dir, f"data_state_{step}.json")
    if not os.path.isfile(path):
        return None
    with open(path) as f:
        return json.load(f)


def _checkpoints(ckpt_dir: str):
    """[(step, fmt)] for every COMMITTED checkpoint present, any format.

    A ``.sharded`` directory counts only once its ``MANIFEST.json``
    exists — the manifest is that codec's commit point (tmp+rename is
    the others'), so a crash mid-save can never make ``latest_checkpoint``
    select a partial directory over the previous complete checkpoint.
    """
    if not os.path.isdir(ckpt_dir):
        return []
    out = []
    for name in os.listdir(ckpt_dir):
        m = _CKPT_RE.match(name)
        if not m:
            continue
        if m.group(2) == "sharded" and not os.path.isfile(
                os.path.join(ckpt_dir, name, "MANIFEST.json")):
            continue  # uncommitted partial save
        out.append((int(m.group(1)), m.group(2)))
    return out


def all_checkpoint_steps(ckpt_dir: str):
    return [step for step, _ in _checkpoints(ckpt_dir)]


def latest_checkpoint(ckpt_dir: str) -> Optional[str]:
    cks = _checkpoints(ckpt_dir)
    if not cks:
        return None
    step, fmt = max(cks)
    return _ckpt_path(ckpt_dir, step, fmt)


def checkpoint_path_at_step(ckpt_dir: str,
                            step: int) -> Optional[str]:
    """The committed checkpoint at EXACTLY ``step`` (any format), or
    None. The fleet publisher pins versions to specific steps and must
    not drift to a neighbor the way latest_checkpoint would."""
    matches = [(s, fmt) for s, fmt in _checkpoints(ckpt_dir)
               if s == step]
    if not matches:
        return None
    return _ckpt_path(ckpt_dir, *max(matches))


def _restore_one(path: str, target: Any, host_target: Any,
                 sharding=None, shard_io_threads: Optional[int] = None,
                 on_event=None) -> Any:
    """Restore ONE specific checkpoint into ``target``'s structure;
    raises ValueError (with the standard classified message) on a
    config mismatch or corrupt bytes."""
    if path.endswith(".sharded"):
        from dml_cnn_cifar10_tpu.ckpt import sharded as sharded_lib

        # No fetch_to_host here: restore_sharded reads only the
        # TARGET'S TREE STRUCTURE and rebuilds every value from the
        # shard files — an allgather of the about-to-be-overwritten
        # values would be exactly the O(full-state) cost this codec
        # exists to avoid.
        try:
            restored = sharded_lib.restore_sharded(
                path, target, threads=shard_io_threads, on_event=on_event)
        except ValueError as e:
            raise ValueError(
                f"failed to restore checkpoint {path}: {e}") from e
        if sharding is not None:
            restored = jax.device_put(restored, sharding)
        return restored
    try:
        if path.endswith(".orbax"):
            import orbax.checkpoint as ocp

            raw = ocp.PyTreeCheckpointer().restore(os.path.abspath(path))
            restored = serialization.from_state_dict(host_target, raw)
        else:
            with open(path, "rb") as f:
                data = f.read()
            restored = serialization.from_bytes(host_target, data)
    except ValueError as e:
        # Usually a config mismatch against the run that wrote the
        # checkpoint; a corrupted file (partial copy, bit rot — msgpack
        # unpack errors are ValueErrors too) reads the same way, so name
        # both instead of a bare pytree-keys traceback.
        raise ValueError(
            f"failed to restore checkpoint {path}: either it was "
            f"written with a different config (--model, --optimizer, "
            f"--ema_decay, --async_staleness ...) or the file is "
            f"corrupted/truncated. Original error: {e}") from e
    if sharding is not None:
        restored = jax.device_put(restored, sharding)
    return restored


def restore_checkpoint(ckpt_dir: str, target: Any,
                       sharding=None, on_fallback=None,
                       shard_io_threads: Optional[int] = None,
                       logger=None, deadline_s: float = 0.0) -> Any:
    """Restore the newest VERIFIABLE checkpoint into ``target``'s
    structure, or return ``target`` unchanged if none exists.

    Candidates are walked newest→oldest: one that fails its integrity
    sidecar (``verify_checkpoint``) or fails to decode is skipped with a
    warning (and ``on_fallback(step, path, reason, walk_ms)`` when given
    — the Trainer logs a ``ckpt_fallback`` JSONL record carrying the
    wall-clock spent in the walk so far) and the next older checkpoint
    is tried, so a corrupt/truncated latest degrades a restart by one
    checkpoint interval instead of killing it. When nothing restores,
    the newest candidate's error is raised (integrity failures
    everywhere raise a summary naming every skip).

    ``deadline_s`` (``--restore_deadline_s``, 0 = unbounded) budgets the
    whole fallback walk: once exceeded, the walk stops trying older
    candidates and raises a classified restore error instead of grinding
    through an arbitrarily deep pile of corrupt checkpoints.

    ``sharding`` (e.g. a replicated NamedSharding) places the restored
    arrays back on the mesh. ``shard_io_threads`` bounds the sharded
    codec's concurrent shard reads; ``logger`` receives its per-shard
    ``shard_io`` telemetry records.
    """
    on_event = _logger_on_event(logger)
    candidates = sorted(_checkpoints(ckpt_dir), reverse=True)
    if not candidates:
        return target
    host_target = None
    first_error: Optional[ValueError] = None
    skipped = []
    walk_t0 = time.perf_counter()

    def walk_ms():
        return (time.perf_counter() - walk_t0) * 1000.0

    def note(step, path, reason):
        print(f"[ckpt] skipping checkpoint {path}: {reason}; falling "
              f"back to an older checkpoint", file=sys.stderr)
        skipped.append(f"{os.path.basename(path)}: {reason}")
        if on_fallback is not None:
            on_fallback(step, path, reason, walk_ms())

    for step, fmt in candidates:
        if deadline_s and (time.perf_counter() - walk_t0) > deadline_s:
            raise ValueError(
                f"checkpoint restore walk in {ckpt_dir} exceeded its "
                f"{deadline_s:.1f}s deadline after {walk_ms():.0f}ms "
                f"({len(skipped)} candidates skipped: "
                f"{'; '.join(skipped)}); nothing restorable in budget")
        path = _ckpt_path(ckpt_dir, step, fmt)
        ok, reason = verify_checkpoint(path)
        if not ok:
            note(step, path, reason)
            continue
        if host_target is None and fmt != "sharded":
            # Collective-safe fetch, computed once across the walk.
            host_target = fetch_to_host(target)
        try:
            return _restore_one(path, target, host_target,
                                sharding=sharding,
                                shard_io_threads=shard_io_threads,
                                on_event=on_event)
        except ValueError as e:
            if first_error is None:
                first_error = e
            note(step, path, str(e))
            continue
    if first_error is not None:
        raise first_error
    raise ValueError(
        f"no restorable checkpoint in {ckpt_dir}: all "
        f"{len(candidates)} candidates failed integrity verification "
        f"({'; '.join(skipped)})")


def restore_checkpoint_at(path: str, target: Any, sharding=None,
                          shard_io_threads: Optional[int] = None,
                          logger=None) -> Any:
    """Restore ONE SPECIFIC checkpoint path into ``target``'s structure.

    Unlike :func:`restore_checkpoint` there is no newest→oldest walk:
    the caller already chose the candidate (the serving fleet's
    hot-swap restores exactly the PUBLISHED version, never "whatever is
    newest"). Integrity failure or a decode mismatch raises — the
    hot-swap seam answers by rejecting the candidate and keeping the
    old weights live.
    """
    ok, reason = verify_checkpoint(path)
    if not ok:
        raise ValueError(f"checkpoint {path} failed integrity "
                         f"verification: {reason}")
    host_target = None if path.endswith(".sharded") \
        else fetch_to_host(target)
    return _restore_one(path, target, host_target, sharding=sharding,
                        shard_io_threads=shard_io_threads,
                        on_event=_logger_on_event(logger))


class CheckpointManager:
    """Periodic chief-only saver (the CheckpointSaverHook role).

    ``async_save=True`` overlaps serialize+disk-write with training: the
    device→host fetch still happens synchronously at the call (the arrays
    must be read before the next donated step reuses their buffers), but
    the msgpack encode and file IO run on a single background writer
    thread. Saves stay ordered (a new save first drains the previous one);
    writer exceptions surface at the next ``maybe_save``/``flush``.

    ``on_committed(step, path)`` — optional chief-only callback invoked
    AFTER a checkpoint and its integrity sidecar are fully committed
    (on the writer thread under ``async_save``). The trainer's fleet
    publish hook rides it: publishing before the sidecar lands would
    hand serve workers a version they must reject.
    """

    def __init__(self, ckpt_dir: str, every_steps: int, keep: int = 3,
                 is_chief: Optional[bool] = None, async_save: bool = False,
                 every_secs: Optional[float] = None,
                 fmt: str = "msgpack", logger=None, on_committed=None,
                 shard_io_threads: Optional[int] = None):
        self.ckpt_dir = ckpt_dir
        self.every_steps = max(1, every_steps)
        self.keep = keep
        self.fmt = fmt
        self.on_committed = on_committed
        # Bounded pool size for the sharded codec's concurrent per-shard
        # writes (ckpt/sharded.py); None = its default.
        self.shard_io_threads = shard_io_threads
        # Optional MetricsLogger-shaped sink for checkpoint-maintenance
        # events (ckpt_prune_error); the writer thread may call it.
        self.logger = logger
        # Fail at construction, not at the first due save 500 steps in
        # (the write path re-checks for direct save_checkpoint callers).
        _check_orbax_single_process(fmt)
        self._last_saved_step = None
        self.is_chief = (jax.process_index() == 0) if is_chief is None \
            else is_chief
        self.async_save = async_save
        # Wall-clock cadence (the MonitoredTrainingSession default was
        # time-based: save_checkpoint_secs=600, cifar10cnn.py:222). The
        # clock only TRIGGERS via time_due(); the caller decides when to
        # act on it — multi-host loops must agree first (fetch_to_host is
        # a collective; one process saving alone would deadlock the rest),
        # which train/loop.py does at its preemption-sync boundary.
        self.every_secs = every_secs
        self._last_time = time.monotonic()
        self._pool = None
        self._pending = None
        if async_save:
            import concurrent.futures
            self._pool = concurrent.futures.ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="ckpt-writer")

    def time_due(self) -> bool:
        """True when the wall-clock cadence has elapsed since the last
        save (this process's clock)."""
        return bool(self.every_secs
                    and time.monotonic() - self._last_time
                    >= self.every_secs)

    def flush(self) -> None:
        """Wait for an in-flight async write; re-raise its error if any."""
        if self._pending is not None:
            pending, self._pending = self._pending, None
            pending.result()

    def close(self) -> None:
        """Drain the writer and shut the thread down (idempotent)."""
        try:
            self.flush()
        finally:
            if self._pool is not None:
                self._pool.shutdown(wait=True)
                self._pool = None

    def due(self, step: int, force: bool = False) -> bool:
        """True when ``maybe_save(state, step, force)`` would attempt a
        write — the ONE source of truth for the cadence predicate, so
        callers that must act before a save (numerics guards) can't
        drift from the manager's own logic.

        The ``step != _last_saved_step`` half exists because the loop's
        state only changes between steps: a boundary save followed by
        the final forced save at the same step would rewrite identical
        bytes — and the orbax codec's same-path re-save has an
        rmtree-before-write window that is NOT crash-atomic. Skip
        instead."""
        if not force and step % self.every_steps != 0:
            return False
        return step != self._last_saved_step

    def maybe_save(self, state: Any, step: int, force: bool = False,
                   data_state: Optional[dict] = None) -> bool:
        """Save if :meth:`due`; returns True on every process that spent
        time on the save (collective fetch, shard write, barrier) — the
        caller re-anchors its throughput meter on it, so it must fire on
        chief and non-chief alike. ``data_state`` (the exact-resume stream
        counts) is committed by the same writer AFTER the checkpoint
        bytes land, so a crash mid-write can never leave a sidecar whose
        checkpoint never existed — the pair commits atomically in
        order even under ``async_save``."""
        if not self.due(step, force):
            return False
        self._last_saved_step = step
        if self.fmt == "sharded":
            # Pod-scale path (ckpt/sharded.py): no full-state gather —
            # every process fetches and writes only its own shards. The
            # local device→host fetch happens HERE, synchronously (the
            # next donated step would reuse the buffers); multi-host
            # saves run fully synchronous (the pre-manifest barrier is a
            # collective and cannot live on the writer thread).
            from dml_cnn_cifar10_tpu.ckpt import sharded as sharded_lib
            os.makedirs(self.ckpt_dir, exist_ok=True)
            path = _ckpt_path(self.ckpt_dir, step, "sharded")
            payload = sharded_lib.collect_local_shards(state)
            if self.async_save and jax.process_count() == 1:
                self.flush()
                self._pending = self._pool.submit(
                    self._finish_sharded, path, payload, state, step,
                    data_state)
            else:
                self._finish_sharded(path, payload, state, step,
                                     data_state)
            self._last_time = time.monotonic()
            # True on EVERY process: all of them did real work here (the
            # shard fetch + file write + pre-manifest barrier), so the
            # loop's DrainMeter must be re-marked everywhere or non-chief
            # processes fold checkpoint time into their images/sec
            # windows.
            return True
        # Collective fetch BEFORE the chief check: with tensor-parallel
        # state on a multi-host mesh the gather is a collective, so every
        # process participates; only the chief touches the filesystem.
        host_state = fetch_to_host(state)
        if not self.is_chief:
            # Clock reset AFTER the slow part (the collective fetch /
            # write): resetting on entry would count the save's own
            # duration against the next interval, turning any
            # every_secs shorter than one save into a checkpoint storm.
            self._last_time = time.monotonic()
            # True like the sharded path: the collective fetch was real
            # time spent on this process too, so the caller's DrainMeter
            # must be re-marked here as well (the return value means
            # "this process did save work", not "this process wrote").
            return True
        if self.async_save:
            self.flush()  # ordered writes + surface prior errors
            self._pending = self._pool.submit(
                self._write_with_sidecar, host_state, step, data_state)
        else:
            self._write_with_sidecar(host_state, step, data_state)
        self._last_time = time.monotonic()
        return True

    def _finish_sharded(self, path: str, payload, state: Any, step: int,
                        data_state: Optional[dict]) -> None:
        from dml_cnn_cifar10_tpu.ckpt import sharded as sharded_lib
        sharded_lib.finish_sharded_save(
            path, payload, state, threads=self.shard_io_threads,
            on_event=_logger_on_event(self.logger))
        if self.is_chief:
            _finalize_checkpoint(self.ckpt_dir, path, self.keep,
                                 logger=self.logger)
            if data_state is not None:
                save_data_state(self.ckpt_dir, step, data_state)
            if self.on_committed is not None:
                self.on_committed(step, path)

    def _write_with_sidecar(self, host_state: Any, step: int,
                            data_state: Optional[dict]) -> str:
        path = _write_checkpoint(self.ckpt_dir, host_state, step,
                                 keep=self.keep, fmt=self.fmt,
                                 logger=self.logger)
        if data_state is not None:
            save_data_state(self.ckpt_dir, step, data_state)
        if self.on_committed is not None:
            self.on_committed(step, path)
        return path
