"""Peer-redundant shard replicas — the diskless-recovery transport.

Every recovery path in the repo funnels through disk checkpoints: the
supervisor's restart restores the newest verifiable checkpoint, elastic
shrink/expand pick a restore step from the same archive, and at pod
scale that walk is storage-bound even with the sharded codec's
concurrent IO. This module keeps a **cold replica of each host's shard
payload on a peer**, so an elastic restart can rebuild the lost host's
state from a surviving peer's copy — zero checkpoint reads — and fall
back to disk (unchanged behavior) only when a replica is missing, stale,
or corrupt.

Protocol (docs/RESILIENCE.md, diskless-recovery section):

- **Ring assignment.** Hosts form a ring over the sorted live world;
  each host pushes its own payload to its ring-successor
  (:func:`ring_successor`). A 1-host world degrades to a no-op — the
  flag stays legal, nothing is pushed.
- **Push.** At every checkpoint boundary the trainer collects its local
  shard payload (``collect_local_shards`` — the same device→host fetch
  the save already pays, on the MAIN thread: donated step buffers make
  background device reads unsafe) and hands it to a bounded background
  push thread: the train step never blocks on replica IO. The payload
  is split (``_split_payload``) and written with the sharded codec's
  per-shard sha256 sidecars into a step-tagged directory under
  ``<cluster_dir>/replicas/host_<owner>/``, committed by atomic
  tmp→rename of the whole directory, retained for the last ``keep``
  steps. Push failures retry with the shared bounded backoff
  (``utils/backoff.py``) and are logged, never raised into training.
- **Staleness.** The owner's newest committed step
  (:attr:`PeerReplicaStore.replica_step`) is advertised in the
  heartbeat ``extra`` payload, so the chief's ``decide_restart`` can
  tell whether a peer restore is viable — and how stale — without
  touching the store.
- **Restore.** Survivors restore their own live shards from the
  in-memory payload cache (falling back to their own on-disk replica
  when the cache misses the decided step), reconstruct each lost
  host's shard from the replica its ring-predecessor pushed, verify
  every sidecar, and assemble the full state with the same
  coverage-mask logic as the sharded codec. Any miss raises the
  classified :class:`ReplicaMiss` so the caller falls back to the
  disk restore walk.

Telemetry: pushes/verifies/reconstructs emit ``peer_replica`` JSONL
records; replica reads emit ``shard_io`` records with
``source="peer"`` (disk reads say ``source="disk"``), so the
zero-disk-reads claim of a peer restore is pinned by the stream.
"""

from __future__ import annotations

import json
import os
import shutil
import tempfile
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np

from dml_cnn_cifar10_tpu.ckpt import sharded
from dml_cnn_cifar10_tpu.ckpt.sharded import collect_local_shards  # noqa: F401  (re-export: the trainer's push seam)
from dml_cnn_cifar10_tpu.utils import backoff

#: Store directory under ``cluster_dir`` — a sibling of ``heartbeats/``.
REPLICAS_DIRNAME = "replicas"

#: Per-replica commit marker, written INSIDE the step dir before the
#: atomic directory rename publishes it.
INDEX = "INDEX.json"

#: Push retry budget: attempts over the shared bounded backoff before a
#: push is abandoned (logged ``ok=False``; the next boundary pushes a
#: fresher payload anyway).
PUSH_ATTEMPTS = 3


class ReplicaMiss(ValueError):
    """A needed replica is missing, stale, or failed integrity
    verification. Classified (a ``ValueError`` naming the replica), so
    the restore seam falls back to the disk walk instead of crashing."""


def ring_successor(pid: int, world: Sequence[int]) -> int:
    """The host ``pid`` pushes its replica TO — the next id on the
    sorted ring. A 1-host world maps a host to itself (no-op)."""
    ring = sorted(world)
    i = ring.index(pid)
    return ring[(i + 1) % len(ring)]


def ring_predecessor(pid: int, world: Sequence[int]) -> int:
    """The host whose replica ``pid`` holds — the previous ring id."""
    ring = sorted(world)
    i = ring.index(pid)
    return ring[(i - 1) % len(ring)]


def _payload_nbytes(payload: Dict[str, list]) -> int:
    total = 0
    for entries in payload.values():
        if isinstance(entries, dict):
            entries = list(entries.values())
        for e in entries:
            total += int(np.asarray(e["data"]).nbytes)
    return total


class PeerReplicaStore:
    """File-backed peer-replica store next to the heartbeat dir.

    File-backed for the same reason the heartbeat store is: it must
    work where the collectives do not, be inspectable post-mortem, and
    be simulatable on CPU — a real RDMA/KV transport can replace it
    behind the same push/read API. One background thread drains a
    bounded queue of at most two pending payloads (newest wins: under
    a slow store the freshest state is the one worth replicating).
    """

    def __init__(self, cluster_dir: str, process_id: int,
                 world: Sequence[int], keep: int = 2,
                 log_fn: Optional[Callable[..., None]] = None,
                 threads: int = 1, client=None):
        # Optional network transport (parallel/net.py CoordClient):
        # pushes stage locally then travel to the coordination service
        # host, committed by a server-side atomic rename — the same
        # tmp→rename protocol, one hop further away. None = the
        # file-backed store (shared directory) as before. TransportError
        # subclasses OSError, so every retry/abandon path below handles
        # a network failure exactly like a filesystem one.
        self._client = client
        self.root = os.path.join(cluster_dir, REPLICAS_DIRNAME)
        self.process_id = process_id
        self.world = sorted(world) if world else [process_id]
        self.keep = max(int(keep), 1)
        self.threads = max(int(threads or 1), 1)
        self._log = log_fn
        #: Committed pushes (the pushes-vs-steps pin reads this).
        self.pushes = 0
        self._mem: Dict[int, Dict[str, list]] = {}
        self._queue: List[Tuple[int, Dict[str, list]]] = []
        self._cv = threading.Condition()
        self._closing = False
        self._inflight = 0
        # Recover continuity after an in-process restart (the supervisor
        # rebuilds the Trainer but the store spans attempts): the newest
        # committed own replica still counts as pushed.
        steps = self.committed_steps(process_id)
        self._replica_step = steps[-1] if steps else -1
        self._worker = threading.Thread(
            target=self._drain, daemon=True, name="peer-replica-push")
        self._worker.start()

    # -- identity ---------------------------------------------------------

    @property
    def enabled(self) -> bool:
        """Redundancy is meaningful only with a peer to hold the copy."""
        return len(self.world) > 1

    @property
    def replica_step(self) -> int:
        """Newest OWN committed replica step (-1 = none yet) — the
        staleness number the heartbeat ``extra`` payload advertises."""
        return self._replica_step

    def successor(self) -> int:
        return ring_successor(self.process_id, self.world)

    def set_world(self, world: Sequence[int]) -> None:
        """Adopt a restart decision's survivor set: the ring re-forms
        over the new world (a 1-host world stops pushing)."""
        with self._cv:
            self.world = sorted(world) if world else [self.process_id]

    # -- paths ------------------------------------------------------------

    def _host_dir(self, owner: int) -> str:
        return os.path.join(self.root, f"host_{owner}")

    def _step_dir(self, owner: int, step: int) -> str:
        return os.path.join(self._host_dir(owner), f"step_{step:08d}")

    def _host_rel(self, owner: int) -> str:
        """Server-relative path of an owner's replica dir (net mode)."""
        return f"{REPLICAS_DIRNAME}/host_{owner}"

    def _step_rel(self, owner: int, step: int) -> str:
        return f"{self._host_rel(owner)}/step_{step:08d}"

    def committed_steps(self, owner: int) -> List[int]:
        """Sorted committed replica steps for ``owner`` (commit marker
        present; half-renamed tmp dirs are invisible). Over the network
        transport an unreachable coordinator reads as no commits — the
        decide seam then falls back to disk, which is the right
        degradation."""
        out: List[int] = []
        if self._client is not None:
            try:
                names = self._client.list_dir(self._host_rel(owner))
            except OSError:
                return out
            # Visibility == committed: the server publishes a step dir
            # only by the atomic rename that ends a push.
            for name in names:
                if not name.startswith("step_") or ".tmp" in name:
                    continue
                try:
                    out.append(int(name[len("step_"):]))
                except ValueError:
                    continue
            return sorted(out)
        try:
            names = os.listdir(self._host_dir(owner))
        except OSError:
            return out
        for name in names:
            if not name.startswith("step_") or ".tmp" in name:
                continue
            try:
                step = int(name[len("step_"):])
            except ValueError:
                continue
            if os.path.isfile(os.path.join(self._host_dir(owner), name,
                                           INDEX)):
                out.append(step)
        return sorted(out)

    # -- telemetry --------------------------------------------------------

    def _emit(self, op: str, step=None, owner=None, nbytes=None,
              secs=None, ok=None, error=None, staleness=None) -> None:
        if self._log is not None:
            self._log("peer_replica", op=op, step=step, owner=owner,
                      bytes=nbytes, secs=secs, ok=ok, error=error,
                      staleness=staleness)

    # -- push side --------------------------------------------------------

    def push_state_async(self, step: int, state: Any) -> bool:
        """The trainer's checkpoint-boundary seam: collect THIS
        process's shard payload (synchronously — the fetch must happen
        before the next donated dispatch reuses the buffers) and hand
        it to the background push thread. Returns whether a push was
        enqueued (False in a 1-host world: no-op by design)."""
        if not self.enabled:
            return False
        return self.push_async(step, collect_local_shards(state))

    def push_async(self, step: int, payload: Dict[str, list]) -> bool:
        if not self.enabled:
            return False
        with self._cv:
            self._mem[int(step)] = payload
            for old in sorted(self._mem)[:-self.keep]:
                del self._mem[old]
            self._queue.append((int(step), payload))
            if len(self._queue) > 2:
                self._queue.pop(0)  # newest wins under a slow store
            self._cv.notify()
        return True

    def _drain(self) -> None:
        while True:
            with self._cv:
                while not self._queue and not self._closing:
                    self._cv.wait()
                if not self._queue:
                    return
                step, payload = self._queue.pop(0)
                self._inflight += 1
            try:
                self._push_with_retry(step, payload)
            finally:
                with self._cv:
                    self._inflight -= 1
                    self._cv.notify_all()

    def _push_with_retry(self, step: int, payload: Dict[str, list]) -> None:
        err = None
        for attempt in range(1, PUSH_ATTEMPTS + 1):
            try:
                self._push(step, payload)
                return
            except OSError as e:
                err = e
                if attempt < PUSH_ATTEMPTS:
                    time.sleep(backoff.delay_s(0.05, 1.0, attempt))
        # Abandoned push: logged, never raised — the next checkpoint
        # boundary replicates a fresher payload anyway, and the decide
        # seam sees the gap through the advertised replica_step.
        self._emit("push", step=step, owner=self.process_id, ok=False,
                   error=str(err)[:300])

    def _push(self, step: int, payload: Dict[str, list]) -> None:
        if self._client is not None:
            return self._push_net(step, payload)
        t0 = time.perf_counter()
        final = self._step_dir(self.process_id, step)
        if os.path.isfile(os.path.join(final, INDEX)):
            return  # already committed (a replayed boundary)
        tmp = final + f".tmp{os.getpid()}"
        shutil.rmtree(tmp, ignore_errors=True)
        os.makedirs(tmp)
        parts = sharded._split_payload(payload, self.threads)
        names = [f"part_{j}.msgpack" for j in range(len(parts))]
        total = 0
        for name, part in zip(names, parts):
            _, nbytes, _ = sharded._write_one_shard(tmp, name, part,
                                                    on_event=None,
                                                    source="peer")
            total += nbytes
        index = {"owner": self.process_id, "dest": self.successor(),
                 "step": int(step), "files": names}
        idx_tmp = os.path.join(tmp, INDEX + ".tmp")
        with open(idx_tmp, "w") as f:
            json.dump(index, f)
        os.replace(idx_tmp, os.path.join(tmp, INDEX))
        os.rename(tmp, final)  # the commit point
        self._replica_step = max(self._replica_step, int(step))
        self.pushes += 1
        self._emit("push", step=step, owner=self.process_id,
                   nbytes=total,
                   secs=round(time.perf_counter() - t0, 6), ok=True)
        self._prune()

    def _push_net(self, step: int, payload: Dict[str, list]) -> None:
        """Network push: stage the split + sidecar-bearing part files
        in a local scratch dir (the same codec writes them), upload
        each under a ``.tmpnet`` step dir, then commit with ONE
        server-side atomic rename — visibility still equals commit."""
        t0 = time.perf_counter()
        if step in self.committed_steps(self.process_id):
            return  # already committed (a replayed boundary)
        rel_final = self._step_rel(self.process_id, step)
        rel_tmp = rel_final + f".tmpnet{os.getpid()}"
        scratch = tempfile.mkdtemp(prefix="dml_peer_push_")
        try:
            parts = sharded._split_payload(payload, self.threads)
            names = [f"part_{j}.msgpack" for j in range(len(parts))]
            total = 0
            for name, part in zip(names, parts):
                _, nbytes, _ = sharded._write_one_shard(
                    scratch, name, part, on_event=None, source="peer")
                total += nbytes
            # Upload parts AND their .sha256 sidecars; INDEX last so a
            # server-side listing of the tmp dir is never mistaken for
            # complete (belt — the rename commit is the suspenders).
            for fname in sorted(os.listdir(scratch)):
                with open(os.path.join(scratch, fname), "rb") as f:
                    self._client.put(f"{rel_tmp}/{fname}", f.read())
            index = {"owner": self.process_id, "dest": self.successor(),
                     "step": int(step), "files": names}
            self._client.put(f"{rel_tmp}/{INDEX}",
                             json.dumps(index).encode())
            self._client.rename(rel_tmp, rel_final)
        finally:
            shutil.rmtree(scratch, ignore_errors=True)
        self._replica_step = max(self._replica_step, int(step))
        self.pushes += 1
        self._emit("push", step=step, owner=self.process_id,
                   nbytes=total,
                   secs=round(time.perf_counter() - t0, 6), ok=True)
        self._prune()

    def _prune(self) -> None:
        for step in self.committed_steps(self.process_id)[:-self.keep]:
            if self._client is not None:
                try:
                    self._client.delete_tree(
                        self._step_rel(self.process_id, step))
                except OSError:
                    pass  # the next boundary's prune retries
            else:
                shutil.rmtree(self._step_dir(self.process_id, step),
                              ignore_errors=True)

    def flush(self, timeout_s: float = 10.0) -> None:
        """Drain pending pushes (tests; never on the step path)."""
        deadline = time.time() + timeout_s
        with self._cv:
            while (self._queue or self._inflight) \
                    and time.time() < deadline:
                self._cv.wait(timeout=0.05)

    # -- read side --------------------------------------------------------

    def _fetch_replica(self, owner: int, step: int) -> str:
        """Net mode: download one committed replica (commit marker,
        parts, sidecars) into a scratch dir shaped like the on-disk
        layout, so the verify path below runs unchanged. Unreachable or
        uncommitted reads raise :class:`ReplicaMiss` — the caller falls
        back to the disk walk."""
        rel = self._step_rel(owner, step)
        try:
            idx_payload = self._client.get(f"{rel}/{INDEX}")
        except OSError as e:
            raise ReplicaMiss(
                f"replica of host {owner} at step {step} unreachable "
                f"over the net transport: {e}")
        if idx_payload is None:
            raise ReplicaMiss(
                f"replica of host {owner} at step {step} is missing or "
                f"stale (committed steps: "
                f"{self.committed_steps(owner) or 'none'})")
        try:
            files = json.loads(idx_payload)["files"]
        except (ValueError, TypeError, KeyError) as e:
            raise ReplicaMiss(
                f"replica of host {owner} at step {step} has an "
                f"undecodable commit marker: {e}")
        scratch = os.path.join(
            tempfile.mkdtemp(prefix="dml_peer_read_"),
            f"step_{step:08d}")
        os.makedirs(scratch)
        with open(os.path.join(scratch, INDEX), "wb") as f:
            f.write(idx_payload)
        for fname in files:
            for name in (fname, sharded.shard_checksum_path(fname)):
                try:
                    payload = self._client.get(f"{rel}/{name}")
                except OSError as e:
                    raise ReplicaMiss(
                        f"replica of host {owner} at step {step} "
                        f"unreachable mid-read: {e}")
                if payload is None:
                    continue  # sidecar-less legacy replica decodes
                with open(os.path.join(scratch, name), "wb") as f:
                    f.write(payload)
        return scratch

    def read_replica(self, owner: int, step: int,
                     on_event=None) -> Dict[str, list]:
        """Read + sidecar-verify one committed replica. Every failure —
        missing dir, missing commit marker, truncated file, digest
        mismatch — raises the classified :class:`ReplicaMiss`, never an
        unclassified crash. A sidecar-less legacy replica decodes (the
        sharded codec's own back-compat rule)."""
        if self._client is not None:
            d = self._fetch_replica(owner, step)
            try:
                return self._read_replica_dir(d, owner, step, on_event)
            finally:
                shutil.rmtree(os.path.dirname(d), ignore_errors=True)
        return self._read_replica_dir(self._step_dir(owner, step),
                                      owner, step, on_event)

    def _read_replica_dir(self, d: str, owner: int, step: int,
                          on_event=None) -> Dict[str, list]:
        idx = os.path.join(d, INDEX)
        if not os.path.isfile(idx):
            newest = self.committed_steps(owner)
            raise ReplicaMiss(
                f"replica of host {owner} at step {step} is missing or "
                f"stale (committed steps: {newest or 'none'})")
        t0 = time.perf_counter()
        try:
            with open(idx) as f:
                files = json.load(f)["files"]
        except (OSError, ValueError, KeyError) as e:
            raise ReplicaMiss(
                f"replica of host {owner} at step {step} has an "
                f"undecodable commit marker: {e}")
        payload: Dict[str, list] = {}
        total = 0
        for fname in files:
            try:
                part = sharded._read_one_shard(d, fname, on_event,
                                               source="peer")
            except (OSError, ValueError) as e:
                self._emit("verify", step=step, owner=owner, ok=False,
                           error=str(e)[:300])
                raise ReplicaMiss(
                    f"replica of host {owner} at step {step} failed "
                    f"verification: {e}") from e
            total += os.path.getsize(os.path.join(d, fname))
            for path, entries in part.items():
                if isinstance(entries, dict):
                    entries = list(entries.values())
                payload.setdefault(path, []).extend(entries)
        self._emit("verify", step=step, owner=owner, nbytes=total,
                   secs=round(time.perf_counter() - t0, 6), ok=True)
        return payload

    def restore(self, target: Any, step: int, world: Sequence[int],
                lost: Sequence[int] = (), on_event=None) -> Any:
        """Assemble the full state at ``step`` from peer replicas onto
        ``target``'s structure — ZERO checkpoint reads. ``world`` is the
        OLD world that wrote the payloads (survivors + lost). Own
        payload comes from the in-memory cache (own replica file when
        the cache misses the step); every other owner's from its
        committed replica, sidecar-verified. Raises :class:`ReplicaMiss`
        when any needed payload is missing/corrupt or coverage is
        incomplete — the caller falls back to the disk walk."""
        lost_set = set(lost)
        payloads: List[Tuple[int, Dict[str, list]]] = []
        # Own payload first: deterministic precedence when replicas
        # redundantly cover the same index ranges (the 1-JAX-world-per-
        # process CPU simulation, where every payload is full-coverage).
        owners = sorted(set(world), key=lambda p: (p != self.process_id,
                                                   p))
        for owner in owners:
            if owner == self.process_id and step in self._mem:
                payload = self._mem[step]
                if on_event is not None:
                    on_event("shard_io", op="restore",
                             shard=f"host_{owner}/step_{step:08d}/memory",
                             bytes=_payload_nbytes(payload), secs=0.0,
                             verify=None, source="peer")
            else:
                t0 = time.perf_counter()
                payload = self.read_replica(owner, step,
                                            on_event=on_event)
                if owner in lost_set:
                    self._emit("reconstruct", step=step, owner=owner,
                               nbytes=_payload_nbytes(payload),
                               secs=round(time.perf_counter() - t0, 6),
                               ok=True)
            payloads.append((owner, payload))
        return _assemble(target, payloads, step)

    # -- lifecycle --------------------------------------------------------

    def close(self) -> None:
        with self._cv:
            self._closing = True
            self._cv.notify_all()
        self._worker.join(timeout=5.0)


def _assemble(target: Any, payloads: List[Tuple[int, Dict[str, list]]],
              step: int) -> Any:
    """Coverage-mask assembly onto ``target``'s structure (shapes and
    dtypes come from the target itself — a peer restore needs no
    manifest). Fully-duplicate entries from redundant replicas are
    skipped (payload order is deterministic); a PARTIAL overlap or a
    coverage hole raises :class:`ReplicaMiss`."""
    shards: Dict[str, list] = {}
    for _owner, payload in payloads:
        for path, entries in payload.items():
            if isinstance(entries, dict):
                entries = list(entries.values())
            shards.setdefault(path, []).extend(entries)

    def build(path: str, leaf: Any) -> np.ndarray:
        shape = tuple(getattr(leaf, "shape", np.shape(leaf)))
        dtype = np.dtype(getattr(leaf, "dtype", None)
                         or np.asarray(leaf).dtype)
        full = np.empty(shape, dtype=dtype)
        seen = np.zeros(shape, dtype=bool)
        for e in shards.get(path, ()):
            idx = tuple(slice(int(s), int(t)) for s, t in
                        np.asarray(e["index"], dtype=np.int64))
            sub = seen[idx]
            if sub.size and sub.all():
                continue  # redundant coverage from a second replica
            if sub.any():
                raise ReplicaMiss(
                    f"leaf {path!r} has partially-overlapping replica "
                    f"entries at {e['index']} for step {step}")
            full[idx] = e["data"]
            seen[idx] = True
        if not seen.all():
            raise ReplicaMiss(
                f"leaf {path!r} only {int(seen.sum())}/{full.size} "
                f"elements covered by peer replicas at step {step}")
        return full

    return jax.tree_util.tree_map_with_path(
        lambda kp, leaf: build(sharded._key_str(kp), leaf), target)
