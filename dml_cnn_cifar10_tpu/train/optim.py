"""Optimizer + LR schedule.

Reference (``train_step``, ``cifar10cnn.py:159-164``): plain
``GradientDescentOptimizer`` with an ``exponential_decay(0.1, gen, 250, 0.9,
staircase=True)`` schedule — where ``gen`` is a variable that is never
incremented (``:216``), so the *effective* reference LR is a constant 0.1.
``OptimConfig.dead_lr_decay=True`` (faithful default) reproduces that;
``False`` keys the decay on the global step as the code intended.

Implemented as a minimal functional optimizer (init/update pytrees) with
optional momentum / weight decay / grad clipping for the config-ladder
models. It is deliberately optax-shaped; ``as_optax()`` exposes the same
thing as a ``GradientTransformation`` for users who want to compose.

The plain-SGD apply runs fused by default (``ops/optimizer.py``:
momentum + weight decay + LR in ONE pass over the param bytes — a
Pallas TPU kernel with an identical-math XLA fallback by platform;
``--fused_optimizer false`` restores the tree_map chain). Under
``--optimizer_sharding zero1`` the caller (``parallel/step.py``)
wraps this update in the reduce-scatter/all-gather schedule; the
moments it reads are then ``data``-sharded and the same elementwise
math partitions 1/N per replica (docs/SHARDING.md).
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from dml_cnn_cifar10_tpu.config import OptimConfig

OptState = Dict[str, Any]


def learning_rate(cfg: OptimConfig, step: jax.Array) -> jax.Array:
    """LR schedule at ``step``.

    ``exponential`` (reference parity): ``tf.train.exponential_decay``
    staircase; faithful (dead_lr_decay) freezes the decay argument at 0 →
    constant base LR, exactly the reference's runtime behavior
    (``cifar10cnn.py:161,216``).
    ``cosine``: half-cosine from base LR to 0 over ``cosine_decay_steps``
    (the ViT/ResNet ladder standard). ``constant``: base LR.
    Any schedule composes with a linear ``warmup_steps`` ramp.
    """
    stepf = step.astype(jnp.float32)
    if cfg.schedule == "exponential":
        decay_steps = jnp.where(cfg.dead_lr_decay, 0.0, stepf)
        exponent = decay_steps / cfg.decay_every
        if cfg.staircase:
            exponent = jnp.floor(exponent)
        lr = cfg.learning_rate * cfg.lr_decay ** exponent
    elif cfg.schedule == "cosine":
        if cfg.cosine_decay_steps <= cfg.warmup_steps:
            raise ValueError(
                f"cosine schedule needs cosine_decay_steps "
                f"({cfg.cosine_decay_steps}) > warmup_steps "
                f"({cfg.warmup_steps}); otherwise the LR collapses to 0 "
                f"right after warmup")
        horizon = cfg.cosine_decay_steps - cfg.warmup_steps
        prog = jnp.clip((stepf - cfg.warmup_steps) / horizon, 0.0, 1.0)
        lr = cfg.learning_rate * 0.5 * (1.0 + jnp.cos(jnp.pi * prog))
    elif cfg.schedule == "constant":
        lr = jnp.asarray(cfg.learning_rate, jnp.float32)
    else:
        raise ValueError(f"unknown schedule {cfg.schedule!r}")
    if cfg.warmup_steps > 0:
        lr = lr * jnp.clip((stepf + 1.0) / cfg.warmup_steps, 0.0, 1.0)
    return lr


def sgd_init(params: Any, cfg: OptimConfig) -> OptState:
    """Optimizer-state init for the configured family (name kept for the
    historical sgd-only API; dispatches on ``cfg.optimizer``)."""
    state: OptState = {"step": jnp.zeros((), jnp.int32)}
    if cfg.optimizer in ("adamw", "lamb"):
        if cfg.momentum:
            raise ValueError(
                f"momentum is an SGD/LARS knob; {cfg.optimizer}'s first "
                "moment is adam_b1 — drop --momentum")
        state["mu"] = jax.tree.map(jnp.zeros_like, params)
        state["nu"] = jax.tree.map(jnp.zeros_like, params)
    elif cfg.optimizer == "lars":
        # LARS always carries momentum (paper default 0.9; our
        # cfg.momentum=0 means "use the conventional 0.9").
        state["momentum"] = jax.tree.map(jnp.zeros_like, params)
    elif cfg.optimizer == "adafactor":
        if cfg.momentum:
            raise ValueError(
                "adafactor's memory-saving mode carries no first moment "
                "(Shazeer & Stern 2018 §9) — drop --momentum")
        # Factored second moments: matrices (ndim>=2) keep only row/col
        # statistics over the trailing two dims — O(n+m) state instead
        # of Adam's O(n*m) — vectors keep the full accumulator. Three
        # parallel full-structure trees (size-0-cost () placeholders on
        # the branch a leaf doesn't use) so every optimizer family
        # checkpoints through the same pytree machinery. Under --fsdp
        # these stats stay replicated by design (shardings.state_pspecs:
        # they are sub-linear in the first place).
        state["vr"] = jax.tree.map(
            lambda p: jnp.zeros(p.shape[:-1], jnp.float32)
            if p.ndim >= 2 else jnp.zeros((), jnp.float32), params)
        state["vc"] = jax.tree.map(
            lambda p: jnp.zeros(p.shape[:-2] + p.shape[-1:], jnp.float32)
            if p.ndim >= 2 else jnp.zeros((), jnp.float32), params)
        state["v"] = jax.tree.map(
            lambda p: jnp.zeros((), jnp.float32)
            if p.ndim >= 2 else jnp.zeros(p.shape, jnp.float32), params)
    elif cfg.optimizer == "sgd":
        if cfg.momentum:
            state["momentum"] = jax.tree.map(jnp.zeros_like, params)
    else:
        raise ValueError(f"unknown optimizer {cfg.optimizer!r}")
    if cfg.async_staleness >= 2:
        if cfg.optimizer in ("sgd", "lars") and cfg.weight_decay:
            # SGD and LARS couple L2 decay into the gradient — a real
            # async worker would compute that term at its STALE
            # snapshot, but the update necessarily couples at the live
            # params, so the emulation would silently deviate. AdamW /
            # LAMB decay decoupled at apply time (a PS-side op in the
            # async world), which IS faithful; gradient-coupled
            # families must run wd=0 like the reference.
            raise ValueError(
                f"async_staleness with {cfg.optimizer}-coupled "
                "weight_decay would not reproduce async semantics (the "
                "L2 term would use live params); use weight_decay=0 "
                "(the reference config) or a decoupled-decay optimizer "
                "(adamw/lamb)")
        # Round-robin snapshot ring for async-PS staleness emulation
        # (config.py:async_staleness): slot t%S serves the forward pass
        # at step t and receives the post-update params.
        state["stale"] = jax.tree.map(
            lambda p: jnp.stack([p] * cfg.async_staleness), params)
    if cfg.ema_decay:
        if not 0.0 <= cfg.ema_decay < 1.0:
            raise ValueError(
                f"ema_decay must be in [0, 1) (got {cfg.ema_decay}); 1.0 "
                "would freeze the EMA at random init forever")
        # Eval-time parameter EMA, seeded at the initial params.
        state["ema"] = jax.tree.map(jnp.array, params)
    return state


def ema_decay_at(cfg: OptimConfig, t) -> jax.Array:
    """Warmup-ramped EMA decay: ``min(d, (1+t)/(10+t))`` for update count
    ``t`` — the standard schedule (optax/TF EMA) that keeps the early
    average close to the live params instead of the random init (a flat
    d=0.999 would leave ~37% init weight after 1000 steps)."""
    t = jnp.asarray(t, jnp.float32)
    return jnp.minimum(jnp.asarray(cfg.ema_decay, jnp.float32),
                       (1.0 + t) / (10.0 + t))


def _clipped(grads: Any, cfg: OptimConfig) -> Any:
    if cfg.grad_clip_norm is None:
        return grads
    gnorm = jnp.sqrt(sum(jnp.sum(jnp.square(g))
                         for g in jax.tree.leaves(grads)))
    scale = jnp.minimum(1.0, cfg.grad_clip_norm / (gnorm + 1e-12))
    return jax.tree.map(lambda g: g * scale, grads)


def sgd_update(
    grads: Any, state: OptState, params: Any, cfg: OptimConfig,
    pallas_ok: Optional[bool] = None
) -> Tuple[Any, OptState]:
    """One optimizer step; returns (new_params, new_state).

    The step counter increments on apply, mirroring ``minimize(...,
    global_step=global_step)`` (``cifar10cnn.py:163``). SGD couples weight
    decay into the gradient (classic L2); AdamW decays decoupled, applied
    directly to the weights (Loshchilov & Hutter). ``cfg.ema_decay`` also
    tracks an eval-time parameter EMA across every family.

    ``pallas_ok=False`` vetoes the fused path's Pallas lowering (same
    math via the XLA expression): the step builders pass it when the
    update's operands are GSPMD-sharded (tp/fsdp/pipe state) — an
    opaque ``pallas_call`` there would force the partitioner to
    materialize full replicas. ``None`` resolves by platform.
    """
    new_params, new_state = _base_update(grads, state, params, cfg,
                                         pallas_ok=pallas_ok)
    if cfg.ema_decay:
        d = ema_decay_at(cfg, new_state["step"])
        new_state["ema"] = jax.tree.map(
            lambda e, p: (d * e + (1 - d) * p).astype(e.dtype),
            state["ema"], new_params)
    return new_params, new_state


def _base_update(
    grads: Any, state: OptState, params: Any, cfg: OptimConfig,
    pallas_ok: Optional[bool] = None
) -> Tuple[Any, OptState]:
    step = state["step"]
    lr = learning_rate(cfg, step)
    grads = _clipped(grads, cfg)

    if cfg.optimizer in ("adamw", "lamb"):
        t = (step + 1).astype(jnp.float32)
        b1, b2 = cfg.adam_b1, cfg.adam_b2
        mu = jax.tree.map(lambda m, g: b1 * m + (1 - b1) * g,
                          state["mu"], grads)
        nu = jax.tree.map(lambda v, g: b2 * v + (1 - b2) * jnp.square(g),
                          state["nu"], grads)
        bc1 = 1.0 - b1 ** t
        bc2 = 1.0 - b2 ** t
        lamb = cfg.optimizer == "lamb"

        def upd(p, m, v):
            # AdamW direction; LAMB then rescales the step to the
            # weight's own norm per layer (You et al. 2019 /
            # optax.scale_by_trust_ratio semantics: ratio 1 when either
            # norm is zero).
            r = (m / bc1) / (jnp.sqrt(v / bc2) + cfg.adam_eps) \
                + cfg.weight_decay * p
            scale = lr * _trust_ratio(p, r) if lamb else lr
            return p - (scale * r).astype(p.dtype)

        new_params = jax.tree.map(upd, params, mu, nu)
        return new_params, {"step": step + 1, "mu": mu, "nu": nu}

    if cfg.optimizer == "adafactor":
        # Shazeer & Stern 2018: scheduled decay b2_t = 1 - t^-0.8 (no
        # bias correction needed), factored rsqrt preconditioner, update
        # RMS-clipped at 1.0, relative (parameter-scale) step size,
        # decoupled weight decay like AdamW. The factored estimate
        # vr_i*vc_j/mean(vr) is EXACT whenever g^2 is rank-1
        # (test-pinned) and an upper-biased approximation otherwise.
        t = (step + 1).astype(jnp.float32)
        b2 = 1.0 - t ** -0.8
        eps1 = 1e-30

        def one(p, g, vr, vc, v):
            g = g.astype(jnp.float32)
            g2 = jnp.square(g) + eps1
            if p.ndim >= 2:
                vr = b2 * vr + (1 - b2) * jnp.mean(g2, axis=-1)
                vc = b2 * vc + (1 - b2) * jnp.mean(g2, axis=-2)
                row = vr / jnp.mean(vr, axis=-1, keepdims=True)
                # Two separate rsqrts, NOT rsqrt(row*vc): for a
                # zero-gradient row the product underflows f32 to 0
                # (~1e-28 * ~1e-30), rsqrt(0)=inf and 0*inf NaNs the
                # update; the factors individually stay normal.
                u = (g * jax.lax.rsqrt(row)[..., None]
                     * jax.lax.rsqrt(vc)[..., None, :])
            else:
                v = b2 * v + (1 - b2) * g2
                u = g * jax.lax.rsqrt(v)
            rms = jnp.sqrt(jnp.mean(jnp.square(u)))
            u = u / jnp.maximum(1.0, rms)
            # Parameter-scale multiply (the paper's relative step /
            # optax default): alpha = lr * max(RMS(p), eps2). Without it
            # the early steps are near-sign-SGD with absolute magnitude
            # lr — catastrophic for layers initialized at small scale.
            alpha = lr * jnp.maximum(
                jnp.sqrt(jnp.mean(jnp.square(p.astype(jnp.float32)))),
                1e-3)
            new_p = p - (alpha * (u + cfg.weight_decay * p)).astype(p.dtype)
            return new_p, vr, vc, v

        out = jax.tree.map(one, params, grads, state["vr"], state["vc"],
                           state["v"])
        # Structural transpose (treedef-driven): params-of-4-tuples →
        # 4-tuple-of-params-trees. An isinstance(tuple) is_leaf unzip
        # would misfire on param trees that use tuples as containers.
        new_params, vr, vc, v = jax.tree_util.tree_transpose(
            jax.tree.structure(params), jax.tree.structure((0, 0, 0, 0)),
            out)
        return new_params, {"step": step + 1, "vr": vr, "vc": vc, "v": v}

    if cfg.optimizer == "lars":
        beta = cfg.momentum or 0.9

        def local_gradient(p, g):
            # Trust-adapted gradient, optax-style convention: local LR
            # eta*||w||/(||g + wd*w|| + eps) — the decayed gradient's
            # norm, NOT the paper's ||g|| + wd*||w|| split (they differ
            # when g and w aren't parallel; test_lars_local_lr_formula
            # pins this form). 1-D leaves (biases, BN) skip the
            # adaptation, the standard practice.
            g = g + cfg.weight_decay * p
            if p.ndim <= 1:
                return g
            pn = jnp.linalg.norm(p)
            gn = jnp.linalg.norm(g)
            local = jnp.where(
                pn > 0,
                jnp.where(gn > 0,
                          cfg.lars_trust_coef * pn / (gn + cfg.lars_eps),
                          1.0),
                1.0)
            return local * g

        adapted = jax.tree.map(local_gradient, params, grads)
        mom = jax.tree.map(lambda m, g: beta * m + g,
                           state["momentum"], adapted)
        new_params = jax.tree.map(lambda p, m: p - (lr * m).astype(p.dtype),
                                  params, mom)
        return new_params, {"step": step + 1, "momentum": mom}

    new_state: OptState = {"step": step + 1}
    if getattr(cfg, "fused_optimizer", True):
        # Fused single-pass update (ops/optimizer.py): decay + momentum
        # + apply in ONE pass over the param bytes — a Pallas TPU kernel,
        # or the identical (bit-equal, PARITY.md) f32 expression as one
        # fused XLA loop on other platforms / under GSPMD-sharded
        # (zero1) layouts. --fused_optimizer false keeps the historical
        # tree_map chain below.
        from dml_cnn_cifar10_tpu.ops import optimizer as fused_lib

        new_params, mom = fused_lib.fused_sgd_update(
            params, grads, state.get("momentum") if cfg.momentum else None,
            lr, cfg.momentum, cfg.weight_decay,
            optimizer_sharding=getattr(cfg, "optimizer_sharding", "none"),
            use_pallas=False if pallas_ok is False else None)
        if mom is not None:
            new_state["momentum"] = mom
        return new_params, new_state
    if cfg.weight_decay:
        grads = jax.tree.map(lambda g, p: g + cfg.weight_decay * p,
                             grads, params)
    if cfg.momentum:
        mom = jax.tree.map(lambda m, g: cfg.momentum * m + g,
                           state["momentum"], grads)
        new_state["momentum"] = mom
        grads = mom
    new_params = jax.tree.map(lambda p, g: p - lr * g.astype(p.dtype),
                              params, grads)
    return new_params, new_state


def _trust_ratio(p: jax.Array, u: jax.Array) -> jax.Array:
    """||p|| / ||u|| with optax's safe guards: 1 when either norm is 0."""
    pn = jnp.linalg.norm(p)
    un = jnp.linalg.norm(u)
    return jnp.where(pn > 0, jnp.where(un > 0, pn / un, 1.0), 1.0)


def as_optax(cfg: OptimConfig):
    """The configured optimizer as an optax ``GradientTransformation``.

    sgd/adamw/lamb compose to the same math as :func:`sgd_update` (LAMB is
    test-pinned to ``optax.lamb``). LARS is the closest optax composition
    — see the inline note on the lr-vs-trace ordering difference.
    ``cfg.ema_decay`` is NOT represented: the parameter EMA is eval-side
    state the driver tracks, not part of the gradient transform."""
    import optax

    def schedule(count):
        return learning_rate(cfg, count)

    clip = ([optax.clip_by_global_norm(cfg.grad_clip_norm)]
            if cfg.grad_clip_norm is not None else [])
    if cfg.optimizer == "adamw":
        return optax.chain(*clip, optax.adamw(
            schedule, b1=cfg.adam_b1, b2=cfg.adam_b2, eps=cfg.adam_eps,
            weight_decay=cfg.weight_decay))
    if cfg.optimizer == "lamb":
        return optax.chain(*clip, optax.lamb(
            schedule, b1=cfg.adam_b1, b2=cfg.adam_b2, eps=cfg.adam_eps,
            weight_decay=cfg.weight_decay))
    if cfg.optimizer == "adafactor":
        # Closest optax composition, NOT bit-identical: optax's
        # scale_by_factored_rms only factors dims >= its
        # min_dim_size_to_factor and picks the two largest dims, where
        # sgd_update always factors the trailing two of any matrix.
        return optax.chain(*clip, optax.adafactor(
            schedule, multiply_by_parameter_scale=True,
            clipping_threshold=1.0, decay_rate=0.8,
            weight_decay_rate=cfg.weight_decay or None))
    if cfg.optimizer == "lars":
        # Closest optax composition, NOT bit-identical to sgd_update's
        # LARS: optax scales by lr before the momentum trace (ours
        # after), so momentum trajectories diverge under a non-constant
        # schedule. The adaptation mask (skip 1-D leaves) and eps ARE
        # forwarded to match.
        return optax.chain(*clip, optax.lars(
            schedule, weight_decay=cfg.weight_decay,
            trust_coefficient=cfg.lars_trust_coef, eps=cfg.lars_eps,
            trust_ratio_mask=lambda params: jax.tree.map(
                lambda p: p.ndim > 1, params),
            momentum=cfg.momentum or 0.9))
    tx = clip + ([optax.trace(decay=cfg.momentum)] if cfg.momentum else [])
    if cfg.weight_decay:
        tx.append(optax.add_decayed_weights(cfg.weight_decay))
    tx.append(optax.scale_by_learning_rate(schedule))
    return optax.chain(*tx)
