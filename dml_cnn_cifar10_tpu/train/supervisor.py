"""Run supervisor: a classified retry loop around ``Trainer.fit``.

The reference's whole recovery story is "the scheduler restarts the
worker and MonitoredTrainingSession restores the latest checkpoint"
(SURVEY §5) — which under synchronous SPMD means any single failure is
a whole-job failure (TF-Replicator, arXiv:1902.00465). The save half of
that contract already exists here (atomic checkpoints, preemption
guard, exact-resume data sidecars); this module is the recover half:
instead of dying on the first recoverable failure and waiting for an
external scheduler, the supervisor

1. classifies the exception (:func:`classify_failure`) — non-finite
   loss under ``on_nonfinite=rollback``, a data-pipeline failure, or a
   checkpoint-restore failure are recoverable; anything else re-raises
   unchanged (a genuine bug must stay loud);
2. restores the last *verifiable* checkpoint (``restore_checkpoint``
   walks past corrupt/truncated candidates via their integrity
   sidecars) and rewinds the exact-resume data state, both of which
   happen naturally inside the next ``fit`` attempt;
3. applies bounded exponential backoff
   (``recovery_backoff_s * 2^(attempt-1)``, capped at
   ``recovery_backoff_max_s``) and retries, up to ``recovery_retries``
   attempts — the budget exhausted degrades to halt (re-raise).

Rollback of a non-finite loss may also scale the learning rate down
(``rollback_lr_scale``): a deterministically diverging run replayed at
the same LR diverges again; shrinking the step size is the classic
operator move, now automated and logged as a ``rollback`` record.

Scope: per-process for the classes above — and, with the
cluster-resilience layer armed (``--cluster_dir``,
``parallel/cluster.py``), **cluster-aware**: a ``peer_lost`` failure
(heartbeats stale past ``--peer_dead_after_s``) is recoverable too.
The chief records a restart decision (survivor set, shrunken world
size, restore step), survivors poll and adopt it, each re-enters
through the same restore path — checkpoints are placement-free
(``tests/test_elastic.py``), so resuming at a smaller world size is
just another elastic restore — and a process the decision excludes
fences itself (:class:`EvictedError`) instead of split-braining the
run. World size decrements stop at ``--min_hosts``; below that the
failure re-raises.

With ``--elastic_expand`` the world also grows back: a ``peer_rejoin``
failure (a returning or brand-new host announced itself with a
``rejoin``-phase beat) is recoverable by a coordinated **expand**
restart through the same monotone-epoch decision file — the chief
grows the survivor set to the live hosts and picks the restore step;
the joiner, instead of fencing on :class:`EvictedError`, requests
rejoin and awaits inclusion; surviving non-chiefs observe the newer
epoch at the next seam check and adopt it. Everything is testable on
CPU in tier-1 via ``--fault_spec`` (utils/faults.py, including
``host_return@N``) and the lockstep simulation harness
(``tests/test_cluster.py``, ``tests/test_elastic_expand.py``).

Chaos hardening (ISSUE 10): the supervisor owns the recovery-phase
fault seams (``@decide`` after a chief commits a decision, ``@adopt``
after any seat adopts one, ``@restore`` armed for the next attempt's
checkpoint restore) so ``tools/chaos.py`` can strike *inside* a
recovery; a non-chief whose ``await_restart`` times out presumes the
chief died mid-decision and takes the decision pen itself when it is
the next live seat (re-deciding at a higher epoch); and
``--retry_budget_window`` resets the attempt budget after sustained
checkpoint progress, so long runs absorbing well-spaced faults never
degrade to halt.
"""

from __future__ import annotations

import time
from typing import Optional

from dml_cnn_cifar10_tpu.autopilot.engine import (AutopilotEngine,
                                                  RemediationRestartError)
from dml_cnn_cifar10_tpu.ckpt import checkpoint as ckpt_lib
from dml_cnn_cifar10_tpu.config import TrainConfig
from dml_cnn_cifar10_tpu.data.pipeline import DataPipelineError
from dml_cnn_cifar10_tpu.parallel import cluster as cluster_lib
from dml_cnn_cifar10_tpu.utils import alerts as alerts_lib
from dml_cnn_cifar10_tpu.utils import backoff
from dml_cnn_cifar10_tpu.utils import faults as faults_lib
from dml_cnn_cifar10_tpu.utils import flightrec as flightrec_lib
from dml_cnn_cifar10_tpu.utils.logging import MetricsLogger

#: Failure classes the supervisor may retry. "remediation" is not a
#: failure at all: an autopilot action changed the step geometry and
#: requested a restore+rebuild — it never charges the retry budget.
RECOVERABLE_FAULTS = ("nonfinite", "data", "ckpt_restore", "peer_lost",
                      "peer_rejoin", "remediation")


def classify_failure(exc: BaseException) -> Optional[str]:
    """Name the recoverable failure class of ``exc``, or None.

    - injected/real data-pipeline failures → ``"data"``
    - non-finite loss (``FloatingPointError``) → ``"nonfinite"`` (only
      actionable when ``on_nonfinite=rollback``; the caller checks)
    - checkpoint-restore failures (the classified ``ValueError`` every
      restore path raises) → ``"ckpt_restore"``
    - a peer declared lost by the collective watchdog → ``"peer_lost"``
      (recoverable by coordinated world-shrink, not by plain retry)
    - a returning host announced rejoin → ``"peer_rejoin"``
      (recoverable by coordinated world-expand — chief seat only)
    - an autopilot remediation restart request → ``"remediation"``
      (deliberate restore+rebuild after a config change; never charges
      the retry budget)
    """
    if isinstance(exc, RemediationRestartError):
        return "remediation"
    if isinstance(exc, cluster_lib.PeerRejoinError):
        return "peer_rejoin"
    if isinstance(exc, cluster_lib.PeerLostError):
        return "peer_lost"
    if isinstance(exc, (faults_lib.DataStallError, DataPipelineError)):
        return "data"
    if isinstance(exc, FloatingPointError):
        return "nonfinite"
    if isinstance(exc, ValueError) and "checkpoint" in str(exc) \
            and ("restore" in str(exc) or "restorable" in str(exc)):
        # Includes the all-candidates-failed-integrity walk ("no
        # restorable checkpoint ..."): retrying cannot resurrect a
        # fully corrupt archive, but classifying it buys bounded,
        # logged retries that degrade to a loud halt instead of an
        # unclassified crash (a chaos-campaign finding).
        return "ckpt_restore"
    return None


def _newest_restore_step(cfg: TrainConfig) -> int:
    steps = ckpt_lib.all_checkpoint_steps(cfg.log_dir)
    return max(steps) if steps else 0


def _fire_phase(injector, phase: str, cfg: TrainConfig, logger,
                monitor) -> None:
    """Fire phase-qualified fault injections (``kind@decide`` /
    ``kind@adopt``) at their supervisor seam — the hooks that let the
    chaos campaign strike *inside* a recovery."""
    if injector is not None:
        injector.phase_hook(phase, cfg.log_dir, logger=logger,
                            cluster=monitor)


def _adopt_decision(cfg: TrainConfig, monitor, decision, logger,
                    attempt: int, lost=(), injector=None):
    """Enter the decided world from any seat: adopt, resize the config,
    and log ``elastic_restart`` (shrink) or ``elastic_expand`` (grow)
    keyed on the decision's kind."""
    prev = set(monitor.live_set())
    monitor.adopt(decision)
    _fire_phase(injector, "adopt", cfg, logger, monitor)
    cfg.parallel.num_processes = decision.world_size
    expand = getattr(decision, "kind", "shrink") == "expand"
    fields = dict(step=decision.restore_step,
                  restore_step=decision.restore_step,
                  world_size=decision.world_size, epoch=decision.epoch,
                  attempt=attempt,
                  source=getattr(decision, "source", "disk"))
    if expand:
        joined = [p for p in decision.survivors if p not in prev]
        logger.log("elastic_expand", joined=joined, **fields)
        print(f"[supervisor] elastic expand epoch {decision.epoch}: "
              f"joined {joined}, world size {decision.world_size}, "
              f"restoring from step {decision.restore_step}")
    else:
        logger.log("elastic_restart", lost=list(lost), **fields)
        print(f"[supervisor] elastic restart epoch {decision.epoch}: "
              f"lost {list(lost)}, world size {decision.world_size}, "
              f"restoring from step {decision.restore_step}")
    return decision


def _coordinate_restart(cfg: TrainConfig, monitor, exc, logger,
                        attempt: int, injector=None):
    """The coordinated elastic-restart protocol, from this process's
    seat. A decision at a newer epoch that already includes us (we
    observed it mid-step, or the chief committed while we were
    unwinding) is adopted as-is — never race the chief's decision file
    with one of our own. Otherwise — chief: shrink the survivor set by
    the lost peers (halting below ``min_hosts``), pick the restore step
    (newest checkpoint on disk — the same one every survivor's
    ``init_or_restore`` walk will find), commit the decision.
    Non-chief: poll for it, fencing if excluded — and when the poll
    times out (the chief died between classifying and committing), the
    decision pen falls to the next live seat: the presumed-dead chief
    joins the lost set, and if that makes THIS process the lowest live
    survivor it re-decides at a higher epoch instead of dying on the
    timeout. All seats: adopt the new world and log the matching JSONL
    record."""
    lost = list(exc.process_ids)
    pending = monitor.coordinator.read()
    if pending is not None and pending.epoch > monitor.epoch \
            and monitor.process_id in pending.survivors:
        decision = pending
    elif monitor.is_chief:
        decision = monitor.decide_restart(lost,
                                          _newest_restore_step(cfg))
        _fire_phase(injector, "decide", cfg, logger, monitor)
    else:
        timeout = max(30.0, cfg.parallel.peer_dead_after_s * 6)
        try:
            decision = monitor.await_restart(timeout)
        except cluster_lib.PeerLostError:
            # Coordinator loss mid-decision: the chief classified the
            # failure but died before (or while) committing. Mark it
            # dead and let chiefship fall to the lowest live survivor
            # — if that is us, re-decide at a higher epoch; otherwise
            # re-raise so the failure stays deterministic (the new
            # chief's decision reaches us through the next attempt's
            # seam check).
            live = [p for p in monitor.live_set()
                    if p not in monitor.watchdog.dead_peers
                    and p not in lost]
            dead_chief = min(live) if live else None
            if dead_chief is None or dead_chief == monitor.process_id:
                raise
            monitor.watchdog.dead_peers.add(dead_chief)
            lost = sorted(set(lost) | {dead_chief})
            monitor.log("peer_lost", step=monitor._step,
                        process_id=dead_chief,
                        reason="coordinator_lost")
            print(f"[supervisor] chief {dead_chief} never committed a "
                  f"restart decision; presuming it lost")
            if not monitor.is_chief:
                raise
            decision = monitor.decide_restart(lost,
                                              _newest_restore_step(cfg))
            _fire_phase(injector, "decide", cfg, logger, monitor)
    return _adopt_decision(cfg, monitor, decision, logger, attempt,
                           lost=lost, injector=injector)


def _coordinate_expand(cfg: TrainConfig, monitor, exc, logger,
                       attempt: int, injector=None):
    """Chief half of the scale-UP protocol (only the chief raises
    ``PeerRejoinError``): grow the world by the announced joiners,
    restore from the newest checkpoint, commit, adopt."""
    decision = monitor.decide_expand(exc.process_ids,
                                     _newest_restore_step(cfg))
    _fire_phase(injector, "decide", cfg, logger, monitor)
    return _adopt_decision(cfg, monitor, decision, logger, attempt,
                           injector=injector)


def _request_rejoin(cfg: TrainConfig, monitor, logger, attempt: int,
                    injector=None):
    """Returning-host half: announce with ``rejoin``-phase beats, wait
    (bounded) for an expand decision that includes us, adopt it.
    Returns the decision, or None when the rejoin was refused/timed out
    — the caller fences cleanly, exactly as without
    ``--elastic_expand``."""
    monitor.request_rejoin()
    logger.log("host_rejoin", step=monitor._step,
               process_id=monitor.process_id, epoch=monitor.epoch)
    print(f"[supervisor] process {monitor.process_id} announcing rejoin "
          f"(epoch {monitor.epoch}); awaiting an expand decision")
    timeout = max(60.0, cfg.parallel.peer_dead_after_s * 24)
    try:
        decision = monitor.await_inclusion(timeout)
    except cluster_lib.PeerLostError as e:
        print(f"[supervisor] rejoin not granted: {e}")
        return None
    return _adopt_decision(cfg, monitor, decision, logger, attempt,
                           injector=injector)


def fit_supervised(cfg: TrainConfig, total_steps: Optional[int] = None,
                   task_index: int = 0, logger=None, alert_engine=None,
                   flight_recorder=None, mesh=None, publish_hook=None,
                   autopilot=None):
    """``Trainer.fit`` under the recovery supervisor; returns the final
    :class:`TrainResult`. Unrecoverable failures — and recoverable ones
    past the ``recovery_retries`` budget — re-raise unchanged. A
    process evicted by a restart decision returns ``None`` after a
    clean notice: it was fenced, not failed.

    The unified runtime (``runtime/core.py``) supervises THROUGH this
    entry by injecting its own substrate — ``logger``, ``alert_engine``,
    ``flight_recorder``, ``mesh``, ``publish_hook`` — so the supervisor
    supervises a job on the runtime's shared stream/mesh rather than
    one standalone trainer. Injected resources are owned by the caller
    (never closed here); a bare call builds and owns its own, exactly
    as before."""
    from dml_cnn_cifar10_tpu.train.loop import Trainer

    # ONE injector across every attempt: fired faults stay fired, so a
    # recovered run replaying the same steps does not re-injure itself.
    # Same ownership rule for the cluster monitor: epoch/world state
    # (and the background beat publisher) must span restarts.
    injector = faults_lib.FaultInjector.from_spec(cfg.fault_spec)
    owns_logger = logger is None
    if owns_logger:
        logger = MetricsLogger(cfg.metrics_jsonl, task_index=task_index)
    monitor = cluster_lib.ClusterMonitor.from_config(cfg.parallel,
                                                     logger=logger)
    # ONE flight recorder across attempts (ring + per-rule capture
    # sequence survive restarts), attached BEFORE the alert engine's
    # observer so the record that trips a rule is ringed before the
    # nested `alert` emission snapshots the ring.
    flightrec = flight_recorder if flight_recorder is not None \
        else flightrec_lib.FlightRecorder.from_config(cfg, logger=logger)
    if flightrec is not None:
        flightrec.logger = logger
        logger.add_observer(flightrec.observer())
    # ONE alert engine too: the fault/recovery records the supervisor
    # logs here must feed the same rule state as the Trainer's stream,
    # and an alert that fired in attempt N must be able to RESOLVE in
    # attempt N+1 (the nonfinite-burst alert resolves only after the
    # recovered run progresses a clean window past the fault).
    if alert_engine is None:
        alert_engine = alerts_lib.AlertEngine.from_config(cfg)
    if alert_engine is not None:
        logger.add_observer(alert_engine.observer(logger))
    # ONE autopilot engine across attempts too (cooldown marks, the
    # remediation budget, and pending-restart state span restarts).
    # The runtime injects its own (with serve/fleet hooks bound); a
    # bare supervised run builds one from --autopilot. attach() is
    # idempotent, so an injected pre-attached engine is fine.
    if autopilot is None:
        autopilot = AutopilotEngine.from_config(cfg, logger=logger,
                                                flightrec=flightrec)
    if autopilot is not None and alert_engine is not None:
        autopilot.attach(alert_engine)
    attempt = 0
    # Progress-based retry-budget reset (--retry_budget_window): the
    # newest checkpoint step at the time the budget was last charged.
    # A long run absorbing many well-spaced faults must not degrade to
    # halt just because its LIFETIME fault count crossed a budget sized
    # for fault bursts.
    budget_anchor = 0
    try:
        while True:
            trainer = Trainer(cfg, mesh=mesh, task_index=task_index,
                              fault_injector=injector, cluster=monitor,
                              alert_engine=alert_engine,
                              flight_recorder=flightrec, logger=logger,
                              publish_hook=publish_hook,
                              autopilot=autopilot)
            try:
                result = trainer.fit(total_steps)
            except cluster_lib.EvictedError as e:
                # The surviving world already restarted without this
                # process (a stalled heartbeat looks dead from outside).
                # Without --elastic_expand: exit cleanly and saveless —
                # rejoining would split-brain the run (the monitor
                # logged `peer_lost` reason "evicted" at detection).
                # WITH it, the fence is an invitation: announce rejoin
                # and wait for the chief's expand decision; only a
                # refused/timed-out rejoin still fences.
                if monitor is not None and cfg.parallel.elastic_expand \
                        and attempt < cfg.recovery_retries:
                    attempt += 1
                    if injector is not None:
                        injector.recovering = True
                    decision = _request_rejoin(cfg, monitor, logger,
                                               attempt,
                                               injector=injector)
                    if decision is not None:
                        continue
                print(f"[supervisor] fenced: {e}")
                return None
            except Exception as e:
                fault = classify_failure(e)
                if fault is None:
                    raise
                if fault == "nonfinite" and cfg.on_nonfinite != "rollback":
                    # halt stays a halt; an exhausted skip budget
                    # already degraded to halt inside the loop.
                    raise
                if fault in ("peer_lost", "peer_rejoin") \
                        and monitor is None:
                    raise
                # Progress-based budget reset: enough sustained
                # progress (checkpoint steps) since the last charge
                # refills the whole budget — spaced faults on a long
                # run stay recoverable; a fault BURST still exhausts
                # the budget and degrades to halt as before. Off by
                # default (window 0 = the historical lifetime budget).
                progress = _newest_restore_step(cfg)
                if cfg.retry_budget_window > 0 and attempt > 0 \
                        and progress - budget_anchor \
                        >= cfg.retry_budget_window:
                    logger.log("recovery", step=progress, fault=fault,
                               action="budget_reset", attempt=attempt)
                    print(f"[supervisor] {progress - budget_anchor} "
                          f"steps of progress since the last retry "
                          f"(>= retry_budget_window="
                          f"{cfg.retry_budget_window}): retry budget "
                          f"reset")
                    attempt = 0
                if fault == "remediation":
                    # Deliberate autopilot restore+rebuild, not a
                    # failure: no retry-budget charge, no backoff, no
                    # recovery-phase injection arming — restore the
                    # newest checkpoint and re-enter with the mutated
                    # config (the compile cache absorbs the rebuild).
                    restore_step = _newest_restore_step(cfg)
                    logger.log("recovery", step=restore_step,
                               fault=fault, action="restart",
                               attempt=attempt, backoff_s=0.0)
                    print(f"[supervisor] remediation restart: {e}; "
                          f"restoring from step {restore_step}")
                    continue
                if attempt >= cfg.recovery_retries:
                    raise
                attempt += 1
                budget_anchor = progress
                if injector is not None:
                    # Arm the recovery-phase injections (@restore fires
                    # at the next attempt's checkpoint-restore seam).
                    injector.recovering = True
                if fault == "peer_rejoin":
                    # Chief seat of the scale-UP: grow the world by the
                    # announced joiners and re-enter restore at the
                    # larger size.
                    decision = _coordinate_expand(cfg, monitor, e,
                                                  logger, attempt,
                                                  injector=injector)
                    restore_step = decision.restore_step
                elif fault == "peer_lost":
                    # May re-raise PeerLostError (below min_hosts —
                    # unrecoverable) or fence this process (the
                    # decision excluded it while it was awaiting).
                    try:
                        decision = _coordinate_restart(cfg, monitor, e,
                                                       logger, attempt,
                                                       injector=injector)
                    except cluster_lib.EvictedError as ev:
                        # Excluded while awaiting the decision: same
                        # fence-or-rejoin choice as the in-loop fence.
                        if cfg.parallel.elastic_expand:
                            decision = _request_rejoin(cfg, monitor,
                                                       logger, attempt,
                                                       injector=injector)
                            if decision is not None:
                                continue
                        print(f"[supervisor] fenced: {ev}")
                        return None
                    restore_step = decision.restore_step
                else:
                    steps = ckpt_lib.all_checkpoint_steps(cfg.log_dir)
                    restore_step = max(steps) if steps else 0
                backoff_s = backoff.delay_s(cfg.recovery_backoff_s,
                                            cfg.recovery_backoff_max_s,
                                            attempt)
                logger.log("fault", step=restore_step, fault=fault,
                           injected=False, error=str(e)[:300])
                if fault == "nonfinite" and cfg.rollback_lr_scale != 1.0 \
                        and not (autopilot is not None and autopilot
                                 .handles("nonfinite_burst", "rollback")):
                    # When an autopilot rollback policy owns
                    # nonfinite_burst, the LR scale is applied by its
                    # action (inside the `fault` emission above, at
                    # alert-firing pace) — scaling here too would
                    # double-apply it.
                    cfg.optim.learning_rate *= cfg.rollback_lr_scale
                if fault == "nonfinite":
                    logger.log("rollback", step=restore_step,
                               restore_step=restore_step,
                               attempt=attempt,
                               lr=cfg.optim.learning_rate)
                logger.log("recovery", step=restore_step, fault=fault,
                           action="restart", attempt=attempt,
                           backoff_s=backoff_s)
                print(f"[supervisor] recoverable {fault} failure "
                      f"(attempt {attempt}/{cfg.recovery_retries}): "
                      f"{e}; restoring from step {restore_step} after "
                      f"{backoff_s:.2f}s backoff")
                time.sleep(backoff_s)
                continue
            if attempt:
                logger.log("recovery", step=result.final_step,
                           fault="none", action="recovered",
                           attempt=attempt)
                print(f"[supervisor] recovered: reached step "
                      f"{result.final_step} after {attempt} "
                      f"restart(s)")
            return result
    finally:
        if monitor is not None:
            monitor.close()
        if owns_logger:
            logger.close()
