"""Run supervisor: a classified retry loop around ``Trainer.fit``.

The reference's whole recovery story is "the scheduler restarts the
worker and MonitoredTrainingSession restores the latest checkpoint"
(SURVEY §5) — which under synchronous SPMD means any single failure is
a whole-job failure (TF-Replicator, arXiv:1902.00465). The save half of
that contract already exists here (atomic checkpoints, preemption
guard, exact-resume data sidecars); this module is the recover half:
instead of dying on the first recoverable failure and waiting for an
external scheduler, the supervisor

1. classifies the exception (:func:`classify_failure`) — non-finite
   loss under ``on_nonfinite=rollback``, a data-pipeline failure, or a
   checkpoint-restore failure are recoverable; anything else re-raises
   unchanged (a genuine bug must stay loud);
2. restores the last *verifiable* checkpoint (``restore_checkpoint``
   walks past corrupt/truncated candidates via their integrity
   sidecars) and rewinds the exact-resume data state, both of which
   happen naturally inside the next ``fit`` attempt;
3. applies bounded exponential backoff
   (``recovery_backoff_s * 2^(attempt-1)``, capped at
   ``recovery_backoff_max_s``) and retries, up to ``recovery_retries``
   attempts — the budget exhausted degrades to halt (re-raise).

Rollback of a non-finite loss may also scale the learning rate down
(``rollback_lr_scale``): a deterministically diverging run replayed at
the same LR diverges again; shrinking the step size is the classic
operator move, now automated and logged as a ``rollback`` record.

Scope: per-process for the classes above — and, with the
cluster-resilience layer armed (``--cluster_dir``,
``parallel/cluster.py``), **cluster-aware**: a ``peer_lost`` failure
(heartbeats stale past ``--peer_dead_after_s``) is recoverable too.
The chief records a restart decision (survivor set, shrunken world
size, restore step), survivors poll and adopt it, each re-enters
through the same restore path — checkpoints are placement-free
(``tests/test_elastic.py``), so resuming at a smaller world size is
just another elastic restore — and a process the decision excludes
fences itself (:class:`EvictedError`) instead of split-braining the
run. World size decrements stop at ``--min_hosts``; below that the
failure re-raises. Everything is testable on CPU in tier-1 via
``--fault_spec`` (utils/faults.py) and the lockstep simulation
harness (``tests/test_cluster.py``).
"""

from __future__ import annotations

import time
from typing import Optional

from dml_cnn_cifar10_tpu.ckpt import checkpoint as ckpt_lib
from dml_cnn_cifar10_tpu.config import TrainConfig
from dml_cnn_cifar10_tpu.data.pipeline import DataPipelineError
from dml_cnn_cifar10_tpu.parallel import cluster as cluster_lib
from dml_cnn_cifar10_tpu.utils import backoff
from dml_cnn_cifar10_tpu.utils import faults as faults_lib
from dml_cnn_cifar10_tpu.utils.logging import MetricsLogger

#: Failure classes the supervisor may retry.
RECOVERABLE_FAULTS = ("nonfinite", "data", "ckpt_restore", "peer_lost")


def classify_failure(exc: BaseException) -> Optional[str]:
    """Name the recoverable failure class of ``exc``, or None.

    - injected/real data-pipeline failures → ``"data"``
    - non-finite loss (``FloatingPointError``) → ``"nonfinite"`` (only
      actionable when ``on_nonfinite=rollback``; the caller checks)
    - checkpoint-restore failures (the classified ``ValueError`` every
      restore path raises) → ``"ckpt_restore"``
    - a peer declared lost by the collective watchdog → ``"peer_lost"``
      (recoverable by coordinated world-shrink, not by plain retry)
    """
    if isinstance(exc, cluster_lib.PeerLostError):
        return "peer_lost"
    if isinstance(exc, (faults_lib.DataStallError, DataPipelineError)):
        return "data"
    if isinstance(exc, FloatingPointError):
        return "nonfinite"
    if isinstance(exc, ValueError) and "restore" in str(exc) \
            and "checkpoint" in str(exc):
        return "ckpt_restore"
    return None


def _coordinate_restart(cfg: TrainConfig, monitor, exc, logger,
                        attempt: int):
    """The coordinated elastic-restart protocol, from this process's
    seat. Chief: shrink the survivor set by the lost peers (halting
    below ``min_hosts``), pick the restore step (newest checkpoint on
    disk — the same one every survivor's ``init_or_restore`` walk will
    find), commit the decision. Non-chief: poll for it, fencing if
    excluded. Both: adopt the new world and log ``elastic_restart``."""
    if monitor.is_chief:
        steps = ckpt_lib.all_checkpoint_steps(cfg.log_dir)
        restore_step = max(steps) if steps else 0
        decision = monitor.decide_restart(exc.process_ids, restore_step)
    else:
        timeout = max(30.0, cfg.parallel.peer_dead_after_s * 6)
        decision = monitor.await_restart(timeout)
    monitor.adopt(decision)
    cfg.parallel.num_processes = decision.world_size
    logger.log("elastic_restart", step=decision.restore_step,
               restore_step=decision.restore_step,
               world_size=decision.world_size, epoch=decision.epoch,
               attempt=attempt, lost=list(exc.process_ids))
    print(f"[supervisor] elastic restart epoch {decision.epoch}: "
          f"lost {list(exc.process_ids)}, world size "
          f"{decision.world_size}, restoring from step "
          f"{decision.restore_step}")
    return decision


def fit_supervised(cfg: TrainConfig, total_steps: Optional[int] = None,
                   task_index: int = 0):
    """``Trainer.fit`` under the recovery supervisor; returns the final
    :class:`TrainResult`. Unrecoverable failures — and recoverable ones
    past the ``recovery_retries`` budget — re-raise unchanged. A
    process evicted by a restart decision returns ``None`` after a
    clean notice: it was fenced, not failed."""
    from dml_cnn_cifar10_tpu.train.loop import Trainer

    # ONE injector across every attempt: fired faults stay fired, so a
    # recovered run replaying the same steps does not re-injure itself.
    # Same ownership rule for the cluster monitor: epoch/world state
    # (and the background beat publisher) must span restarts.
    injector = faults_lib.FaultInjector.from_spec(cfg.fault_spec)
    logger = MetricsLogger(cfg.metrics_jsonl, task_index=task_index)
    monitor = cluster_lib.ClusterMonitor.from_config(cfg.parallel,
                                                     logger=logger)
    attempt = 0
    try:
        while True:
            trainer = Trainer(cfg, task_index=task_index,
                              fault_injector=injector, cluster=monitor)
            try:
                result = trainer.fit(total_steps)
            except cluster_lib.EvictedError as e:
                # The surviving world already restarted without this
                # process (a stalled heartbeat looks dead from outside).
                # Exit cleanly and saveless — rejoining would
                # split-brain the run. The monitor logged `peer_lost`
                # (reason "evicted") at detection.
                print(f"[supervisor] fenced: {e}")
                return None
            except Exception as e:
                fault = classify_failure(e)
                if fault is None or attempt >= cfg.recovery_retries:
                    raise
                if fault == "nonfinite" and cfg.on_nonfinite != "rollback":
                    # halt stays a halt; an exhausted skip budget
                    # already degraded to halt inside the loop.
                    raise
                if fault == "peer_lost" and monitor is None:
                    raise
                attempt += 1
                if fault == "peer_lost":
                    # May re-raise PeerLostError (below min_hosts —
                    # unrecoverable) or fence this process (the
                    # decision excluded it while it was awaiting).
                    try:
                        decision = _coordinate_restart(cfg, monitor, e,
                                                       logger, attempt)
                    except cluster_lib.EvictedError as ev:
                        print(f"[supervisor] fenced: {ev}")
                        return None
                    restore_step = decision.restore_step
                else:
                    steps = ckpt_lib.all_checkpoint_steps(cfg.log_dir)
                    restore_step = max(steps) if steps else 0
                backoff_s = backoff.delay_s(cfg.recovery_backoff_s,
                                            cfg.recovery_backoff_max_s,
                                            attempt)
                logger.log("fault", step=restore_step, fault=fault,
                           injected=False, error=str(e)[:300])
                if fault == "nonfinite" and cfg.rollback_lr_scale != 1.0:
                    cfg.optim.learning_rate *= cfg.rollback_lr_scale
                if fault == "nonfinite":
                    logger.log("rollback", step=restore_step,
                               restore_step=restore_step,
                               attempt=attempt,
                               lr=cfg.optim.learning_rate)
                logger.log("recovery", step=restore_step, fault=fault,
                           action="restart", attempt=attempt,
                           backoff_s=backoff_s)
                print(f"[supervisor] recoverable {fault} failure "
                      f"(attempt {attempt}/{cfg.recovery_retries}): "
                      f"{e}; restoring from step {restore_step} after "
                      f"{backoff_s:.2f}s backoff")
                time.sleep(backoff_s)
                continue
            if attempt:
                logger.log("recovery", step=result.final_step,
                           fault="none", action="recovered",
                           attempt=attempt)
                print(f"[supervisor] recovered: reached step "
                      f"{result.final_step} after {attempt} "
                      f"restart(s)")
            return result
    finally:
        if monitor is not None:
            monitor.close()
        logger.close()
