"""Run supervisor: a classified retry loop around ``Trainer.fit``.

The reference's whole recovery story is "the scheduler restarts the
worker and MonitoredTrainingSession restores the latest checkpoint"
(SURVEY §5) — which under synchronous SPMD means any single failure is
a whole-job failure (TF-Replicator, arXiv:1902.00465). The save half of
that contract already exists here (atomic checkpoints, preemption
guard, exact-resume data sidecars); this module is the recover half:
instead of dying on the first recoverable failure and waiting for an
external scheduler, the supervisor

1. classifies the exception (:func:`classify_failure`) — non-finite
   loss under ``on_nonfinite=rollback``, a data-pipeline failure, or a
   checkpoint-restore failure are recoverable; anything else re-raises
   unchanged (a genuine bug must stay loud);
2. restores the last *verifiable* checkpoint (``restore_checkpoint``
   walks past corrupt/truncated candidates via their integrity
   sidecars) and rewinds the exact-resume data state, both of which
   happen naturally inside the next ``fit`` attempt;
3. applies bounded exponential backoff
   (``recovery_backoff_s * 2^(attempt-1)``, capped at
   ``recovery_backoff_max_s``) and retries, up to ``recovery_retries``
   attempts — the budget exhausted degrades to halt (re-raise).

Rollback of a non-finite loss may also scale the learning rate down
(``rollback_lr_scale``): a deterministically diverging run replayed at
the same LR diverges again; shrinking the step size is the classic
operator move, now automated and logged as a ``rollback`` record.

Scope: per-process. Under multi-host SPMD a peer that died takes the
collectives with it — whole-job restart remains the scheduler's job;
this supervisor makes the single-process (and the restarted-job) path
self-healing and, via ``--fault_spec`` (utils/faults.py), testable on
CPU in tier-1.
"""

from __future__ import annotations

import time
from typing import Optional

from dml_cnn_cifar10_tpu.ckpt import checkpoint as ckpt_lib
from dml_cnn_cifar10_tpu.config import TrainConfig
from dml_cnn_cifar10_tpu.data.pipeline import DataPipelineError
from dml_cnn_cifar10_tpu.utils import faults as faults_lib
from dml_cnn_cifar10_tpu.utils.logging import MetricsLogger

#: Failure classes the supervisor may retry.
RECOVERABLE_FAULTS = ("nonfinite", "data", "ckpt_restore")


def classify_failure(exc: BaseException) -> Optional[str]:
    """Name the recoverable failure class of ``exc``, or None.

    - injected/real data-pipeline failures → ``"data"``
    - non-finite loss (``FloatingPointError``) → ``"nonfinite"`` (only
      actionable when ``on_nonfinite=rollback``; the caller checks)
    - checkpoint-restore failures (the classified ``ValueError`` every
      restore path raises) → ``"ckpt_restore"``
    """
    if isinstance(exc, (faults_lib.DataStallError, DataPipelineError)):
        return "data"
    if isinstance(exc, FloatingPointError):
        return "nonfinite"
    if isinstance(exc, ValueError) and "restore" in str(exc) \
            and "checkpoint" in str(exc):
        return "ckpt_restore"
    return None


def fit_supervised(cfg: TrainConfig, total_steps: Optional[int] = None,
                   task_index: int = 0):
    """``Trainer.fit`` under the recovery supervisor; returns the final
    :class:`TrainResult`. Unrecoverable failures — and recoverable ones
    past the ``recovery_retries`` budget — re-raise unchanged."""
    from dml_cnn_cifar10_tpu.train.loop import Trainer

    # ONE injector across every attempt: fired faults stay fired, so a
    # recovered run replaying the same steps does not re-injure itself.
    injector = faults_lib.FaultInjector.from_spec(cfg.fault_spec)
    logger = MetricsLogger(cfg.metrics_jsonl, task_index=task_index)
    attempt = 0
    try:
        while True:
            trainer = Trainer(cfg, task_index=task_index,
                              fault_injector=injector)
            try:
                result = trainer.fit(total_steps)
            except Exception as e:
                fault = classify_failure(e)
                if fault is None or attempt >= cfg.recovery_retries:
                    raise
                if fault == "nonfinite" and cfg.on_nonfinite != "rollback":
                    # halt stays a halt; an exhausted skip budget
                    # already degraded to halt inside the loop.
                    raise
                attempt += 1
                steps = ckpt_lib.all_checkpoint_steps(cfg.log_dir)
                restore_step = max(steps) if steps else 0
                backoff = min(
                    cfg.recovery_backoff_s * (2 ** (attempt - 1)),
                    cfg.recovery_backoff_max_s)
                logger.log("fault", step=restore_step, fault=fault,
                           injected=False, error=str(e)[:300])
                if fault == "nonfinite" and cfg.rollback_lr_scale != 1.0:
                    cfg.optim.learning_rate *= cfg.rollback_lr_scale
                if fault == "nonfinite":
                    logger.log("rollback", step=restore_step,
                               restore_step=restore_step,
                               attempt=attempt,
                               lr=cfg.optim.learning_rate)
                logger.log("recovery", step=restore_step, fault=fault,
                           action="restart", attempt=attempt,
                           backoff_s=backoff)
                print(f"[supervisor] recoverable {fault} failure "
                      f"(attempt {attempt}/{cfg.recovery_retries}): "
                      f"{e}; restoring from step {restore_step} after "
                      f"{backoff:.2f}s backoff")
                time.sleep(backoff)
                continue
            if attempt:
                logger.log("recovery", step=result.final_step,
                           fault="none", action="recovered",
                           attempt=attempt)
                print(f"[supervisor] recovered: reached step "
                      f"{result.final_step} after {attempt} "
                      f"restart(s)")
            return result
    finally:
        logger.close()
