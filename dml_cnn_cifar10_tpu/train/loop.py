"""The training driver.

Replaces the reference's worker branch (``cifar10cnn.py:193-242``): graph
construction becomes building the jitted SPMD step; MonitoredTrainingSession
becomes explicit restore-if-present + periodic checkpointing +
stop-at-step; the queue runners become the prefetching pipeline. Console
cadence is parity: the training line every ``output_every`` (200) local
steps, an eval line every ``eval_every`` (500) (``cifar10cnn.py:232-241``).

Faithful-mode details mirrored deliberately:
- Train accuracy at the 200-step mark is computed on a *fresh* train batch
  (the reference reruns ``accuracy_train``, pulling a new batch from the
  queue — ``cifar10cnn.py:235``), not the batch just trained on.
- Eval is one *shuffled* test batch (``cifar10cnn.py:202,238``);
  ``eval_full_test_set=True`` sweeps the whole split instead.
- The stop condition is the *global* step, like ``StopAtStepHook``
  (``cifar10cnn.py:219``), so restore + finish works.
"""

from __future__ import annotations

import dataclasses
import sys
import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from dml_cnn_cifar10_tpu import ckpt as ckpt_lib
from dml_cnn_cifar10_tpu import compilecache
from dml_cnn_cifar10_tpu.ckpt import peerstore as peerstore_lib
from dml_cnn_cifar10_tpu.config import TrainConfig
from dml_cnn_cifar10_tpu.data import pipeline as pipe
from dml_cnn_cifar10_tpu.models.registry import get_model
from dml_cnn_cifar10_tpu.parallel import cluster as cluster_lib
from dml_cnn_cifar10_tpu.parallel import mesh as mesh_lib
from dml_cnn_cifar10_tpu.parallel import multihost
from dml_cnn_cifar10_tpu.parallel import shardings as shardings_lib
from dml_cnn_cifar10_tpu.parallel import step as step_lib
from dml_cnn_cifar10_tpu.utils import alerts as alerts_lib
from dml_cnn_cifar10_tpu.utils import devprof as devprof_lib
from dml_cnn_cifar10_tpu.utils import faults as faults_lib
from dml_cnn_cifar10_tpu.utils import metrics_registry
from dml_cnn_cifar10_tpu.utils import telemetry as telemetry_lib
from dml_cnn_cifar10_tpu.utils.logging import MetricsLogger
from dml_cnn_cifar10_tpu.utils.preemption import PreemptionGuard
from dml_cnn_cifar10_tpu.utils.profiling import (DrainMeter, abstractify,
                                                 compiled_flops,
                                                 correct_stack_flops,
                                                 profile_trace)


@dataclasses.dataclass
class TrainResult:
    final_step: int
    train_loss: list
    test_accuracy: list
    images_per_sec: float
    state: step_lib.TrainState
    preempted: bool = False


class Trainer:
    def __init__(self, cfg: TrainConfig, mesh=None, task_index: int = 0,
                 fault_injector=None, cluster=None, alert_engine=None,
                 flight_recorder=None, logger=None, publish_hook=None,
                 autopilot=None):
        self.cfg = cfg
        self.task_index = task_index
        # Alert-driven remediation (autopilot/engine.py): injected by
        # the supervisor/runtime only — a restart request needs a
        # supervisor above this Trainer to catch it, so a bare Trainer
        # never builds its own engine.
        self.autopilot = autopilot
        if cfg.on_nonfinite not in ("halt", "skip", "rollback"):
            raise ValueError(
                f"on_nonfinite={cfg.on_nonfinite!r} must be one of "
                f"halt | skip | rollback")
        # Deterministic fault injection (utils/faults.py). The supervisor
        # passes ONE injector across restart attempts so fired events
        # stay fired; a bare Trainer builds its own from the config.
        self.faults = fault_injector if fault_injector is not None \
            else faults_lib.FaultInjector.from_spec(cfg.fault_spec)
        self.mesh = mesh if mesh is not None else mesh_lib.build_mesh(
            cfg.parallel)
        self.model_def = get_model(cfg.model.name)
        # Logger before the step builders: the compile cache logs a
        # `compile` JSONL event at every seam, including the ones armed
        # below. The runtime (runtime/core.py) injects ITS logger so a
        # whole process shares one stream; an injected logger is never
        # closed here — its owner closes it.
        self.logger = logger if logger is not None else MetricsLogger(
            cfg.metrics_jsonl, task_index=task_index,
            tensorboard_dir=(cfg.tensorboard_dir
                             if jax.process_index() == 0 else None))
        # In-process publish hook (runtime/core.py): called as
        # ``hook(step, path, params, model_state)`` after a checkpoint
        # COMMITS, with an independent device-side copy of the weights a
        # server would restore from that checkpoint (EMA when armed).
        # Copies, never references: step buffers are donated, so handing
        # out the live pytree would dangle at the next dispatch. The
        # copy is device-to-device — zero jax.device_get, the
        # fetch-parity invariant holds.
        self._publish_hook = publish_hook
        # Live operational observability (docs/OBSERVABILITY.md): the
        # streaming alert engine watches every record this logger
        # writes (built-in SLO rules + --alert_rules), and --stats_port
        # serves GET /metrics from the process registry the same
        # records feed. The supervisor passes ONE engine across restart
        # attempts — alert state (an un-resolved nonfinite burst) must
        # survive the Trainer that detected it; a bare Trainer builds
        # its own. Both are pure host work: the fetch-parity test pins
        # zero extra device fetches.
        # Flight recorder BEFORE the alert observer (attach order is
        # run order): the record that trips a rule must reach the ring
        # before the engine's nested `alert` emission triggers the
        # capture. Like the alert engine, the supervisor passes ONE
        # recorder across restart attempts; a bare Trainer builds its
        # own (armed only by --postmortem_dir).
        from dml_cnn_cifar10_tpu.utils.flightrec import FlightRecorder
        self.flightrec = flight_recorder if flight_recorder is not None \
            else FlightRecorder.from_config(cfg, logger=self.logger)
        if self.flightrec is not None:
            self.flightrec.logger = self.logger
            self.logger.add_observer(self.flightrec.observer())
        self.alerts = alert_engine if alert_engine is not None \
            else alerts_lib.AlertEngine.from_config(cfg)
        if self.alerts is not None:
            self.logger.add_observer(self.alerts.observer(self.logger))
        metrics_registry.ensure_stats_server(cfg.stats_port)
        # Persistent compilation cache (compilecache/): every compile
        # seam this Trainer builds — train step/chunk, init, eval —
        # routes through it when --compile_cache_dir is set, so a
        # supervisor restart or elastic re-entry deserializes the
        # executables its predecessor compiled instead of recompiling.
        # The on_event hook feeds obtain-time into the goodput `compile`
        # fraction (the tracer exists only while fit() runs).
        self._tracer = None
        self.compile_cache = compilecache.CompileCache.from_config(
            cfg, logger=self.logger, on_event=self._note_compile_event)
        # One sharding tree, computed once, used everywhere state is placed
        # (init, restore, train/eval in_shardings). The explicit-collectives
        # path is dp-only and expects replicated state.
        if cfg.parallel.explicit_collectives and cfg.parallel.fsdp:
            raise ValueError(
                "fsdp needs the GSPMD (default) step: the "
                "explicit_collectives shard_map path expects replicated "
                "state")
        zero1 = cfg.optim.optimizer_sharding == "zero1"
        if zero1 and cfg.parallel.fsdp:
            raise ValueError(
                "optimizer_sharding=zero1 does not compose with --fsdp: "
                "ZeRO-3 already shards the optimizer moments (and the "
                "params) over the data axis")
        # Partition-rule override (--partition_rules): parsed once, used
        # by every sharding-tree/step build below so the layouts agree.
        self.partition_rules = shardings_lib.parse_partition_rules(
            cfg.parallel.partition_rules)
        self.state_sharding = None if cfg.parallel.explicit_collectives \
            else step_lib.train_state_shardings(
                self.mesh, self.model_def, cfg.model, cfg.data, cfg.optim,
                fsdp=cfg.parallel.fsdp, zero1=zero1,
                rules=self.partition_rules,
                strict=cfg.parallel.partition_rules_strict)
        if cfg.parallel.partition_report and jax.process_index() == 0:
            # The which-rule-matched-which-param report, over the same
            # abstract params the sharding tree was computed from.
            abstract = jax.eval_shape(
                lambda k: step_lib.init_train_state(
                    k, self.model_def, cfg.model, cfg.data, cfg.optim),
                jax.random.key(0))
            table = self.partition_rules if self.partition_rules \
                is not None else shardings_lib.rule_for(
                    cfg.model.name,
                    pipe=self.mesh.shape.get("pipe", 1) > 1)
            print("[shardings] partition report (params):")
            print(shardings_lib.format_partition_report(
                shardings_lib.explain_partition_rules(table,
                                                      abstract.params)))
        self.train_step = step_lib.make_train_step(
            self.model_def, cfg.model, cfg.optim, self.mesh,
            explicit_collectives=cfg.parallel.explicit_collectives,
            state_sharding=self.state_sharding,
            health_metrics=cfg.health_metrics,
            compile_cache=self.compile_cache,
            rules=self.partition_rules)
        self.steps_per_dispatch = max(1, cfg.steps_per_dispatch)
        if self.steps_per_dispatch > 1:
            k = self.steps_per_dispatch
            # total_steps is validated in fit() against the actual resume
            # point (fit can override it).
            for name in ("output_every", "eval_every", "checkpoint_every"):
                if getattr(cfg, name) % k:
                    raise ValueError(
                        f"{name}={getattr(cfg, name)} must be a multiple "
                        f"of steps_per_dispatch={k} so every observable "
                        f"boundary lands on a dispatch edge")
            if cfg.parallel.explicit_collectives:
                raise ValueError(
                    "steps_per_dispatch > 1 needs the GSPMD (default) "
                    "step, not explicit_collectives")
            self.train_chunk = step_lib.make_train_chunk(
                self.model_def, cfg.model, cfg.optim, self.mesh,
                state_sharding=self.state_sharding, data_cfg=cfg.data,
                health_metrics=cfg.health_metrics,
                compile_cache=self.compile_cache,
                rules=self.partition_rules)
        self.eval_step = step_lib.make_eval_step(
            self.model_def, cfg.model, self.mesh,
            state_sharding=self.state_sharding,
            compile_cache=self.compile_cache)
        # Cluster-resilience monitor (parallel/cluster.py): heartbeats,
        # collective watchdog, eviction checks at the dispatch seam.
        # The supervisor passes ONE monitor across restart attempts
        # (epoch/world state must survive them); a bare Trainer builds
        # its own from the config and owns its lifecycle.
        self._owns_cluster = cluster is None \
            and cfg.parallel.cluster_dir is not None
        self.cluster = cluster if cluster is not None \
            else cluster_lib.ClusterMonitor.from_config(
                cfg.parallel, logger=self.logger)
        # Resident-eval fns; built per-fit when the resident path is active.
        self._resident_full_eval = None
        self._resident_test_eval = None
        self._resident_acc_eval = None
        self._idx1_sharding = None
        self._resident_idx = None

    def _note_compile_event(self, ev: dict) -> None:
        """Compile-cache event hook: attribute obtain time (trace +
        load-or-compile) to the goodput `compile` fraction. Only while a
        fit()'s tracer is live — pre-loop compiles (init before the
        tracer epoch) are logged as JSONL events but not attributed."""
        tracer = self._tracer
        if tracer is not None and tracer.enabled:
            tracer.add_secs("compile", ev.get("compile_s") or 0.0)

    def init_or_restore(self) -> step_lib.TrainState:
        key = jax.random.key(self.cfg.seed)
        sharding = self.state_sharding if self.state_sharding is not None \
            else mesh_lib.replicated(self.mesh)
        state = step_lib.init_train_state(
            key, self.model_def, self.cfg.model, self.cfg.data,
            self.cfg.optim, self.mesh, state_sharding=sharding,
            compile_cache=self.compile_cache)

        def note_fallback(step, path, reason, walk_ms=None):
            # A skipped candidate during the newest-verifiable walk
            # (ckpt/checkpoint.py) — surfaced in the JSONL stream so a
            # restart that silently lost a checkpoint interval is
            # visible after the fact. walk_ms is the wall-clock spent
            # in the walk so far (--restore_deadline_s budgets it).
            self.logger.log("ckpt_fallback", step=step, path=path,
                            error=str(reason), walk_ms=walk_ms)

        if self.faults is not None:
            # Recovery-phase injection seam (utils/faults.py): a
            # `kind@restore` fault strikes here, right before the
            # restore walk reads anything — e.g. ckpt_corrupt@restore
            # corrupts the newest checkpoint at the exact moment a
            # recovery tries to restore it. Gated inside the injector
            # to RECOVERY restores (the supervisor arms it); a fresh
            # run's initial restore never fires.
            self.faults.phase_hook("restore", self.cfg.log_dir,
                                   logger=self.logger,
                                   cluster=self.cluster)

        restored = self._restore_from_peers(state, sharding)
        if restored is not None:
            return restored

        return ckpt_lib.restore_checkpoint(
            self.cfg.log_dir, state, sharding=sharding,
            on_fallback=note_fallback,
            shard_io_threads=self.cfg.shard_io_threads,
            logger=self.logger,
            deadline_s=self.cfg.restore_deadline_s)

    def _restore_from_peers(self, state, sharding):
        """Diskless restore (ckpt/peerstore.py): when the adopted
        restart decision says ``source="peer"``, rebuild the state from
        the survivors' in-memory payloads plus the lost hosts' replicas
        — zero checkpoint reads. Any classified miss (replica missing,
        stale, or corrupt) logs an explicit ``peer_replica`` fallback
        record and returns None, so the caller runs the unchanged disk
        walk. None also when no peer-sourced decision is pending."""
        cluster = self.cluster
        if cluster is None or cluster.peer_store is None:
            return None
        pending = cluster.take_peer_restore()
        if pending is None:
            return None
        decision, world, lost = pending
        store = cluster.peer_store
        from dml_cnn_cifar10_tpu.ckpt.checkpoint import _logger_on_event
        on_event = _logger_on_event(self.logger)
        try:
            restored = store.restore(state, decision.restore_step,
                                     world, lost=lost,
                                     on_event=on_event)
        except peerstore_lib.ReplicaMiss as e:
            cluster.log("peer_replica", op="fallback",
                        step=decision.restore_step, owner=None,
                        bytes=None, secs=None, ok=False,
                        error=str(e)[:300], staleness=None)
            print(f"[ckpt] peer restore at step "
                  f"{decision.restore_step} not servable ({e}); "
                  f"falling back to the disk restore walk",
                  file=sys.stderr)
            return None
        if sharding is not None:
            restored = jax.device_put(restored, sharding)
        print(f"[ckpt] restored step {decision.restore_step} from peer "
              f"replicas (zero checkpoint reads)")
        return restored

    def _placed(self, batch: pipe.Batch):
        return mesh_lib.shard_batch(
            self.mesh, batch.images, batch.labels,
            spatial=mesh_lib.spatial_enabled(self.model_def, self.mesh))

    def evaluate(self, state, test_it: pipe.ShuffleBatchIterator) -> float:
        """Faithful: accuracy on ONE shuffled test batch
        (``cifar10cnn.py:202,238``); fixed: full-split sweep.

        On the resident path (set up by ``fit``) the whole test split
        lives in HBM and either mode is one dispatch + one fetch. The
        host-fed sweep uses fixed-shape padded batches (pad label -1 ⇒ 0
        correct) so every process issues the same number of collective
        eval steps — correct under any process/shard layout."""
        if self.cfg.eval_full_test_set:
            if self._resident_full_eval is not None:
                fn, total = self._resident_full_eval
                return int(jax.device_get(fn(state))) / max(total, 1)
            # Accumulate the correct-count ON DEVICE across the sweep and
            # fetch once: a per-batch int() fetch is a full host<->device
            # round trip x M batches per eval (~100 ms each on a tunneled
            # TPU), and under multi-host it serialized every process on
            # every batch. The adds are async dispatches; the single
            # device_get at the end is the only drain — O(1) fetches
            # under any process count.
            correct = None
            for batch in test_it.full_sweep_padded():
                c = self.eval_step(state, *self._placed(batch))["correct"]
                correct = c if correct is None else correct + c
            if correct is None:
                return 0.0
            return int(jax.device_get(correct)) / max(
                test_it.total_records, 1)
        if self._resident_test_eval is not None:
            idx = self._resident_idx(test_it.next_index_chunk(1)[0])
            return float(jax.device_get(self._resident_test_eval(state,
                                                                 idx)))
        m = self.eval_step(state, *self._placed(next(test_it)))
        return float(m["accuracy"])

    def fit(self, total_steps: Optional[int] = None,
            state: Optional[step_lib.TrainState] = None) -> TrainResult:
        cfg = self.cfg
        total_steps = total_steps or cfg.total_steps
        state = state if state is not None else self.init_or_restore()
        start_step = int(jax.device_get(state.step))
        if self.steps_per_dispatch > 1 and \
                (total_steps - start_step) % self.steps_per_dispatch:
            # Covers fit(total_steps=...) overrides and resumes from
            # checkpoints written at non-multiple steps — the loop advances
            # k at a time and must land exactly on the stop step
            # (StopAtStepHook parity, cifar10cnn.py:219).
            raise ValueError(
                f"remaining steps {total_steps - start_step} (stop "
                f"{total_steps}, resume {start_step}) must be a multiple "
                f"of steps_per_dispatch={self.steps_per_dispatch}")

        num_shards = jax.process_count()
        shard = jax.process_index()
        per_process_batch = cfg.batch_size // num_shards
        # Resident-eval fns are fit-scoped: reset so a prior fit's
        # closures (bound to THAT run's iterators and HBM-pinned splits)
        # can't leak into this one or into standalone evaluate() calls.
        self._resident_full_eval = None
        self._resident_test_eval = None
        self._resident_acc_eval = None
        self._resident_idx = None
        train_data_cfg = cfg.data
        if (self.steps_per_dispatch > 1 and cfg.resident_data
                and cfg.data.use_native_loader):
            # The HBM-resident path needs the index view only the
            # in-memory permutation iterator provides; the native C++
            # stream would silently force the ~90x-slower host-fed chunk
            # path. Resident wins: build the train iterator non-native.
            train_data_cfg = dataclasses.replace(cfg.data,
                                                 use_native_loader=False)
        train_it = pipe.input_pipeline(
            train_data_cfg, per_process_batch, train=True,
            seed=cfg.seed + shard, shard=shard, num_shards=num_shards)
        # Full-split byte size, computed PROCESS-UNIFORMLY: per-shard
        # nbytes differ when records don't divide evenly, and any
        # size-gated decision below must come out identical on every
        # process or the SPMD programs diverge and the job deadlocks.
        def full_split_bytes(it):
            per_record = int(np.prod(it.images.shape[1:])) \
                * it.images.dtype.itemsize
            return it.total_records * per_record

        if (train_data_cfg is not cfg.data
                and full_split_bytes(train_it)
                > cfg.resident_data_max_bytes):
            # Dataset turned out to exceed the HBM-resident cap: losing
            # the native loader AND the resident path would be strictly
            # worse than doing nothing, so rebuild the native stream.
            train_data_cfg = cfg.data
            train_it = pipe.input_pipeline(
                train_data_cfg, per_process_batch, train=True,
                seed=cfg.seed + shard, shard=shard, num_shards=num_shards)
        test_it = pipe.input_pipeline(
            train_data_cfg, per_process_batch, train=False,
            seed=cfg.seed + shard, shard=shard, num_shards=num_shards)
        # Fresh-batch train accuracy (cifar10cnn.py:235) — an independent
        # stream over the same decoded arrays (no second decode).
        acc_it = train_it.clone(seed=cfg.seed + 7 + shard)
        k = self.steps_per_dispatch
        # The resident cap is judged on the FULL split — multi-host
        # replicates the whole dataset into every process's HBM (the
        # host ships only per-process index slices).
        resident = (k > 1 and cfg.resident_data
                    and getattr(train_it, "supports_index_stream", False)
                    and full_split_bytes(train_it)
                    <= cfg.resident_data_max_bytes)
        # Exact-resume data order: fast-forward the fresh streams to the
        # cumulative consumption recorded at the checkpoint being
        # resumed, so interrupted+resumed training is bit-identical to
        # an uninterrupted run (the reference's MTS restart replays the
        # stream from scratch — a documented improvement). Must happen
        # BEFORE the prefetch threads start drawing. Augmentation draws
        # are replayed only on paths whose ``_finish`` makes them: the
        # per-step train stream (k==1) and the host-fed acc stream.
        # Scope: params + stream position are exact at ANY resume step;
        # the metric/eval CADENCE is keyed to the LOCAL step (reference
        # parity, cifar10cnn.py:232), so resuming at a step that is not
        # a cadence multiple (possible only via wall-clock or preemption
        # saves) shifts WHEN eval batches are drawn relative to the
        # uninterrupted run.
        base_counts = {"train": 0, "acc": 0, "test": 0}
        exact_ok = all(getattr(it, "supports_skip", False)
                       for it in (train_it, acc_it, test_it))
        if start_step > 0 and exact_ok:
            prior = ckpt_lib.load_data_state(cfg.log_dir, start_step)
            if prior:
                base_counts.update(
                    {name: int(prior.get(name, 0)) for name in base_counts})
                train_it.skip_batches(base_counts["train"], aug=(k == 1))
                acc_it.skip_batches(base_counts["acc"], aug=not resident)
                test_it.skip_batches(base_counts["test"])
        consumed = {"acc": 0, "test": 0}
        if resident:
            # HBM-resident data path: dataset lives on device, the host
            # ships only shuffled index arrays; gather+decode+K steps are
            # one dispatch (parallel/step.py:make_train_chunk_resident).
            # Multi-host: the FULL split replicates into every process's
            # HBM, each process keeps its disjoint strided index stream
            # (pipeline.py shards records as [shard::num_shards], so
            # local row i is full-split row shard + i*num_shards) and
            # contributes its slice of the global [K, B] index array —
            # the same ~16x win over host-fed chunks as single-host.
            repl = mesh_lib.replicated(self.mesh)
            host_imgs, host_lbls = _full_split_arrays(
                train_it, lambda: pipe.input_pipeline(
                    train_data_cfg, per_process_batch, train=True,
                    seed=cfg.seed))
            ds_images = mesh_lib.place_local(repl, host_imgs)
            ds_labels = mesh_lib.place_local(repl,
                                             host_lbls.astype(np.int32))

            def to_global(idx):
                if num_shards > 1:
                    return (shard + idx * num_shards).astype(np.int32)
                return idx

            # Device-generated index stream: the training dispatch takes
            # ONLY the donated state — no host index generation, no H2D
            # upload, and exact resume is free (the stream position is
            # state.step). Requires the global row space: the full split
            # is replicated in HBM, and the stateless stream emits GLOBAL
            # rows directly (identical on every process by purity).
            dev_stream = cfg.data.device_index_stream
            if dev_stream:
                # uint32 position domain — refuse runs that would wrap
                # (data/device_stream.py module docstring).
                from dml_cnn_cifar10_tpu.data import device_stream
                device_stream.check_supported_range(cfg.total_steps,
                                                    cfg.batch_size)
            chunk_fn = step_lib.make_train_chunk_resident(
                self.model_def, cfg.model, cfg.optim, self.mesh,
                ds_images, ds_labels,
                state_sharding=self.state_sharding, data_cfg=cfg.data,
                index_stream=((cfg.data.seed, cfg.batch_size, k)
                              if dev_stream else None),
                health_metrics=cfg.health_metrics,
                compile_cache=self.compile_cache,
                rules=self.partition_rules)
            idx_sh = mesh_lib.batch_sharding(self.mesh, 2, leading_dims=1)
            # Eval also goes resident: boundary train-accuracy is index-fed
            # from the in-HBM train split, test eval is one dispatch over
            # the in-HBM test split — each boundary costs ONE host↔device
            # round trip instead of a decoded-batch H2D + per-batch
            # fetches (decisive when the device link is a ~100 ms-RTT
            # tunnel).
            self._idx1_sharding = mesh_lib.batch_sharding(self.mesh, 1)
            self._resident_idx = lambda a: mesh_lib.place_local(
                self._idx1_sharding, to_global(a))
            self._resident_acc_eval = step_lib.make_batch_eval_resident(
                self.model_def, cfg.model, self.mesh, ds_images, ds_labels,
                cfg.data, state_sharding=self.state_sharding,
                compile_cache=self.compile_cache)
            if cfg.eval_full_test_set:
                # Multi-host included (round 3): each process contributes
                # its padded strided shard as its slice of the global
                # [M, B, ...] arrays; the scan's replicated output is the
                # GLOBAL correct count — one dispatch + one fetch per
                # eval on every process (the host-fed fallback cost M
                # per-batch H2D uploads per eval).
                self._resident_full_eval = step_lib.make_eval_resident(
                    self.model_def, cfg.model, self.mesh,
                    test_it.images, test_it.labels, cfg.data,
                    state_sharding=self.state_sharding,
                    batch_size=per_process_batch,
                    num_shards=num_shards,
                    total_records=test_it.total_records,
                    expected_batches=test_it.num_padded_sweep_batches(),
                    compile_cache=self.compile_cache)
            else:
                t_imgs, t_lbls = _full_split_arrays(
                    test_it, lambda: pipe.input_pipeline(
                        train_data_cfg, per_process_batch, train=False,
                        seed=cfg.seed))
                t_images = mesh_lib.place_local(repl, t_imgs)
                t_labels = mesh_lib.place_local(repl,
                                                t_lbls.astype(np.int32))
                self._resident_test_eval = step_lib.make_batch_eval_resident(
                    self.model_def, cfg.model, self.mesh, t_images,
                    t_labels, cfg.data, state_sharding=self.state_sharding,
                    compile_cache=self.compile_cache)

            if dev_stream:
                def produce():
                    # The chunk generates its own indices in-graph; a
                    # dispatch has no data arguments at all.
                    return ()
            else:
                def produce():
                    local = train_it.next_index_chunk(k)
                    return (mesh_lib.place_local(idx_sh, to_global(local)),)

            prefetch = pipe.PrefetchIterator(
                iter(produce, None), depth=cfg.data.prefetch, place=None)
            step_fn = chunk_fn
        elif k > 1:
            # Host-fed chunked path (multi-host, or dataset too big for
            # HBM): the host gathers raw uint8 bytes; decode/augment runs
            # on device inside the compiled chunk (ops/preprocess.py).
            spatial = mesh_lib.spatial_enabled(self.model_def, self.mesh)

            def produce():
                b = train_it.next_raw_chunk(k)
                return mesh_lib.shard_batch(self.mesh, b.images, b.labels,
                                            leading_dims=1, spatial=spatial)

            prefetch = pipe.PrefetchIterator(
                iter(produce, None), depth=cfg.data.prefetch, place=None)
            step_fn = self.train_chunk
        else:
            prefetch = pipe.PrefetchIterator(
                train_it, depth=cfg.data.prefetch, place=self._placed)
            step_fn = self.train_step

        # Host-loop telemetry (utils/telemetry.py): span tracing, goodput
        # accounting, HBM snapshots — all emitted at the existing metrics
        # boundaries with zero extra device fetches. Disabled spans reduce
        # to a shared no-op context manager.
        tracer = telemetry_lib.SpanTracer(enabled=cfg.telemetry)
        self._tracer = tracer  # exposed for tests/diagnostics
        # Device-time attribution (utils/devprof.py): the always-on
        # step-time estimator rides the existing fused boundary fetch
        # (two clock reads, zero device traffic — the parity test pins
        # it), and --profile_at_steps arms a bounded jax.profiler
        # window whose trace is parsed host-side into `devtime` JSONL.
        dev_est = devprof_lib.DeviceStepEstimator()
        devwin = devprof_lib.ProfileWindow.from_config(cfg,
                                                       logger=self.logger)
        # True when `devwin` was popped from the flight recorder (an
        # alert-armed one-shot) rather than --profile_at_steps: those
        # retire once done so a later capture can arm a fresh window.
        flight_win = False
        # Online train-and-serve (--fleet_publish): every committed
        # checkpoint is published to the fleet's coordination dir so
        # live serve workers hot-swap to it between micro-batches. The
        # hook runs AFTER the integrity sidecar commits (it rides the
        # manager's on_committed seam, writer thread under async_save)
        # because the workers' swap gate requires a verifiable sidecar.
        on_committed = None
        if cfg.fleet.publish:
            from dml_cnn_cifar10_tpu.fleet.publisher import (
                fleet_coord_dir, publish_checkpoint)
            pub_dir = fleet_coord_dir(cfg)

            def on_committed(step, path, _dir=pub_dir):
                publish_checkpoint(_dir, path, step, logger=self.logger)
        # In-process publish (runtime/core.py): guarded_save below parks
        # a device-side copy of the serving weights for each due save;
        # the commit callback hands it to the hook so the publish honors
        # the same commit ordering the fleet publisher does (a failed or
        # skipped save never publishes). Entries are pruned on commit
        # and bounded, so at most a few snapshots are ever live.
        publish_pending: dict = {}
        if self._publish_hook is not None:
            _chained = on_committed

            def on_committed(step, path, _chained=_chained):
                if _chained is not None:
                    _chained(step, path)
                parked = publish_pending.pop(step, None)
                if parked is not None:
                    self._publish_hook(step, path, parked[0], parked[1])
        ckpt_mgr = ckpt_lib.CheckpointManager(
            cfg.log_dir, cfg.checkpoint_every, keep=cfg.keep_checkpoints,
            async_save=cfg.async_checkpoint,
            every_secs=cfg.checkpoint_every_secs, fmt=cfg.ckpt_format,
            logger=self.logger, on_committed=on_committed,
            shard_io_threads=cfg.shard_io_threads)
        train_loss, test_accuracy = [], []
        last_metrics = None
        # on_nonfinite="skip" keeps a device-side snapshot of the last
        # known-finite state, refreshed at every finite metrics boundary;
        # a detection restores it (discarding every update since) and
        # training continues forward. A real buffer copy: step buffers
        # are donated, so holding a reference alone would dangle.
        keep_snapshot = cfg.check_numerics and cfg.on_nonfinite == "skip"
        snapshot = _copy_state(state) if keep_snapshot else None
        skips = {"n": 0}

        def _nonfinite(loss, step):
            """Apply the on_nonfinite policy to a detected non-finite
            loss. halt — and an exhausted skip budget — raises via
            ``_numerics_halt``; rollback logs the classified fault and
            raises for the supervisor; skip returns a fresh copy of the
            snapshot with the step counter advanced to ``step`` (the
            updates are discarded but the steps still happened — data
            consumption, cadences, and checkpoint naming key on it)."""
            if cfg.on_nonfinite == "rollback":
                self.logger.log("fault", step=step, fault="nonfinite",
                                injected=False)
                raise FloatingPointError(
                    f"non-finite train loss ({loss}) at step {step}; "
                    f"raising for supervisor rollback "
                    f"(on_nonfinite=rollback)")
            if cfg.on_nonfinite == "skip" and snapshot is not None \
                    and skips["n"] < cfg.recovery_retries:
                skips["n"] += 1
                self.logger.log("fault", step=step, fault="nonfinite",
                                injected=False)
                self.logger.log("recovery", step=step, fault="nonfinite",
                                action="skip", attempt=skips["n"])
                print(f"[recover] non-finite loss at step {step}: "
                      f"discarding updates since the last finite "
                      f"boundary (skip {skips['n']}/"
                      f"{cfg.recovery_retries})")
                restored = _copy_state(snapshot)
                opt = dict(restored.opt)
                opt["step"] = restored.opt["step"] * 0 + step
                return restored._replace(opt=opt)
            _numerics_halt(loss, step)

        def guarded_save(save_state, step, force=False):
            """ckpt_mgr.maybe_save, but under check_numerics no save may
            persist a non-finite state: the loss of the LAST dispatch is
            fetched (one round trip, only when a save is actually due)
            and a poisoned state follows the on_nonfinite policy —
            halt/rollback raise instead of overwriting the last good
            checkpoint; skip discards the poisoned update and skips this
            save (the next due boundary checkpoints the restored
            state)."""
            nonlocal state, last_metrics
            if not ckpt_mgr.due(step, force):
                # Early out BEFORE opening the checkpoint span: due() is
                # the manager's own save predicate, so a skipped boundary
                # records no span and the telemetry stream only carries
                # checkpoints that actually spent wall-clock.
                return False
            if cfg.check_numerics and last_metrics is not None:
                loss = float(jax.device_get(last_metrics["loss"]))
                if not np.isfinite(loss):
                    state = _nonfinite(loss, step)
                    last_metrics = None
                    return False
            # Sidecar pairing the checkpoint with the streams' cumulative
            # consumption (counts identical on every process under SPMD
            # lockstep). The manager's writer commits it AFTER the
            # checkpoint bytes land — chief-only, ordered even when
            # async — so the pair can never be half-written.
            data_state = {
                "train": base_counts["train"] + (step - start_step),
                "acc": base_counts["acc"] + consumed["acc"],
                "test": base_counts["test"] + consumed["test"],
            } if exact_ok else None
            if self._publish_hook is not None and ckpt_mgr.is_chief:
                # Park the serving weights (EMA when armed — the same
                # selection --mode serve/export restore) BEFORE the save:
                # under async_save the commit callback runs on the writer
                # thread after further steps may have donated the live
                # buffers. jnp.copy is device-side — no fetch.
                pub_params = save_state.opt.get("ema", save_state.params)
                pub_mstate = save_state.opt.get(
                    "ema_mstate", save_state.model_state) \
                    if self.model_def.has_state else None
                publish_pending[step] = (_copy_state(pub_params),
                                         _copy_state(pub_mstate))
                while len(publish_pending) > 4:
                    # A skipped/failed save never commits: drop the
                    # oldest parked snapshot instead of accreting them.
                    publish_pending.pop(min(publish_pending))
            if self.cluster is not None:
                self.cluster.set_phase("checkpoint")
            with tracer.span("checkpoint", cat="checkpoint"):
                saved = ckpt_mgr.maybe_save(save_state, step, force=force,
                                            data_state=data_state)
            if saved and self.cluster is not None:
                store = self.cluster.peer_store
                if store is not None and store.enabled:
                    # Peer redundancy (ckpt/peerstore.py): mirror this
                    # boundary's shard payload to the ring successor.
                    # Collect happens here on the step thread (donated
                    # buffers are not touched off-thread); only the
                    # file push runs in the store's background worker.
                    store.push_state_async(step, save_state)
            return saved

        def _numerics_halt(loss, step):
            self.logger.log("numerics_halt", step=step)
            raise FloatingPointError(
                f"non-finite train loss ({loss}) at step {step}; "
                f"halting without checkpointing the poisoned state "
                f"(check_numerics=True)")

        # FLOPs per dispatch (XLA cost analysis of the compiled step).
        # The AOT lower().compile() the probe needs does NOT share the
        # call-path executable cache — it recompiles (seconds for the
        # chunked step) — so it runs ONCE on a background thread,
        # launched right after the first dispatch; metrics boundaries
        # read the cell non-blockingly and omit the perf keys until it
        # lands ({} = pending, {"flops": 0.0} = probe failed).
        step_abs = None
        flops_cell = {}
        # Exposed for tests/diagnostics: the probe thread posts its result
        # here after fit() may already have returned.
        self._flops_cell = flops_cell
        probe_thread = None
        run_t0 = None  # post-compile wall anchor for the run-average rate
        # Drain-anchored throughput for the metrics stream (see
        # DrainMeter: async dispatch makes host intervals meaningless).
        meter = DrainMeter(cfg.batch_size)

        print("Starting Training")  # parity: cifar10cnn.py:225
        i = 0  # local step, like the reference's `i` (cifar10cnn.py:224)
        global_step = start_step
        stop = False
        # Dispatches between preemption allgathers: ~preempt_sync_every
        # STEPS regardless of chunk size (at least every dispatch).
        sync_stride = max(1, cfg.preempt_sync_every // k)
        n_dispatch = 0
        try:
            # A step-gated capture window owns the profiler when armed;
            # whole-run capture into --profile_dir remains the default.
            with PreemptionGuard() as preempt, profile_trace(
                    cfg.profile_dir if devwin is None else None):
                while global_step < total_steps and not stop:
                    drained = False
                    if devwin is None and cfg.profile_dir is None \
                            and self.flightrec is not None:
                        # An alert capture arms a one-shot post-mortem
                        # window; adopting it as `devwin` lets the
                        # existing stop/close seams drive it. Skipped
                        # whenever --profile_dir or --profile_at_steps
                        # already owns the profiler.
                        devwin = self.flightrec.pop_devprof_window(
                            global_step, logger=self.logger)
                        flight_win = devwin is not None
                    if devwin is not None:
                        devwin.maybe_start(global_step)
                    if self.autopilot is not None:
                        # Autopilot restart seam: a remediation action
                        # that changed the step geometry (shrink) asks
                        # for a restart here, BEFORE the cluster beat
                        # and the data draw — the supervisor restores
                        # the newest checkpoint and rebuilds the step
                        # through the compile cache with the new config.
                        reason = self.autopilot.poll_restart()
                        if reason is not None:
                            from dml_cnn_cifar10_tpu.autopilot.engine \
                                import RemediationRestartError
                            raise RemediationRestartError(reason)
                    if self.cluster is not None:
                        # Dispatch-seam liveness (parallel/cluster.py):
                        # publish a beat, check for eviction, arm the
                        # collective watchdog. Raises PeerLostError when
                        # a peer's heartbeats went stale — determinism
                        # instead of blocking in XLA.
                        self.cluster.begin_step(global_step)
                    if self.faults is not None:
                        # Deterministic fault injection at the host seam
                        # (utils/faults.py): may poison the state, corrupt
                        # the latest checkpoint on disk, deliver SIGTERM,
                        # raise an injected data stall, or fire a cluster
                        # fault (stalled beats / abrupt death / wedged
                        # collective) against the armed watchdog.
                        state = self.faults.step_hook(
                            global_step, state, cfg.log_dir, self.logger,
                            cluster=self.cluster)
                    if self.cluster is not None:
                        # Lockstep simulation barrier (no-op outside the
                        # CPU sim): wait for every live peer to reach
                        # this step, the software stand-in for the XLA
                        # collective a real pod would block in.
                        self.cluster.sync(global_step)
                    first = probe_thread is None
                    with tracer.span("data_wait", cat="data"):
                        try:
                            batch = next(prefetch)
                        except pipe.DataPipelineError:
                            raise
                        except Exception as e:
                            # Classify the data seam: anything that dies
                            # while drawing input is a pipeline failure
                            # the supervisor may restart from the last
                            # checkpoint, not a model bug.
                            raise pipe.DataPipelineError(
                                f"input pipeline failed at step "
                                f"{global_step}: {e!r}") from e
                    if step_abs is None:
                        step_abs = abstractify((state, *batch))
                    # First call traces + compiles before it enqueues
                    # (goodput cat "compile"); steady-state dispatches are
                    # async enqueue — traced but uncategorized, i.e. part
                    # of the productive-train remainder. With the compile
                    # cache armed, the cache's own obtain-time events
                    # carry the compile attribution (via
                    # _note_compile_event) — the span stays uncategorized
                    # so the seconds aren't counted twice.
                    with tracer.span("compile_first_dispatch" if first
                                     else "dispatch",
                                     cat="compile" if first
                                     and self.compile_cache is None
                                     else None):
                        state, metrics = step_fn(state, *batch)
                    if self.cluster is not None:
                        # The dispatch came back: disarm the watchdog.
                        # Boundary work (eval/checkpoint) runs unarmed —
                        # the background publisher keeps this process
                        # looking alive to its peers throughout.
                        self.cluster.end_step(global_step + k)

                    if probe_thread is None:
                        # First dispatch returned ⇒ trace+compile are done
                        # and device execution is only now starting: anchor
                        # the drain meter here so the FIRST boundary
                        # reports a real post-compile rate instead of 0.0.
                        meter.mark(global_step)
                        dev_est.mark(global_step)
                        run_t0 = time.perf_counter()
                        import threading

                        def _probe(fn=step_fn, abs_args=step_abs):
                            f = compiled_flops(fn, abs_args) or 0.0
                            if f and k > 1:
                                # Verify, don't assume, that this backend
                                # counts the K-step scan body ONCE: probe
                                # the scan-free per-step fn too; a
                                # chunk/step flops ratio near K means the
                                # scan was unrolled or counted
                                # per-iteration — scale back by K.
                                d = cfg.data
                                img = jax.ShapeDtypeStruct(
                                    (cfg.batch_size, d.crop_height,
                                     d.crop_width, d.num_channels),
                                    jnp.float32)
                                lab = jax.ShapeDtypeStruct(
                                    (cfg.batch_size,), jnp.int32)
                                f1 = compiled_flops(
                                    self.train_step,
                                    (abs_args[0], img, lab)) or 0.0
                                if f1 and f >= (1 + k) / 2 * f1:
                                    flops_cell["assume"] = "per_iteration"
                                    f = f / k
                                elif f1:
                                    flops_cell["assume"] = "scan_once"
                            # Models that scan their LAYER stack (ViT)
                            # also get their scan body counted once —
                            # ~1/depth of the real FLOPs (round-2
                            # verdict weak #4). The model's stack_probe
                            # measures one block standalone: bf_counted
                            # (as the step runs it — Pallas attention is
                            # an opaque custom call counted as 0) and
                            # bf_true (dense-equivalent, fully counted);
                            # correct_stack_flops swaps counted for true
                            # at full depth. Only on pure-data-parallel
                            # meshes: under seq/model/pipe partitioning
                            # the unsharded block probe doesn't match
                            # the per-chip share, so the figure stays
                            # uncorrected and is LABELED as such. The
                            # block probe runs at the PER-CHIP
                            # microbatch (batch / grad_accum / data
                            # axis) to match f's per-device accounting.
                            sp = getattr(self.model_def, "stack_probe",
                                         None)
                            if f and sp is not None:
                                mesh_shape = dict(self.mesh.shape) \
                                    if self.mesh is not None else {}
                                ndata = mesh_shape.get("data", 1)
                                pure_dp = all(
                                    v == 1 for a, v in mesh_shape.items()
                                    if a != "data")
                                if not pure_dp:
                                    flops_cell["stack"] = (
                                        "uncorrected_model_parallel")
                                else:
                                    micro = max(1, cfg.batch_size // max(
                                        1, cfg.optim.grad_accum) // ndata)
                                    try:
                                        depth, bfc, bft = sp(
                                            cfg.model, cfg.data, micro)
                                    except Exception:
                                        depth, bfc, bft = 0, None, None
                                    f, flops_cell["stack"] = \
                                        correct_stack_flops(f, depth,
                                                            bfc, bft)
                                    if flops_cell["stack"] == \
                                            "probe_failed":
                                        # Don't publish a known ~1/depth
                                        # undercount as TFLOP/s.
                                        f = 0.0
                            flops_cell["flops"] = f

                        probe_thread = threading.Thread(target=_probe,
                                                        daemon=True)
                        probe_thread.start()
                    last_metrics = metrics
                    global_step += k

                    if (i + k) % cfg.output_every == 0:
                        # Fresh-batch train accuracy (cifar10cnn.py:235), then
                        # ONE fused device->host fetch for loss+accuracy.
                        if self._resident_acc_eval is not None:
                            aidx = self._resident_idx(
                                acc_it.next_index_chunk(1)[0])
                            acc_arr = self._resident_acc_eval(state, aidx)
                        else:
                            acc_arr = self.eval_step(
                                state, *self._placed(next(acc_it)))["accuracy"]
                        consumed["acc"] += 1
                        # Router health for MoE models (ops/moe.py stats
                        # via parallel/step.py) and the optional
                        # training-health scalars (grad/param norms,
                        # update ratio — health_metrics=True) ride the
                        # SAME fused fetch as loss/accuracy: everything
                        # concatenates into one 1-D f32 array -> one
                        # device->host round trip per boundary (the
                        # ~100 ms-RTT tunnel makes a second fetch a real
                        # cost).
                        fused_keys = sorted(
                            mk for mk in metrics
                            if mk.startswith(("moe_", "health_")))
                        parts = [jnp.reshape(metrics["loss"], (1,)),
                                 jnp.reshape(
                                     jnp.asarray(acc_arr, jnp.float32),
                                     (1,))]
                        parts += [jnp.reshape(metrics[mk], (-1,)).astype(
                                      jnp.float32) for mk in fused_keys]
                        # The fused fetch is a true drain: the host blocks
                        # on device compute, so the span is device-busy
                        # time — traced, but counted as productive. The
                        # two clock reads around it feed the device
                        # step-time estimator (no extra fetches).
                        t_drain0 = time.perf_counter()
                        with tracer.span("boundary_drain"):
                            fused = jax.device_get(
                                jnp.concatenate(parts))
                        t_drain1 = time.perf_counter()
                        device_step_ms, drain_wait_ms = dev_est.boundary(
                            global_step, t_drain0, t_drain1)
                        rate = meter.rate(global_step)
                        drained = True
                        loss, acc = float(fused[0]), float(fused[1])
                        train_loss.append(loss)
                        perf = {}
                        off = 2
                        for mk in fused_keys:
                            nleaf = int(np.prod(metrics[mk].shape)) \
                                if metrics[mk].shape else 1
                            mv = fused[off:off + nleaf]
                            off += nleaf
                            perf[mk] = (round(float(mv[0]), 5)
                                        if nleaf == 1
                                        else [round(float(x), 5)
                                              for x in mv])
                        flops_probe = flops_cell.get("flops")
                        if flops_probe and rate > 0:
                            # steps/sec x flops/step. XLA cost analysis
                            # reports the PER-DEVICE share of the
                            # partitioned program (already per-chip, no
                            # device_count divide). Whether it counted
                            # the K-step scan body once was VERIFIED by
                            # the probe's chunk-vs-step cross-check
                            # (flops_scan in the metrics records which
                            # case held); grad-accum microbatches scale
                            # back in. Models that scan their layer
                            # stack (ViT) are corrected to full depth
                            # via stack_probe (flops_stack label);
                            # exact for the CNN.
                            tf = (flops_probe
                                  * max(1, cfg.optim.grad_accum)
                                  * (rate / cfg.batch_size) / 1e12)
                            perf["tflops_per_sec_per_chip"] = round(tf, 3)
                            if cfg.peak_tflops:
                                perf["mfu"] = round(
                                    tf / cfg.peak_tflops, 4)
                        if "assume" in flops_cell:
                            # Logged once, OUTSIDE the rate guard (like
                            # flops_stack below): a 0-rate boundary must
                            # defer the TFLOP/s figure, not silently
                            # swallow the scan-accounting label.
                            perf["flops_scan"] = flops_cell.pop("assume")
                        if "stack" in flops_cell:
                            # Logged once, OUTSIDE the flops>0 guard: the
                            # layer-stack accounting case
                            # (scan_once_x<depth> = corrected;
                            # probe_failed = TFLOP/s withheld;
                            # uncorrected_model_parallel = raw figure,
                            # trust accordingly).
                            perf["flops_stack"] = flops_cell.pop("stack")
                        self.logger.train_print(global_step, i + k - 1, acc)
                        # optimizer_ms: per-step device time inside the
                        # step's named_scope("optimizer"), measured by
                        # the last --profile_at_steps window (null until
                        # one completes) — the kernel/sharding win is
                        # attributed, not inferred.
                        self.logger.log("train", step=global_step, loss=loss,
                                        train_accuracy=acc,
                                        images_per_sec=rate,
                                        lr=_current_lr(cfg, global_step),
                                        device_step_ms=device_step_ms,
                                        drain_wait_ms=drain_wait_ms,
                                        optimizer_ms=(
                                            devwin.optimizer_step_ms
                                            if devwin is not None
                                            else None),
                                        **perf)
                        telemetry_lib.flush_boundary(tracer, self.logger,
                                                     global_step,
                                                     alerts=self.alerts)
                        if cfg.check_numerics:
                            # Loss is a replicated metric, so every
                            # process takes the same branch on the same
                            # boundary — no peer hangs.
                            if not np.isfinite(loss):
                                state = _nonfinite(loss, global_step)
                                last_metrics = None
                            elif keep_snapshot:
                                snapshot = _copy_state(state)
                    if (i + k) % cfg.eval_every == 0:
                        if self.cluster is not None:
                            self.cluster.set_phase("eval")
                        with tracer.span("eval", cat="eval"):
                            ta = self.evaluate(state, test_it)
                        if not cfg.eval_full_test_set:
                            # Full sweeps are sequential slices (no
                            # stream draws); single-batch eval consumes
                            # one shuffled test batch.
                            consumed["test"] += 1
                        test_accuracy.append(ta)
                        self.logger.eval_print(ta)
                        self.logger.log("eval", step=global_step,
                                        test_accuracy=ta)
                        drained = True
                    if guarded_save(state, global_step):
                        drained = True
                    i += k
                    n_dispatch += 1
                    # Preemption: a single process reacts immediately; a
                    # multi-host job must AGREE first — under synchronous SPMD
                    # no process may leave the step loop alone (its peers would
                    # hang in the next collective), so the flag is allgathered
                    # at a shared dispatch boundary and every process exits on
                    # the same iteration.
                    if num_shards == 1:
                        stop = preempt.requested
                        # Wall-clock checkpoint cadence (MTS parity: the
                        # reference's MonitoredTrainingSession saved every
                        # 600 s by default, cifar10cnn.py:222).
                        if ckpt_mgr.time_due():
                            if guarded_save(state, global_step, force=True):
                                drained = True
                    elif n_dispatch % sync_stride == 0:
                        from jax.experimental import multihost_utils
                        # One DCN allgather carries both flags: no process may
                        # leave the loop OR enter the collective checkpoint
                        # fetch alone.
                        with tracer.span("preempt_allgather", cat="sync"):
                            flags = multihost_utils.process_allgather(
                                np.asarray([preempt.requested,
                                            ckpt_mgr.time_due()]))
                        stop = bool(np.asarray(flags)[..., 0].any())
                        if bool(np.asarray(flags)[..., 1].any()):
                            if guarded_save(state, global_step, force=True):
                                drained = True
                    if drained:
                        # End-of-iteration mark: the next rate window
                        # starts AFTER this iteration's eval/checkpoint
                        # work, so only training dispatches are timed.
                        meter.mark(global_step)
                        dev_est.mark(global_step)
                    if devwin is not None:
                        # The capture stops only at a drained boundary
                        # at/after its stop step — quiesced devices, no
                        # truncated in-flight dispatches.
                        devwin.maybe_stop(global_step, drained=drained)
                        if flight_win and devwin.state == "done":
                            devwin = None
                            flight_win = False

                # Final save covers both normal completion and preemption: the
                # in-flight step finished, so the checkpoint loses zero work.
                # It runs INSIDE the guard so a second signal during the
                # write (Ctrl-C twice, pool re-sending SIGTERM) can't kill the
                # process before the atomic rename lands.
                # Run-average throughput over the post-compile window,
                # drain-anchored: fetch one scalar of the LAST dispatch
                # (waits for everything before it) and read the clock
                # BEFORE the final checkpoint save — a host-interval
                # enqueue rate would be garbage on the chunked path, and
                # including the final save would charge checkpoint IO
                # against training throughput.
                avg_rate = 0.0
                if run_t0 is not None and global_step > start_step:
                    jax.device_get(last_metrics["loss"])
                    avg_rate = ((global_step - start_step) * cfg.batch_size
                                / max(time.perf_counter() - run_t0, 1e-9))
                # A preempted NON-CHIEF host does not attempt the drain
                # save: the chief owns the checkpoint decision, and a
                # non-chief writing its own view of step N is how
                # restore races start. It emits a peer_lost-style
                # notice and exits cleanly instead. Gated to the
                # process-local case (jax.process_count() == 1 — the
                # cluster-sim / independent-world layout): in a real
                # jax.distributed world the save is a COLLECTIVE fetch
                # the allgathered stop makes every process enter
                # together, and skipping it on one would hang the rest.
                nonchief_preempt = (stop and num_shards == 1
                                    and not multihost.is_chief(
                                        cfg.parallel))
                if nonchief_preempt:
                    self.logger.log(
                        "peer_lost", step=global_step,
                        process_id=cfg.parallel.process_id,
                        reason="preempt_nonchief_exit")
                    print(f"[preempt] signal {preempt.signum} on "
                          f"non-chief process "
                          f"{cfg.parallel.process_id}: exiting cleanly "
                          f"without saving (chief owns the checkpoint)")
                else:
                    guarded_save(state, global_step, force=True)
                if stop and not nonchief_preempt:
                    print(f"[preempt] signal {preempt.signum}: checkpointed at "
                          f"step {global_step}, exiting cleanly")
                if stop:
                    self.logger.log("preempt", step=global_step,
                                    signum=preempt.signum)
                self.logger.log("done", step=global_step,
                                images_per_sec=avg_rate)
                # Run-end telemetry: the spans finished since the last
                # boundary (final eval/checkpoint included) plus the
                # cumulative goodput breakdown, marked final so
                # tools/telemetry_report.py can anchor on it.
                telemetry_lib.flush_boundary(tracer, self.logger,
                                             global_step, final=True,
                                             alerts=self.alerts)
        finally:
            # Crash paths clean up too: the async checkpoint writer must
            # drain (surfacing any background write error alongside the
            # original exception), the prefetch thread must stop, and
            # tensorboardX's daemon writer dies unflushed at interpreter
            # exit — an OOM/NaN abort is exactly when the last scalars
            # matter.
            ckpt_mgr.close()
            prefetch.close()
            # A capture window the run ended (or crashed) inside still
            # stops, parses, and emits its devtime records — like the
            # Chrome trace below, the runs that die mid-window are
            # exactly the ones worth attributing.
            if devwin is not None:
                devwin.close(global_step)
            # A supervisor-owned monitor must keep its threads (and
            # epoch/world state) across fit attempts; only a monitor
            # this Trainer built for itself dies with the fit.
            if self._owns_cluster and self.cluster is not None:
                self.cluster.close()
            # The Chrome trace exports from the finally block so a
            # crashed/preempted run still leaves its host-loop timeline —
            # exactly the runs worth opening in Perfetto.
            if tracer.enabled and cfg.trace_events_path:
                path = cfg.trace_events_path
                if self.task_index:
                    path += f".task{self.task_index}"
                tracer.export_chrome_trace(path, pid=self.task_index)
            self.logger.flush()
        # Release the fit-scoped resident closures — their partials pin
        # the train/test splits in HBM.
        self._resident_full_eval = None
        self._resident_test_eval = None
        self._resident_acc_eval = None
        return TrainResult(global_step, train_loss, test_accuracy,
                           avg_rate, state, preempted=stop)


def _copy_state(state):
    """Independent buffer copy of a train state (same shardings): the
    on_nonfinite="skip" snapshot must survive the donation of every
    subsequent step's buffers, so a reference is not enough."""
    return jax.tree.map(
        lambda x: jnp.copy(x) if isinstance(x, jax.Array) else x, state)


def _full_split_arrays(it, reload_fn):
    """``(images, labels)`` of the FULL split backing a possibly-sharded
    iterator. A sharded iterator holds strided views
    (``pipeline.py``: ``arr[shard::num_shards]``) whose ``.base`` IS the
    full decoded split in original order — reuse it instead of decoding
    the files a second time (and pinning a second full-split copy in
    host RAM); fall back to a fresh unsharded load if the view structure
    ever stops matching."""
    if it.num_shards == 1:
        return it.images, it.labels
    base_i, base_l = it.images.base, it.labels.base
    n = it.total_records
    if (isinstance(base_i, np.ndarray) and isinstance(base_l, np.ndarray)
            and base_i.shape == (n, *it.images.shape[1:])
            and base_l.shape[:1] == (n,)):
        return base_i, base_l
    full = reload_fn()
    return full.images, full.labels


def _current_lr(cfg: TrainConfig, step: int) -> float:
    """Host-math mirror of ``optim.learning_rate`` for the metrics log —
    a device dispatch + fetch here would cost a full link round trip per
    boundary. ``test_train_math.py`` pins it equal to the jnp version."""
    import math
    o = cfg.optim
    if o.schedule == "exponential":
        e = 0.0 if o.dead_lr_decay else step / o.decay_every
        if o.staircase:
            e = math.floor(e)
        lr = o.learning_rate * o.lr_decay ** e
    elif o.schedule == "cosine":
        horizon = max(o.cosine_decay_steps - o.warmup_steps, 1)
        prog = min(max((step - o.warmup_steps) / horizon, 0.0), 1.0)
        lr = o.learning_rate * 0.5 * (1.0 + math.cos(math.pi * prog))
    else:
        lr = o.learning_rate
    if o.warmup_steps > 0:
        lr *= min((step + 1.0) / o.warmup_steps, 1.0)
    return lr


