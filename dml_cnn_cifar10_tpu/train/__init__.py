"""Training math + driver: loss, optimizer/schedule, metrics, loop.

Reference: ``cifar_loss`` (``cifar10cnn.py:150-157``), ``train_step``
(``:159-164``), ``batch_accuracy`` (``:166-176``), and the monitored-session
step loop (``:219-242``).
"""

from dml_cnn_cifar10_tpu.train.loss import softmax_cross_entropy  # noqa: F401
from dml_cnn_cifar10_tpu.train.metrics import batch_accuracy  # noqa: F401
from dml_cnn_cifar10_tpu.train.optim import sgd_init, sgd_update, learning_rate  # noqa: F401
from dml_cnn_cifar10_tpu.train.loop import Trainer  # noqa: F401
