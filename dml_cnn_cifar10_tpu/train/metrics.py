"""Metrics.

``batch_accuracy`` is parity with ``cifar10cnn.py:166-176``: argmax over
logits vs int labels, mean over the batch.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def batch_accuracy(logits: jax.Array, labels: jax.Array) -> jax.Array:
    preds = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    return jnp.mean((preds == labels.astype(jnp.int32)).astype(jnp.float32))


def correct_count(logits: jax.Array, labels: jax.Array) -> jax.Array:
    """Unnormalized correct count — summable across batches for full-test-set
    eval (fixed mode; the reference only ever does single-batch eval)."""
    preds = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    return jnp.sum((preds == labels.astype(jnp.int32)).astype(jnp.int32))
