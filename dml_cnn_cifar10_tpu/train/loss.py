"""Loss functions.

``softmax_cross_entropy`` is the parity loss: sparse softmax cross-entropy
averaged over the batch (``cifar_loss``, ``cifar10cnn.py:150-157`` —
squeeze/cast of targets happens in the data layer, which already yields int32
labels).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def softmax_cross_entropy(logits: jax.Array, labels: jax.Array,
                          label_smoothing: float = 0.0) -> jax.Array:
    """Mean sparse softmax CE. logits [B, K] float, labels [B] int.

    ``label_smoothing`` ε mixes the one-hot target with uniform:
    ``(1-ε)·onehot + ε/K`` (the ladder-config regularizer; 0 = parity).
    """
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    nll = -jnp.take_along_axis(logp, labels[:, None].astype(jnp.int32),
                               axis=-1)[:, 0]
    if label_smoothing:
        uniform = -jnp.mean(logp, axis=-1)  # ε/K on every class
        nll = (1.0 - label_smoothing) * nll + label_smoothing * uniform
    return jnp.mean(nll)
