"""Loss functions.

``softmax_cross_entropy`` is the parity loss: sparse softmax cross-entropy
averaged over the batch (``cifar_loss``, ``cifar10cnn.py:150-157`` —
squeeze/cast of targets happens in the data layer, which already yields int32
labels).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def softmax_cross_entropy(logits: jax.Array, labels: jax.Array) -> jax.Array:
    """Mean sparse softmax CE. logits [B, K] float, labels [B] int."""
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    nll = -jnp.take_along_axis(logp, labels[:, None].astype(jnp.int32),
                               axis=-1)[:, 0]
    return jnp.mean(nll)
