"""The remediation policy engine behind ``--autopilot``.

Deterministic closed-loop remediation: the engine attaches to the
alert trigger seam (:meth:`AlertEngine.add_trigger`) and maps alert
patterns to remediation actions through the seams the repo already
has — config mutation picked up by the supervisor's restart path, a
restart request the Trainer's dispatch loop polls, and bound hooks
into the serving/fleet layers. Every qualifying alert firing is
answered by exactly one ``remediation`` JSONL record per matching
policy — including explicit ``suppressed_cooldown`` /
``suppressed_budget`` records, so a chaos campaign can assert the
loop considered every firing. Records link back to the firing alert's
``id`` and to the flight-recorder postmortem bundle captured at the
moment it fired.

Policy table (defaults; ``--autopilot_policies`` replaces it):

====================  ==================================  =================
alert pattern         action                              gate
====================  ==================================  =================
nonfinite_burst       rollback (LR × --rollback_lr_scale) 50-step cooldown
hbm_headroom          shrink_memory (halve resident K,     100-step cooldown
                      recompile through the compile cache)
serve_p99_slo /       scale_up_shed (fleet scale-up +     60 s cooldown
serve_shed/fleet_shed tier-by-tenant shed)
peer_churn            raise_replica_keep (+1, max 4)      300-step cooldown
====================  ==================================  =================

All actions share one :class:`RemediationBudget` (the
``--max_finetunes`` pattern generalized): when it is spent, every
further firing is answered by a ``suppressed_budget`` record and the
plain alert stands — the engine fails open, never closed.
"""

from __future__ import annotations

import dataclasses
import fnmatch
import re
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from dml_cnn_cifar10_tpu.utils.alerts import AlertRule

#: action name -> one-line description (the validation set for
#: ``--autopilot_policies`` and the docs table).
ACTIONS = {
    "rollback": "restore + scale LR by rollback_lr_scale (params: "
                "lr_scale)",
    "shrink_memory": "halve resident steps_per_dispatch (bit-identical) "
                     "or batch (params: shrink_batch=1) and recompile "
                     "through the compile cache",
    "scale_up_shed": "fleet scale-up + tier-by-tenant shed (params: "
                     "tier)",
    "raise_replica_keep": "raise --replica_keep by one (params: max)",
}


class RemediationRestartError(RuntimeError):
    """Raised by the Trainer's autopilot seam when a policy requested a
    restart (config already mutated): the supervisor classifies it as
    the recoverable ``remediation`` fault, restores the newest
    checkpoint, and rebuilds the step through the compile cache with
    the new geometry."""


@dataclasses.dataclass
class RemediationPolicy:
    """One alert-pattern → action mapping with its cooldown gate."""

    name: str
    rules: Tuple[str, ...]             # fnmatch patterns on rule names
    action: str
    cooldown: float = 0.0
    cooldown_unit: str = "steps"       # steps | seconds
    params: Dict[str, float] = dataclasses.field(default_factory=dict)

    def __post_init__(self):
        if self.action not in ACTIONS:
            raise ValueError(
                f"autopilot policy {self.name!r}: unknown action "
                f"{self.action!r} (known: {sorted(ACTIONS)})")
        if self.cooldown_unit not in ("steps", "seconds"):
            raise ValueError(
                f"autopilot policy {self.name!r}: cooldown unit must "
                f"be steps or seconds")

    def matches(self, rule_name: str) -> bool:
        return any(fnmatch.fnmatchcase(rule_name, p)
                   for p in self.rules)

    def cooldown_str(self) -> str:
        w = int(self.cooldown) if float(self.cooldown).is_integer() \
            else self.cooldown
        return f"{w}s" if self.cooldown_unit == "seconds" \
            else f"{w} steps"


def default_policies() -> List[RemediationPolicy]:
    """The built-in table (module docstring)."""
    return [
        RemediationPolicy("rollback_nonfinite", ("nonfinite_burst",),
                          "rollback", cooldown=50,
                          cooldown_unit="steps"),
        RemediationPolicy("shrink_memory", ("hbm_headroom",),
                          "shrink_memory", cooldown=100,
                          cooldown_unit="steps"),
        RemediationPolicy("scale_up_shed",
                          ("serve_p99_slo", "serve_shed", "fleet_shed"),
                          "scale_up_shed", cooldown=60,
                          cooldown_unit="seconds"),
        RemediationPolicy("raise_replica_keep", ("peer_churn",),
                          "raise_replica_keep", cooldown=300,
                          cooldown_unit="steps"),
    ]


_PARAM_RE = re.compile(r"^\w+=-?[\d.]+$")


def parse_policies(spec: Optional[str]) -> List[RemediationPolicy]:
    """Parse the ``--autopilot_policies`` grammar.

    ``;``-separated entries, each
    ``name=pattern[|pattern...]->action[:k=v,...][@cooldown]``:

    - ``roll=nonfinite_burst->rollback@50`` — 50-STEP cooldown
      (``@30s`` = 30 seconds; default 0 = no cooldown),
    - ``shed=serve_*|fleet_shed->scale_up_shed:tier=2@60s`` — fnmatch
      patterns, numeric action params.

    A non-empty spec REPLACES the default table. Raises ``ValueError``
    at flag-parse time on any mismatch — a typo'd policy must fail the
    run, not silently never remediate.
    """
    out: List[RemediationPolicy] = []
    if not spec:
        return out
    for raw in spec.split(";"):
        entry = raw.strip()
        if not entry:
            continue
        name, eq, rest = entry.partition("=")
        name = name.strip()
        if not eq or not re.fullmatch(r"\w+", name):
            raise ValueError(
                f"bad autopilot policy {entry!r}: want "
                f"name=pattern->action[:params][@cooldown]")
        cooldown, unit = 0.0, "steps"
        if "@" in rest:
            rest, _, cd = rest.rpartition("@")
            cd = cd.strip()
            if cd.endswith("s") and cd[:-1]:
                cooldown, unit = float(cd[:-1]), "seconds"
            else:
                cooldown = float(cd)
        pats, arrow, action = rest.partition("->")
        if not arrow:
            raise ValueError(
                f"bad autopilot policy {entry!r}: missing '->action'")
        patterns = tuple(p.strip() for p in pats.split("|") if p.strip())
        if not patterns:
            raise ValueError(
                f"bad autopilot policy {entry!r}: empty rule pattern")
        action = action.strip()
        params: Dict[str, float] = {}
        if ":" in action:
            action, _, plist = action.partition(":")
            action = action.strip()
            for kv in plist.split(","):
                kv = kv.strip()
                if not _PARAM_RE.match(kv):
                    raise ValueError(
                        f"bad autopilot policy {entry!r}: param "
                        f"{kv!r} is not key=number")
                k, _, v = kv.partition("=")
                params[k] = float(v)
        out.append(RemediationPolicy(name, patterns, action,
                                     cooldown=cooldown,
                                     cooldown_unit=unit, params=params))
    names = [p.name for p in out]
    dupes = {n for n in names if names.count(n) > 1}
    if dupes:
        raise ValueError(
            f"duplicate autopilot policy name(s): {sorted(dupes)}")
    return out


def required_extra_rules(policies) -> List[AlertRule]:
    """Alert rules a policy set needs that have no built-in: today the
    ``peer_churn`` rate rule (repeated ``peer_lost``-classified faults
    inside a trailing step window) behind ``raise_replica_keep``."""
    wants_churn = any(p.matches("peer_churn") for p in policies)
    if not wants_churn:
        return []
    return [AlertRule("peer_churn", "rate", "fault", op=">=", value=2,
                      window=300, window_unit="steps", severity="page",
                      match={"fault": "peer_lost"})]


class RemediationBudget:
    """Global action budget — the ``--max_finetunes`` counter pattern
    generalized. ``try_charge`` reserves a unit; ``refund`` returns it
    when the action turned out to be a noop or failed (a no-change
    firing must not eat the budget). Thread-safe."""

    def __init__(self, total: int):
        self.total = int(total)
        self._lock = threading.Lock()
        self._spent = 0
        self.per_policy: Dict[str, int] = {}

    def try_charge(self, name: str) -> bool:
        with self._lock:
            if self._spent >= self.total:
                return False
            self._spent += 1
            self.per_policy[name] = self.per_policy.get(name, 0) + 1
            return True

    def refund(self, name: str) -> None:
        with self._lock:
            if self._spent > 0:
                self._spent -= 1
            if self.per_policy.get(name, 0) > 0:
                self.per_policy[name] -= 1

    @property
    def spent(self) -> int:
        with self._lock:
            return self._spent

    def remaining(self) -> int:
        with self._lock:
            return max(0, self.total - self._spent)


class AutopilotEngine:
    """Map emitted alert firings to remediation actions.

    Attach with :meth:`attach` (adds any missing pattern rules and the
    3-arg trigger hook). Actions act through ``cfg`` mutation (the
    supervisor's rebuild-per-attempt picks them up), a pending-restart
    flag the Trainer polls (:meth:`poll_restart`), and hooks bound by
    the hosting layer (:meth:`bind`): ``scale_up`` (fleet controller)
    and ``shed_tier`` (micro-batcher / router admission).

    Every qualifying firing emits exactly one ``remediation`` record
    per matching policy with status ``applied`` / ``noop`` /
    ``failed`` / ``suppressed_cooldown`` / ``suppressed_budget``.
    Failures are fail-open: the record says so and the plain alert
    stands — remediation must never make an incident worse.
    """

    def __init__(self, cfg, policies: Optional[List[RemediationPolicy]]
                 = None, budget=8, logger=None, flightrec=None):
        self.cfg = cfg
        self.policies = (list(policies) if policies is not None
                         else default_policies())
        self.budget = (budget if isinstance(budget, RemediationBudget)
                       else RemediationBudget(budget))
        self.logger = logger
        self.flightrec = flightrec
        self._lock = threading.Lock()
        self._last_applied: Dict[str, float] = {}   # policy -> mark
        self._restart_pending: Optional[str] = None
        self._hooks: Dict[str, Callable] = {}
        self.history: List[dict] = []               # emitted records
        # ONE bound-method object for the trigger hook: ``self.on_alert``
        # evaluates to a fresh bound method every access, which would
        # defeat ``AlertEngine.add_trigger``'s idempotent-by-identity
        # check and double every remediation (Runtime attaches the
        # engine, then injects it into fit_supervised, which attaches
        # again).
        self._trigger = self.on_alert

    # -- wiring ----------------------------------------------------------

    def bind(self, name: str, fn: Callable) -> None:
        """Bind an action seam (``scale_up`` / ``shed_tier``)."""
        self._hooks[name] = fn

    def attach(self, alerts) -> None:
        """Register on an :class:`AlertEngine`: inject the pattern
        rules the policy set needs but the engine lacks, then the
        trigger hook. Idempotent."""
        have = {r.name for r in alerts.rules}
        missing = [r for r in required_extra_rules(self.policies)
                   if r.name not in have]
        if missing:
            alerts.add_rules(missing)
        alerts.add_trigger(self._trigger)

    def handles(self, rule_name: str,
                action: Optional[str] = None) -> bool:
        """True when some policy maps ``rule_name`` (optionally to a
        specific action) — the supervisor consults this so the LR
        scale is applied exactly once."""
        return any(p.matches(rule_name)
                   and (action is None or p.action == action)
                   for p in self.policies)

    def poll_restart(self) -> Optional[str]:
        """Return-and-clear the pending restart reason (the Trainer's
        dispatch-loop seam)."""
        with self._lock:
            reason, self._restart_pending = self._restart_pending, None
            return reason

    # -- the trigger hook ------------------------------------------------

    def on_alert(self, rule, value, meta=None) -> None:
        """AlertEngine trigger (3-arg form). Called once per EMITTED
        firing — never for rate-limit-suppressed re-fires or
        resolutions (the engine's trigger contract)."""
        meta = meta or {}
        alert_id = meta.get("id")
        step = meta.get("step")
        for policy in self.policies:
            if not policy.matches(rule.name):
                continue
            with self._lock:
                status, detail = self._consider(policy, rule, value,
                                                step)
            self._emit(policy, rule, alert_id, status, detail, step)

    # -- decision + actions (lock held) ----------------------------------

    def _mark(self, policy, step) -> float:
        if policy.cooldown_unit == "steps" \
                and isinstance(step, (int, float)):
            return float(step)
        return time.time()

    def _consider(self, policy, rule, value, step):
        mark = self._mark(policy, step)
        last = self._last_applied.get(policy.name)
        if policy.cooldown > 0 and last is not None \
                and mark - last < policy.cooldown:
            remaining = policy.cooldown - (mark - last)
            return "suppressed_cooldown", (
                f"cooldown {policy.cooldown_str()}: "
                f"{remaining:g} remaining")
        if not self.budget.try_charge(policy.name):
            return "suppressed_budget", (
                f"budget {self.budget.total} spent")
        try:
            status, detail = getattr(self, "_act_" + policy.action)(
                policy, rule, value, step)
        except Exception as e:   # fail-open: the plain alert stands
            status, detail = "failed", f"{type(e).__name__}: {e}"[:200]
        if status == "applied":
            self._last_applied[policy.name] = mark
        else:
            self.budget.refund(policy.name)
        return status, detail

    def _act_rollback(self, policy, rule, value, step):
        cfg = self.cfg
        scale = float(policy.params.get("lr_scale",
                                        cfg.rollback_lr_scale))
        cfg.on_nonfinite = "rollback"
        if scale != 1.0:
            cfg.optim.learning_rate *= scale
        return "applied", (f"lr_scale={scale:g} "
                           f"lr={cfg.optim.learning_rate:.6g}")

    def _act_shrink_memory(self, policy, rule, value, step):
        cfg = self.cfg
        k = int(getattr(cfg, "steps_per_dispatch", 1) or 1)
        if k > 1:
            new_k = k // 2 if k % 2 == 0 else 1
            cfg.steps_per_dispatch = new_k
            self._restart_pending = (
                f"shrink_memory: steps_per_dispatch {k}->{new_k}")
            return "applied", (f"steps_per_dispatch {k}->{new_k} "
                               f"(restart+recompile)")
        if policy.params.get("shrink_batch"):
            bs = int(cfg.batch_size)
            if bs >= 2:
                cfg.batch_size = bs // 2
                self._restart_pending = (
                    f"shrink_memory: batch_size {bs}->{bs // 2}")
                return "applied", (f"batch_size {bs}->{bs // 2} "
                                   f"(restart+recompile, NOT "
                                   f"bit-identical)")
        return "noop", "nothing left to shrink"

    def _act_scale_up_shed(self, policy, rule, value, step):
        tier = int(policy.params.get("tier", 1))
        did = []
        up = self._hooks.get("scale_up")
        if up is not None:
            up(rule.name)
            did.append("scale_up")
        shed = self._hooks.get("shed_tier")
        if shed is not None:
            shed(tier)
            did.append(f"shed_tier={tier}")
        if not did:
            return "noop", "no serve/fleet seam bound"
        return "applied", " ".join(did)

    def _act_raise_replica_keep(self, policy, rule, value, step):
        cfg = self.cfg
        cap = int(policy.params.get("max", 4))
        cur = int(cfg.parallel.replica_keep)
        if cur >= cap:
            return "noop", f"replica_keep already {cur} (max {cap})"
        cfg.parallel.replica_keep = cur + 1
        return "applied", f"replica_keep {cur}->{cur + 1}"

    # -- the record ------------------------------------------------------

    def _emit(self, policy, rule, alert_id, status, detail, step):
        bundle = None
        if self.flightrec is not None \
                and getattr(self.flightrec, "bundles", None):
            # The flight recorder observes records BEFORE triggers run,
            # so the newest bundle is this firing's capture.
            bundle = self.flightrec.bundles[-1]
        rec = dict(policy=policy.name, rule=rule.name,
                   alert_id=alert_id, action=policy.action,
                   status=status, postmortem=bundle, detail=detail,
                   step=step)
        self.history.append(rec)
        if self.logger is not None:
            try:
                self.logger.log("remediation", **rec)
            except Exception as e:   # never take down the alert path
                print(f"[autopilot] remediation record failed: {e!r}",
                      flush=True)

    # -- construction ----------------------------------------------------

    @classmethod
    def from_config(cls, cfg, logger=None, flightrec=None
                    ) -> Optional["AutopilotEngine"]:
        """Engine for a TrainConfig when ``--autopilot`` is armed;
        None otherwise (the disarmed path costs nothing)."""
        ap = getattr(cfg, "autopilot", None)
        if ap is None or not ap.enabled:
            return None
        policies = parse_policies(ap.policies) or None
        return cls(cfg, policies=policies, budget=ap.budget,
                   logger=logger, flightrec=flightrec)
