"""Autopilot: alert-driven remediation policy engine.

Closes the loop between the alert engine (utils/alerts.py) and the
remediation seams the rest of the repo already exposes — supervisor
restart decisions, RuntimeConfig/JobScheduler, the fleet controller,
the compile cache. See docs/AUTOPILOT.md.
"""

from dml_cnn_cifar10_tpu.autopilot.engine import (  # noqa: F401
    ACTIONS, AutopilotEngine, RemediationBudget, RemediationPolicy,
    RemediationRestartError, default_policies, parse_policies,
    required_extra_rules)
