"""Unified multi-job runtime (``--mode run``): one process, one mesh,
one telemetry substrate — train, eval, and serve as concurrent jobs.

See docs/RUNTIME.md. :class:`~dml_cnn_cifar10_tpu.runtime.core.Runtime`
owns the process-wide substrate exactly once (mesh, metrics stream,
registry + stats server, alert engine, flight recorder, serving compile
cache); :class:`~dml_cnn_cifar10_tpu.runtime.jobs.JobScheduler` runs
typed jobs on it. The trainer publishes every committed checkpoint's
weights straight into the in-process serving engine (a locked pointer
swap — no checkpoint read), and an emitted alert can trigger a
:class:`~dml_cnn_cifar10_tpu.runtime.jobs.FineTuneJob`, closing the
train→serve→observe loop into online continual learning.
"""

from dml_cnn_cifar10_tpu.runtime.core import Runtime, main_run
from dml_cnn_cifar10_tpu.runtime.jobs import (EvalJob, FineTuneJob, Job,
                                              JobScheduler, ServeJob,
                                              TrainJob, parse_jobs)

__all__ = ["Runtime", "main_run", "Job", "JobScheduler", "TrainJob",
           "EvalJob", "ServeJob", "FineTuneJob", "parse_jobs"]
