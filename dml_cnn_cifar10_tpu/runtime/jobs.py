"""Typed jobs + the scheduler that runs them on the shared runtime.

Job taxonomy (docs/RUNTIME.md):

- **task jobs** (``service = False``) do a bounded piece of work and
  finish: :class:`TrainJob` (the whole configured training run, under
  the run supervisor when ``--supervise``) and :class:`FineTuneJob`
  (alert-triggered continuation for ``--finetune_steps`` more steps).
  Task jobs that train serialize on the runtime's ``train_seat`` lock —
  two concurrent trainers would fight over the checkpoint dir and each
  other's donated buffers.
- **service jobs** (``service = True``) run until the task jobs drain:
  :class:`ServeJob` (the in-process HTTP serving head over the
  runtime's engine) and :class:`EvalJob` (periodic accuracy of the
  latest PUBLISHED weights — the eval never tears down the train step,
  it is one more forward on the shared mesh).

Every state transition writes a ``job`` JSONL record
(``pending``/``running``/``done``/``failed``; alert-born jobs carry
``trigger=<rule>``) and completion writes one ``job_done`` — the
telemetry_report jobs section and the acceptance smoke read the
lifecycle straight off the stream.
"""

from __future__ import annotations

import threading
import time
import traceback


class Job:
    """Base job: subclasses set ``jtype``/``service`` and implement
    :meth:`run`. ``stop`` is the scheduler's shutdown event — service
    jobs poll it; task jobs usually finish on their own."""

    jtype = "job"
    service = False

    def __init__(self, name=None):
        self.name = name or self.jtype
        self.state = "pending"
        self.trigger = None
        self.error = None
        self.thread = None

    def run(self, rt, stop: threading.Event) -> None:
        raise NotImplementedError


def parse_jobs(spec: str):
    """``--jobs`` spec → job instances. Comma-separated names from
    {train, serve, eval}; ``finetune`` is rejected — FineTuneJobs are
    born from alert triggers (``--finetune_steps``), never listed."""
    out, seen = [], set()
    for name in (spec or "").split(","):
        name = name.strip().lower()
        if not name:
            continue
        if name in seen:
            raise ValueError(f"--jobs lists {name!r} twice")
        seen.add(name)
        if name == "train":
            out.append(TrainJob())
        elif name == "serve":
            out.append(ServeJob())
        elif name == "eval":
            out.append(EvalJob())
        elif name == "finetune":
            raise ValueError(
                "--jobs cannot list 'finetune': FineTuneJobs are "
                "triggered by alerts (--finetune_steps / "
                "--finetune_rules), not scheduled up front")
        else:
            raise ValueError(f"unknown job {name!r} in --jobs "
                             f"(known: train, serve, eval)")
    if not out:
        raise ValueError("--jobs resolved to no jobs")
    return out


class JobScheduler:
    """Run jobs on threads over one runtime; journal their lifecycle."""

    def __init__(self, rt):
        self.rt = rt
        self._lock = threading.Lock()
        self._jobs = []
        self._stop = threading.Event()

    @property
    def jobs(self):
        with self._lock:
            return list(self._jobs)

    def add(self, job: Job) -> Job:
        """Register + start ``job`` (also the mid-run submit seam the
        alert trigger uses — ``submit`` is an alias)."""
        with self._lock:
            if any(j.name == job.name for j in self._jobs):
                raise ValueError(f"duplicate job name {job.name!r}")
            self._jobs.append(job)
        self._log_state(job, "pending")
        t = threading.Thread(target=self._run_job, args=(job,),
                             name=f"job-{job.name}", daemon=True)
        job.thread = t
        t.start()
        return job

    submit = add

    def _log_state(self, job: Job, state: str) -> None:
        job.state = state
        fields = dict(job=job.name, jtype=job.jtype, state=state)
        if job.trigger:
            fields["trigger"] = job.trigger
        self.rt.logger.log("job", **fields)

    def _run_job(self, job: Job) -> None:
        t0 = time.perf_counter()
        self._log_state(job, "running")
        ok = True
        try:
            job.run(self.rt, self._stop)
        except Exception as e:
            ok = False
            job.error = f"{type(e).__name__}: {e}"[:300]
            traceback.print_exc()
        self._log_state(job, "done" if ok else "failed")
        rec = dict(job=job.name, jtype=job.jtype, ok=ok,
                   secs=round(time.perf_counter() - t0, 4))
        if job.error:
            rec["error"] = job.error
        self.rt.logger.log("job_done", **rec)

    def wait(self) -> None:
        """Join every TASK job — including ones submitted while waiting
        (an alert trigger fires synchronously on the emitting thread, so
        a FineTuneJob born during training is registered before its
        TrainJob's thread exits and is picked up here) — then stop the
        service jobs."""
        while True:
            with self._lock:
                tasks = [j for j in self._jobs if not j.service]
            for j in tasks:
                if j.thread is not None:
                    j.thread.join()
            with self._lock:
                settled = all(j.state in ("done", "failed")
                              for j in self._jobs if not j.service)
            if settled:
                break
        self.stop()

    def stop(self) -> None:
        self._stop.set()
        with self._lock:
            services = [j for j in self._jobs if j.service]
        for j in services:
            if j.thread is not None:
                j.thread.join(timeout=30)


class TrainJob(Job):
    """The configured training run as a job. Under ``--supervise`` the
    run supervisor wraps it WITH the runtime's substrate injected (one
    stream, one mesh, one alert engine across restart attempts);
    otherwise a bare Trainer on the shared mesh. Either way the
    in-process publish hook rides every committed checkpoint."""

    jtype = "train"

    def __init__(self, total_steps=None, name="train"):
        super().__init__(name)
        self.total_steps = total_steps
        self.result = None

    def run(self, rt, stop):
        with rt.train_seat:
            rt.publisher_job = self.name
            if rt.cfg.supervise:
                from dml_cnn_cifar10_tpu.train.supervisor import \
                    fit_supervised
                result = fit_supervised(
                    rt.cfg, total_steps=self.total_steps,
                    task_index=rt.task_index, logger=rt.logger,
                    alert_engine=rt.alerts,
                    flight_recorder=rt.flightrec, mesh=rt.mesh,
                    publish_hook=rt.publish, autopilot=rt.autopilot)
            else:
                from dml_cnn_cifar10_tpu.train.loop import Trainer
                trainer = Trainer(rt.cfg, mesh=rt.mesh,
                                  task_index=rt.task_index,
                                  alert_engine=rt.alerts,
                                  flight_recorder=rt.flightrec,
                                  logger=rt.logger,
                                  publish_hook=rt.publish)
                result = trainer.fit(self.total_steps)
            self.result = result
            if result is not None:
                rt.last_train_state = result.state


class FineTuneJob(Job):
    """Alert-triggered continuation: ``steps`` more training steps from
    the last in-process train state (zero checkpoint reads when a
    TrainJob ran here — the state hand-off is a device pytree; a
    runtime with no prior trainer restores the newest checkpoint).
    Publishes ride the same hook, stamped ``job=finetune-N`` so the
    alert→job→publish lineage is one grep of the stream."""

    jtype = "finetune"

    def __init__(self, steps, trigger=None, name="finetune"):
        super().__init__(name)
        self.steps = int(steps)
        self.trigger = trigger
        self.result = None

    def run(self, rt, stop):
        import jax

        from dml_cnn_cifar10_tpu.train.loop import Trainer
        with rt.train_seat:
            rt.publisher_job = self.name
            trainer = Trainer(rt.cfg, mesh=rt.mesh,
                              task_index=rt.task_index,
                              alert_engine=rt.alerts,
                              flight_recorder=rt.flightrec,
                              logger=rt.logger, publish_hook=rt.publish)
            state = rt.last_train_state
            if state is None:
                state = trainer.init_or_restore()
            start = int(jax.device_get(state.step))
            result = trainer.fit(total_steps=start + self.steps,
                                 state=state)
            self.result = result
            rt.last_train_state = result.state


class ServeJob(Job):
    """The in-process serving head: the same HTTP surface as ``--mode
    serve`` (POST /predict, GET /metrics//stats//healthz) over the
    runtime's engine. Waits for the FIRST publish (nothing to serve
    before a checkpoint commits), advertises its bound port in
    ``runtime.json``, and keeps serving — hot-swapped by every later
    publish — until the task jobs drain. No second stats bind, no
    second registry: the handler renders the process default registry
    the trainer's series already feed."""

    jtype = "serve"
    service = True

    def __init__(self, name="serve"):
        super().__init__(name)

    def run(self, rt, stop):
        from http.server import ThreadingHTTPServer

        from dml_cnn_cifar10_tpu.serve.batcher import MicroBatcher
        from dml_cnn_cifar10_tpu.serve.metrics import ServeMetrics
        from dml_cnn_cifar10_tpu.serve.server import (_make_handler,
                                                      _MetricsFlusher)
        while rt.engine is None:
            if stop.wait(0.02):
                return  # stopped before the first publish
        serve_cfg = rt.cfg.serve
        metrics = ServeMetrics()
        batcher = MicroBatcher(
            rt.engine, buckets=serve_cfg.buckets,
            max_queue_depth=serve_cfg.max_queue_depth,
            batch_window_s=serve_cfg.batch_window_ms / 1e3,
            default_deadline_s=(serve_cfg.deadline_ms / 1e3
                                if serve_cfg.deadline_ms else None),
            metrics=metrics, warmup=rt.cfg.runtime.serve_warmup,
            logger=rt.logger)
        # Advertise the live batcher on the runtime: the autopilot's
        # shed_tier action reaches tier-by-tenant shedding through it.
        rt.batcher = batcher
        server = ThreadingHTTPServer(
            ("", serve_cfg.port),
            _make_handler(batcher, metrics, replica_id=rt.task_index,
                          hop="server", logger=rt.logger,
                          sample_rate=serve_cfg.trace_sample_rate))
        flusher = _MetricsFlusher(metrics, rt.logger,
                                  serve_cfg.metrics_every_s,
                                  alerts=rt.alerts)
        flusher.start()
        accept = threading.Thread(target=server.serve_forever,
                                  name="runtime-serve-accept",
                                  daemon=True)
        drained = True
        try:
            accept.start()
            rt.note_serve_port(server.server_address[1])
            print(f"[runtime] serving version {rt.engine.version} on "
                  f":{server.server_address[1]} (POST /predict)")
            stop.wait()
            server.shutdown()
            accept.join()
            drained = batcher.drain(timeout=serve_cfg.drain_deadline_s)
        finally:
            rt.batcher = None
            server.server_close()
            flusher.stop()
            if batcher._worker.is_alive():
                batcher.close()
            metrics.emit(rt.logger, final=True)
        print(f"[runtime] serve job exiting "
              f"({'drained' if drained else 'drain deadline hit'})")


class EvalJob(Job):
    """Periodic eval of the latest PUBLISHED weights, without touching
    the train loop: every ``--runtime_eval_every_s`` it runs
    ``--runtime_eval_batches`` test batches through the runtime's
    serving engine (the same forward a request takes, on the same
    mesh) and emits a normal ``eval`` record — which feeds the alert
    rules, so an accuracy rule over these records is exactly the drift
    signal that can trigger a FineTuneJob."""

    jtype = "eval"
    service = True

    def __init__(self, name="eval"):
        super().__init__(name)

    def run(self, rt, stop):
        cfg = rt.cfg
        data = None
        offset = 0
        tick = max(0.05, float(cfg.runtime.eval_every_s))
        while not stop.wait(tick):
            eng = rt.engine
            if eng is None:
                continue  # nothing published yet
            if data is None:
                from dml_cnn_cifar10_tpu.data import download
                from dml_cnn_cifar10_tpu.data.pipeline import _load_split
                download.ensure_dataset(cfg.data)
                data = _load_split(download.test_files(cfg.data),
                                   cfg.data)
            images, labels = data
            bsz = min(int(max(cfg.serve.buckets)), len(images))
            correct = total = 0
            version = eng.version
            for _ in range(max(1, int(cfg.runtime.eval_batches))):
                if offset + bsz > len(images):
                    offset = 0
                img = images[offset:offset + bsz]
                lab = labels[offset:offset + bsz]
                offset += bsz
                logits, _, version = eng.forward_timed_versioned(img)
                correct += int((logits.argmax(axis=1) == lab).sum())
                total += len(lab)
            step = int(version) if str(version).isdigit() else -1
            rt.logger.log("eval", step=step,
                          test_accuracy=round(correct / max(1, total),
                                              4),
                          source="runtime_eval")
