"""The :class:`Runtime`: process-wide substrate for the unified
multi-job runtime (``--mode run``), docs/RUNTIME.md.

Ownership contract — each of these exists exactly ONCE per process and
every job borrows it (never builds its own):

- the **mesh** (``parallel/mesh.py``): trainers and the serving engine
  attach to the same device mesh, so devices are shared instead of
  partitioned per workload (the TF-Replicator / Mesh-TensorFlow
  single-runtime-many-jobs shape the paper's cluster had);
- the **metrics stream** (one :class:`MetricsLogger` on
  ``--metrics_jsonl``) plus its observer chain: flight recorder FIRST,
  alert engine second (attach order is run order);
- the **metrics registry + stats server**: one
  ``ensure_stats_server(--stats_port)`` bind; the serve job's HTTP
  ``/metrics`` renders the SAME process registry, so both job families'
  series appear on one endpoint, never split;
- the **serving compile cache** handle (trainer seams keep their own
  handle over the same ``--compile_cache_dir`` so their goodput
  attribution hook stays wired — the DISK cache is shared either way).

The publish protocol: the Trainer's in-process publish hook
(``train/loop.py``) parks a device-side copy of the serving weights at
each due save and hands it to :meth:`Runtime.publish` from the
checkpoint manager's ``on_committed`` callback — so a publish happens
iff the checkpoint COMMITTED, carries live device buffers (zero
checkpoint reads, zero ``jax.device_get``), and installs via the
engine's locked pointer swap. One ``publish`` JSONL record per commit
pins it.

The control loop: :meth:`Runtime._on_alert` rides the alert engine's
trigger seam (``utils/alerts.py``) — an EMITTED firing whose rule is
listed in ``--finetune_rules`` (or any rule, when unset) enqueues a
:class:`~dml_cnn_cifar10_tpu.runtime.jobs.FineTuneJob`, budgeted by
``--max_finetunes``, while the flight recorder's capture of the same
firing preserves the evidence. Lineage is on the stream: ``alert``
(rule) → ``job`` (trigger=rule) → ``publish`` (job=finetune-N).
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Optional

from dml_cnn_cifar10_tpu.autopilot.engine import (AutopilotEngine,
                                                  RemediationBudget)
from dml_cnn_cifar10_tpu.config import TrainConfig
from dml_cnn_cifar10_tpu.models import get_model
from dml_cnn_cifar10_tpu.parallel import mesh as mesh_lib
from dml_cnn_cifar10_tpu.utils import alerts as alerts_lib
from dml_cnn_cifar10_tpu.utils import flightrec as flightrec_lib
from dml_cnn_cifar10_tpu.utils import metrics_registry
from dml_cnn_cifar10_tpu.utils.logging import MetricsLogger


class Runtime:
    """One per process. Build, :meth:`start` the configured jobs,
    :meth:`wait` for the task jobs (train + any triggered fine-tunes)
    to drain — service jobs (serve, eval) are then stopped — and
    :meth:`close`."""

    def __init__(self, cfg: TrainConfig, task_index: int = 0):
        import jax

        from dml_cnn_cifar10_tpu.compilecache import CompileCache
        from dml_cnn_cifar10_tpu.runtime.jobs import JobScheduler

        self.cfg = cfg
        self.task_index = task_index
        self.model_def = get_model(cfg.model.name)
        self.mesh = mesh_lib.build_mesh(cfg.parallel)
        self.logger = MetricsLogger(
            cfg.metrics_jsonl, task_index=task_index,
            tensorboard_dir=(cfg.tensorboard_dir
                             if jax.process_index() == 0 else None))
        # Flight recorder BEFORE the alert observer (attach order is run
        # order): the record that trips a rule reaches the ring before
        # the engine's nested `alert` emission snapshots it.
        self.flightrec = flightrec_lib.FlightRecorder.from_config(
            cfg, context_fn=self._context, logger=self.logger)
        if self.flightrec is not None:
            self.logger.add_observer(self.flightrec.observer())
        self.alerts = alerts_lib.AlertEngine.from_config(cfg)
        if self.alerts is not None:
            self.logger.add_observer(self.alerts.observer(self.logger))
            self.alerts.add_trigger(self._on_alert)
        #: the live ServeJob's MicroBatcher, while one runs — the
        #: autopilot's shed_tier action reaches tier-by-tenant shedding
        #: through it (runtime/jobs.py sets/clears it).
        self.batcher = None
        # Alert-driven remediation (--autopilot; autopilot/engine.py):
        # one engine for the whole runtime, shared with every
        # supervised TrainJob attempt, with the serve shed seam bound.
        self.autopilot = AutopilotEngine.from_config(
            cfg, logger=self.logger, flightrec=self.flightrec)
        if self.autopilot is not None:
            self.autopilot.bind("shed_tier", self._shed_tier)
            if self.alerts is not None:
                self.autopilot.attach(self.alerts)
        # ONE registry, ONE stats bind for the whole process: every
        # Trainer/job repeats this call and gets the same server back
        # (ensure_stats_server is idempotent under its process lock).
        self.registry = metrics_registry.default_registry()
        metrics_registry.ensure_stats_server(cfg.stats_port)
        self.compile_cache = CompileCache.from_config(cfg,
                                                      logger=self.logger)
        #: serializes the training seat: TrainJob and FineTuneJobs hold
        #: it across their fit() — two concurrent trainers would fight
        #: over the checkpoint dir and donated buffers.
        self.train_seat = threading.Lock()
        self.scheduler = JobScheduler(self)
        #: the in-process serving engine; created at the FIRST publish
        #: (before that, the serve job has nothing to serve and waits).
        self.engine = None
        self._engine_lock = threading.Lock()
        #: final TrainState of the last train/fine-tune job — the
        #: zero-checkpoint-read continuation seam for FineTuneJob.
        self.last_train_state = None
        #: name of the job currently holding the train seat (stamped
        #: into `publish` records for the alert→job→publish lineage).
        self.publisher_job = "train"
        self.serve_port: Optional[int] = None
        self._pub_seq = 0
        # The --max_finetunes counter, generalized: one RemediationBudget
        # (autopilot/engine.py) gates the alert->FineTuneJob loop —
        # same thread-safe charge/spent semantics the autopilot's
        # action budget uses.
        self.ft_budget = RemediationBudget(cfg.runtime.max_finetunes)
        self.state_path = cfg.runtime.state_path or os.path.join(
            cfg.log_dir, "runtime.json")

    # -- lifecycle -------------------------------------------------------

    def start(self) -> None:
        from dml_cnn_cifar10_tpu.runtime.jobs import parse_jobs
        for job in parse_jobs(self.cfg.runtime.jobs):
            self.scheduler.add(job)

    def wait(self) -> None:
        self.scheduler.wait()

    def close(self) -> None:
        self.scheduler.stop()
        self._write_state()
        self.logger.flush()
        self.logger.close()

    # -- publish protocol ------------------------------------------------

    def publish(self, step, path, params, model_state) -> bool:
        """The Trainer's in-process publish hook target: install the
        committed checkpoint's weights into the serving engine. Called
        with device-resident copies (see ``train/loop.py``) — the first
        commit CREATES the engine on the shared mesh, later commits
        pointer-swap it. Emits one ``publish`` record either way."""
        t0 = time.perf_counter()
        cfg = self.cfg
        version = str(int(step))
        with self._engine_lock:
            if self.engine is None:
                from dml_cnn_cifar10_tpu.serve.engine import ServingEngine
                self.engine = ServingEngine.from_params(
                    self.model_def, cfg.model, cfg.data, params,
                    model_state, compile_cache=self.compile_cache,
                    logger=self.logger, version=version,
                    replica_id=self.task_index, mesh=self.mesh)
                if cfg.runtime.serve_warmup:
                    self.engine.warmup(cfg.serve.buckets)
                swapped, note = True, "installed"
            else:
                swapped, note = self.engine.try_swap(
                    params, model_state, version=version)
        self._pub_seq += 1
        self.logger.log("publish", step=int(step), version=version,
                        source="live_params", swapped=bool(swapped),
                        latency_ms=round((time.perf_counter() - t0) * 1e3,
                                         3),
                        job=self.publisher_job, seq=self._pub_seq,
                        note=note, path=path)
        self._write_state()
        return bool(swapped)

    # -- alert → job control loop ----------------------------------------

    def _on_alert(self, rule, value) -> None:
        """Alert-engine trigger hook: an EMITTED firing may enqueue a
        FineTuneJob (docs/RUNTIME.md alert-trigger table). Suppressed
        re-fires and resolutions never reach this seam by the engine's
        contract; the ``--max_finetunes`` budget bounds the rest."""
        rtc = self.cfg.runtime
        if rtc.finetune_steps <= 0:
            return
        if rtc.finetune_rules:
            allowed = {n.strip() for n in rtc.finetune_rules.split(",")
                       if n.strip()}
            if rule.name not in allowed:
                return
        if not self.ft_budget.try_charge("finetune"):
            return
        n = self.ft_budget.spent
        from dml_cnn_cifar10_tpu.runtime.jobs import FineTuneJob
        job = FineTuneJob(rtc.finetune_steps, trigger=rule.name,
                          name=f"finetune-{n}")
        print(f"[runtime] alert {rule.name!r} (value {value}) triggered "
              f"{job.name} (+{rtc.finetune_steps} steps, "
              f"{n}/{rtc.max_finetunes})")
        self.scheduler.submit(job)

    def _shed_tier(self, tier: int) -> None:
        """Autopilot shed seam: turn on tier-by-tenant admission
        shedding on the live serve batcher. No serve job running means
        there is nothing to shed — raising lets the engine record the
        action as ``failed`` (fail-open: the plain alert stands)."""
        b = self.batcher
        if b is None:
            raise RuntimeError("no live serve batcher to shed")
        b.set_shed_tier(int(tier))

    # -- advertised state ------------------------------------------------

    def note_serve_port(self, port: int) -> None:
        self.serve_port = int(port)
        self._write_state()

    def _write_state(self) -> None:
        """Atomic ``runtime.json`` advert (``tools/loadgen.py
        --runtime`` discovery). Fail-open: a read-only log_dir must not
        take down the jobs."""
        try:
            os.makedirs(os.path.dirname(self.state_path) or ".",
                        exist_ok=True)
            tmp = f"{self.state_path}.tmp{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump({"pid": os.getpid(),
                           "serve_port": self.serve_port,
                           "version": (self.engine.version
                                       if self.engine is not None
                                       else None),
                           "publishes": self._pub_seq,
                           "jobs": self.cfg.runtime.jobs}, f)
            os.replace(tmp, self.state_path)
        except OSError:
            pass

    def _context(self) -> dict:
        """Flight-recorder live-context hook."""
        return {"serving_version": (self.engine.version
                                    if self.engine is not None else None),
                "publishes": self._pub_seq,
                "jobs": [f"{j.name}:{j.state}"
                         for j in self.scheduler.jobs]}


def main_run(cfg: TrainConfig, task_index: int = 0) -> int:
    """``--mode run`` entry: build the runtime, run the configured jobs
    to completion, stop the service jobs, exit 0. A failed TASK job
    (train/fine-tune) exits 1 so drivers notice."""
    rt = Runtime(cfg, task_index=task_index)
    try:
        rt.start()
        rt.wait()
    finally:
        rt.close()
    failed = [j.name for j in rt.scheduler.jobs
              if not j.service and j.state == "failed"]
    if failed:
        print(f"[runtime] task job(s) failed: {', '.join(failed)}")
        return 1
    return 0
