"""Network transport for cluster + fleet coordination.

The file-backed :class:`~dml_cnn_cifar10_tpu.parallel.cluster.HeartbeatStore`
and :class:`~dml_cnn_cifar10_tpu.parallel.cluster.RestartCoordinator`
assume every host mounts one shared directory — true on NFS/GCS-fuse
pods, false everywhere the interesting failures live. This module keeps
their exact contracts but carries them over a socket: one process (the
lowest process id for a training cluster; the controller for a serving
fleet) hosts :class:`CoordServer`, a stdlib ``ThreadingHTTPServer``
gateway over the coordination directory, and every process talks to it
through :class:`CoordClient`. Stdlib HTTP deliberately — no new
dependencies, inspectable with ``curl``, and the server's on-disk state
stays ``cat``-able post-mortem exactly like the file store's.

The transport rules (docs/RESILIENCE.md, transport-selection section):

- **Every request is bounded.** Each operation carries a socket
  timeout (``--net_timeout_s``) and a retry budget (``--net_retries``)
  over the shared bounded backoff (``utils/backoff.py``). There is no
  unbounded wait anywhere in the client — the ``no_net_timeout``
  planted chaos regression exists to prove the campaign notices if one
  sneaks back in.
- **Every failure is classified.** Socket-level failures raise
  :class:`TransportError` with a machine-readable ``reason``
  (``timeout`` / ``unreachable`` / ``http_<code>`` / ``proto``).
  ``TransportError`` subclasses ``OSError`` on purpose: every caller
  hardened against file-store IO errors (the peer-replica push retry,
  the beat read paths) handles the network failure the same way,
  unchanged.
- **Degraded, never hung.** :class:`NetHeartbeatStore` turns transport
  failures into the same observable the file store produces for a dead
  peer — an absent beat — so the watchdog's ``peer_lost``
  classification fires unmodified. :class:`NetRestartCoordinator`
  turns a transport failure on ``record`` into
  :class:`~dml_cnn_cifar10_tpu.parallel.cluster.EvictedError`: a chief
  that cannot commit a decision is, from the cluster's point of view,
  cut off — and the supervisor's fence-or-rejoin path is exactly the
  right answer (under ``elastic_expand`` it re-announces and rejoins
  when the partition heals — the headline ``net_partition`` chaos
  invariant).

Fault injection: the server consults ``utils/netfaults.py`` once per
request (partition = hold the connection and never answer; delay =
answer late; drop = 503 every second request; dup = apply writes
twice), armed remotely via ``POST /fault`` by the fault injector
(``utils/faults.py``) from whichever process the chaos schedule says to
isolate.

Rendezvous: the server atomically writes ``coord_addr.json`` into the
coordination directory; clients resolve it lazily with a small
first-resolution grace so a client racing the server's bind classifies
as ``unreachable`` only once the grace is spent.
"""

from __future__ import annotations

import dataclasses
import hashlib
import http.client
import json
import os
import shutil
import socket
import sys
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Sequence

from dml_cnn_cifar10_tpu.parallel import cluster as cluster_lib
from dml_cnn_cifar10_tpu.utils import backoff, netfaults

#: Rendezvous file the server commits (atomic rename) into the
#: coordination directory; clients resolve it lazily.
ADDR_FILENAME = "coord_addr.json"

#: Request header naming the calling process id — how the server (and
#: the armed netfaults state) knows WHOM a request belongs to.
PROC_HEADER = "X-DML-Proc"

#: Grace a client grants the server's bind on FIRST resolution only:
#: in the lockstep sims every process starts at once and the server
#: host pays JAX import before it binds.
RESOLVE_GRACE_S = 10.0

#: Sentinel: "use the client's configured timeout". Distinct from None,
#: which means NO timeout at all — the misconfiguration the
#: ``no_net_timeout`` planted regression injects on purpose.
_DEFAULT = object()


class TransportError(OSError):
    """A classified transport failure. ``reason`` is machine-readable:
    ``timeout`` (the bounded wait expired), ``unreachable`` (connect
    refused / no address published), ``http_<code>`` (the server
    answered but unhappily), ``proto`` (undecodable response)."""

    def __init__(self, reason: str, message: str):
        super().__init__(f"[{reason}] {message}")
        self.reason = reason


class CoordClient:
    """Bounded, classified, retrying HTTP client for one coordination
    directory. Thread-safe; one per process (the beat publisher,
    watchdog, and seam threads share it)."""

    def __init__(self, coord_dir: str, process_id: int,
                 timeout_s: float = 5.0, retries: int = 2,
                 log_fn=None, resolve_grace_s: float = RESOLVE_GRACE_S):
        self.coord_dir = coord_dir
        self.process_id = int(process_id)
        self.timeout_s = float(timeout_s)
        self.retries = max(int(retries), 0)
        self.resolve_grace_s = float(resolve_grace_s)
        self._addr_path = os.path.join(coord_dir, ADDR_FILENAME)
        self._addr: Optional[tuple] = None
        self._resolved_once = False
        self._log = log_fn
        self._lock = threading.Lock()
        self._last_note: Dict[tuple, float] = {}

    # -- plumbing ---------------------------------------------------------

    def _resolve(self) -> tuple:
        with self._lock:
            if self._addr is not None:
                return self._addr
            grace = 0.0 if self._resolved_once else self.resolve_grace_s
        deadline = time.time() + grace
        attempt = 0
        while True:
            try:
                with open(self._addr_path) as f:
                    doc = json.load(f)
                addr = (str(doc["host"]), int(doc["port"]))
            except (OSError, ValueError, KeyError, TypeError) as e:
                if time.time() >= deadline:
                    raise TransportError(
                        "unreachable",
                        f"no coordinator address at {self._addr_path}: "
                        f"{e}")
                attempt += 1
                time.sleep(backoff.delay_s(0.05, 0.5, attempt))
                continue
            with self._lock:
                self._addr = addr
                self._resolved_once = True
            return addr

    def _request(self, method: str, path: str, body=None,
                 timeout_s=_DEFAULT):
        """ONE bounded attempt: returns ``(status, payload_bytes)`` for
        any HTTP answer, raises classified :class:`TransportError` for
        socket-level failures. ``timeout_s=None`` disables the bound —
        never passed by this module; it exists so the ``no_net_timeout``
        chaos plant can demonstrate what happens when it is."""
        host, port = self._resolve()
        url = f"http://{host}:{port}{path}"
        req = urllib.request.Request(url, data=body, method=method)
        req.add_header(PROC_HEADER, str(self.process_id))
        req.add_header("Content-Type", "application/octet-stream")
        timeout = self.timeout_s if timeout_s is _DEFAULT else timeout_s
        try:
            with urllib.request.urlopen(req, timeout=timeout) as resp:
                return resp.getcode(), resp.read()
        except urllib.error.HTTPError as e:
            try:
                payload = e.read()
            except OSError:
                payload = b""
            return e.code, payload
        except urllib.error.URLError as e:
            if isinstance(e.reason, (socket.timeout, TimeoutError)):
                raise TransportError(
                    "timeout", f"{method} {path} overran "
                               f"{timeout}s") from e
            raise TransportError(
                "unreachable", f"{method} {path}: {e.reason}") from e
        except (socket.timeout, TimeoutError) as e:
            raise TransportError(
                "timeout", f"{method} {path} overran {timeout}s") from e
        except http.client.HTTPException as e:
            raise TransportError(
                "proto", f"{method} {path}: {e!r}") from e
        except ConnectionError as e:
            raise TransportError(
                "unreachable", f"{method} {path}: {e}") from e

    def _call(self, op: str, method: str, path: str, body=None,
              ok: Sequence[int] = (200,),
              retry_status: Sequence[int] = (500, 502, 503)):
        """Retrying wrapper: ``retries`` extra attempts over the shared
        bounded backoff, ``net`` telemetry on resolution (rate-limited
        per op+outcome — a partition must not flood the stream at the
        heartbeat cadence)."""
        attempts = self.retries + 1
        err: Optional[TransportError] = None
        t0 = time.perf_counter()
        for attempt in range(1, attempts + 1):
            try:
                status, payload = self._request(method, path, body=body)
            except TransportError as e:
                err = e
                if e.reason == "unreachable":
                    # The address may be stale (server restarted on a
                    # new port): drop the cache so the next attempt
                    # re-resolves.
                    with self._lock:
                        self._addr = None
            else:
                if status in ok:
                    self._note(op, True, attempt,
                               time.perf_counter() - t0, status=status)
                    return status, payload
                err = TransportError(
                    f"http_{status}",
                    f"{method} {path} -> {status}: {payload[:200]!r}")
                if status not in retry_status:
                    break
            if attempt < attempts:
                time.sleep(backoff.delay_s(0.05, 0.5, attempt))
        self._note(op, False, attempts, time.perf_counter() - t0,
                   error=err.reason)
        raise err

    def _note(self, op: str, ok: bool, attempts: int, secs: float,
              status=None, error=None) -> None:
        if self._log is None:
            return
        key = (op, error or "ok")
        now = time.time()
        if now - self._last_note.get(key, 0.0) < 1.0:
            return
        self._last_note[key] = now
        self._log("net", op=op, ok=ok, ms=round(secs * 1000.0, 3),
                  attempts=attempts, status=status, error=error,
                  wallclock=round(now, 3))

    # -- operations (paths are RELATIVE to the coordination dir) ----------

    @staticmethod
    def _q(rel: str) -> str:
        return urllib.parse.quote(rel, safe="/")

    def get(self, rel: str) -> Optional[bytes]:
        status, payload = self._call("get", "GET", "/kv/" + self._q(rel),
                                     ok=(200, 404))
        return None if status == 404 else payload

    def put(self, rel: str, data: bytes) -> None:
        self._call("put", "PUT", "/kv/" + self._q(rel), body=data)

    def delete(self, rel: str) -> None:
        self._call("delete", "DELETE", "/kv/" + self._q(rel),
                   ok=(200, 404))

    def scan(self, rel: str) -> Dict[str, str]:
        """All ``*.json`` files directly under ``rel``, name → raw
        text, in ONE round trip (``read_all`` must not pay a request
        per peer)."""
        _, payload = self._call("scan", "GET", "/scan/" + self._q(rel))
        try:
            return dict(json.loads(payload)["files"])
        except (ValueError, TypeError, KeyError) as e:
            raise TransportError("proto", f"undecodable scan of "
                                          f"{rel!r}: {e}")

    def list_dir(self, rel: str) -> List[str]:
        _, payload = self._call("list", "GET", "/list/" + self._q(rel))
        try:
            return list(json.loads(payload)["names"])
        except (ValueError, TypeError, KeyError) as e:
            raise TransportError("proto", f"undecodable listing of "
                                          f"{rel!r}: {e}")

    def rename(self, src: str, dst: str) -> None:
        body = json.dumps({"src": src, "dst": dst}).encode()
        self._call("rename", "POST", "/rename", body=body)

    def delete_tree(self, rel: str) -> None:
        self._call("delete_tree", "DELETE", "/tree/" + self._q(rel),
                   ok=(200, 404))

    def post_fault(self, kind: str, isolate: Sequence[int],
                   duration_s: Optional[float] = None) -> Dict:
        """Arm a network fault ON THE SERVER (utils/netfaults.py). The
        injector calls this from the process being isolated — the arm
        request itself must land before the fault takes effect."""
        doc = {"kind": kind, "isolate": list(isolate)}
        if duration_s is not None:
            doc["duration_s"] = float(duration_s)
        _, payload = self._call("fault", "POST", "/fault",
                                body=json.dumps(doc).encode())
        try:
            return dict(json.loads(payload))
        except (ValueError, TypeError) as e:
            raise TransportError("proto", f"undecodable fault ack: {e}")

    def healthz(self) -> bool:
        try:
            self._call("healthz", "GET", "/healthz")
            return True
        except TransportError:
            return False


class _CoordHTTPServer(ThreadingHTTPServer):
    # Handler threads may be parked forever inside an armed partition
    # hold; they must neither outlive-block process exit nor stall
    # server_close().
    daemon_threads = True
    block_on_close = False
    coord_root = ""
    coord_stopping = False


class _CoordHandler(BaseHTTPRequestHandler):
    """File-gateway endpoints over the coordination directory:

    ``GET/PUT/DELETE /kv/<rel>`` (octet-stream; writes are atomic
    tmp→rename server-side), ``GET /scan/<rel>`` (every ``*.json``
    under a dir in one response), ``GET /list/<rel>``,
    ``POST /rename`` ``{src, dst}`` (the peer-replica commit),
    ``DELETE /tree/<rel>``, ``GET /healthz``, ``POST /fault``
    (arm utils/netfaults.py state)."""

    server_version = "DMLCoord/1.0"

    def log_message(self, fmt, *args):  # quiet: telemetry is JSONL
        pass

    # -- helpers ----------------------------------------------------------

    def _pid(self) -> Optional[int]:
        raw = self.headers.get(PROC_HEADER)
        try:
            return int(raw) if raw is not None else None
        except ValueError:
            return None

    def _gate(self) -> Optional[str]:
        """Armed-fault gate, consulted once per request. Returns the
        write mode (``"ok"`` / ``"dup"``) or None when the request was
        consumed by the fault (held or dropped)."""
        action = netfaults.server_action(self._pid())
        if action[0] == "hold":
            # A partitioned link eats the reply: hold the connection
            # and NEVER answer. The client's socket timeout is what
            # bounds this — strip it (--plant no_net_timeout) and the
            # caller hangs to the chaos deadline, by design.
            while not self.server.coord_stopping:
                time.sleep(0.05)
            return None
        if action[0] == "drop":
            self._json(503, {"error": "injected_drop"})
            return None
        if action[0] == "delay":
            time.sleep(action[1])
            return "ok"
        return action[0]

    def _safe(self, rel: str) -> str:
        root = self.server.coord_root
        p = os.path.normpath(os.path.join(root, rel))
        if p != root and not p.startswith(root + os.sep):
            raise ValueError(f"path escapes coordination dir: {rel!r}")
        return p

    def _body(self) -> bytes:
        length = int(self.headers.get("Content-Length") or 0)
        return self.rfile.read(length) if length else b""

    def _reply(self, status: int, body: bytes, ctype: str) -> None:
        try:
            self.send_response(status)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        except OSError:
            pass  # client gave up (timed out) — nothing to tell it

    def _json(self, status: int, doc) -> None:
        self._reply(status, json.dumps(doc).encode(),
                    "application/json")

    # -- verbs ------------------------------------------------------------

    def do_GET(self):
        if self._gate() is None:
            return
        try:
            if self.path == "/healthz":
                return self._json(200, {"ok": True})
            if self.path.startswith("/kv/"):
                target = self._safe(
                    urllib.parse.unquote(self.path[len("/kv/"):]))
                try:
                    with open(target, "rb") as f:
                        payload = f.read()
                except OSError:
                    return self._json(404, {"error": "not_found"})
                return self._reply(200, payload,
                                   "application/octet-stream")
            if self.path.startswith("/scan/"):
                d = self._safe(
                    urllib.parse.unquote(self.path[len("/scan/"):]))
                files: Dict[str, str] = {}
                try:
                    names = os.listdir(d)
                except OSError:
                    names = []
                for name in names:
                    if not name.endswith(".json"):
                        continue
                    try:
                        with open(os.path.join(d, name)) as f:
                            files[name] = f.read()
                    except OSError:
                        continue  # mid-rename; self-heals next poll
                return self._json(200, {"files": files})
            if self.path.startswith("/list/"):
                d = self._safe(
                    urllib.parse.unquote(self.path[len("/list/"):]))
                try:
                    names = sorted(os.listdir(d))
                except OSError:
                    names = []
                return self._json(200, {"names": names})
            return self._json(400, {"error": "bad_path"})
        except ValueError as e:
            return self._json(400, {"error": str(e)[:200]})

    def do_PUT(self):
        mode = self._gate()
        if mode is None:
            return
        if not self.path.startswith("/kv/"):
            return self._json(400, {"error": "bad_path"})
        try:
            target = self._safe(
                urllib.parse.unquote(self.path[len("/kv/"):]))
        except ValueError as e:
            return self._json(400, {"error": str(e)[:200]})
        payload = self._body()
        # A net_dup window applies the write twice: duplicate delivery
        # must be invisible because every commit is an atomic replace.
        for _ in range(2 if mode == "dup" else 1):
            os.makedirs(os.path.dirname(target), exist_ok=True)
            tmp = target + f".tmp{os.getpid()}.{threading.get_ident()}"
            with open(tmp, "wb") as f:
                f.write(payload)
            os.replace(tmp, target)
        return self._json(200, {"ok": True, "dup": mode == "dup"})

    def do_POST(self):
        mode = self._gate()
        if mode is None:
            return
        if self.path == "/fault":
            try:
                doc = json.loads(self._body())
                rec = netfaults.arm(doc["kind"],
                                    doc.get("isolate") or [],
                                    duration_s=doc.get("duration_s"))
            except (ValueError, TypeError, KeyError) as e:
                return self._json(400, {"error": str(e)[:200]})
            return self._json(200, {k: rec[k] for k in
                                    ("kind", "isolate", "duration_s",
                                     "until")})
        if self.path == "/rename":
            try:
                doc = json.loads(self._body())
                src = self._safe(str(doc["src"]))
                dst = self._safe(str(doc["dst"]))
            except (ValueError, TypeError, KeyError) as e:
                return self._json(400, {"error": str(e)[:200]})
            try:
                for _ in range(2 if mode == "dup" else 1):
                    if os.path.isdir(src):
                        os.rename(src, dst)  # dir commit (peerstore)
                    else:
                        os.replace(src, dst)
            except OSError as e:
                return self._json(404, {"error": str(e)[:200]})
            return self._json(200, {"ok": True})
        return self._json(400, {"error": "bad_path"})

    def do_DELETE(self):
        if self._gate() is None:
            return
        try:
            if self.path.startswith("/kv/"):
                target = self._safe(
                    urllib.parse.unquote(self.path[len("/kv/"):]))
                try:
                    os.remove(target)
                except FileNotFoundError:
                    return self._json(404, {"error": "not_found"})
                except OSError as e:
                    return self._json(500, {"error": str(e)[:200]})
                return self._json(200, {"ok": True})
            if self.path.startswith("/tree/"):
                target = self._safe(
                    urllib.parse.unquote(self.path[len("/tree/"):]))
                shutil.rmtree(target, ignore_errors=True)
                return self._json(200, {"ok": True})
            return self._json(400, {"error": "bad_path"})
        except ValueError as e:
            return self._json(400, {"error": str(e)[:200]})


class CoordServer:
    """The coordination service: an HTTP gateway over one directory,
    hosted by the server-side process (lowest cluster process id /
    fleet controller). Publishes its address via atomic rename of
    ``coord_addr.json`` into the directory it serves."""

    def __init__(self, coord_dir: str, host: str = "127.0.0.1",
                 port: int = 0):
        os.makedirs(coord_dir, exist_ok=True)
        self.coord_dir = os.path.abspath(coord_dir)
        self._httpd = _CoordHTTPServer((host, port), _CoordHandler)
        self._httpd.coord_root = self.coord_dir
        self._httpd.coord_stopping = False
        self.host = self._httpd.server_address[0]
        self.port = int(self._httpd.server_address[1])
        addr_path = os.path.join(self.coord_dir, ADDR_FILENAME)
        tmp = addr_path + f".tmp{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump({"host": self.host, "port": self.port}, f)
        os.replace(tmp, addr_path)
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            kwargs={"poll_interval": 0.1}, daemon=True,
            name="coord-server")
        self._thread.start()

    def stop(self) -> None:
        self._httpd.coord_stopping = True
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=2.0)


class NetHeartbeatStore:
    """The :class:`~dml_cnn_cifar10_tpu.parallel.cluster.HeartbeatStore`
    contract over :class:`CoordClient`.

    Failure mapping is the whole design: a publish that cannot reach
    the coordinator is swallowed (the classified ``net`` record is the
    trace) — from the rest of the cluster this process simply stops
    beating, which is what a partitioned host IS. A read that cannot
    reach the coordinator returns None/empty — from this process every
    peer looks absent, and the watchdog ages them from ``started_at``
    into the ordinary ``peer_lost`` path. No caching: a partition must
    look like silence, not like a frozen-but-fresh world."""

    def __init__(self, cluster_dir: str, process_id: int,
                 client: CoordClient, log_fn=None):
        self.dir = os.path.join(cluster_dir, "heartbeats")
        self.process_id = process_id
        self.client = client
        self.started_at = time.time()
        self._log = log_fn
        self._last_decode_note: Dict[str, float] = {}

    def _rel(self, pid: int) -> str:
        return f"heartbeats/proc_{pid}.json"

    def publish(self, step: int, phase: str,
                extra: Optional[Dict] = None) -> "cluster_lib.Beat":
        beat = cluster_lib.Beat(self.process_id, int(step), time.time(),
                                phase, extra=extra)
        try:
            self.client.put(self._rel(self.process_id),
                            json.dumps(dataclasses.asdict(beat)).encode())
        except TransportError:
            pass  # classified by the client's net record; stay silent
        return beat

    def read(self, pid: int) -> Optional["cluster_lib.Beat"]:
        try:
            payload = self.client.get(self._rel(pid))
        except TransportError:
            return None
        if payload is None:
            return None
        try:
            return cluster_lib.Beat(**json.loads(payload))
        except (ValueError, TypeError):
            return None

    def read_peers(self, expected: Sequence[int]
                   ) -> Dict[int, Optional["cluster_lib.Beat"]]:
        return {pid: self.read(pid) for pid in expected
                if pid != self.process_id}

    def _note_decode(self, path: str, error: str) -> None:
        if self._log is None:
            return
        now = time.time()
        if now - self._last_decode_note.get(path, 0.0) < 1.0:
            return
        self._last_decode_note[path] = now
        self._log("beat_decode_error", path=path, error=error[:200])

    def read_all(self) -> Dict[int, "cluster_lib.Beat"]:
        try:
            files = self.client.scan("heartbeats")
        except TransportError:
            return {}
        out: Dict[int, cluster_lib.Beat] = {}
        for name, text in files.items():
            if not (name.startswith("proc_") and name.endswith(".json")):
                continue
            try:
                pid = int(name[len("proc_"):-len(".json")])
            except ValueError:
                continue
            try:
                out[pid] = cluster_lib.Beat(**json.loads(text))
            except (ValueError, TypeError) as e:
                self._note_decode(f"heartbeats/{name}", str(e))
        return out


class NetRestartCoordinator:
    """The :class:`~dml_cnn_cifar10_tpu.parallel.cluster.RestartCoordinator`
    contract over :class:`CoordClient`: same payload, same sha256
    sidecar, same payload→sidecar commit order (each PUT is an atomic
    replace server-side), same monotone-epoch rule.

    The one new failure mode — the coordinator is unreachable — maps
    onto the existing protocol: ``read`` reports the decision absent
    (poll loops self-heal, ``await_decision`` times out into the
    coordinator-lost ``PeerLostError``), and ``record`` raises
    :class:`~dml_cnn_cifar10_tpu.parallel.cluster.EvictedError` after
    the bounded retries — a chief that cannot commit is cut off from
    the world it is deciding for, and fencing (or, under
    ``elastic_expand``, rejoining once the partition heals) is the only
    split-brain-free move."""

    REL = "restart_decision.json"

    def __init__(self, cluster_dir: str, client: CoordClient,
                 log_fn=None):
        self.path = os.path.join(cluster_dir, self.REL)
        self.sidecar_path = self.path + ".sha256"
        self.client = client
        self._log = log_fn
        self._last_bad_digest: Optional[str] = None

    def _note_corrupt(self, digest: str, error: str) -> None:
        if digest == self._last_bad_digest:
            return
        self._last_bad_digest = digest
        print(f"[cluster] corrupt restart decision {self.path}: "
              f"{error}; reading as absent", file=sys.stderr)
        if self._log is not None:
            self._log("decision_corrupt", path=self.path, error=error)

    def read(self) -> Optional["cluster_lib.RestartDecision"]:
        try:
            payload = self.client.get(self.REL)
        except TransportError:
            return None
        if payload is None:
            return None
        digest = hashlib.sha256(payload).hexdigest()
        want = None
        try:
            sidecar = self.client.get(self.REL + ".sha256")
        except TransportError:
            sidecar = None  # answered for payload, lost for sidecar:
            #                 treat as mid-commit, self-heal next poll
        if sidecar is not None:
            try:
                want = json.loads(sidecar)["digest"]
            except (ValueError, TypeError, KeyError) as e:
                self._note_corrupt(digest, f"undecodable sidecar: {e}")
                return None
        if want is not None and want != digest:
            self._note_corrupt(
                digest, f"sidecar digest mismatch (have {digest[:12]}…, "
                        f"sidecar says {str(want)[:12]}…)")
            return None
        try:
            return cluster_lib.RestartDecision(**json.loads(payload))
        except (ValueError, TypeError) as e:
            self._note_corrupt(digest, f"undecodable decision: {e}")
            return None

    def record(self, decision: "cluster_lib.RestartDecision"
               ) -> "cluster_lib.RestartDecision":
        prior = self.read()
        if prior is not None and prior.epoch >= decision.epoch:
            # Decision race: this seat classified a failure and decided
            # while ANOTHER seat's decision for the same (or a newer)
            # epoch was already committed — the partitioned-minority
            # case, where the majority's shrink landed while our reads
            # were timing out. The committed file wins, always:
            # excluded → the fence/rejoin path (exactly what a healed
            # minority must do); included → adopt the committed world
            # instead of racing it. Unlike the file coordinator's
            # monotone ValueError, this is a REACHABLE runtime state
            # under net, not a programming error.
            if self.client.process_id not in prior.survivors:
                raise cluster_lib.EvictedError(
                    f"decision race lost: epoch {prior.epoch} already "
                    f"committed excluding process "
                    f"{self.client.process_id} (was recording epoch "
                    f"{decision.epoch}); fencing")
            return prior
        payload = json.dumps(dataclasses.asdict(decision)).encode()
        sidecar = json.dumps(
            {"algo": "sha256",
             "digest": hashlib.sha256(payload).hexdigest()}).encode()
        try:
            self.client.put(self.REL, payload)
            self.client.put(self.REL + ".sha256", sidecar)
        except TransportError as e:
            raise cluster_lib.EvictedError(
                f"cut off from the coordination service while "
                f"recording epoch {decision.epoch} ({e.reason}); "
                f"fencing") from e
        return decision

    def await_decision(self, min_epoch: int, timeout_s: float,
                       poll_s: float = 0.05
                       ) -> "cluster_lib.RestartDecision":
        deadline = time.time() + timeout_s
        attempt = 0
        while True:
            d = self.read()
            if d is not None and d.epoch >= min_epoch:
                return d
            if time.time() > deadline:
                raise cluster_lib.PeerLostError(
                    [0], f"no restart decision at epoch >= {min_epoch} "
                         f"within {timeout_s:.1f}s — coordinator lost")
            attempt += 1
            time.sleep(backoff.delay_s(poll_s, poll_s * 10.0, attempt))
