"""The compiled SPMD training step.

One ``jit``-compiled function replaces the reference's per-step machinery —
graph pruning/partitioning, PS→worker param Recv, worker compute,
worker→PS grad Send, PS apply (``cifar10cnn.py:228-230`` and SURVEY §3.3).
Parameters are replicated over the mesh, the batch is sharded on ``data``,
and XLA compiles the gradient all-reduce (psum over ICI) directly into the
step. Two modes:

- default: ``jit`` with sharding annotations; the partitioner inserts the
  collectives (idiomatic, composes with tensor/sequence axes).
- ``explicit_collectives``: the same math under ``shard_map`` with a literal
  ``lax.psum``/``lax.pmean`` — the hand-written SPMD form, used by tests to
  pin down the semantics and as the template for custom-collective work.

Three weight-update paths exist, with PINNED (tested) equivalence
tolerances — see PARITY.md "Update-path equivalence":

- replicated (the default) vs ``explicit_collectives``: bit-identical
  (``test_step.py`` asserts exact equality — same reduction schedule).
- ``--optimizer_sharding zero1`` (reduce-scatter / sharded update /
  all-gather) vs replicated: final params within 1e-6 absolute
  (``test_zero1.py`` — the reduce-scatter may reorder the gradient sum).
- the fused single-pass optimizer (``ops/optimizer.py``) vs the
  ``tree_map`` chain: the XLA form is bit-identical (same f32
  elementwise expression); the Pallas kernel is within a few f32 ULPs
  of it (≤ 5e-7 absolute — FMA contraction differences; both pinned in
  ``test_zero1.py``).

Every mode donates the input state so parameter memory is updated in
place in HBM.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dml_cnn_cifar10_tpu.parallel.compat import shard_map

from dml_cnn_cifar10_tpu.compilecache import mesh_context
from dml_cnn_cifar10_tpu.compilecache import wrap as _cc_wrap
from dml_cnn_cifar10_tpu.config import DataConfig, ModelConfig, OptimConfig
from dml_cnn_cifar10_tpu.models.registry import ModelDef
from dml_cnn_cifar10_tpu.parallel import mesh as mesh_lib
from dml_cnn_cifar10_tpu.parallel import shardings as shardings_lib
from dml_cnn_cifar10_tpu.train import loss as loss_lib
from dml_cnn_cifar10_tpu.train import metrics as metrics_lib
from dml_cnn_cifar10_tpu.train import optim as optim_lib


class TrainState(NamedTuple):
    """Replicated training state: params + optimizer + model state (BN).

    NamedTuple => already a pytree; flows through jit/shard_map/device_put.
    """

    params: Any
    opt: Any
    model_state: Any

    @property
    def step(self) -> jax.Array:
        return self.opt["step"]


def init_train_state(
    key: jax.Array,
    model_def: ModelDef,
    model_cfg: ModelConfig,
    data_cfg: DataConfig,
    optim_cfg: OptimConfig,
    mesh: Optional[Mesh] = None,
    state_sharding: Optional[TrainState] = None,
    compile_cache=None,
) -> TrainState:
    """Initialize params/opt/model-state and place them on the mesh.

    Replaces chief-initializes-variables-on-PS + workers-wait
    (``cifar10cnn.py:222`` via MonitoredTrainingSession): under SPMD every
    process runs the same deterministic init from the same seed, and the
    mesh placement guarantees consistent values on every chip.

    Placement defaults to replicated — symmetric with ``make_train_step``'s
    default in_shardings. For tensor parallelism pass the SAME
    ``train_state_shardings`` tree to both (as ``Trainer`` does).

    The whole construction is ONE jitted program when a mesh/sharding is
    given (``out_shardings`` places every leaf directly): initializing a
    deep model leaf-by-leaf eagerly costs one device dispatch per tensor
    — ~60 round trips for a ResNet, ~20 s of pure RTT on a remote-tunnel
    TPU — where the fused init is a single dispatch.
    """
    def build(key):
        params = model_def.init(key, model_cfg, data_cfg)
        opt = optim_lib.sgd_init(params, optim_cfg)
        model_state = model_def.init_state(params)
        if optim_cfg.ema_decay and model_def.has_state and model_state:
            # BatchNorm running stats track the RAW param trajectory; eval
            # with EMA params needs matching averaged stats, so the EMA
            # covers model_state too ("ema_mstate" — replicated like the
            # live model_state by the sharding rules' default).
            opt["ema_mstate"] = jax.tree.map(jnp.array, model_state)
        return TrainState(params=params, opt=opt, model_state=model_state)

    def _cached(jitted):
        # The fused init is a single compiled dispatch — worth caching:
        # a supervisor/elastic restart re-runs it before every restore.
        return _cc_wrap(jitted, compile_cache, "init",
                        mesh_context(mesh, compute_dtype=model_cfg.dtype,
                                     model=model_cfg.name))

    if state_sharding is not None:
        return _cached(jax.jit(build, out_shardings=state_sharding))(key)
    if mesh is not None:
        return _cached(jax.jit(
            build, out_shardings=mesh_lib.replicated(mesh)))(key)
    return build(key)


def train_state_shardings(
    mesh: Mesh,
    model_def: ModelDef,
    model_cfg: ModelConfig,
    data_cfg: DataConfig,
    optim_cfg: OptimConfig,
    fsdp: bool = False,
    zero1: bool = False,
    rules=None,
    strict: bool = False,
) -> TrainState:
    """The ``TrainState`` sharding tree (tensor-parallel rules applied) for
    a model config, computed shape-only via ``eval_shape``. Compute it ONCE
    and hand the same tree to ``make_train_step`` / ``make_eval_step`` /
    ``restore_checkpoint`` — it is the single currency for state layout.
    ``fsdp=True`` adds the ZeRO-3 ``data``-axis sharding of params +
    moments; ``zero1=True`` shards ONLY the optimizer moments (+ EMA)
    over ``data`` (``--optimizer_sharding zero1`` — the state is
    ALLOCATED sharded from init on, which is the HBM win). ``rules`` is
    an optional ``--partition_rules`` table overriding the model's
    default (:mod:`~dml_cnn_cifar10_tpu.parallel.shardings`); ``strict``
    errors on leaves no rule matches."""
    abstract = jax.eval_shape(
        lambda k: init_train_state(k, model_def, model_cfg, data_cfg,
                                   optim_cfg),
        jax.random.key(0))
    return shardings_lib.state_shardings(mesh, model_cfg.name, abstract,
                                         fsdp=fsdp, zero1=zero1,
                                         rules=rules, strict=strict)


def _forward_loss(model_def: ModelDef, model_cfg: ModelConfig,
                  axis_name: Optional[str] = None,
                  mesh: Optional[Mesh] = None,
                  label_smoothing: float = 0.0):
    """loss_fn(params, model_state, images, labels) →
    (loss, (logits, new_model_state, stats)).

    ``stats`` is the auxiliary-metrics dict destined for the step metrics
    stream — ``moe_*`` router health for MoE models (aux loss, dropped
    fraction, [E] per-expert load; round-4 verdict #1), ``{}`` otherwise.
    Pytree structure is static per model config, so it scans/accumulates
    like any other metric.
    """
    mesh_kwargs = {"mesh": mesh} if (model_def.wants_mesh and
                                     mesh is not None) else {}
    ce = functools.partial(loss_lib.softmax_cross_entropy,
                           label_smoothing=label_smoothing)

    def loss_fn(params, model_state, images, labels):
        stats = {}
        if model_def.has_state:
            kwargs = {"axis_name": axis_name} if axis_name else {}
            logits, new_state = model_def.apply(
                params, model_state, images, model_cfg, train=True, **kwargs)
            loss = ce(logits, labels)
        elif model_def.has_aux:
            logits, aux = model_def.apply(params, images, model_cfg,
                                          train=True, **mesh_kwargs)
            new_state = model_state
            if isinstance(aux, dict):
                loss = ce(logits, labels) \
                    + model_cfg.moe_aux_coef * aux["aux_loss"]
                stats = {"moe_" + k: lax.stop_gradient(v)
                         for k, v in aux.items()}
            else:
                loss = ce(logits, labels) \
                    + model_cfg.moe_aux_coef * aux
        else:
            logits = model_def.apply(params, images, model_cfg, train=True,
                                     **mesh_kwargs)
            new_state = model_state
            loss = ce(logits, labels)
        return loss, (logits, new_state, stats)

    return loss_fn


def _fsdp_gather_wrap(loss_fn, mesh: Optional[Mesh], model_cfg: ModelConfig,
                      state_sharding: Optional[TrainState], rules=None):
    """ZeRO-3's gather-before-compute, stated explicitly.

    When the parameter STORAGE layout shards over ``data`` (FSDP), leaving
    the layout implicit lets GSPMD propagate the data-axis weight sharding
    into forward/backward, where it meets batch-over-``data`` activations
    at reshape boundaries the partitioner cannot reshard efficiently (the
    "Involuntary full rematerialization" the 8-device dryrun surfaced on
    the CNN's flatten↔conv edge). Constraining params to their base
    (tensor-parallel-only) layout at the point of use compiles to one
    all-gather per step before compute; the constraint's transpose applies
    the same layout to the gradient cotangents, and XLA's
    all-reduce-reassociation turns the grad psum + storage-layout slice
    back into a reduce-scatter — exactly the ZeRO-3 schedule.
    """
    if mesh is None or state_sharding is None:
        return loss_fn
    if not shardings_lib.specs_name_axis(state_sharding.params, "data"):
        return loss_fn
    pipe = mesh.shape.get("pipe", 1) > 1

    def gathered(params, model_state, images, labels):
        specs = shardings_lib.param_pspecs(model_cfg.name, params,
                                           pipe=pipe, rules=rules)
        shs = jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                           is_leaf=lambda x: isinstance(x, P))
        params = lax.with_sharding_constraint(params, shs)
        return loss_fn(params, model_state, images, labels)

    return gathered


def _zero1_update(mesh: Mesh, model_cfg: ModelConfig,
                  optim_cfg: OptimConfig, rules=None):
    """The ZeRO-1 weight-update schedule (arxiv 2004.13336), stated as
    sharding constraints: ``(grads, opt, params) -> (new_params,
    new_opt)``.

    Gradients are constrained to the ``data``-sharded layout of the
    optimizer moments, which — composed with the batch-sharded loss's
    gradient psum — XLA's all-reduce reassociation compiles to a
    REDUCE-SCATTER over ``data``; the optimizer update then runs on 1/N
    of the param bytes per replica (the moments live sharded, so the
    elementwise update partitions to match), and constraining the new
    params back to their base (tensor-parallel-only) layout compiles to
    the ALL-GATHER that rebuilds the full weights for the next forward.
    Same math as the replicated update to reduction-reorder tolerance
    (pinned ≤ 1e-6 by ``test_zero1.py``; PARITY.md)."""
    ndata = mesh.shape["data"]
    pipe = mesh.shape.get("pipe", 1) > 1

    def named(specs):
        return jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                            is_leaf=lambda x: isinstance(x, P))

    def update(grads, opt, params):
        shard_sh = named(shardings_lib.param_pspecs(
            model_cfg.name, params, pipe=pipe, fsdp_data=ndata,
            rules=rules))
        base_sh = named(shardings_lib.param_pspecs(
            model_cfg.name, params, pipe=pipe, rules=rules))
        grads = lax.with_sharding_constraint(grads, shard_sh)
        # pallas_ok=False: the update operands are data-sharded here —
        # the XLA expression is what GSPMD partitions into the 1/N
        # per-replica update (ops/optimizer.py module docstring).
        new_params, new_opt = optim_lib.sgd_update(grads, opt, params,
                                                   optim_cfg,
                                                   pallas_ok=False)
        new_params = lax.with_sharding_constraint(new_params, base_sh)
        return new_params, new_opt

    return update


def _global_norm(tree) -> jax.Array:
    """L2 norm over every leaf of a pytree (f32 accumulation so bf16
    params/grads don't overflow the sum of squares)."""
    leaves = jax.tree.leaves(tree)
    if not leaves:
        return jnp.zeros((), jnp.float32)
    return jnp.sqrt(sum(jnp.sum(jnp.square(l.astype(jnp.float32)))
                        for l in leaves))


def _health_stats(params, new_params, grads) -> dict:
    """Training-health scalars, compiled into the step so they ride the
    loop's single fused boundary fetch: global grad norm (exploding /
    vanishing gradients), param norm (weight growth / decay balance), and
    update ratio ||Δθ||/||θ|| (the effective step size — healthy runs sit
    around 1e-3; ~1 means the optimizer is overwriting the weights)."""
    pnorm = _global_norm(params)
    unorm = _global_norm(jax.tree.map(
        lambda a, b: a.astype(jnp.float32) - b.astype(jnp.float32),
        new_params, params))
    return {"health_grad_norm": _global_norm(grads),
            "health_param_norm": pnorm,
            "health_update_ratio": unorm / (pnorm + 1e-12)}


def _step_body(loss_fn, optim_cfg: OptimConfig,
               health_metrics: bool = False, update_fn=None,
               pallas_ok=None):
    """``(state, images, labels) -> (new_state, metrics)`` — the shared
    grad/update/metrics math of ``make_train_step`` and
    ``make_train_chunk`` (one source of truth for both).

    ``optim_cfg.grad_accum > 1`` scans over that many microbatches,
    averaging grads/metrics, then applies ONE optimizer update — the same
    math as the full batch (equal-sized microbatches ⇒ mean of means) in
    1/accum of the activation memory.

    ``update_fn(grads, opt, params) -> (new_params, new_opt)`` overrides
    the plain ``optim_lib.sgd_update`` apply — the ZeRO-1 schedule
    (:func:`_zero1_update`) rides this seam; the default is the
    replicated update. ``pallas_ok=False`` vetoes the fused optimizer's
    Pallas lowering (see :func:`_pallas_veto`).
    """
    accum = max(1, optim_cfg.grad_accum)
    if update_fn is None:
        def update_fn(grads, opt, params):
            return optim_lib.sgd_update(grads, opt, params, optim_cfg,
                                        pallas_ok=pallas_ok)

    def grad_and_metrics(params, model_state, images, labels):
        # named_scope prefixes the emitted ops so a --profile_at_steps
        # device-time table (utils/devprof.py) can attribute fwd/bwd
        # work vs the optimizer update by name; no numeric effect.
        with jax.named_scope("fwd_bwd"):
            (loss, (logits, new_model_state, stats)), grads = \
                jax.value_and_grad(loss_fn, has_aux=True)(
                    params, model_state, images, labels)
            acc = metrics_lib.batch_accuracy(logits, labels)
        metrics = {"loss": loss, "accuracy": acc, **stats}
        return grads, metrics, new_model_state

    staleness = max(0, optim_cfg.async_staleness)

    def step(state: TrainState, images, labels):
        # Async-PS staleness emulation: the forward/backward runs at a
        # snapshot S-1 updates old (slot t%S of the ring), the update
        # applies to the LIVE params — exactly a PS worker whose fetch
        # raced S-1 other workers' applies (cifar10cnn.py:162,230;
        # SURVEY §3.3), made deterministic.
        if staleness >= 2:
            slot = state.opt["step"] % staleness
            fwd_params = jax.tree.map(
                lambda b: lax.dynamic_index_in_dim(b, slot, 0,
                                                   keepdims=False),
                state.opt["stale"])
        else:
            fwd_params = state.params
        if accum == 1:
            grads, metrics, new_model_state = grad_and_metrics(
                fwd_params, state.model_state, images, labels)
        else:
            b = images.shape[0]
            if b % accum:
                raise ValueError(
                    f"batch {b} not divisible by grad_accum {accum}")
            ims = images.reshape(accum, b // accum, *images.shape[1:])
            lbs = labels.reshape(accum, b // accum)

            def micro(carry, xs):
                gsum, msum, mstate = carry
                g, m, mstate = grad_and_metrics(fwd_params, mstate,
                                                xs[0], xs[1])
                return (jax.tree.map(jnp.add, gsum, g),
                        jax.tree.map(jnp.add, msum, m), mstate), None

            # Trace-time structure of the metrics dict (loss/accuracy +
            # any model stats) so the scan carry starts from zeros of the
            # right pytree.
            m_abs = jax.eval_shape(grad_and_metrics, fwd_params,
                                   state.model_state, ims[0], lbs[0])[1]
            zeros = jax.tree.map(jnp.zeros_like, state.params)
            zeros_m = jax.tree.map(
                lambda s: jnp.zeros(s.shape, s.dtype), m_abs)
            (gsum, msum, new_model_state), _ = lax.scan(
                micro, (zeros, zeros_m, state.model_state), (ims, lbs))
            grads = jax.tree.map(lambda g: g / accum, gsum)
            metrics = jax.tree.map(lambda v: v / accum, msum)
        with jax.named_scope("optimizer"):
            new_params, new_opt = update_fn(grads, state.opt, state.params)
        if health_metrics:
            metrics.update(_health_stats(state.params, new_params, grads))
        if staleness >= 2:
            # The slot just consumed receives the freshly updated params
            # (the worker pushes its apply and re-fetches).
            new_opt["stale"] = jax.tree.map(
                lambda b, p: lax.dynamic_update_index_in_dim(
                    b, p.astype(b.dtype), slot, 0),
                state.opt["stale"], new_params)
        if "ema_mstate" in state.opt:
            d = optim_lib.ema_decay_at(optim_cfg, new_opt["step"])
            new_opt["ema_mstate"] = jax.tree.map(
                lambda e, m: (d * e + (1 - d) * m).astype(e.dtype),
                state.opt["ema_mstate"], new_model_state)
        return TrainState(new_params, new_opt, new_model_state), metrics

    return step


def _check_optimizer_sharding(optim_cfg: OptimConfig,
                              explicit_collectives: bool = False) -> None:
    """Reject invalid ``--optimizer_sharding`` combinations at build
    time (every step builder calls this)."""
    mode = getattr(optim_cfg, "optimizer_sharding", "none")
    if mode not in ("none", "zero1"):
        raise ValueError(
            f"optimizer_sharding={mode!r} must be one of none | zero1")
    if mode == "zero1":
        if explicit_collectives:
            raise ValueError(
                "optimizer_sharding=zero1 needs the GSPMD (default) "
                "step: the explicit_collectives shard_map path applies "
                "the update replicated per device")
        if optim_cfg.async_staleness >= 2:
            raise ValueError(
                "optimizer_sharding=zero1 does not compose with "
                "async_staleness: the snapshot ring serves the forward "
                "pass and must stay whole, but zero1 shards the update "
                "state it is refreshed from")


def _maybe_zero1(mesh: Optional[Mesh], model_cfg: ModelConfig,
                 optim_cfg: OptimConfig, rules=None):
    """The ZeRO-1 update override when configured and meaningful
    (a mesh exists), else None (plain replicated update)."""
    if mesh is None or \
            getattr(optim_cfg, "optimizer_sharding", "none") != "zero1":
        return None
    return _zero1_update(mesh, model_cfg, optim_cfg, rules=rules)


def _pallas_veto(state_sharding: Optional[TrainState]):
    """``pallas_ok`` for the fused optimizer: ``False`` when the update
    operands are GSPMD-sharded (tp/fsdp/pipe/seq param layout) — a
    ``pallas_call`` is an opaque custom call the partitioner cannot
    split, so a sharded update must stay on the (identical-math,
    partitionable) XLA expression. ``None`` (platform default) when
    params are replicated."""
    if state_sharding is None:
        return None
    if any(shardings_lib.specs_name_axis(state_sharding.params, ax)
           for ax in ("model", "pipe", "seq", "data")):
        return False
    return None


def make_train_step(
    model_def: ModelDef,
    model_cfg: ModelConfig,
    optim_cfg: OptimConfig,
    mesh: Optional[Mesh] = None,
    explicit_collectives: bool = False,
    state_sharding: Optional[TrainState] = None,
    health_metrics: bool = False,
    compile_cache=None,
    rules=None,
) -> Callable[[TrainState, jax.Array, jax.Array],
              Tuple[TrainState, dict]]:
    """Build the jitted train step:
    ``(state, images, labels) -> (new_state, {"loss", "accuracy"})``.

    ``state_sharding`` (a ``train_state_shardings`` tree) keeps weights
    partitioned per the model's tensor-parallel rules
    (:mod:`~dml_cnn_cifar10_tpu.parallel.shardings`); ``None`` means
    replicated state — identical layout when the ``model`` axis is 1.
    ``rules`` is the optional ``--partition_rules`` table (must match
    the one ``state_sharding`` was built with).
    """
    _check_optimizer_sharding(optim_cfg, explicit_collectives)

    if explicit_collectives and mesh is not None:
        if (mesh.shape["model"] * mesh.shape["seq"]
                * mesh.shape.get("pipe", 1)) > 1:
            raise ValueError(
                "explicit_collectives is the pedagogical dp-only path; "
                "tensor/sequence/pipeline axes need the GSPMD (default) step")
        if optim_cfg.grad_accum > 1:
            raise ValueError(
                "grad_accum > 1 is not implemented on the "
                "explicit_collectives path; use the GSPMD (default) step")
        if optim_cfg.async_staleness >= 2:
            raise ValueError(
                "async_staleness needs the GSPMD (default) step, not "
                "explicit_collectives")
        return _make_explicit_train_step(model_def, model_cfg, optim_cfg,
                                         mesh, health_metrics=health_metrics)

    if (optim_cfg.async_staleness >= 2 and mesh is not None
            and mesh.shape.get("pipe", 1) > 1):
        # The pipe layout rule shards the LEADING axis of stacked
        # leaves, which for the stale ring is the snapshot axis S, not
        # depth — the layouts conflict. (Pipelined async emulation has
        # no meaningful reference counterpart either.)
        raise ValueError(
            "async_staleness does not compose with pipeline parallelism "
            "(the pipe sharding rule would claim the snapshot ring's "
            "leading axis)")

    loss_fn = _fsdp_gather_wrap(
        _forward_loss(model_def, model_cfg, mesh=mesh,
                      label_smoothing=optim_cfg.label_smoothing),
        mesh, model_cfg, state_sharding, rules=rules)
    step = _step_body(loss_fn, optim_cfg, health_metrics=health_metrics,
                      update_fn=_maybe_zero1(mesh, model_cfg, optim_cfg,
                                             rules),
                      pallas_ok=_pallas_veto(state_sharding))

    def _cached(jitted):
        return _cc_wrap(jitted, compile_cache, "train_step",
                        mesh_context(mesh, donate=(0,),
                                     compute_dtype=model_cfg.compute_dtype,
                                     model=model_cfg.name))

    if mesh is None:
        return _cached(jax.jit(step, donate_argnums=0))
    repl = mesh_lib.replicated(mesh)
    state_sh = state_sharding if state_sharding is not None else repl
    # Conv models use a nontrivial ``seq`` axis for spatial partitioning:
    # the image H dim shards over ``seq`` and GSPMD inserts the conv/pool
    # halo exchanges (the vision analog of sequence parallelism).
    spatial = mesh_lib.spatial_enabled(model_def, mesh)
    data = mesh_lib.batch_sharding(mesh, 4, spatial=spatial)
    lab = mesh_lib.batch_sharding(mesh, 1)
    return _cached(jax.jit(
        step,
        in_shardings=(state_sh, data, lab),
        out_shardings=(state_sh, repl),
        donate_argnums=0,
    ))


def _chunk_body(loss_fn, optim_cfg: OptimConfig,
                data_cfg: Optional[DataConfig],
                health_metrics: bool = False, update_fn=None,
                pallas_ok=None):
    """``(state, images [K,B,...], labels [K,B]) -> (state, last-step
    metrics)`` — the shared scan-over-K-steps math of ``make_train_chunk``
    and ``make_train_chunk_resident`` (one source of truth).

    With ``data_cfg``, images are RAW uint8 and cast/crop/normalize run
    on device first — one vectorized op over the whole [K,B,...] chunk
    BEFORE the scan (uint8 stays a single layout-friendly op, the scan
    then slices float32). Augmented configs fold the global step into the
    data seed so every chunk draws fresh crops/flips, deterministically
    per (seed, step).
    """
    one_step = _step_body(loss_fn, optim_cfg,
                          health_metrics=health_metrics,
                          update_fn=update_fn, pallas_ok=pallas_ok)
    if data_cfg is not None:
        from dml_cnn_cifar10_tpu.ops.preprocess import device_preprocess

    augmented = data_cfg is not None and data_cfg.augmented
    # Whole-chunk decode materializes [K, B, crop, crop, C] float32. At
    # CIFAR geometry that is ~90 MB and the single vectorized op wins; at
    # ImageNet geometry (224², K=100, B=256) it is ~15 GB — past HBM. Past
    # this threshold the decode moves INSIDE the scan: fp32 exists one
    # step at a time, only the uint8 chunk stays whole.
    DECODE_IN_SCAN_BYTES = 1 << 30

    def decode(imgs, step):
        # One source of truth for both size regimes: per-(seed, step) key
        # so draws are distinct and deterministic wherever decode runs.
        if augmented:
            key = jax.random.fold_in(jax.random.key(data_cfg.seed), step)
            return device_preprocess(imgs, data_cfg, key)
        return device_preprocess(imgs, data_cfg)

    def run(state: TrainState, images, labels):
        decode_in_scan = False
        if data_cfg is not None:
            # Peak decode allocation is the float32 view at the LARGER of
            # the source and crop geometry: device_preprocess casts the
            # full-size [K,B,H,W,C] to fp32 before cropping (and the
            # random-crop einsum materializes that operand), while a
            # crop-larger-than-source config pads up instead.
            k, b, h, w = images.shape[:4]
            ph = max(h, data_cfg.crop_height)
            pw = max(w, data_cfg.crop_width)
            decoded = k * b * ph * pw * data_cfg.num_channels * 4
            decode_in_scan = decoded > DECODE_IN_SCAN_BYTES
            if not decode_in_scan:
                images = decode(images, state.step)

        def body(st, batch):
            imgs, lbs = batch
            if decode_in_scan:
                imgs = decode(imgs, st.step)
            return one_step(st, imgs, lbs)

        state, ms = lax.scan(body, state, (images, labels))
        return state, jax.tree.map(lambda x: x[-1], ms)

    return run


def make_train_chunk(
    model_def: ModelDef,
    model_cfg: ModelConfig,
    optim_cfg: OptimConfig,
    mesh: Optional[Mesh] = None,
    state_sharding: Optional[TrainState] = None,
    data_cfg: Optional[DataConfig] = None,
    health_metrics: bool = False,
    compile_cache=None,
    rules=None,
) -> Callable[[TrainState, jax.Array, jax.Array],
              Tuple[TrainState, dict]]:
    """K training steps per dispatch: ``(state, images [K,B,...], labels
    [K,B]) -> (new_state, metrics of the LAST step)``.

    A ``lax.scan`` over stacked batches amortizes per-step host dispatch —
    the small-model regime (the reference CNN is ~1 ms of MXU work per
    step) is dispatch-bound otherwise. Same math as ``make_train_step``
    applied K times; the chunk is the unit the driver hands to the device,
    metrics cadence stays per-chunk.

    With ``data_cfg`` the chunk takes RAW uint8 full-size images
    ([K, B, H, W, C]) and runs cast/crop/normalize on device
    (:func:`~dml_cnn_cifar10_tpu.ops.preprocess.device_preprocess`) — the
    host only shuffles bytes, H2D moves uint8.
    """
    _check_optimizer_sharding(optim_cfg)
    chunk = _chunk_body(
        _fsdp_gather_wrap(
            _forward_loss(model_def, model_cfg, mesh=mesh,
                          label_smoothing=optim_cfg.label_smoothing),
            mesh, model_cfg, state_sharding, rules=rules),
        optim_cfg, data_cfg, health_metrics=health_metrics,
        update_fn=_maybe_zero1(mesh, model_cfg, optim_cfg, rules),
        pallas_ok=_pallas_veto(state_sharding))

    def _cached(jitted):
        return _cc_wrap(jitted, compile_cache, "train_chunk",
                        mesh_context(mesh, donate=(0,),
                                     compute_dtype=model_cfg.compute_dtype,
                                     model=model_cfg.name))

    if mesh is None:
        return _cached(jax.jit(chunk, donate_argnums=0))
    repl = mesh_lib.replicated(mesh)
    state_sh = state_sharding if state_sharding is not None else repl
    spatial = mesh_lib.spatial_enabled(model_def, mesh)
    data = mesh_lib.batch_sharding(mesh, 5, leading_dims=1, spatial=spatial)
    lab = mesh_lib.batch_sharding(mesh, 2, leading_dims=1)
    return _cached(jax.jit(
        chunk,
        in_shardings=(state_sh, data, lab),
        out_shardings=(state_sh, repl),
        donate_argnums=0,
    ))


def make_train_chunk_resident(
    model_def: ModelDef,
    model_cfg: ModelConfig,
    optim_cfg: OptimConfig,
    mesh: Mesh,
    dataset_images: jax.Array,
    dataset_labels: jax.Array,
    state_sharding: Optional[TrainState] = None,
    data_cfg: Optional[DataConfig] = None,
    index_stream: Optional[Tuple[int, int, int]] = None,
    health_metrics: bool = False,
    compile_cache=None,
    rules=None,
) -> Callable[[TrainState, jax.Array], Tuple[TrainState, dict]]:
    """Chunked training against an HBM-resident dataset:
    ``(state, idx [K, B] int32) -> (new_state, metrics of the LAST step)``.

    The decisive TPU-native data-path move for small-sample workloads: the
    full uint8 dataset (CIFAR-10 train = 50k x 3073B = 154 MB) lives in
    HBM once, replicated over the mesh; per chunk the host ships only the
    shuffled **index** array (K*B int32 = ~10 KB), and the gather, decode,
    augment, and K training steps all run on device. Eliminates the
    host-side image gather + 8 MB H2D per chunk that otherwise bound
    throughput (measured ~8 ms/chunk host vs ~0.1-2 ms/chunk device on the
    reference CNN).

    ``dataset_images`` [N, H, W, C] uint8 and ``dataset_labels`` [N] int32
    should be placed replicated on ``mesh`` (``jax.device_put`` with
    ``mesh_lib.replicated``) before building the step. Same math as
    ``make_train_chunk`` on the same indices (tests assert it).

    ``index_stream=(seed, global_batch, K)`` goes one step further
    (round-3 verdict #4): the shuffled indices are GENERATED ON DEVICE
    inside the scan (``data/device_stream.py``'s stateless per-epoch
    pseudo-permutation keyed on ``state.step``), so the chunk signature
    becomes ``(state,) -> (new_state, metrics)`` — a training dispatch
    moves NOTHING host→device. Exact resume is free: the stream position
    is the step itself.
    """
    if data_cfg is None:
        # The resident input is ALWAYS raw uint8 from HBM; without a
        # decode config the model would silently train on 0-255
        # un-cropped pixels.
        raise ValueError(
            "make_train_chunk_resident requires data_cfg (the gathered "
            "dataset rows are raw uint8 and must be decoded on device)")
    _check_optimizer_sharding(optim_cfg)
    loss = _fsdp_gather_wrap(
        _forward_loss(model_def, model_cfg, mesh=mesh,
                      label_smoothing=optim_cfg.label_smoothing),
        mesh, model_cfg, state_sharding, rules=rules)

    spatial = mesh_lib.spatial_enabled(model_def, mesh)
    repl = mesh_lib.replicated(mesh)
    state_sh = state_sharding if state_sharding is not None else repl

    body = _chunk_body(loss, optim_cfg, data_cfg,
                       health_metrics=health_metrics,
                       update_fn=_maybe_zero1(mesh, model_cfg, optim_cfg,
                                              rules),
                       pallas_ok=_pallas_veto(state_sharding))
    gathered_sh = mesh_lib.batch_sharding(mesh, 5, leading_dims=1,
                                          spatial=spatial)

    def _cached(jitted, donate):
        # Wrapped BEFORE the dataset-binding partial: the cache key then
        # covers the dataset avals too (a different split size is a
        # different program). ``fn.cached`` exposes the wrapper so
        # bench.py can read the timed artifact's cost analysis and
        # hit/compile_s record without a second compile.
        return _cc_wrap(jitted, compile_cache, "train_chunk_resident",
                        mesh_context(mesh, donate=(donate,),
                                     compute_dtype=model_cfg.compute_dtype,
                                     model=model_cfg.name))

    if index_stream is not None:
        from dml_cnn_cifar10_tpu.data import device_stream

        seed, global_batch, k = index_stream
        n = dataset_images.shape[0]
        idx_sh2 = mesh_lib.batch_sharding(mesh, 2, leading_dims=1)

        def chunk_dev(ds_images, ds_labels, state: TrainState):
            # The whole chunk's [K, B] indices in one vectorized call
            # from state.step — then the identical whole-chunk gather +
            # vectorized decode as the host-index path (a per-step
            # in-scan gather measured ~10 % slower).
            idx = device_stream.chunk_shuffle_indices(
                seed, state.step, global_batch, k, n)
            idx = lax.with_sharding_constraint(idx, idx_sh2)
            images = ds_images[idx]
            if spatial:
                images = lax.with_sharding_constraint(images, gathered_sh)
            return body(state, images, ds_labels[idx])

        jitted_dev = _cached(jax.jit(
            chunk_dev,
            in_shardings=(repl, repl, state_sh),
            out_shardings=(state_sh, repl),
            donate_argnums=2,
        ), donate=2)
        fn = functools.partial(jitted_dev, dataset_images, dataset_labels)

        def lower_dev(*abs_args):
            from dml_cnn_cifar10_tpu.utils.profiling import abstractify
            return jitted_dev.lower(*abstractify((dataset_images,
                                                  dataset_labels)),
                                    *abs_args)

        fn.lower = lower_dev
        fn.cached = jitted_dev if compile_cache is not None else None
        if fn.cached is not None:
            def flops_dev(abs_args):
                from dml_cnn_cifar10_tpu.utils.profiling import abstractify
                return jitted_dev.cached_flops(
                    (*abstractify((dataset_images, dataset_labels)),
                     *abs_args))
            fn.cached_flops = flops_dev
        return fn

    def chunk(dataset_images, dataset_labels, state: TrainState, idx):
        # Device-side gather: [K, B] indices into the HBM-resident arrays.
        # Conv models on a seq>1 mesh pin the gathered chunk to the
        # spatial (H-over-seq) layout so the resident path partitions
        # activations the same way the host-fed paths do.
        images = dataset_images[idx]
        if spatial:
            images = lax.with_sharding_constraint(images, gathered_sh)
        return body(state, images, dataset_labels[idx])

    idx_sh = mesh_lib.batch_sharding(mesh, 2, leading_dims=1)
    jitted = _cached(jax.jit(
        chunk,
        in_shardings=(repl, repl, state_sh, idx_sh),
        out_shardings=(state_sh, repl),
        donate_argnums=2,
    ), donate=2)
    fn = functools.partial(jitted, dataset_images, dataset_labels)

    def lower(*abs_args):
        # Expose AOT lowering through the partial so the driver's
        # flops probe (utils/profiling.compiled_flops) works on the
        # resident path too: prepend the bound dataset avals.
        from dml_cnn_cifar10_tpu.utils.profiling import abstractify
        return jitted.lower(*abstractify((dataset_images,
                                          dataset_labels)), *abs_args)

    fn.lower = lower
    fn.cached = jitted if compile_cache is not None else None
    if fn.cached is not None:
        def flops_idx(abs_args):
            from dml_cnn_cifar10_tpu.utils.profiling import abstractify
            return jitted.cached_flops(
                (*abstractify((dataset_images, dataset_labels)),
                 *abs_args))
        fn.cached_flops = flops_idx
    return fn


def _eval_logits_fn(model_def: ModelDef, model_cfg: ModelConfig, mesh):
    mesh_kwargs = {"mesh": mesh} if (model_def.wants_mesh and
                                     mesh is not None) else {}

    def logits_fn(state: TrainState, images):
        # When the optimizer tracks a parameter EMA, eval uses it (the
        # standard recipe: train on raw params, evaluate the average),
        # paired with the matching EMA of the BN running stats. Key
        # presence is a static pytree property — resolved at trace.
        params = state.opt.get("ema", state.params)
        if model_def.has_state:
            mstate = state.opt.get("ema_mstate", state.model_state)
            logits, _ = model_def.apply(params, mstate,
                                        images, model_cfg, train=False)
        elif model_def.has_aux:
            logits, _ = model_def.apply(params, images, model_cfg,
                                        train=False, **mesh_kwargs)
        else:
            logits = model_def.apply(params, images, model_cfg,
                                     train=False, **mesh_kwargs)
        return logits

    return logits_fn


def make_eval_resident(
    model_def: ModelDef,
    model_cfg: ModelConfig,
    mesh: Mesh,
    images_u8,
    labels,
    data_cfg: DataConfig,
    state_sharding: Optional[TrainState] = None,
    batch_size: int = 128,
    num_shards: int = 1,
    total_records: Optional[int] = None,
    expected_batches: Optional[int] = None,
    compile_cache=None,
):
    """Full-split eval in ONE dispatch against an HBM-resident split:
    returns ``(fn, total)`` with ``fn(state) -> GLOBAL correct count``
    (device scalar, replicated) over all ``total`` real records.

    The split is padded to a whole number of batches (pad labels -1 ⇒ 0
    correct, mirroring ``full_sweep_padded``), reshaped ``[M, B, ...]``,
    and placed once; eval is a ``lax.scan`` of decode→forward→count over
    the M batches. Replaces M host-fed eval dispatches + M device→host
    fetches per eval with one dispatch + one fetch — decisive when
    host↔device round trips are ~100 ms (remote-tunnel TPU).

    Multi-host (``num_shards`` > 1): ``images_u8``/``labels`` are THIS
    process's strided shard and ``batch_size`` its per-process share of
    the global eval batch. Every process pads to the same batch count
    ``M = ceil(ceil(total/num_shards)/batch_size)`` (strided shards
    differ by ≤1 record — same rule as ``full_sweep_padded``) and
    contributes its slice of the global ``[M, B_global, ...]`` arrays
    (``place_local``); the replicated output scalar IS the global
    correct count (GSPMD inserts the cross-data-axis reduction), so one
    dispatch + one ``device_get`` per process covers the whole split —
    round 2's multi-host host-fed fallback (M H2D uploads per eval) is
    gone.
    """
    import numpy as np

    from dml_cnn_cifar10_tpu.ops.preprocess import device_preprocess

    n = images_u8.shape[0]                       # local shard size
    if num_shards > 1 and total_records is None:
        # m derived from the LOCAL shard would differ across processes
        # (strided shards differ by 1 record) → mismatched global arrays
        # and a hang instead of an error. Fail at build time.
        raise ValueError(
            "make_eval_resident with num_shards > 1 needs total_records "
            "(the pre-shard split size) so every process pads to the "
            "same batch count")
    total = int(total_records) if total_records is not None else n
    largest_shard = -(-total // max(num_shards, 1))
    m = -(-largest_shard // batch_size)
    if expected_batches is not None and m != expected_batches:
        # The iterator's padded-sweep rule
        # (pipeline.num_padded_sweep_batches) and this one must agree —
        # the host-fed and resident paths count correctness over the
        # same geometry, and multi-host correctness needs every process
        # on the same M.
        raise ValueError(
            f"resident eval computed {m} padded batches but the "
            f"iterator's sweep rule says {expected_batches}")
    pad = m * batch_size - n
    if pad:
        images_u8 = np.concatenate(
            [images_u8, np.zeros((pad, *images_u8.shape[1:]),
                                 images_u8.dtype)])
        labels = np.concatenate([labels, np.full((pad,), -1, labels.dtype)])
    ims = images_u8.reshape(m, batch_size, *images_u8.shape[1:])
    lbs = labels.reshape(m, batch_size).astype(np.int32)

    logits_fn = _eval_logits_fn(model_def, model_cfg, mesh)
    eval_cfg = _eval_data_cfg(data_cfg)

    def ev(ims, lbs, state: TrainState):
        def body(total, batch):
            images = device_preprocess(batch[0], eval_cfg)
            logits = logits_fn(state, images)
            return total + metrics_lib.correct_count(logits, batch[1]), None

        total, _ = lax.scan(body, jnp.zeros((), jnp.int32), (ims, lbs))
        return total

    repl = mesh_lib.replicated(mesh)
    state_sh = state_sharding if state_sharding is not None else repl
    data_sh = mesh_lib.batch_sharding(
        mesh, ims.ndim, leading_dims=1,
        spatial=mesh_lib.spatial_enabled(model_def, mesh))
    lab_sh = mesh_lib.batch_sharding(mesh, 2, leading_dims=1)
    jitted = _cc_wrap(
        jax.jit(ev, in_shardings=(data_sh, lab_sh, state_sh),
                out_shardings=repl),
        compile_cache, "eval_resident",
        mesh_context(mesh, compute_dtype=model_cfg.compute_dtype,
                     model=model_cfg.name))
    ims_d = mesh_lib.place_local(data_sh, ims)
    lbs_d = mesh_lib.place_local(lab_sh, lbs)
    return functools.partial(jitted, ims_d, lbs_d), total


def make_batch_eval_resident(
    model_def: ModelDef,
    model_cfg: ModelConfig,
    mesh: Mesh,
    dataset_images: jax.Array,
    dataset_labels: jax.Array,
    data_cfg: DataConfig,
    state_sharding: Optional[TrainState] = None,
    compile_cache=None,
):
    """Single-batch accuracy against an HBM-resident dataset:
    ``fn(state, idx [B] int32) -> accuracy`` (device scalar). The
    index-fed mirror of ``make_eval_step`` for the boundary metrics —
    ~0.5 KB host→device instead of a decoded image batch."""
    from dml_cnn_cifar10_tpu.ops.preprocess import device_preprocess

    logits_fn = _eval_logits_fn(model_def, model_cfg, mesh)
    eval_cfg = _eval_data_cfg(data_cfg)

    spatial = mesh_lib.spatial_enabled(model_def, mesh)
    gathered_sh = mesh_lib.batch_sharding(mesh, 4, spatial=spatial)

    def ev(dataset_images, dataset_labels, state: TrainState, idx):
        images = dataset_images[idx]
        if spatial:
            images = lax.with_sharding_constraint(images, gathered_sh)
        images = device_preprocess(images, eval_cfg)
        labels = dataset_labels[idx]
        return metrics_lib.batch_accuracy(logits_fn(state, images), labels)

    repl = mesh_lib.replicated(mesh)
    state_sh = state_sharding if state_sharding is not None else repl
    jitted = _cc_wrap(
        jax.jit(
            ev,
            in_shardings=(repl, repl, state_sh,
                          mesh_lib.batch_sharding(mesh, 1)),
            out_shardings=repl,
        ),
        compile_cache, "eval_batch_resident",
        mesh_context(mesh, compute_dtype=model_cfg.compute_dtype,
                     model=model_cfg.name))
    return functools.partial(jitted, dataset_images, dataset_labels)


def _eval_data_cfg(data_cfg: DataConfig) -> DataConfig:
    """Eval-time decode config: deterministic (all augmentation off)."""
    return data_cfg.without_augmentation()


def _make_explicit_train_step(model_def, model_cfg, optim_cfg, mesh: Mesh,
                              health_metrics: bool = False):
    """shard_map form: per-device forward/backward on the local batch shard,
    explicit ``lax.psum`` of gradients — the literal translation of
    "workers compute grads, aggregation applies them" minus the
    asynchrony (SURVEY §2.3, §3.3)."""
    loss_fn = _forward_loss(model_def, model_cfg, axis_name="data",
                             label_smoothing=optim_cfg.label_smoothing)
    ndev = mesh.shape["data"]

    def local_step(state: TrainState, images, labels):
        (loss, (logits, new_model_state, stats)), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(state.params, state.model_state, images,
                                   labels)
        # Gradient all-reduce over ICI — the replacement for worker→PS
        # gradient RPCs (cifar10cnn.py:230, SURVEY §3.3). Mean, because each
        # device's loss is already a mean over its local shard.
        grads = lax.pmean(grads, "data")
        loss = lax.pmean(loss, "data")
        acc = lax.pmean(metrics_lib.batch_accuracy(logits, labels), "data")
        stats = lax.pmean(stats, "data")
        new_params, new_opt = optim_lib.sgd_update(grads, state.opt,
                                                   state.params, optim_cfg)
        # Health scalars come AFTER the pmean: the reduced grads/params
        # are replicated, so the norms match the GSPMD step's and satisfy
        # the out_specs=P() replication contract.
        if health_metrics:
            stats = {**stats, **_health_stats(state.params, new_params,
                                              grads)}
        if model_def.has_state:
            new_model_state = lax.pmean(new_model_state, "data")
        if "ema_mstate" in state.opt:
            d = optim_lib.ema_decay_at(optim_cfg, new_opt["step"])
            new_opt["ema_mstate"] = jax.tree.map(
                lambda e, m: (d * e + (1 - d) * m).astype(e.dtype),
                state.opt["ema_mstate"], new_model_state)
        return (TrainState(new_params, new_opt, new_model_state),
                {"loss": loss, "accuracy": acc, **stats})

    shmapped = shard_map(
        local_step,
        mesh=mesh,
        in_specs=(P(), P("data"), P("data")),
        out_specs=(P(), P()),
        check_vma=False,
    )
    return jax.jit(shmapped, donate_argnums=0)


def make_eval_step(
    model_def: ModelDef,
    model_cfg: ModelConfig,
    mesh: Optional[Mesh] = None,
    state_sharding: Optional[TrainState] = None,
    compile_cache=None,
) -> Callable[[TrainState, jax.Array, jax.Array], dict]:
    """Jitted eval: ``(state, images, labels) -> {"accuracy", "correct"}`` —
    single-batch accuracy for faithful parity eval (``cifar10cnn.py:
    237-241``); ``correct`` is the global summable count for full-test-set
    eval (pad rows labeled -1 contribute 0)."""

    logits_fn = _eval_logits_fn(model_def, model_cfg, mesh)

    def step(state: TrainState, images, labels):
        logits = logits_fn(state, images)
        return {
            "accuracy": metrics_lib.batch_accuracy(logits, labels),
            "correct": metrics_lib.correct_count(logits, labels),
        }

    def _cached(jitted):
        return _cc_wrap(jitted, compile_cache, "eval_step",
                        mesh_context(mesh,
                                     compute_dtype=model_cfg.compute_dtype,
                                     model=model_cfg.name))

    if mesh is None:
        return _cached(jax.jit(step))
    repl = mesh_lib.replicated(mesh)
    state_sh = state_sharding if state_sharding is not None else repl
    spatial = mesh_lib.spatial_enabled(model_def, mesh)
    return _cached(jax.jit(
        step,
        in_shardings=(state_sh,
                      mesh_lib.batch_sharding(mesh, 4, spatial=spatial),
                      mesh_lib.batch_sharding(mesh, 1)),
        out_shardings=repl,
    ))
