"""Ulysses-style all-to-all sequence parallelism over the ``seq`` mesh axis.

The second long-context strategy next to
:mod:`~dml_cnn_cifar10_tpu.parallel.ring_attention` (no reference
counterpart — the reference is attention-free, ``cifar10cnn.py:94-147``;
SURVEY §2.3/§5 scope long-context as a first-class capability here).

Design (the DeepSpeed-Ulysses recipe, TPU-native): activations live
sequence-sharded ``[B, S/n, H, D]`` between blocks — identical layout to
the ring path, so the two are drop-in alternatives. At the attention
boundary an ``all_to_all`` over ``seq`` re-partitions from
sequence-sharded to *head*-sharded ``[B, S, H/n, D]``; each device then
runs ordinary full-sequence attention on its head slice (any local kernel
— the Pallas flash kernel for long S), and a second ``all_to_all``
restores sequence sharding.

Trade-off vs the ring: Ulysses moves Q, K, V and O each once through an
all-to-all (4·B·S·H·D/n per device, one shot, rides ICI), while the ring
moves K/V n−1 times but never re-partitions and has no head-count
constraint. Ulysses needs ``heads % n == 0``; its local attention is a
single dense kernel (best MXU utilization at moderate n), whereas the
ring's blockwise pieces win when S is too long for even one full-sequence
attention to fit.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh

from dml_cnn_cifar10_tpu.parallel import compat
from dml_cnn_cifar10_tpu.ops import attention as attn
from dml_cnn_cifar10_tpu.parallel.ring_attention import (
    sequence_sharding, sp_partition_spec, sp_shard_map)

__all__ = ["ulysses_attention", "ulysses_attention_local",
           "sequence_sharding"]


def ulysses_attention_local(q: jax.Array, k: jax.Array, v: jax.Array,
                            axis_name: str,
                            scale: Optional[float] = None,
                            use_pallas: bool = False,
                            causal: bool = False,
                            segment_ids: Optional[jax.Array] = None,
                            window: Optional[int] = None
                            ) -> jax.Array:
    """Per-device body under ``shard_map``: Q/K/V sequence-sharded
    ``[B, S_local, H, D]`` → out ``[B, S_local, H, D]``.

    ``all_to_all`` (seq→head re-partition) → full-seq local attention →
    ``all_to_all`` back. Heads must divide the axis size. Causality is
    position-exact here: the local kernel sees the full sequence, so the
    flag passes straight through. Differentiable end to end (all_to_all
    has a transpose rule; the flash path brings its custom_vjp).
    """
    n = compat.axis_size(axis_name)
    if n == 1:
        return attn.dispatch_attention(q, k, v, use_pallas=use_pallas,
                                       scale=scale, causal=causal,
                                       segment_ids=segment_ids,
                                       window=window)
    if segment_ids is not None:
        # Per-position ids are tiny (~2 B/token): all-gather the
        # sequence-sharded ids so the post-all-to-all full-sequence
        # kernel masks exactly.
        segment_ids = lax.all_gather(segment_ids, axis_name, axis=1,
                                     tiled=True)
    # [B, S/n, H, D] -> [B, S, H/n, D]: split the head dim over the axis,
    # concatenate the sequence dim. tiled=True keeps the dims in place.
    q, k, v = (
        lax.all_to_all(t, axis_name, split_axis=2, concat_axis=1, tiled=True)
        for t in (q, k, v))
    o = attn.dispatch_attention(q, k, v, use_pallas=use_pallas, scale=scale,
                                causal=causal, segment_ids=segment_ids,
                                window=window)
    # [B, S, H/n, D] -> [B, S/n, H, D]
    return lax.all_to_all(o, axis_name, split_axis=1, concat_axis=2,
                          tiled=True)


def ulysses_attention(q: jax.Array, k: jax.Array, v: jax.Array, mesh: Mesh,
                      scale: Optional[float] = None,
                      axis_name: str = "seq",
                      use_pallas: bool = False,
                      causal: bool = False,
                      segment_ids: Optional[jax.Array] = None,
                      window: Optional[int] = None) -> jax.Array:
    """Sequence-parallel attention via head/sequence all-to-all.

    Global-view entrypoint, same contract as
    :func:`~dml_cnn_cifar10_tpu.parallel.ring_attention.ring_attention`
    (layout rule shared via ``sp_partition_spec``): ``[B, S, H, D]``
    arrays, S divisible by the ``seq`` axis; batch stays sharded on
    ``data`` so dp × sp compose. Heads shard over ``model`` when they
    divide it (sp × tp), and the per-device head count must additionally
    divide the ``seq`` axis.
    """
    nseq = mesh.shape[axis_name]
    _, head_axis = sp_partition_spec(mesh, axis_name, q.shape[1],
                                     q.shape[2])
    local_heads = q.shape[2] // (mesh.shape["model"] if head_axis else 1)
    if local_heads % nseq:
        raise ValueError(
            f"{local_heads} per-device heads not divisible by seq axis "
            f"{nseq}; use ring attention for head counts the axis can't "
            f"split")
    kw = dict(axis_name=axis_name, scale=scale, use_pallas=use_pallas,
              causal=causal, window=window)
    if segment_ids is None:
        local = functools.partial(ulysses_attention_local, **kw)
        args = (q, k, v)
    else:
        def local(q, k, v, seg):
            return ulysses_attention_local(q, k, v, segment_ids=seg, **kw)
        args = (q, k, v, segment_ids.astype(jnp.int32))
    fn = sp_shard_map(local, mesh, axis_name, q.shape[1], q.shape[2],
                      with_segments=segment_ids is not None)
    return fn(*args)
