"""Device mesh construction + sharding helpers.

Axes:
- ``data``  — synchronous data parallelism (batch dim). The replacement for
  the reference's async PS data parallelism (``cifar10cnn.py:195-196``).
- ``model`` — tensor parallelism (attention heads / MLP columns in ViT,
  wide FCs elsewhere). Degree 1 for reference parity.
- ``seq``   — sequence/context parallelism (ring attention) for long-context
  configs. Degree 1 for image models at CIFAR scale.
- ``pipe``  — pipeline parallelism (GPipe microbatch schedule over the ViT
  block stack, :mod:`~dml_cnn_cifar10_tpu.parallel.pipeline`). Degree 1
  unless pipelining.

Collectives ride ICI when the mesh axes are laid out over the physical
torus; DCN is only used for the multi-host bootstrap
(:mod:`~dml_cnn_cifar10_tpu.parallel.multihost`).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dml_cnn_cifar10_tpu.config import ParallelConfig

AXES = ("data", "model", "seq", "pipe")


def build_mesh(cfg: Optional[ParallelConfig] = None,
               devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Build a ``(data, model, seq, pipe)`` mesh over the given (default:
    all) devices. ``data_axis=-1`` absorbs every device not claimed by
    model/seq/pipe."""
    cfg = cfg or ParallelConfig()
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    model, seq = max(1, cfg.model_axis), max(1, cfg.seq_axis)
    pipe = max(1, getattr(cfg, "pipe_axis", 1))
    data = cfg.data_axis if cfg.data_axis > 0 else n // (model * seq * pipe)
    if data * model * seq * pipe != n:
        raise ValueError(
            f"mesh {data}x{model}x{seq}x{pipe} != {n} devices "
            f"(data_axis={cfg.data_axis}, model_axis={model}, "
            f"seq_axis={seq}, pipe_axis={pipe})")
    arr = np.asarray(devices).reshape(data, model, seq, pipe)
    return Mesh(arr, AXES)


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def spatial_enabled(model_def, mesh: Mesh) -> bool:
    """True when this model/mesh pair does spatial partitioning (conv
    family + nontrivial ``seq`` axis) — the ONE predicate every step
    builder and batch placement consults, so the layouts can't drift."""
    return bool(getattr(model_def, "spatial", False)
                and mesh.shape["seq"] > 1)


def batch_sharding(mesh: Mesh, ndim: int = 4,
                   leading_dims: int = 0,
                   spatial: bool = False) -> NamedSharding:
    """Batch dim over ``data``, preceded by ``leading_dims`` replicated axes
    (the K axis of a ``[K, B, ...]`` step chunk); rest replicated.
    ``spatial=True`` additionally shards the dim after batch (image H) over
    ``seq`` — spatial partitioning for conv models (GSPMD halo exchange)."""
    spec = [None] * leading_dims + ["data"]
    if spatial and ndim > len(spec):
        spec.append("seq")
    spec += [None] * (ndim - len(spec))
    return NamedSharding(mesh, P(*spec))


def place_local(sharding: NamedSharding, arr):
    """Place one host array under ``sharding``: plain ``device_put``
    single-process; per-process local-data assembly multi-host (each
    process passes its slice of the sharded dims — for a replicated
    sharding, the identical full array)."""
    if jax.process_count() == 1:
        return jax.device_put(arr, sharding)
    return jax.make_array_from_process_local_data(sharding, arr)


def shard_batch(mesh: Mesh, images, labels, leading_dims: int = 0,
                spatial: bool = False):
    """Place a host batch on the mesh, batch dim sharded over ``data``.

    Single-process: a plain ``device_put`` with a NamedSharding. Multi-host:
    each process contributes its local slice of the global batch
    (``jax.make_array_from_process_local_data``), the moral replacement for
    every worker feeding its own queue in the reference
    (``cifar10cnn.py:201``).
    """
    img_s = batch_sharding(mesh, images.ndim, leading_dims, spatial=spatial)
    lab_s = batch_sharding(mesh, labels.ndim, leading_dims)
    return place_local(img_s, images), place_local(lab_s, labels)
