"""Multi-host bootstrap over DCN.

Replaces ``tf.train.ClusterSpec`` + ``tf.train.Server`` (``cifar10cnn.py:
184-192``): instead of a gRPC parameter-server cluster there is one SPMD
program per host, bootstrapped by ``jax.distributed.initialize`` (the
coordinator fills the role of the TF master; all training traffic is XLA
collectives over ICI/DCN, not parameter RPCs).

The reference CLI shape is preserved: a comma list of ``host:port`` worker
addresses plus a task index maps 1:1 onto (coordinator_address,
num_processes, process_id) — see ``cli/main.py``.

Bootstrap is hardened two ways (docs/RESILIENCE.md):
- inputs are validated up front — a bad ``--task_index`` or a duplicated
  ``host:port`` used to surface as a late ``jax.distributed`` hang, the
  single worst failure mode to debug on a pod;
- ``initialize`` retries a refused/slow coordinator with the shared
  bounded exponential backoff (``utils/backoff.py``) under
  ``--coordinator_timeout_s`` per attempt — workers routinely win the
  race against the coordinator process on real schedulers, and losing
  that race should be a retry, not a crash.
"""

from __future__ import annotations

import time
from typing import List, Optional

import jax

from dml_cnn_cifar10_tpu.config import ParallelConfig
from dml_cnn_cifar10_tpu.utils import backoff


def validate_hosts(worker_hosts: List[str], task_index: int) -> None:
    """Fail fast with a clear ``ValueError`` on inputs that would
    otherwise hang ``jax.distributed`` late: empty/duplicate
    ``host:port`` entries, entries without a port, or a ``task_index``
    outside ``[0, len(worker_hosts))``."""
    if not worker_hosts:
        raise ValueError("worker_hosts is empty: need at least one "
                         "host:port entry")
    seen = set()
    for i, entry in enumerate(worker_hosts):
        entry = entry.strip()
        if not entry:
            raise ValueError(
                f"worker_hosts[{i}] is empty — a trailing/doubled comma "
                f"in --worker_hosts?")
        host, sep, port = entry.rpartition(":")
        if not sep or not host or not port.isdigit():
            raise ValueError(
                f"worker_hosts[{i}] = {entry!r} is not host:port")
        if entry in seen:
            raise ValueError(
                f"worker_hosts[{i}] = {entry!r} is duplicated — two "
                f"processes on one address never form a cluster, they "
                f"hang it")
        seen.add(entry)
    if not 0 <= task_index < len(worker_hosts):
        raise ValueError(
            f"task_index={task_index} out of range for "
            f"{len(worker_hosts)} worker host(s)")


def initialize_from_hosts(worker_hosts: List[str], task_index: int) -> None:
    """README-recipe compat: ``--worker_hosts=a:2222,b:2222 --task_index=i``.

    The first worker is the coordinator, exactly as task 0 is the TF chief
    (``cifar10cnn.py:222`` ``is_chief=(task_index==0)``).
    """
    validate_hosts(worker_hosts, task_index)
    initialize(ParallelConfig(
        coordinator_address=worker_hosts[0],
        num_processes=len(worker_hosts),
        process_id=task_index,
    ))


def _is_initialized() -> bool:
    """Version-tolerant "has jax.distributed already initialized?".

    ``jax.distributed.is_initialized`` only exists in newer jax; older
    releases (the pinned 0.4.x included) expose the same fact as the
    internal global state's live client. Neither probe touches the XLA
    backend."""
    probe = getattr(jax.distributed, "is_initialized", None)
    if probe is not None:
        return bool(probe())
    from jax._src import distributed as _dist
    state = getattr(_dist, "global_state", None)
    return state is not None and getattr(state, "client", None) is not None


def initialize(cfg: ParallelConfig) -> None:
    """Idempotent ``jax.distributed.initialize`` from config, with
    bounded retry + backoff around a slow-to-start coordinator."""
    if cfg.num_processes <= 1:
        return
    # NB: must not touch jax.process_count() here — it initializes the XLA
    # backend, after which jax.distributed.initialize refuses to run.
    if _is_initialized():
        return
    attempt = 0
    while True:
        try:
            jax.distributed.initialize(
                coordinator_address=cfg.coordinator_address,
                num_processes=cfg.num_processes,
                process_id=cfg.process_id,
                initialization_timeout=int(cfg.coordinator_timeout_s),
            )
            return
        except (RuntimeError, ConnectionError, OSError, TimeoutError) as e:
            attempt += 1
            if attempt > cfg.coordinator_retries:
                raise RuntimeError(
                    f"coordinator {cfg.coordinator_address} unreachable "
                    f"after {attempt} attempt(s) x "
                    f"{cfg.coordinator_timeout_s:.0f}s: {e}") from e
            delay = backoff.delay_s(1.0, 30.0, attempt)
            print(f"[multihost] coordinator {cfg.coordinator_address} "
                  f"not ready (attempt {attempt}/"
                  f"{cfg.coordinator_retries}): {e}; retrying in "
                  f"{delay:.1f}s")
            time.sleep(delay)


def is_chief(cfg: Optional[ParallelConfig] = None) -> bool:
    """Process 0 plays the chief role (init/checkpointing decisions).

    With a :class:`ParallelConfig` that declares a multi-process world
    (``num_processes > 1``), chiefness comes from ``cfg.process_id`` —
    this is what the cluster-resilience CPU simulation relies on, where
    every simulated host is ``jax.process_index() == 0`` in its own
    single-process JAX world. Without one, the live JAX process index
    decides, as before."""
    if cfg is not None and cfg.num_processes > 1:
        return cfg.process_id == 0
    return jax.process_index() == 0
