"""Multi-host bootstrap over DCN.

Replaces ``tf.train.ClusterSpec`` + ``tf.train.Server`` (``cifar10cnn.py:
184-192``): instead of a gRPC parameter-server cluster there is one SPMD
program per host, bootstrapped by ``jax.distributed.initialize`` (the
coordinator fills the role of the TF master; all training traffic is XLA
collectives over ICI/DCN, not parameter RPCs).

The reference CLI shape is preserved: a comma list of ``host:port`` worker
addresses plus a task index maps 1:1 onto (coordinator_address,
num_processes, process_id) — see ``cli/main.py``.
"""

from __future__ import annotations

from typing import List, Optional

import jax

from dml_cnn_cifar10_tpu.config import ParallelConfig


def initialize_from_hosts(worker_hosts: List[str], task_index: int) -> None:
    """README-recipe compat: ``--worker_hosts=a:2222,b:2222 --task_index=i``.

    The first worker is the coordinator, exactly as task 0 is the TF chief
    (``cifar10cnn.py:222`` ``is_chief=(task_index==0)``).
    """
    initialize(ParallelConfig(
        coordinator_address=worker_hosts[0],
        num_processes=len(worker_hosts),
        process_id=task_index,
    ))


def initialize(cfg: ParallelConfig) -> None:
    """Idempotent ``jax.distributed.initialize`` from config."""
    if cfg.num_processes <= 1:
        return
    # NB: must not touch jax.process_count() here — it initializes the XLA
    # backend, after which jax.distributed.initialize refuses to run.
    if jax.distributed.is_initialized():
        return
    jax.distributed.initialize(
        coordinator_address=cfg.coordinator_address,
        num_processes=cfg.num_processes,
        process_id=cfg.process_id,
    )


def is_chief() -> bool:
    """Process 0 plays the chief role (init/checkpointing decisions)."""
    return jax.process_index() == 0
