"""Version-compat shims for the parallel package.

``shard_map`` graduated from ``jax.experimental.shard_map`` to a
top-level ``jax.shard_map`` (renaming ``check_rep`` → ``check_vma``
along the way) across the JAX versions this repo runs under. Every
per-device SPMD entry point (explicit-collectives data parallel, ring
attention, Ulysses, pipeline stages) imports the one wrapper below so
call sites use the modern spelling unconditionally and tier-1 collects
clean on either API.
"""

from __future__ import annotations

import inspect

import jax

_IMPL = getattr(jax, "shard_map", None)
if _IMPL is None:  # pre-graduation JAX: the experimental module
    from jax.experimental.shard_map import shard_map as _IMPL

_PARAMS = inspect.signature(_IMPL).parameters
_ACCEPTS_CHECK_VMA = "check_vma" in _PARAMS


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True, **kwargs):
    """``jax.shard_map`` where it exists; otherwise the experimental
    one with ``check_vma`` translated back to ``check_rep``."""
    if _ACCEPTS_CHECK_VMA:
        kwargs["check_vma"] = check_vma
    elif "check_rep" in _PARAMS:
        kwargs["check_rep"] = check_vma
    return _IMPL(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                 **kwargs)


def axis_size(axis_name):
    """``lax.axis_size`` where it exists; otherwise the static size from
    the tracing axis env (an int — constant-folds, no collective)."""
    from jax import lax
    fn = getattr(lax, "axis_size", None)
    if fn is not None:
        return fn(axis_name)
    import jax.core as core
    frame = core.axis_frame(axis_name)
    return frame if isinstance(frame, int) else frame.size
