"""Ring attention — sequence/context parallelism over the ``seq`` mesh axis.

Long-context support (SURVEY §5 "Long-context / sequence parallelism"; no
reference counterpart — the reference is attention-free with fixed 24×24
inputs, ``cifar10cnn.py:15-18,94-147`` — but sequence parallelism is a
first-class capability of this framework, not an afterthought).

Design (the ring/blockwise-attention recipe): Q, K, V are sharded on the
sequence dimension over the ``seq`` mesh axis. Each device keeps its Q
shard resident and walks the ring: compute blockwise attention of local Q
against the currently-held K/V shard, fold the result into FlashAttention
running statistics (m, l, acc), then ``lax.ppermute`` the K/V shard to the
next ring neighbor. After ``seq`` steps every Q shard has attended to the
full sequence while only ever holding 1/seq of K/V — attention memory per
chip stays O(S·D/seq + block²), and the K/V transfers ride ICI neighbor
links, overlappable with the block compute by XLA's latency-hiding
scheduler.

**Backward is a second ring**, not autodiff through the forward scan
(which would checkpoint every ring step's K/V — O(S) per device, exactly
what the ring exists to avoid). ``ring_attention_local`` carries a
``jax.custom_vjp``: the forward saves only ``(q, k, v, out, lse)`` — all
local, O(S/seq) — and the backward rotates ``(k, v, dk, dv)`` around the
ring. Because the saved ``lse`` is the *global* row logsumexp, each ring
step can rebuild its block's exact softmax probabilities and apply the
standard FlashAttention-2 block backward (``ops.flash_attention.
flash_attention_bwd`` — the Pallas kernels — or a jnp twin for short
shards); per-block dK/dV contributions travel with the visiting shard and
arrive home after the full loop.

Causality: shards are equal-sized and aligned, so a (Q shard i, K/V shard
j) pair is entirely below the diagonal (full attention), entirely above
(skipped — a ``lax.switch`` branch that does no FLOPs, the ~2× causal
saving), or exactly on it (j == i — local causal mask, no offsets needed).

The per-block math has two local engines: plain jnp (each ring step
materializes only the local S/seq × S/seq score block, which XLA fuses
on-chip — right for short shards) or, with ``use_pallas=True`` and shards
≥128, the Pallas flash kernels so even the local block never materializes
its score matrix — the long-context configuration.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dml_cnn_cifar10_tpu.parallel import compat
from dml_cnn_cifar10_tpu.parallel.compat import shard_map

NEG_INF = -1e30


def _block_stats(q, k, v, scale, causal=False, segment_ids=None,
                 window=None, kv_start=0):
    """One blockwise attention piece → (m, l, unnormalized acc).

    q: [B,Sq,H,D]; k,v: [B,Sk,H,D]. Returns per-row stats for the online
    softmax merge: m=[B,H,Sq,1] row max, l=[B,H,Sq,1] sum exp, acc
    [B,Sq,H,D] = exp(s-m)·V. ``causal`` masks above the local diagonal
    (used only for the on-diagonal ring block, where local row/col indices
    align with the global ones).
    """
    from dml_cnn_cifar10_tpu.ops.attention import mask_scores

    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    s = mask_scores(s, q.shape[1], k.shape[1], causal=causal,
                    segment_ids=segment_ids, window=window,
                    kv_start=kv_start)
    m = jnp.max(s, axis=-1, keepdims=True)            # [B,H,Sq,1]
    p = jnp.exp(s - m)
    # Dead rows (every key masked) have m == NEG_INF, so exp(s - m) = 1
    # for masked entries; zero them so such rows keep l = 0 and the
    # final normalize emits zeros, matching the flash kernels and
    # xla_attention (one dead-row contract across all engines).
    p = jnp.where(s > NEG_INF * 0.5, p, 0.0)
    l = jnp.sum(p, axis=-1, keepdims=True)            # [B,H,Sq,1]
    acc = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    return m, l, acc


def _merge(m1, l1, a1, m2, l2, a2):
    """Fold two online-softmax partials into one (the flash merge rule)."""
    m = jnp.maximum(m1, m2)
    w1 = jnp.exp(m1 - m)
    w2 = jnp.exp(m2 - m)
    l = l1 * w1 + l2 * w2
    # broadcast [B,H,Sq,1] weights onto [B,Sq,H,D] accumulators
    wa1 = jnp.transpose(w1, (0, 2, 1, 3))
    wa2 = jnp.transpose(w2, (0, 2, 1, 3))
    return m, l, a1 * wa1 + a2 * wa2


def _block_stats_pallas(q, k, v, scale, causal=False, segment_ids=None,
                        window=None, kv_start=0):
    """The same ``(m, l, acc)`` partials as :func:`_block_stats`, computed
    by the Pallas flash kernel (``flash_attention_stats``): the local
    S/seq × S/seq block runs blocked on the MXU with the score matrix
    never leaving VMEM — the long-context ring configuration."""
    from dml_cnn_cifar10_tpu.ops import flash_attention as fa

    acc, m, l = fa.flash_attention_stats(q, k, v, scale=scale,
                                         causal=causal,
                                         segment_ids=segment_ids,
                                         window=window, kv_start=kv_start)
    m_ = jnp.transpose(m, (0, 2, 1))[..., None]       # [B,H,Sq,1]
    l_ = jnp.transpose(l, (0, 2, 1))[..., None]
    return m_, l_, acc                                # acc already f32


def _block_bwd_jnp(q, k, v, do, lse, delta, scale, causal=False,
                   segment_ids=None, window=None, kv_start=0):
    """FlashAttention-2 block backward in plain jnp (the short-shard twin
    of ``ops.flash_attention.flash_attention_bwd``): rebuild the block's
    scores, recover exact probabilities from the global ``lse``
    ([B,Sq,H]), and apply the ``D = rowsum(dO ∘ O)`` softmax Jacobian
    (``delta`` [B,Sq,H])."""
    from dml_cnn_cifar10_tpu.ops.attention import mask_scores

    qf = q.astype(jnp.float32)
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    dof = do.astype(jnp.float32)
    s = jnp.einsum("bqhd,bkhd->bhqk", qf, kf) * scale
    s = mask_scores(s, q.shape[1], k.shape[1], causal=causal,
                    segment_ids=segment_ids, window=window,
                    kv_start=kv_start)
    lse_t = jnp.transpose(lse, (0, 2, 1))[..., None]      # [B,H,Sq,1]
    delta_t = jnp.transpose(delta, (0, 2, 1))[..., None]  # [B,H,Sq,1]
    p = jnp.exp(s - lse_t)                                # exact probs
    dv = jnp.einsum("bhqk,bqhd->bkhd", p, dof)
    dp = jnp.einsum("bqhd,bkhd->bhqk", dof, vf)
    ds = p * (dp - delta_t) * scale
    dq = jnp.einsum("bhqk,bkhd->bqhd", ds, kf)
    dk = jnp.einsum("bhqk,bqhd->bkhd", ds, qf)
    return dq, dk, dv


def _zero_partials(b, h, sq, d):
    return (jnp.full((b, h, sq, 1), NEG_INF, jnp.float32),
            jnp.zeros((b, h, sq, 1), jnp.float32),
            jnp.zeros((b, sq, h, d), jnp.float32))


def _ring_perm(nsteps):
    return [(i, (i + 1) % nsteps) for i in range(nsteps)]


def _causal_switch(src, my, full, diag, skip):
    """The shared causal ring-step dispatch: a held shard whose home index
    ``src`` is < ``my`` lies fully below the diagonal (full attention),
    == ``my`` is the diagonal block (local causal mask), > ``my`` is fully
    above (skipped — no FLOPs spent). Shards are equal-sized and aligned,
    so these three cases are exhaustive."""
    branch = jnp.where(src < my, 0, jnp.where(src == my, 1, 2))
    return lax.switch(branch, [full, diag, skip], None)


def _window_switch(src, my, causal, diag, left, right, skip):
    """Ring-step dispatch for sliding-window attention with W ≤ S_local:
    the band ``|row − col| < W`` only ever reaches the IMMEDIATELY
    adjacent shards, so a held shard is the diagonal block (local
    causal+window mask), the left neighbor (columns sit S_local below —
    static ``kv_start=-S_local`` in the block mask), the right neighbor
    (bidirectional windows only, ``kv_start=+S_local``), or fully
    out-of-band (skipped — no FLOPs, no fetch). The W ≤ S_local
    precondition is asserted at the public entry."""
    delta = my - src
    if causal:
        branch = jnp.where(delta == 0, 0, jnp.where(delta == 1, 1, 2))
        return lax.switch(branch, [diag, left, skip], None)
    branch = jnp.where(delta == 0, 0,
                       jnp.where(delta == 1, 1,
                                 jnp.where(delta == -1, 2, 3)))
    return lax.switch(branch, [diag, left, right, skip], None)


# ---------------------------------------------------------------------------
# custom_vjp core. Forward: ring of flash partials, saving (q,k,v,out,lse).
# Backward: second ring rotating (k, v, dk, dv).
# ---------------------------------------------------------------------------


def _ring_fwd_scan(q, k, v, seg, my, axis_name, scale, use_pallas, causal,
                   window=None):
    nsteps = compat.axis_size(axis_name)
    b, sq, h, d = q.shape
    stats = _block_stats_pallas if use_pallas else _block_stats
    perm = _ring_perm(nsteps)
    # Segment ids are sequence-sharded like Q; the K/V shard's ids must
    # travel the ring WITH it (a visiting shard's positions keep their
    # home segments). ~2 bytes/token of extra ppermute traffic.
    kv_seg0 = seg

    def body(carry, t):
        k, v, kv_seg, m, l, acc = carry
        src = (my - t) % nsteps          # home index of the held shard
        pair = None if seg is None else (seg, kv_seg)

        if window is not None:
            bm, bl, bacc = _window_switch(
                src, my, causal,
                lambda _: stats(q, k, v, scale, causal=causal,
                                window=window, segment_ids=pair),
                lambda _: stats(q, k, v, scale, causal=False,
                                window=window, kv_start=-sq,
                                segment_ids=pair),
                lambda _: stats(q, k, v, scale, causal=False,
                                window=window, kv_start=sq,
                                segment_ids=pair),
                lambda _: _zero_partials(b, h, sq, d))
        elif causal:
            bm, bl, bacc = _causal_switch(
                src, my,
                lambda _: stats(q, k, v, scale, causal=False,
                                segment_ids=pair),
                lambda _: stats(q, k, v, scale, causal=True,
                                segment_ids=pair),
                lambda _: _zero_partials(b, h, sq, d))
        else:
            bm, bl, bacc = stats(q, k, v, scale, segment_ids=pair)
        m, l, acc = _merge(m, l, acc, bm, bl, bacc)
        # Rotate K/V one ring hop (neighbor ppermute over ICI). The final
        # rotation returns the shards to their home device, so the carry
        # stays consistent for any caller that reuses K/V.
        k = lax.ppermute(k, axis_name, perm)
        v = lax.ppermute(v, axis_name, perm)
        if kv_seg is not None:
            kv_seg = lax.ppermute(kv_seg, axis_name, perm)
        return (k, v, kv_seg, m, l, acc), None

    m0, l0, a0 = _zero_partials(b, h, sq, d)
    (k, v, _, m, l, acc), _ = lax.scan(
        body, (k, v, kv_seg0, m0, l0, a0), jnp.arange(nsteps))
    # Dead rows (no live key on ANY ring step) end with m == NEG_INF —
    # the jnp engine also keeps l = 0 there while the Pallas stats
    # engine may carry garbage l/acc (exp(NEG_INF - NEG_INF) = 1), so
    # the guard keys on m: emit exact zeros and a LARGE lse so the
    # backward's p = exp(s - lse) is exactly 0 — the same dead-row
    # contract as the flash kernels' finalizers (_dead_rows).
    live = m > NEG_INF * 0.5                                  # [B,H,Sq,1]
    l_t = jnp.transpose(l, (0, 2, 1, 3))
    live_t = jnp.transpose(live, (0, 2, 1, 3))
    out = jnp.where(live_t, acc / jnp.maximum(l_t, 1e-30), 0.0)
    out = out.astype(q.dtype)
    lse4 = jnp.where(live, m + jnp.log(jnp.maximum(l, 1e-30)), 1e30)
    lse = jnp.transpose(lse4[..., 0], (0, 2, 1))              # [B,Sq,H]
    return out, lse


# ``my`` (this device's ring position, ``lax.axis_index``) is computed by
# the caller and passed through as a traced argument: a partition-id op
# inside the custom_vjp closed-call body lands outside the SPMD manual
# section on older JAX and fails to partition.
@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9))
def _ring_core(q, k, v, seg, my, axis_name, scale, use_pallas, causal,
               window):
    out, _ = _ring_fwd_scan(q, k, v, seg, my, axis_name, scale, use_pallas,
                            causal, window=window)
    return out


def _ring_core_fwd(q, k, v, seg, my, axis_name, scale, use_pallas, causal,
                   window):
    out, lse = _ring_fwd_scan(q, k, v, seg, my, axis_name, scale,
                              use_pallas, causal, window=window)
    return out, (q, k, v, seg, my, out, lse)


def _ring_core_bwd(axis_name, scale, use_pallas, causal, window, res, do):
    from dml_cnn_cifar10_tpu.ops import flash_attention as fa

    q, k, v, seg, my, out, lse = res
    nsteps = compat.axis_size(axis_name)
    delta = fa.attention_delta(out, do)               # [B,Sq,H] f32
    perm = _ring_perm(nsteps)

    # Per-step partials are f32 from either engine (out_dtype=f32 keeps
    # the Pallas kernels from quantizing each step to the input dtype
    # before the cross-step accumulation, matching the jnp twin); the
    # carry accumulates in f32 and casts once at the end.
    if use_pallas:
        def block_bwd(k_, v_, causal_local, pair, kv_start=0):
            return fa.flash_attention_bwd(q, k_, v_, do, lse, delta,
                                          scale=scale, causal=causal_local,
                                          out_dtype=jnp.float32,
                                          segment_ids=pair, window=window,
                                          kv_start=kv_start)
    else:
        def block_bwd(k_, v_, causal_local, pair, kv_start=0):
            return _block_bwd_jnp(q, k_, v_, do, lse, delta, scale,
                                  causal=causal_local, segment_ids=pair,
                                  window=window, kv_start=kv_start)

    def body(carry, t):
        k, v, kv_seg, dk, dv, dq = carry
        src = (my - t) % nsteps
        pair = None if seg is None else (seg, kv_seg)

        if window is not None:
            sq_ = q.shape[1]
            dq_c, dk_c, dv_c = _window_switch(
                src, my, causal,
                lambda _: block_bwd(k, v, causal, pair),
                lambda _: block_bwd(k, v, False, pair, kv_start=-sq_),
                lambda _: block_bwd(k, v, False, pair, kv_start=sq_),
                lambda _: (jnp.zeros_like(dq), jnp.zeros_like(dk),
                           jnp.zeros_like(dv)))
        elif causal:
            dq_c, dk_c, dv_c = _causal_switch(
                src, my,
                lambda _: block_bwd(k, v, False, pair),
                lambda _: block_bwd(k, v, True, pair),
                lambda _: (jnp.zeros_like(dq), jnp.zeros_like(dk),
                           jnp.zeros_like(dv)))
        else:
            dq_c, dk_c, dv_c = block_bwd(k, v, False, pair)
        dq = dq + dq_c
        # dK/dV partials travel WITH the visiting shard: after n hops they
        # have collected a contribution on every device and are home.
        dk = dk + dk_c
        dv = dv + dv_c
        k = lax.ppermute(k, axis_name, perm)
        v = lax.ppermute(v, axis_name, perm)
        if kv_seg is not None:
            kv_seg = lax.ppermute(kv_seg, axis_name, perm)
        dk = lax.ppermute(dk, axis_name, perm)
        dv = lax.ppermute(dv, axis_name, perm)
        return (k, v, kv_seg, dk, dv, dq), None

    dq0 = jnp.zeros(q.shape, jnp.float32)
    dk0 = jnp.zeros(k.shape, jnp.float32)
    dv0 = jnp.zeros(v.shape, jnp.float32)
    (k, v, _, dk, dv, dq), _ = lax.scan(
        body, (k, v, seg, dk0, dv0, dq0), jnp.arange(nsteps))
    dseg = jax.tree.map(
        lambda s: np.zeros(s.shape, jax.dtypes.float0), seg)
    dmy = np.zeros((), jax.dtypes.float0)
    return (dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype),
            dseg, dmy)


_ring_core.defvjp(_ring_core_fwd, _ring_core_bwd)


def ring_attention_local(q: jax.Array, k: jax.Array, v: jax.Array,
                         axis_name: str, scale: Optional[float] = None,
                         use_pallas: bool = False,
                         causal: bool = False,
                         segment_ids: Optional[jax.Array] = None,
                         window: Optional[int] = None,
                         my: Optional[jax.Array] = None
                         ) -> jax.Array:
    """Per-device body: runs under ``shard_map`` with Q/K/V sequence-sharded
    on ``axis_name``. Shapes [B, S_local, H, D] → [B, S_local, H, D].

    Differentiable (custom_vjp: the backward is a second ring pass with
    O(S/seq) memory — see module docstring). ``use_pallas`` routes each
    local block through the flash kernels when the local shard is long
    enough to benefit (same ≥128 threshold as ``dispatch_attention``);
    ``causal`` masks the global lower triangle and skips above-diagonal
    ring steps entirely. ``segment_ids`` is THIS shard's [B, S_local]
    slice of the packed-sequence ids; visiting K/V shards bring their
    own ids around the ring. ``window`` is the sliding-window band
    (global coordinates, same semantics as the flash kernels); it must
    satisfy ``window <= S_local`` so the band reaches at most the
    adjacent ring shard (see :func:`_window_switch`)."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    if window is not None and window > q.shape[1]:
        raise ValueError(
            f"ring window {window} exceeds the local shard length "
            f"{q.shape[1]}; the ring dispatch only visits adjacent "
            f"shards. Use fewer seq-axis devices (longer shards) or a "
            f"smaller window.")
    if my is None:
        my = lax.axis_index(axis_name)
    return _ring_core(q, k, v, segment_ids, my,
                      axis_name, float(scale),
                      bool(use_pallas and q.shape[1] >= 128), bool(causal),
                      None if window is None else int(window))


def sp_partition_spec(mesh: Mesh, axis_name: str, seq_len: int,
                      num_heads: int):
    """The shared sequence-parallel layout rule → ``(spec, head_axis)``.

    ``[B, S, H, D]`` partition spec for any SP attention kernel (ring or
    Ulysses): batch over ``data``, sequence over ``axis_name``. Heads are
    batch-like inside the local bodies, so when the mesh also has a
    nontrivial ``model`` (tensor-parallel) axis the heads dim shards over
    it — sp × tp compose with zero resharding at the kernel edge. When the
    head count doesn't divide the axis (e.g. default ViT-Ti's 3 heads on
    model=2), fall back to replicated heads: correct, just an all-gather
    at the kernel edge instead of a free composition. Raises on a sequence
    length the ``seq`` axis can't split.
    """
    nseq = mesh.shape[axis_name]
    if seq_len % nseq:
        raise ValueError(
            f"sequence length {seq_len} not divisible by seq axis {nseq}")
    nmodel = mesh.shape.get("model", 1)
    head_axis = "model" if nmodel > 1 and num_heads % nmodel == 0 else None
    return P("data", axis_name, head_axis, None), head_axis


def sp_shard_map(local_fn, mesh: Mesh, axis_name: str, seq_len: int,
                 num_heads: int, with_segments: bool = False,
                 extra_in_specs=()):
    """Wrap an SP-local attention body in the standard shard_map: one
    ``(q, k, v[, segment_ids]) -> out`` callable with all tensors laid
    out per :func:`sp_partition_spec` (segment ids, when present, shard
    ``[B, S]`` as ``(data, axis_name)`` — the same sequence split).
    ``extra_in_specs`` appends specs for trailing positional inputs."""
    spec, _ = sp_partition_spec(mesh, axis_name, seq_len, num_heads)
    in_specs = (spec, spec, spec)
    if with_segments:
        in_specs += (P("data", axis_name),)
    in_specs += tuple(extra_in_specs)
    return shard_map(
        local_fn,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=spec,
        check_vma=False,
    )


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array, mesh: Mesh,
                   scale: Optional[float] = None,
                   axis_name: str = "seq",
                   use_pallas: bool = False,
                   causal: bool = False,
                   segment_ids: Optional[jax.Array] = None,
                   window: Optional[int] = None) -> jax.Array:
    """Sequence-parallel attention over the mesh's ``seq`` axis.

    Global-view entrypoint: [B, S, H, D] arrays (sharded or not); S must be
    divisible by the ``seq`` axis size. Batch stays sharded on ``data`` so
    dp × sp compose. ``use_pallas`` runs each local block on the Pallas
    flash kernels (long-shard configs); ``causal`` applies the global
    lower-triangular mask with above-diagonal ring steps skipped;
    ``segment_ids`` [B, S] int32 (global view, sharded like the sequence)
    restricts attention to same-segment pairs — packed sequences through
    the ring.
    """
    kw = dict(axis_name=axis_name, scale=scale, use_pallas=use_pallas,
              causal=causal, window=window)
    # The ring position rides in as a sequence-sharded iota (each
    # device's shard IS its index) instead of ``lax.axis_index``: a
    # partition-id op inside the body fails SPMD partitioning under an
    # outer jit on older JAX (it lands in a non-inlined called
    # computation).
    pos = jnp.arange(mesh.shape[axis_name], dtype=jnp.int32)
    if segment_ids is None:
        def local(q, k, v, pos):
            return ring_attention_local(q, k, v, my=pos[0], **kw)
        args = (q, k, v, pos)
    else:
        def local(q, k, v, seg, pos):
            return ring_attention_local(q, k, v, segment_ids=seg,
                                        my=pos[0], **kw)
        args = (q, k, v, segment_ids.astype(jnp.int32), pos)
    fn = sp_shard_map(local, mesh, axis_name, q.shape[1], q.shape[2],
                      with_segments=segment_ids is not None,
                      extra_in_specs=(P(axis_name),))
    return fn(*args)


def sequence_sharding(mesh: Mesh) -> NamedSharding:
    """[B, S, H, D] sharding: batch over ``data``, sequence over ``seq``."""
    return NamedSharding(mesh, P("data", "seq", None, None))
