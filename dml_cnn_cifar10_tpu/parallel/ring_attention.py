"""Ring attention — sequence/context parallelism over the ``seq`` mesh axis.

Long-context support (SURVEY §5 "Long-context / sequence parallelism"; no
reference counterpart — the reference is attention-free with fixed 24×24
inputs, ``cifar10cnn.py:15-18,94-147`` — but sequence parallelism is a
first-class capability of this framework, not an afterthought).

Design (the ring/blockwise-attention recipe): Q, K, V are sharded on the
sequence dimension over the ``seq`` mesh axis. Each device keeps its Q
shard resident and walks the ring: compute blockwise attention of local Q
against the currently-held K/V shard, fold the result into FlashAttention
running statistics (m, l, acc), then ``lax.ppermute`` the K/V shard to the
next ring neighbor. After ``seq`` steps every Q shard has attended to the
full sequence while only ever holding 1/seq of K/V — attention memory per
chip stays O(S·D/seq + block²), and the K/V transfers ride ICI neighbor
links, overlappable with the block compute by XLA's latency-hiding
scheduler.

The per-block math is the flash merge rule (running m/l/acc, same as
:mod:`~dml_cnn_cifar10_tpu.ops.flash_attention`) with two local-block
engines: plain jnp (each ring step materializes only the local
S/seq × S/seq score block, which XLA fuses on-chip — right for short
shards) or, with ``use_pallas=True`` and shards ≥128, the Pallas flash
kernel's stats interface (``flash_attention_stats``) so even the local
block never materializes its score matrix — the long-context
configuration.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

NEG_INF = -1e30


def _block_stats(q, k, v, scale):
    """One blockwise attention piece → (m, l, unnormalized acc).

    q: [B,Sq,H,D]; k,v: [B,Sk,H,D]. Returns per-row stats for the online
    softmax merge: m=[B,H,Sq,1] row max, l=[B,H,Sq,1] sum exp, acc
    [B,Sq,H,D] = exp(s-m)·V.
    """
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    m = jnp.max(s, axis=-1, keepdims=True)            # [B,H,Sq,1]
    p = jnp.exp(s - m)
    l = jnp.sum(p, axis=-1, keepdims=True)            # [B,H,Sq,1]
    acc = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    return m, l, acc


def _merge(m1, l1, a1, m2, l2, a2):
    """Fold two online-softmax partials into one (the flash merge rule)."""
    m = jnp.maximum(m1, m2)
    w1 = jnp.exp(m1 - m)
    w2 = jnp.exp(m2 - m)
    l = l1 * w1 + l2 * w2
    # broadcast [B,H,Sq,1] weights onto [B,Sq,H,D] accumulators
    wa1 = jnp.transpose(w1, (0, 2, 1, 3))
    wa2 = jnp.transpose(w2, (0, 2, 1, 3))
    return m, l, a1 * wa1 + a2 * wa2


def _block_stats_pallas(q, k, v, scale):
    """The same ``(m, l, acc)`` partials as :func:`_block_stats`, computed
    by the Pallas flash kernel (``flash_attention_stats``): the local
    S/seq × S/seq block runs blocked on the MXU with the score matrix
    never leaving VMEM — the long-context ring configuration."""
    from dml_cnn_cifar10_tpu.ops import flash_attention as fa

    acc, m, l = fa.flash_attention_stats(q, k, v, scale=scale)
    m_ = jnp.transpose(m, (0, 2, 1))[..., None]       # [B,H,Sq,1]
    l_ = jnp.transpose(l, (0, 2, 1))[..., None]
    return m_, l_, acc                                # acc already f32


def _ring_body(carry, _, axis_name: str, scale: float, nsteps: int,
               use_pallas: bool = False):
    q, k, v, m, l, acc = carry
    stats = _block_stats_pallas if use_pallas else _block_stats
    bm, bl, bacc = stats(q, k, v, scale)
    m, l, acc = _merge(m, l, acc, bm, bl, bacc)
    # Rotate K/V one ring hop (neighbor ppermute over ICI). The final
    # rotation returns the shards to their home device, so the carry stays
    # consistent for any caller that reuses K/V.
    perm = [(i, (i + 1) % nsteps) for i in range(nsteps)]
    k = lax.ppermute(k, axis_name, perm)
    v = lax.ppermute(v, axis_name, perm)
    return (q, k, v, m, l, acc), None


def ring_attention_local(q: jax.Array, k: jax.Array, v: jax.Array,
                         axis_name: str, scale: Optional[float] = None,
                         use_pallas: bool = False) -> jax.Array:
    """Per-device body: runs under ``shard_map`` with Q/K/V sequence-sharded
    on ``axis_name``. Shapes [B, S_local, H, D] → [B, S_local, H, D].

    ``use_pallas`` routes each local block through the flash kernel's
    stats interface when the local shard is long enough to benefit
    (same ≥128 threshold as ``dispatch_attention``)."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    nsteps = lax.axis_size(axis_name)
    b, sq, h, d = q.shape
    m0 = jnp.full((b, h, sq, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, sq, 1), jnp.float32)
    a0 = jnp.zeros((b, sq, h, d), jnp.float32)

    body = functools.partial(_ring_body, axis_name=axis_name, scale=scale,
                             nsteps=nsteps,
                             use_pallas=use_pallas and sq >= 128)
    (q, k, v, m, l, acc), _ = lax.scan(
        body, (q, k, v, m0, l0, a0), None, length=nsteps)
    out = acc / jnp.transpose(l, (0, 2, 1, 3))
    return out.astype(q.dtype)


def sp_partition_spec(mesh: Mesh, axis_name: str, seq_len: int,
                      num_heads: int):
    """The shared sequence-parallel layout rule → ``(spec, head_axis)``.

    ``[B, S, H, D]`` partition spec for any SP attention kernel (ring or
    Ulysses): batch over ``data``, sequence over ``axis_name``. Heads are
    batch-like inside the local bodies, so when the mesh also has a
    nontrivial ``model`` (tensor-parallel) axis the heads dim shards over
    it — sp × tp compose with zero resharding at the kernel edge. When the
    head count doesn't divide the axis (e.g. default ViT-Ti's 3 heads on
    model=2), fall back to replicated heads: correct, just an all-gather
    at the kernel edge instead of a free composition. Raises on a sequence
    length the ``seq`` axis can't split.
    """
    nseq = mesh.shape[axis_name]
    if seq_len % nseq:
        raise ValueError(
            f"sequence length {seq_len} not divisible by seq axis {nseq}")
    nmodel = mesh.shape.get("model", 1)
    head_axis = "model" if nmodel > 1 and num_heads % nmodel == 0 else None
    return P("data", axis_name, head_axis, None), head_axis


def sp_shard_map(local_fn, mesh: Mesh, axis_name: str, seq_len: int,
                 num_heads: int):
    """Wrap an SP-local attention body in the standard shard_map: one
    ``(q, k, v) -> out`` callable with all tensors laid out per
    :func:`sp_partition_spec`."""
    spec, _ = sp_partition_spec(mesh, axis_name, seq_len, num_heads)
    return jax.shard_map(
        local_fn,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array, mesh: Mesh,
                   scale: Optional[float] = None,
                   axis_name: str = "seq",
                   use_pallas: bool = False) -> jax.Array:
    """Sequence-parallel attention over the mesh's ``seq`` axis.

    Global-view entrypoint: [B, S, H, D] arrays (sharded or not); S must be
    divisible by the ``seq`` axis size. Batch stays sharded on ``data`` so
    dp × sp compose. ``use_pallas`` runs each local block on the Pallas
    flash kernel (long-shard configs).
    """
    fn = sp_shard_map(
        functools.partial(ring_attention_local, axis_name=axis_name,
                          scale=scale, use_pallas=use_pallas),
        mesh, axis_name, q.shape[1], q.shape[2])
    return fn(q, k, v)


def sequence_sharding(mesh: Mesh) -> NamedSharding:
    """[B, S, H, D] sharding: batch over ``data``, sequence over ``seq``."""
    return NamedSharding(mesh, P("data", "seq", None, None))
