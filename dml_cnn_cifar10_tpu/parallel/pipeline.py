"""Pipeline parallelism over the ``pipe`` mesh axis — 1F1B (default) and
GPipe schedules.

No reference counterpart (SURVEY §2.3: pipeline parallelism absent), but a
first-class axis of this framework's mesh. The layer stack's leading
``[depth]`` axis is sharded over ``pipe`` (each stage holds ``depth/P``
contiguous layers resident in HBM), activations flow stage→stage with
neighbor ``lax.ppermute`` over ICI, and the schedule is a ``lax.scan``
over ticks inside one ``shard_map`` — data-flow in one compiled SPMD
program, not host-side orchestration, so XLA overlaps the ppermute
transfers with per-stage compute.

**1F1B** (the default; round-2 verdict weak #3 named GPipe's two costs):

- *No garbage compute*: a stage only runs its block stack when it holds a
  real microbatch (``lax.cond`` on the per-stage schedule — the grid is
  sequential per device, so a skipped tick really is skipped). GPipe's
  scan ran ``block_fn`` on junk for P−1 of M+P−1 ticks.
- *O(P) live activations*: the schedule carries a ``jax.custom_vjp``. The
  forward saves only ``(x, params)``; the backward runs ONE combined
  pipeline in which a just-in-time re-forward regenerates each stage's
  microbatch input ``2(P−s)−1`` ticks before the backward consumes it —
  the 1F1B interleave on the virtual 2P-stage pipeline (stage s hosts
  virtual stage ``s`` forward and ``2P−1−s`` backward; microbatch ``m``
  occupies virtual stage ``v`` at tick ``m+v``). Each device keeps a
  ring buffer of 2P microbatch inputs, independent of M. Autodiff
  through the GPipe scan instead checkpoints every tick's carry —
  O(M) microbatch buffers.
- *Composes with grad accumulation*: the custom_vjp makes the pipeline an
  ordinary differentiable op, so the step's grad-accum scan wraps it like
  any other model body.

Two 1F1B backward flavors (``schedule="1f1b"`` keeps the full-remat
default; ``"1f1b_ring"`` opts into the residual ring):

- **Recompute (default)** — the ring stores only each stage's
  microbatch INPUT; the consuming tick replays the primal inside
  ``jax.vjp``. Total 3 forwards + 1 backward (the re-forward and the
  replay run in different scan ticks, so XLA cannot CSE them), with
  the minimal O(P·microbatch) activation footprint.
- **Residual ring (round-4 verdict #3, built round 5)** — the
  just-in-time re-forward runs under ``jax.vjp`` and the ring stores
  the flattened VJP RESIDUALS (weight passthroughs filtered out by
  tracer identity — they stay loop-invariant closures, never
  duplicated per slot); the consuming tick applies the stored linear
  backward. Total 2 forwards + 1 backward, memory 2P slots × the
  per-microbatch activation-residual set (still flat in M).

**Measured verdict (tools/bench_pp.py, 8-virtual-CPU substrate,
round 5): the ring LOSES to recompute at every geometry tried** —
dim 64: 180 vs 126 ms (M=P), 213 vs 173 (M=4P); dim 256 batch 64:
3167 vs 2830 (M=P), 3385 vs 2733 (M=4P) — so recompute stays the
default and the ring ships opt-in. Mechanism: a transformer block's
residual set is ~10 activation-sized tensors per microbatch, so the
ring's store+load traffic exceeds the replay's FLOP cost until the
stage's arithmetic intensity is much higher (replay FLOPs grow
O(dim²·tokens), ring bytes O(dim·tokens) — the crossover sits at
dim ≈ thousands on real TPU ratios, and this substrate never reached
it). The negative result is recorded here the same way the maxpool-bwd
and block-512 rejections are (ops/layers.py, ops/flash_attention.py),
so it isn't silently retried; geometry where the ring should win can
be re-checked any time with ``bench_pp.py --dim``.

Composition: ``pipe`` composes with ``data`` (batch stays sharded
outside). Tensor/sequence axes inside a pipelined stack would need
hand-written collectives in the stage body (shard_map does not nest); the
step guards reject that combination rather than silently replicating.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from dml_cnn_cifar10_tpu.parallel.compat import shard_map

SCHEDULES = ("1f1b", "1f1b_ring", "gpipe")


def _validate(x, stacked_params, mesh, num_microbatches):
    nstages = mesh.shape["pipe"]
    depth = jax.tree.leaves(stacked_params)[0].shape[0]
    if depth % nstages:
        raise ValueError(
            f"depth {depth} not divisible by pipe axis {nstages}")
    m = num_microbatches or nstages
    ndata = mesh.shape["data"]
    if x.shape[0] % (ndata * m):
        raise ValueError(
            f"global batch {x.shape[0]} not divisible by data axis * "
            f"microbatches = {ndata}*{m}")
    return nstages, m


def pipeline_blocks(
    x: jax.Array,
    stacked_params: Any,
    block_fn: Callable[[jax.Array, Any], jax.Array],
    mesh: Mesh,
    num_microbatches: Optional[int] = None,
    schedule: str = "1f1b",
) -> jax.Array:
    """Run a stacked layer sequence as a pipeline over ``pipe``.

    x: global ``[B, S, D]`` activations (batch sharded over ``data``).
    stacked_params: pytree whose leaves have a leading ``[depth]`` axis.
    block_fn: ``(x_microbatch, one_layer_params) -> x_microbatch``.

    Returns the global ``[B, S, D]`` output (same sharding as ``x``).
    ``schedule``: ``"1f1b"`` (no bubble compute, recompute backward —
    3F+1B, minimal O(P·microbatch) memory; the measured default),
    ``"1f1b_ring"`` (residual-ring backward — 2F+1B, measured slower
    here; see module docstring), or ``"gpipe"`` (round-2 baseline, kept
    for comparison benches).
    """
    if schedule not in SCHEDULES:
        raise ValueError(f"unknown pipeline schedule {schedule!r}; "
                         f"have {SCHEDULES}")
    nstages = mesh.shape["pipe"]
    if nstages == 1:
        def seq_body(c, p):
            return block_fn(c, p), None
        return lax.scan(seq_body, x, stacked_params)[0]
    nstages, m = _validate(x, stacked_params, mesh, num_microbatches)
    if schedule == "gpipe":
        return _gpipe(x, stacked_params, block_fn, mesh, nstages, m)
    return _one_f_one_b(x, stacked_params, block_fn, mesh, nstages, m,
                        residual_ring=(schedule == "1f1b_ring"))


# ---------------------------------------------------------------------------
# Shared per-stage helpers.
# ---------------------------------------------------------------------------


def _stage_fn(block_fn):
    def stage(h, pl):
        return lax.scan(lambda c, p: (block_fn(c, p), None), h, pl)[0]
    return stage


def _specs(mesh, x, stacked_params):
    spec_x = P("data", *([None] * (x.ndim - 1)))
    spec_p = jax.tree.map(lambda _: P("pipe"), stacked_params)
    return spec_x, spec_p


# ---------------------------------------------------------------------------
# GPipe (round-2 baseline): always-on compute, autodiff through the scan.
# ---------------------------------------------------------------------------


def _gpipe(x, stacked_params, block_fn, mesh, nstages, m):
    stage = _stage_fn(block_fn)

    def local_fn(xl: jax.Array, pl: Any) -> jax.Array:
        stage_idx = lax.axis_index("pipe")
        bl, s, d = xl.shape
        mb = xl.reshape(m, bl // m, s, d)
        perm = [(i, (i + 1) % nstages) for i in range(nstages)]
        zeros = jnp.zeros_like(mb[0])

        def tick(carry, t):
            inflight, out_buf = carry
            feed = lax.dynamic_index_in_dim(
                mb, jnp.clip(t, 0, m - 1), keepdims=False)
            h = jnp.where(stage_idx == 0, feed, inflight)
            h = stage(h, pl)
            write = jnp.clip(t - (nstages - 1), 0, m - 1)
            out_buf = lax.dynamic_update_index_in_dim(
                out_buf, h, write, axis=0)
            inflight = lax.ppermute(h, "pipe", perm)
            return (inflight, out_buf), None

        (_, out_buf), _ = lax.scan(
            tick, (zeros, jnp.zeros_like(mb)),
            jnp.arange(m + nstages - 1))
        out = out_buf.reshape(bl, s, d)
        out = jnp.where(stage_idx == nstages - 1, out, 0)
        return lax.psum(out, "pipe")

    spec_x, spec_p = _specs(mesh, x, stacked_params)
    fn = shard_map(local_fn, mesh=mesh, in_specs=(spec_x, spec_p),
                       out_specs=spec_x, check_vma=False)
    return fn(x, stacked_params)


# ---------------------------------------------------------------------------
# 1F1B.
# ---------------------------------------------------------------------------


def _1f1b_forward_local(xl, pl, *, stage, nstages, m):
    """Forward schedule: microbatch t−s at stage s on tick t, bubbles
    skipped (lax.cond; the ppermute collective stays outside)."""
    stage_idx = lax.axis_index("pipe")
    bl, s, d = xl.shape
    mb = xl.reshape(m, bl // m, s, d)
    perm = [(i, (i + 1) % nstages) for i in range(nstages)]
    zeros = jnp.zeros_like(mb[0])

    def tick(carry, t):
        inflight, out_buf = carry
        mf = t - stage_idx
        valid = (mf >= 0) & (mf < m)
        feed = lax.dynamic_index_in_dim(
            mb, jnp.clip(mf, 0, m - 1), keepdims=False)
        h_in = jnp.where(stage_idx == 0, feed, inflight)
        h_out = lax.cond(valid, lambda h: stage(h, pl),
                         lambda h: jnp.zeros_like(h), h_in)
        is_last = stage_idx == nstages - 1
        out_buf = lax.cond(
            valid & is_last,
            lambda b: lax.dynamic_update_index_in_dim(
                b, h_out, jnp.clip(mf, 0, m - 1), axis=0),
            lambda b: b, out_buf)
        inflight = lax.ppermute(h_out, "pipe", perm)
        return (inflight, out_buf), None

    (_, out_buf), _ = lax.scan(
        tick, (zeros, jnp.zeros_like(mb)), jnp.arange(m + nstages - 1))
    out = out_buf.reshape(bl, s, d)
    out = jnp.where(stage_idx == nstages - 1, out, 0)
    return lax.psum(out, "pipe")


def _1f1b_backward_local(xl, pl, gl, *, stage, nstages, m):
    """The combined just-in-time-re-forward + backward pipeline.

    Virtual 2P-stage schedule: physical stage s re-forwards microbatch
    ``t−s`` and backwards microbatch ``t−(2P−1−s)`` on tick t. A stage's
    re-forward therefore runs ``2(P−s)−1`` ticks before its backward
    consumes the saved input — the ring buffer of 2P microbatch inputs is
    the entire activation footprint, independent of M.
    """
    stage_idx = lax.axis_index("pipe")
    bl, s, d = xl.shape
    mb = xl.reshape(m, bl // m, s, d)
    gmb = gl.reshape(m, bl // m, s, d)
    nring = 2 * nstages
    perm_f = [(i, (i + 1) % nstages) for i in range(nstages)]
    perm_b = [(i, (i - 1) % nstages) for i in range(nstages)]
    zeros = jnp.zeros_like(mb[0])

    def tick(carry, t):
        f_in, b_in, save, dx_buf, dpl = carry

        # --- forward sub-tick: recompute microbatch mf = t - s.
        mf = t - stage_idx
        valid_f = (mf >= 0) & (mf < m)
        feed = lax.dynamic_index_in_dim(
            mb, jnp.clip(mf, 0, m - 1), keepdims=False)
        h_in = jnp.where(stage_idx == 0, feed, f_in)
        h_out = lax.cond(valid_f, lambda h: stage(h, pl),
                         lambda h: jnp.zeros_like(h), h_in)
        # Save the stage INPUT for the backward, slot t mod 2P. The same
        # slot is rewritten 2P ticks later; max residual lifetime is
        # 2P−1 ticks (s=0), so reads always win the race.
        save = lax.cond(
            valid_f,
            lambda sv: lax.dynamic_update_index_in_dim(
                sv, h_in, jnp.asarray(t % nring), axis=0),
            lambda sv: sv, save)

        # --- backward sub-tick: microbatch mbb = t - (2P-1-s).
        mbb = t - (2 * nstages - 1 - stage_idx)
        valid_b = (mbb >= 0) & (mbb < m)
        g_feed = lax.dynamic_index_in_dim(
            gmb, jnp.clip(mbb, 0, m - 1), keepdims=False)
        g_in = jnp.where(stage_idx == nstages - 1, g_feed, b_in)
        slot = jnp.asarray((mbb + stage_idx) % nring)
        h_saved = lax.dynamic_index_in_dim(save, jnp.clip(slot, 0, nring - 1),
                                           keepdims=False)

        def run_bwd(args):
            h_saved, g_in = args
            _, vjp = jax.vjp(stage, h_saved, pl)
            return vjp(g_in)

        def skip_bwd(args):
            return (jnp.zeros_like(zeros),
                    jax.tree.map(jnp.zeros_like, pl))

        dh, dp = lax.cond(valid_b, run_bwd, skip_bwd, (h_saved, g_in))
        dpl = jax.tree.map(jnp.add, dpl, dp)
        dx_buf = lax.cond(
            valid_b & (stage_idx == 0),
            lambda b: lax.dynamic_update_index_in_dim(
                b, dh, jnp.clip(mbb, 0, m - 1), axis=0),
            lambda b: b, dx_buf)

        f_in = lax.ppermute(h_out, "pipe", perm_f)
        b_in = lax.ppermute(dh, "pipe", perm_b)
        return (f_in, b_in, save, dx_buf, dpl), None

    save0 = jnp.zeros((nring, *zeros.shape), zeros.dtype)
    dpl0 = jax.tree.map(jnp.zeros_like, pl)
    (_, _, _, dx_buf, dpl), _ = lax.scan(
        tick, (zeros, zeros, save0, jnp.zeros_like(mb), dpl0),
        jnp.arange(m + 2 * nstages - 1))
    dx = dx_buf.reshape(bl, s, d)
    # Only stage 0 computed real dx; make it identical on every stage so
    # the out sharding (replicated over pipe) holds.
    dx = jnp.where(stage_idx == 0, dx, 0)
    # Params are replicated over the data axis, so their cotangent is the
    # SUM over data shards (each device differentiated against its own
    # batch shard). Autodiff inserts this psum for the GPipe path as the
    # transpose of the unmentioned-axis broadcast; the manual backward
    # must say it.
    dpl = lax.psum(dpl, "data")
    return lax.psum(dx, "pipe"), dpl


def _1f1b_ring_backward_local(xl, pl, gl, *, stage, nstages, m):
    """The residual-ring combined re-forward + backward pipeline (2F+1B).

    Same virtual 2P-stage schedule as ``_1f1b_backward_local``, but the
    just-in-time re-forward runs under ``jax.vjp`` and the ring stores
    the FLATTENED VJP RESIDUALS of each live microbatch; the consuming
    tick rebuilds the vjp Partial from its ring slot and applies the
    stored linear backward — no primal replay. Ring lifetime analysis is
    unchanged (slot ``t mod 2P``, max residual lifetime ``2(P−s)−1 <
    2P`` ticks), so reads always win the race.

    Residual contents are whatever partial-eval saves for a generic
    ``block_fn`` — per-layer matmul/attention inputs AND the stage
    weights (needed for ``dx = g·Wᵀ``); the weights replicate into every
    ring slot, which is the memory premium over the recompute flavor.
    Memory stays flat in M (``tests/test_pp.py``).
    """
    stage_idx = lax.axis_index("pipe")
    bl, s, d = xl.shape
    mb = xl.reshape(m, bl // m, s, d)
    gmb = gl.reshape(m, bl // m, s, d)
    nring = 2 * nstages
    perm_f = [(i, (i + 1) % nstages) for i in range(nstages)]
    perm_b = [(i, (i - 1) % nstages) for i in range(nstages)]
    zeros = jnp.zeros_like(mb[0])

    # Residual pytree structure (treedef + leaf avals) from one trace of
    # the stage vjp. Leaves that are PASSTHROUGH INPUTS (the stage
    # weights — partial-eval forwards unmodified inputs into the
    # residual set as the same traced value, so identity against pl's
    # leaves detects them) are loop-invariant: they stay closed over
    # instead of ring-stored, so the ring never duplicates weights —
    # only the per-microbatch activation residuals ride it. The
    # template's microbatch-dependent VALUES are never used (rings init
    # from fresh zeros), so XLA dead-code-eliminates the trace.
    pl_leaf_ids = {id(l) for l in jax.tree.leaves(pl)}
    _, vjp0 = jax.vjp(stage, zeros, pl)
    leaves0, res_tree = jax.tree.flatten(vjp0)
    stored = tuple(id(l) not in pl_leaf_ids for l in leaves0)
    ring0 = tuple(jnp.zeros((nring, *l.shape), l.dtype)
                  for l, st in zip(leaves0, stored) if st)

    def tick(carry, t):
        f_in, b_in, rings, dx_buf, dpl = carry

        # --- forward sub-tick: recompute microbatch mf = t - s under
        # vjp, capturing residuals instead of the raw input.
        mf = t - stage_idx
        valid_f = (mf >= 0) & (mf < m)
        feed = lax.dynamic_index_in_dim(
            mb, jnp.clip(mf, 0, m - 1), keepdims=False)
        h_in = jnp.where(stage_idx == 0, feed, f_in)

        def run_fwd(h):
            h_out, vjp_fn = jax.vjp(stage, h, pl)
            ls = jax.tree.flatten(vjp_fn)[0]
            # The ring layout was sized from the TEMPLATE trace's leaves
            # (leaves0) and the consuming tick re-interleaves by
            # position — all on the undocumented assumption that every
            # per-tick vjp trace produces residual leaves in the same
            # order with the same avals. Partial-eval gives no such
            # contract across jax versions, so verify it at trace time
            # instead of silently corrupting gradients on mismatch.
            if len(ls) != len(leaves0) or any(
                    l.shape != l0.shape or l.dtype != l0.dtype
                    for l, l0 in zip(ls, leaves0)):
                raise AssertionError(
                    "1f1b_ring: per-tick vjp residual leaves diverge "
                    "from the template trace (positional shape/dtype "
                    "mismatch) — the ring buffers no longer line up "
                    "with the stored-leaf mask; got "
                    f"{[(l.shape, str(l.dtype)) for l in ls]} vs "
                    f"{[(l.shape, str(l.dtype)) for l in leaves0]}")
            return h_out, tuple(l for l, st in zip(ls, stored) if st)

        def skip_fwd(h):
            return (jnp.zeros_like(h),
                    tuple(jnp.zeros(l.shape, l.dtype)
                          for l, st in zip(leaves0, stored) if st))

        h_out, new_leaves = lax.cond(valid_f, run_fwd, skip_fwd, h_in)
        # UNCONDITIONAL ring write: slot t mod 2P's previous resident was
        # consumed by tick t−1 at the latest (lifetime ≤ 2P−1), so a
        # bubble tick writing zeros never clobbers live state — and
        # skipping the cond lets XLA lower a true in-place
        # dynamic-update-slice instead of double-buffering the rings
        # through both cond branches.
        rings = tuple(
            lax.dynamic_update_index_in_dim(
                r, nl, jnp.asarray(t % nring), axis=0)
            for r, nl in zip(rings, new_leaves))

        # --- backward sub-tick: microbatch mbb = t - (2P-1-s) applies
        # its stored linear backward.
        mbb = t - (2 * nstages - 1 - stage_idx)
        valid_b = (mbb >= 0) & (mbb < m)
        g_feed = lax.dynamic_index_in_dim(
            gmb, jnp.clip(mbb, 0, m - 1), keepdims=False)
        g_in = jnp.where(stage_idx == nstages - 1, g_feed, b_in)
        slot = jnp.clip(jnp.asarray((mbb + stage_idx) % nring), 0,
                        nring - 1)
        leaves_at = tuple(
            lax.dynamic_index_in_dim(r, slot, keepdims=False)
            for r in rings)

        def run_bwd(args):
            leaves, g = args
            # Re-interleave ring-stored activation residuals with the
            # loop-invariant weight residuals (closed over from the
            # template trace — identical arrays every microbatch).
            it = iter(leaves)
            full = [next(it) if st else l0
                    for l0, st in zip(leaves0, stored)]
            vjp_fn = jax.tree.unflatten(res_tree, full)
            return vjp_fn(g)

        def skip_bwd(args):
            return (jnp.zeros_like(zeros),
                    jax.tree.map(jnp.zeros_like, pl))

        dh, dp = lax.cond(valid_b, run_bwd, skip_bwd, (leaves_at, g_in))
        dpl = jax.tree.map(jnp.add, dpl, dp)
        dx_buf = lax.cond(
            valid_b & (stage_idx == 0),
            lambda b: lax.dynamic_update_index_in_dim(
                b, dh, jnp.clip(mbb, 0, m - 1), axis=0),
            lambda b: b, dx_buf)

        f_in = lax.ppermute(h_out, "pipe", perm_f)
        b_in = lax.ppermute(dh, "pipe", perm_b)
        return (f_in, b_in, rings, dx_buf, dpl), None

    dpl0 = jax.tree.map(jnp.zeros_like, pl)
    (_, _, _, dx_buf, dpl), _ = lax.scan(
        tick, (zeros, zeros, ring0, jnp.zeros_like(mb), dpl0),
        jnp.arange(m + 2 * nstages - 1))
    dx = dx_buf.reshape(bl, s, d)
    dx = jnp.where(stage_idx == 0, dx, 0)
    # Same psum rationale as the recompute flavor (see below).
    dpl = lax.psum(dpl, "data")
    return lax.psum(dx, "pipe"), dpl


def _one_f_one_b(x, stacked_params, block_fn, mesh, nstages, m,
                 residual_ring: bool = False):
    stage = _stage_fn(block_fn)
    spec_x, spec_p = _specs(mesh, x, stacked_params)

    fwd_local = functools.partial(_1f1b_forward_local, stage=stage,
                                  nstages=nstages, m=m)
    bwd_local = functools.partial(
        _1f1b_ring_backward_local if residual_ring
        else _1f1b_backward_local,
        stage=stage, nstages=nstages, m=m)

    fwd_sm = shard_map(fwd_local, mesh=mesh, in_specs=(spec_x, spec_p),
                           out_specs=spec_x, check_vma=False)
    bwd_sm = shard_map(bwd_local, mesh=mesh,
                           in_specs=(spec_x, spec_p, spec_x),
                           out_specs=(spec_x, spec_p), check_vma=False)

    @jax.custom_vjp
    def pipe(x, params):
        return fwd_sm(x, params)

    def pipe_fwd(x, params):
        return fwd_sm(x, params), (x, params)

    def pipe_bwd(res, g):
        x, params = res
        return bwd_sm(x, params, g.astype(x.dtype))

    pipe.defvjp(pipe_fwd, pipe_bwd)
    return pipe(x, stacked_params)
