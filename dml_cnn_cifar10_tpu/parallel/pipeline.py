"""Pipeline parallelism — GPipe microbatch schedule over the ``pipe`` axis.

No reference counterpart (SURVEY §2.3: pipeline parallelism absent), but a
first-class axis of this framework's mesh. The design is the idiomatic TPU
pipelining recipe: the layer stack's leading ``[depth]`` axis is sharded
over ``pipe`` (each stage holds ``depth/P`` contiguous layers resident in
HBM), activations flow stage→stage with neighbor ``lax.ppermute`` over ICI,
and a ``lax.scan`` over ``M + P - 1`` ticks runs the classic GPipe
schedule: microbatch ``m`` occupies stage ``s`` at tick ``t = s + m``.

Everything is one compiled SPMD program — the schedule is data-flow inside
``shard_map``, not host-side orchestration, so XLA overlaps the ppermute
transfers with the per-stage compute (the same latency-hiding that makes
ring attention cheap). Autodiff just works: the backward pass of the
scan-of-ppermute is the reverse pipeline.

Composition: ``pipe`` composes with ``data`` (batch stays sharded outside).
Tensor/sequence axes inside a pipelined stack would need hand-written
collectives in the stage body (shard_map does not nest); the step guards
reject that combination rather than silently replicating.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P


def pipeline_blocks(
    x: jax.Array,
    stacked_params: Any,
    block_fn: Callable[[jax.Array, Any], jax.Array],
    mesh: Mesh,
    num_microbatches: Optional[int] = None,
) -> jax.Array:
    """Run a stacked layer sequence as a GPipe pipeline over ``pipe``.

    x: global ``[B, S, D]`` activations (batch sharded over ``data``).
    stacked_params: pytree whose leaves have a leading ``[depth]`` axis.
    block_fn: ``(x_microbatch, one_layer_params) -> x_microbatch``.

    Returns the global ``[B, S, D]`` output (same sharding as ``x``).
    """
    nstages = mesh.shape["pipe"]
    if nstages == 1:
        def seq_body(c, p):
            return block_fn(c, p), None
        return lax.scan(seq_body, x, stacked_params)[0]

    depth = jax.tree.leaves(stacked_params)[0].shape[0]
    if depth % nstages:
        raise ValueError(
            f"depth {depth} not divisible by pipe axis {nstages}")
    m = num_microbatches or nstages
    ndata = mesh.shape["data"]
    if x.shape[0] % (ndata * m):
        raise ValueError(
            f"global batch {x.shape[0]} not divisible by data axis * "
            f"microbatches = {ndata}*{m}")

    def local_fn(xl: jax.Array, pl: Any) -> jax.Array:
        # xl: [B_local, S, D] (this data-shard's batch, replicated over
        # pipe); pl: leaves [depth/P, ...] (this stage's layers).
        stage_idx = lax.axis_index("pipe")
        bl, s, d = xl.shape
        mb = xl.reshape(m, bl // m, s, d)

        def stage(h):
            return lax.scan(lambda c, p: (block_fn(c, p), None), h, pl)[0]

        perm = [(i, (i + 1) % nstages) for i in range(nstages)]
        zeros = jnp.zeros_like(mb[0])

        def tick(carry, t):
            inflight, out_buf = carry
            # Stage 0 injects microbatch t (clamped; ticks >= M push
            # garbage that no valid slot ever reads). Other stages consume
            # what the previous stage sent last tick.
            feed = lax.dynamic_index_in_dim(
                mb, jnp.clip(t, 0, m - 1), keepdims=False)
            h = jnp.where(stage_idx == 0, feed, inflight)
            h = stage(h)
            # The last stage owns microbatch t-(P-1) at tick t. Early ticks
            # write garbage to slot 0, overwritten when the real microbatch
            # 0 arrives at t = P-1 (writes happen in slot order).
            write = jnp.clip(t - (nstages - 1), 0, m - 1)
            out_buf = lax.dynamic_update_index_in_dim(
                out_buf, h, write, axis=0)
            inflight = lax.ppermute(h, "pipe", perm)
            return (inflight, out_buf), None

        (_, out_buf), _ = lax.scan(
            tick, (zeros, jnp.zeros_like(mb)),
            jnp.arange(m + nstages - 1))
        out = out_buf.reshape(bl, s, d)
        # Only the last stage holds real outputs; broadcast to every stage
        # so downstream (head/loss) math is replicated over pipe.
        out = jnp.where(stage_idx == nstages - 1, out, 0)
        return lax.psum(out, "pipe")

    spec_x = P("data", None, None)
    spec_p = jax.tree.map(lambda _: P("pipe"), stacked_params)
    fn = jax.shard_map(
        local_fn,
        mesh=mesh,
        in_specs=(spec_x, spec_p),
        out_specs=spec_x,
        check_vma=False,
    )
    return fn(x, stacked_params)
