"""Cluster resilience: heartbeats, collective watchdog, coordinated restart.

The reference's PS runtime survived worker churn because a dead worker
only idled its own queue (``cifar10cnn.py:184-196``); the chief and the
other workers kept optimizing. Synchronous SPMD inverts that failure
mode: one hung or dead host stalls every XLA collective forever, with
no error, no timeout, and no log line. This module is the missing
liveness layer (what TF-Replicator calls out as the coordination half
of the contract, arXiv:1902.00465):

- :class:`HeartbeatStore` — a file-backed beat store (any shared
  directory: NFS/GCS-fuse in production, a tmpdir in the CPU
  simulation). Every process publishes ``{process_id, step, wallclock,
  phase}`` via atomic rename; peers read without locks.
- :class:`CollectiveWatchdog` — a daemon thread armed around each
  dispatch seam. When the seam overruns ``straggler_after_s`` it reads
  the peer beats and classifies: a peer still beating but behind is a
  **straggler** (telemetry only — emit a ``straggler`` record naming
  the lagging process); a peer whose beat is stale past
  ``peer_dead_after_s`` is a **hang / host loss** (mark it dead so the
  seam can abort deterministically instead of blocking in XLA). If the
  main thread is genuinely wedged inside a collective past
  ``collective_timeout_s``, the watchdog aborts the process itself
  (``os._exit``) after logging — a loud corpse beats a silent hang.
- :class:`RestartCoordinator` — the chief records a restart decision
  ``{epoch, world_size, restore_step, survivors, kind}`` (atomic
  rename); surviving non-chiefs poll for it; a process excluded from
  the survivor set fences itself (:class:`EvictedError`) instead of
  rejoining a world that already gave up on it — unless elastic
  scale-UP (``elastic_expand``) is armed, in which case the fence is an
  invitation: the excluded/returning process announces itself with a
  ``rejoin``-phase beat, the chief records a monotone-epoch **expand**
  decision growing the world to the live hosts, and everyone re-enters
  restore at the larger world size (the device index stream reshards
  deterministically — no per-host sidecar state to migrate).
- :class:`ClusterMonitor` — the per-process façade the Trainer and the
  run supervisor use: background beat publisher, watchdog lifecycle,
  seam hooks (``begin_step`` / ``sync`` / ``end_step``), and the
  eviction check.

Simulation: with ``cluster_lockstep=True`` the ``sync`` seam waits for
every live peer's beat to reach the local step — a software stand-in
for the XLA collective barrier — so a 2-process CPU run (each process
its own single-process JAX world) exercises straggler detection, death
classification, and the coordinated elastic restart end-to-end in
tier-1 (``tests/test_cluster.py``). Real multi-host runs leave
lockstep off: the collectives already enforce it, and the watchdog's
job is only to observe and abort.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import sys
import threading
import time
from typing import Dict, List, Optional, Sequence

from dml_cnn_cifar10_tpu.utils import backoff

#: Exit code of a watchdog abort (dead peer while blocked in a
#: collective, or self-classified hang) — distinct from a crash so the
#: scheduler can tell "fenced by the resilience layer" from "bug".
EXIT_WATCHDOG_ABORT = 78


class PeerLostError(RuntimeError):
    """One or more peers' heartbeats went stale past
    ``peer_dead_after_s`` — the run cannot continue at this world size.
    Classified as recoverable by the supervisor (``peer_lost``). Also
    raised (with an EMPTY ``process_ids``) when a newer coordinator
    epoch is observed mid-step: the chief already committed a new world
    and the clean move is to exit the step loop and adopt it, not to
    race the decision file."""

    def __init__(self, process_ids: Sequence[int], message: str):
        super().__init__(message)
        self.process_ids = sorted(process_ids)


class PeerRejoinError(RuntimeError):
    """A returning (or brand-new) host announced itself with a
    ``rejoin``-phase beat while this chief was mid-run. Classified as
    recoverable by the supervisor (``peer_rejoin``): the chief answers
    with a coordinated **expand** restart growing the world to the live
    hosts."""

    def __init__(self, process_ids: Sequence[int], message: str):
        super().__init__(message)
        self.process_ids = sorted(process_ids)


class EvictedError(RuntimeError):
    """A restart decision excluded this process: the surviving world
    declared it dead (stalled heartbeats look identical to a dead host
    from outside). The only correct move is a clean, saveless exit —
    rejoining would split-brain the run."""


@dataclasses.dataclass
class Beat:
    process_id: int
    step: int
    wallclock: float
    phase: str
    # Free-form payload beyond the train-loop fields. The serving fleet
    # publishes {replica_id, version, queue_depth, port} here (its
    # "step" is the batch-dispatch counter); train phases leave it
    # None. Old beat files without the key still decode (default).
    extra: Optional[Dict] = None

    def age_s(self, now: Optional[float] = None) -> float:
        return (now if now is not None else time.time()) - self.wallclock


@dataclasses.dataclass
class RestartDecision:
    epoch: int
    world_size: int
    restore_step: int
    survivors: List[int]
    # "shrink" (a host was lost; PR 4) or "expand" (a host rejoined /
    # arrived; the scale-UP half). Default keeps pre-expand decision
    # files decodable.
    kind: str = "shrink"
    # Where survivors restore from: "disk" (the newest-verifiable
    # checkpoint walk — the historical behavior) or "peer" (the
    # peer-replica store, ckpt/peerstore.py: own shards from memory,
    # lost hosts' from their ring-successors' replicas — zero
    # checkpoint reads). Default keeps pre-redundancy decision files
    # decodable AND restoring exactly as today.
    source: str = "disk"


class HeartbeatStore:
    """Atomic-rename JSON beats under ``<cluster_dir>/heartbeats/``.

    File-backed deliberately: the store must work where the collectives
    do NOT (that is the whole point), must be inspectable post-mortem
    with ``cat``, and must be simulatable on CPU without a network
    stack. A socket/KV backend can replace it behind the same
    publish/read API."""

    def __init__(self, cluster_dir: str, process_id: int, log_fn=None):
        self.dir = os.path.join(cluster_dir, "heartbeats")
        self.process_id = process_id
        os.makedirs(self.dir, exist_ok=True)
        self.started_at = time.time()
        # Telemetry sink for torn/undecodable beats found mid-scan
        # (read_all). Rate-limited per path: discovery consumers (the
        # fleet router) scan at poll cadence and one corrupt file must
        # not flood the stream.
        self._log = log_fn
        self._last_decode_note: Dict[str, float] = {}

    def _path(self, pid: int) -> str:
        return os.path.join(self.dir, f"proc_{pid}.json")

    def publish(self, step: int, phase: str,
                extra: Optional[Dict] = None) -> Beat:
        beat = Beat(self.process_id, int(step), time.time(), phase,
                    extra=extra)
        # Tmp name unique per pid AND thread: the background publisher
        # thread and a dispatch-seam publish from the main thread would
        # otherwise race on one tmp file (write/replace interleaving →
        # FileNotFoundError on the loser's replace).
        tmp = self._path(self.process_id) \
            + f".tmp{os.getpid()}.{threading.get_ident()}"
        with open(tmp, "w") as f:
            json.dump(dataclasses.asdict(beat), f)
        os.replace(tmp, self._path(self.process_id))
        return beat

    def read(self, pid: int) -> Optional[Beat]:
        """The peer's latest beat, or None if it never published (a
        torn read — mid-rename on exotic filesystems — reads as None
        too and self-heals on the next poll)."""
        try:
            with open(self._path(pid)) as f:
                return Beat(**json.load(f))
        except (OSError, ValueError, TypeError):
            return None

    def read_peers(self, expected: Sequence[int]) -> Dict[int, Optional[Beat]]:
        return {pid: self.read(pid) for pid in expected
                if pid != self.process_id}

    def _note_decode(self, path: str, error: str) -> None:
        if self._log is None:
            return
        now = time.time()
        if now - self._last_decode_note.get(path, 0.0) < 1.0:
            return
        self._last_decode_note[path] = now
        self._log("beat_decode_error", path=path, error=error[:200])

    def read_all(self) -> Dict[int, Beat]:
        """Every beat present on disk, keyed by process id — discovery
        for consumers that do NOT know the membership up front (the
        fleet router learns replicas, and their advertised ports, from
        whoever beats here). Self included. A file that VANISHES
        mid-scan is a benign rename race and is skipped silently; a
        file that is present but undecodable (torn/partial write on a
        non-atomic filesystem) is skipped with a classified
        ``beat_decode_error`` record — the scan must survive one bad
        peer, and the stream must say which one."""
        out: Dict[int, Beat] = {}
        try:
            names = os.listdir(self.dir)
        except OSError:
            return out
        for name in names:
            if not (name.startswith("proc_") and name.endswith(".json")):
                continue
            try:
                pid = int(name[len("proc_"):-len(".json")])
            except ValueError:
                continue
            path = os.path.join(self.dir, name)
            try:
                with open(path) as f:
                    text = f.read()
            except OSError:
                continue  # mid-rename; self-heals on the next poll
            try:
                out[pid] = Beat(**json.loads(text))
            except (ValueError, TypeError) as e:
                self._note_decode(path, str(e))
        return out


class RestartCoordinator:
    """Chief-written, survivor-polled restart decisions.

    The decision file is the cluster's only piece of mutable shared
    truth, so it follows the checkpoint rules: written to a tmp name,
    committed by atomic rename, monotone ``epoch`` so a stale decision
    can never be mistaken for a new one — and, like a checkpoint, it
    carries a sha256 integrity sidecar (``restart_decision.json.sha256``)
    committed AFTER the payload. A decision every survivor is about to
    rebuild its world around must not be trusted on a successful JSON
    parse alone: bit rot / a half-synced shared filesystem can serve a
    decodable-but-wrong payload. :meth:`read` therefore returns **None
    with a classified ``decision_corrupt`` telemetry record** on an
    undecodable or sidecar-mismatched file, instead of either crashing
    unclassified or silently adopting garbage; the poll loops that call
    it self-heal on the next read. A payload without any sidecar is a
    pre-hardening (or mid-commit) decision file and still decodes."""

    def __init__(self, cluster_dir: str, log_fn=None):
        self.path = os.path.join(cluster_dir, "restart_decision.json")
        self.sidecar_path = self.path + ".sha256"
        os.makedirs(cluster_dir, exist_ok=True)
        # Telemetry sink for corrupt-decision reads; the owning
        # ClusterMonitor wires its (locked) log method in. Rate-limited
        # per payload digest — await_decision polls at 20 Hz and one
        # corrupt file must not flood the stream.
        self._log = log_fn
        self._last_bad_digest: Optional[str] = None

    def _note_corrupt(self, digest: str, error: str) -> None:
        if digest == self._last_bad_digest:
            return
        self._last_bad_digest = digest
        print(f"[cluster] corrupt restart decision {self.path}: "
              f"{error}; reading as absent", file=sys.stderr)
        if self._log is not None:
            self._log("decision_corrupt", path=self.path, error=error)

    def read(self) -> Optional[RestartDecision]:
        try:
            with open(self.path, "rb") as f:
                payload = f.read()
        except OSError:
            return None
        digest = hashlib.sha256(payload).hexdigest()
        want = None
        try:
            with open(self.sidecar_path) as f:
                want = json.load(f)["digest"]
        except OSError:
            want = None  # no sidecar: legacy / mid-commit — decode only
        except (ValueError, TypeError, KeyError) as e:
            self._note_corrupt(digest, f"undecodable sidecar: {e}")
            return None
        if want is not None and want != digest:
            self._note_corrupt(
                digest, f"sidecar digest mismatch (have {digest[:12]}…, "
                        f"sidecar says {str(want)[:12]}…)")
            return None
        try:
            return RestartDecision(**json.loads(payload))
        except (ValueError, TypeError) as e:
            self._note_corrupt(digest, f"undecodable decision: {e}")
            return None

    def record(self, decision: RestartDecision) -> RestartDecision:
        prior = self.read()
        if prior is not None and prior.epoch >= decision.epoch:
            raise ValueError(
                f"restart epoch must be monotone: have {prior.epoch}, "
                f"recording {decision.epoch}")
        payload = json.dumps(dataclasses.asdict(decision)).encode()
        # Commit order is payload → sidecar (each via atomic rename):
        # a reader between the two renames sees new payload + stale
        # sidecar, reads it as corrupt-absent, and self-heals on the
        # next poll — strictly better than a window where a mismatched
        # pair could be half-trusted.
        tmp = self.path + f".tmp{os.getpid()}"
        with open(tmp, "wb") as f:
            f.write(payload)
        os.replace(tmp, self.path)
        sidecar = {"algo": "sha256",
                   "digest": hashlib.sha256(payload).hexdigest()}
        tmp = self.sidecar_path + f".tmp{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(sidecar, f)
        os.replace(tmp, self.sidecar_path)
        return decision

    def await_decision(self, min_epoch: int, timeout_s: float,
                       poll_s: float = 0.05) -> RestartDecision:
        """Non-chief survivors block here until the chief commits a
        decision at/after ``min_epoch``. A chief that never decides is
        a coordinator loss: raise ``PeerLostError(chief)`` so the
        caller fails deterministically instead of polling forever."""
        deadline = time.time() + timeout_s
        attempt = 0
        while True:
            d = self.read()
            if d is not None and d.epoch >= min_epoch:
                return d
            if time.time() > deadline:
                raise PeerLostError(
                    [0], f"no restart decision at epoch >= {min_epoch} "
                         f"within {timeout_s:.1f}s — coordinator lost")
            # Shared bounded backoff (utils/backoff.py) instead of a
            # fixed-cadence poll: N survivors polling one shared file
            # at 20 Hz hammers the store at larger world sizes; the
            # cap keeps adoption latency bounded at ~10x the base.
            attempt += 1
            time.sleep(backoff.delay_s(poll_s, poll_s * 10.0, attempt))


class CollectiveWatchdog(threading.Thread):
    """Deadline thread around the dispatch seam.

    ``arm(step)`` starts the clock; ``disarm()`` stops it. While armed
    past ``straggler_after_s`` the thread polls the beat store and
    classifies each peer: stale past ``peer_dead_after_s`` → dead
    (recorded in ``dead_peers``; the seam raises ``PeerLostError``
    deterministically); beating but behind → ``straggler`` telemetry,
    rate-limited per peer. Armed past ``collective_timeout_s`` the main
    thread is presumed wedged inside XLA (a state Python cannot unwind)
    and the watchdog aborts the process after logging — classification
    ``peer_dead`` if a corpse was found, ``self_hang`` otherwise."""

    def __init__(self, store: HeartbeatStore, monitor: "ClusterMonitor",
                 straggler_after_s: float, peer_dead_after_s: float,
                 collective_timeout_s: float, abort_fn=None):
        super().__init__(daemon=True, name="collective-watchdog")
        self.store = store
        self.monitor = monitor
        self.straggler_after_s = straggler_after_s
        self.peer_dead_after_s = peer_dead_after_s
        self.collective_timeout_s = collective_timeout_s
        self.dead_peers: set = set()
        self._abort_fn = abort_fn if abort_fn is not None else self._abort
        self._armed_at: Optional[float] = None
        self._armed_step = 0
        self._stop_evt = threading.Event()
        self._lock = threading.Lock()
        self._last_straggle_log: Dict[int, float] = {}

    def arm(self, step: int) -> None:
        with self._lock:
            self._armed_at = time.time()
            self._armed_step = step

    def disarm(self) -> None:
        with self._lock:
            self._armed_at = None

    def stop(self) -> None:
        self._stop_evt.set()

    def _abort(self, verdict: str) -> None:  # pragma: no cover - os._exit
        os._exit(EXIT_WATCHDOG_ABORT)

    def check_peers(self, now: Optional[float] = None) -> None:
        """One classification pass (also called directly by the seam's
        sync wait, so detection does not depend on thread timing)."""
        now = now if now is not None else time.time()
        step = self._armed_step
        for pid, beat in self.store.read_peers(self.monitor.live_set()).items():
            if pid in self.dead_peers:
                continue
            # A peer that never published counts from the store's birth:
            # a host that failed to even start is as dead as one that
            # stopped.
            age = beat.age_s(now) if beat is not None \
                else now - self.store.started_at
            if age > self.peer_dead_after_s:
                self.dead_peers.add(pid)
                self.monitor.log("peer_lost", step=step, process_id=pid,
                                 reason="stale_heartbeat",
                                 beat_age_s=round(age, 3))
                print(f"[cluster] process {pid} heartbeat stale "
                      f"{age:.1f}s > {self.peer_dead_after_s:.1f}s: "
                      f"declaring host lost")
            elif beat is not None and beat.step < step:
                last = self._last_straggle_log.get(pid, 0.0)
                if now - last >= self.straggler_after_s:
                    self._last_straggle_log[pid] = now
                    self.monitor.log("straggler", step=step,
                                     process_id=pid,
                                     behind_steps=step - beat.step,
                                     beat_age_s=round(age, 3))

    def run(self) -> None:
        poll = max(0.02, min(self.straggler_after_s / 4, 0.25))
        while not self._stop_evt.wait(poll):
            with self._lock:
                armed_at, step = self._armed_at, self._armed_step
            if armed_at is None:
                continue
            now = time.time()
            overrun = now - armed_at
            if overrun < self.straggler_after_s:
                continue
            self.check_peers(now)
            if overrun > self.collective_timeout_s:
                # The seam did not come back: the main thread is blocked
                # (a real XLA collective with a dead peer, or a wedged
                # dispatch). raising in this thread cannot unwind it —
                # abort deterministically.
                verdict = "peer_dead" if self.dead_peers else "self_hang"
                self.monitor.log(
                    "peer_lost", step=step,
                    process_id=self.store.process_id,
                    reason=f"watchdog_abort_{verdict}",
                    beat_age_s=round(overrun, 3))
                print(f"[cluster] dispatch seam armed {overrun:.1f}s > "
                      f"collective_timeout_s="
                      f"{self.collective_timeout_s:.1f}; aborting "
                      f"({verdict})")
                self.monitor.flush()
                self._abort_fn(verdict)
                self.disarm()  # only reached when abort_fn is a test stub


class ClusterMonitor:
    """Per-process cluster-resilience runtime.

    Owns the beat publisher thread (beats keep flowing while the main
    thread compiles, blocks, or sleeps in backoff — a slow host must
    look SLOW, not dead), the watchdog, and the restart coordinator.
    Created once by the supervisor and threaded through every fit
    attempt, like the fault injector, so epoch/world state survives
    restarts."""

    def __init__(self, cluster_dir: str, process_id: int,
                 num_processes: int, heartbeat_interval_s: float = 0.5,
                 straggler_after_s: float = 2.0,
                 peer_dead_after_s: float = 10.0,
                 collective_timeout_s: float = 120.0,
                 min_hosts: int = 1, lockstep: bool = False,
                 elastic_expand: bool = False,
                 peer_redundancy: bool = False, replica_keep: int = 2,
                 transport: str = "file", net_timeout_s: float = 5.0,
                 net_retries: int = 2, logger=None, abort_fn=None):
        self.cluster_dir = cluster_dir
        self.process_id = process_id
        self.min_hosts = min_hosts
        self.lockstep = lockstep
        self.elastic_expand = elastic_expand
        self.heartbeat_interval_s = heartbeat_interval_s
        self.peer_dead_after_s = peer_dead_after_s
        self._logger = logger
        self._log_lock = threading.Lock()
        self._survivors = list(range(num_processes))
        self.epoch = 0
        self._step = 0
        self._phase = "init"
        self._stalled = False
        self._last_beat_log = 0.0
        self._last_rejoin_scan = 0.0
        # Transport selection (--cluster_transport): the file store is
        # the n=1/shared-filesystem default; "net" carries the SAME
        # store/coordinator contracts over parallel/net.py — the lowest
        # process id hosts the coordination service over cluster_dir,
        # every process (the host included, via loopback, so one code
        # path is exercised) talks to it through a bounded, classified,
        # retrying client.
        self.net_server = None
        self.net_client = None
        if transport == "net":
            from dml_cnn_cifar10_tpu.parallel import net as net_lib
            if process_id == 0:
                self.net_server = net_lib.CoordServer(cluster_dir)
            self.net_client = net_lib.CoordClient(
                cluster_dir, process_id, timeout_s=net_timeout_s,
                retries=net_retries, log_fn=self.log)
            self.store = net_lib.NetHeartbeatStore(
                cluster_dir, process_id, self.net_client,
                log_fn=self.log)
            self.coordinator = net_lib.NetRestartCoordinator(
                cluster_dir, self.net_client, log_fn=self.log)
        elif transport == "file":
            self.store = HeartbeatStore(cluster_dir, process_id,
                                        log_fn=self.log)
            self.coordinator = RestartCoordinator(cluster_dir,
                                                  log_fn=self.log)
        else:
            raise ValueError(
                f"unknown cluster transport {transport!r} "
                f"(want 'file' or 'net')")
        # Peer-replica store (ckpt/peerstore.py): rides the monitor so
        # its in-memory payload cache, push thread, and committed-step
        # bookkeeping span supervisor restart attempts — exactly like
        # the epoch/world state. None = diskless recovery off.
        self.peer_store = None
        self._pending_peer_restore = None
        if peer_redundancy:
            from dml_cnn_cifar10_tpu.ckpt.peerstore import \
                PeerReplicaStore
            self.peer_store = PeerReplicaStore(
                cluster_dir, process_id, list(range(num_processes)),
                keep=replica_keep, log_fn=self.log,
                client=self.net_client)
        self.watchdog = CollectiveWatchdog(
            self.store, self, straggler_after_s, peer_dead_after_s,
            collective_timeout_s, abort_fn=abort_fn)
        self._stop = threading.Event()
        self._publisher = threading.Thread(
            target=self._publish_loop, daemon=True,
            name="heartbeat-publisher")
        self.store.publish(0, "init", extra=self._beat_extra())
        self._publisher.start()
        self.watchdog.start()

    @classmethod
    def from_config(cls, parallel_cfg, logger=None,
                    abort_fn=None) -> Optional["ClusterMonitor"]:
        """None when the cluster layer is off (no ``cluster_dir``)."""
        if not getattr(parallel_cfg, "cluster_dir", None):
            return None
        return cls(
            parallel_cfg.cluster_dir, parallel_cfg.process_id,
            max(parallel_cfg.num_processes, 1),
            heartbeat_interval_s=parallel_cfg.heartbeat_interval_s,
            straggler_after_s=parallel_cfg.straggler_after_s,
            peer_dead_after_s=parallel_cfg.peer_dead_after_s,
            collective_timeout_s=parallel_cfg.collective_timeout_s,
            min_hosts=parallel_cfg.min_hosts,
            lockstep=parallel_cfg.cluster_lockstep,
            elastic_expand=getattr(parallel_cfg, "elastic_expand", False),
            peer_redundancy=getattr(parallel_cfg, "peer_redundancy",
                                    False),
            replica_keep=getattr(parallel_cfg, "replica_keep", 2),
            transport=getattr(parallel_cfg, "cluster_transport",
                              "file"),
            net_timeout_s=getattr(parallel_cfg, "net_timeout_s", 5.0),
            net_retries=getattr(parallel_cfg, "net_retries", 2),
            logger=logger, abort_fn=abort_fn)

    # -- identity / world ------------------------------------------------

    @property
    def is_chief(self) -> bool:
        """Lowest LIVE process id plays chief: when process 0 itself is
        the lost host, the next survivor inherits the restart decision
        (coordinator-loss handling, docs/RESILIENCE.md)."""
        live = [p for p in self._survivors
                if p not in self.watchdog.dead_peers]
        return bool(live) and self.process_id == min(live)

    def live_set(self) -> List[int]:
        return list(self._survivors)

    def world_size(self) -> int:
        return len(self._survivors)

    # -- logging (watchdog + publisher + seam threads share the sink) ---

    def log(self, kind: str, **fields) -> None:
        if self._logger is not None:
            with self._log_lock:
                self._logger.log(kind, **fields)

    def flush(self) -> None:
        if self._logger is not None and hasattr(self._logger, "flush"):
            with self._log_lock:
                self._logger.flush()

    # -- heartbeat publishing -------------------------------------------

    def _beat_extra(self) -> Optional[Dict]:
        """Replica staleness rides the heartbeat: the chief's decide
        seam learns every host's newest pushed replica step — including
        a LOST host's, from its last persisted beat — without ever
        touching the replica store."""
        if self.peer_store is None:
            return None
        return {"replica_step": self.peer_store.replica_step}

    def _publish_loop(self) -> None:
        while not self._stop.wait(self.heartbeat_interval_s):
            if not self._stalled:
                self.store.publish(self._step, self._phase,
                                   extra=self._beat_extra())

    def set_phase(self, phase: str) -> None:
        self._phase = phase

    def stall_heartbeats(self) -> None:
        """Fault hook (``heartbeat_stall@N``): stop publishing while the
        process keeps running — from outside, indistinguishable from a
        dead host. The peers will declare this process lost; the
        eviction check is how it finds out."""
        self._stalled = True

    # -- dispatch-seam hooks --------------------------------------------

    def begin_step(self, step: int, phase: str = "train") -> None:
        """Publish a beat, check for eviction, arm the watchdog. Raises
        ``PeerLostError`` immediately when a peer was already declared
        dead (detected while this process was off in eval/checkpoint)."""
        self._step = step
        self._phase = phase
        if not self._stalled:
            self.store.publish(step, phase, extra=self._beat_extra())
            now = time.time()
            if now - self._last_beat_log >= self.heartbeat_interval_s:
                self._last_beat_log = now
                # wallclock anchors cross-host clock alignment: each
                # process's JSONL `t` is relative to ITS logger start,
                # so tools/trace_aggregate.py recovers a per-stream
                # unix offset from (wallclock - t) to merge streams
                # onto one timeline.
                self.log("heartbeat", step=step,
                         process_id=self.process_id, phase=phase,
                         wallclock=round(now, 3))
                # Live-export gauges (GET /metrics), at the same
                # rate-limited cadence: the live world size and each
                # peer's beat staleness — numbers that never enter the
                # JSONL stream but are exactly what an operator (or
                # the live monitor) watches during an incident.
                self._export_gauges(now)
        self.check_evicted(step)
        self.watchdog.arm(step)
        self._raise_if_dead(step)
        self._maybe_raise_rejoin(step)

    def _export_gauges(self, now: float) -> None:
        """Registry-only export (utils/metrics_registry.py). Fail-open
        and rate-limited to the heartbeat cadence by the caller — one
        directory scan per interval, same cost as a watchdog pass."""
        try:
            from dml_cnn_cifar10_tpu.utils.metrics_registry import \
                default_registry
            reg = default_registry()
            live = [p for p in self._survivors
                    if p not in self.watchdog.dead_peers]
            reg.gauge("dml_cluster_world_size",
                      "World size adopted by the last restart decision"
                      ).set(len(live))
            reg.gauge("dml_cluster_epoch", "Adopted coordination epoch"
                      ).set(self.epoch)
            age_g = reg.gauge("dml_cluster_peer_beat_age_seconds",
                              "Age of each peer's newest heartbeat",
                              labelnames=("peer",))
            for pid, beat in self.store.read_peers(
                    self.live_set()).items():
                age = beat.age_s(now) if beat is not None \
                    else now - self.store.started_at
                age_g.set(round(age, 3), peer=str(pid))
        except Exception:
            pass

    def sync(self, step: int, poll_s: float = 0.02) -> None:
        """Simulated collective barrier (``cluster_lockstep``): wait for
        every live peer's beat to reach ``step``. The wait is where a
        2-process CPU simulation "blocks in the collective" — and where
        the watchdog's classification frees it: a dead peer raises
        ``PeerLostError``, an eviction raises ``EvictedError``."""
        if not self.lockstep:
            return
        attempt = 0
        while True:
            self._raise_if_dead(step)
            self.check_evicted(step)
            beats = self.store.read_peers(self.live_set())
            if all(b is not None and b.step >= step
                   for b in beats.values()):
                return
            self.watchdog.check_peers()
            # Bounded backoff (utils/backoff.py), reset per barrier: an
            # in-sync world pays the base poll; a straggler-bound wait
            # decays to the cap instead of re-scanning the store at
            # 50 Hz for the whole gap.
            attempt += 1
            time.sleep(backoff.delay_s(poll_s, 0.2, attempt))

    def end_step(self, step: int) -> None:
        self._step = step
        self.watchdog.disarm()

    def _raise_if_dead(self, step: int) -> None:
        dead = sorted(self.watchdog.dead_peers)
        if dead:
            self.watchdog.disarm()
            raise PeerLostError(
                dead, f"process(es) {dead} lost (heartbeats stale > "
                      f"{self.peer_dead_after_s:.1f}s) at step {step}")

    def check_evicted(self, step: int) -> None:
        """Seam check against the coordinator's decision file. Three
        outcomes for a decision at a NEWER epoch than ours:

        - this process excluded → :class:`EvictedError` (fence; under
          ``elastic_expand`` the supervisor turns the fence into a
          rejoin request instead of exiting);
        - this process included → the chief already committed a new
          world while we were mid-step (a shrink we have not classified
          yet, or an expand). Re-read with bounded backoff so we settle
          on the NEWEST epoch instead of racing a chief that may be
          writing again, then exit through the clean ``peer_lost`` path
          (empty ``process_ids``) — the supervisor adopts the pending
          decision rather than deciding one of its own."""
        d = self.coordinator.read()
        if d is None or d.epoch <= self.epoch:
            return
        if self.process_id in d.survivors:
            # Bounded re-read + backoff (utils/backoff.py): one decision
            # write can be chased by another (e.g. shrink then expand in
            # quick succession); settle before acting.
            for attempt in range(1, 4):
                time.sleep(backoff.delay_s(0.02, 0.2, attempt))
                d2 = self.coordinator.read()
                if d2 is None or d2.epoch <= d.epoch:
                    break
                d = d2
        if self.process_id not in d.survivors:
            self.log("peer_lost", step=step, process_id=self.process_id,
                     reason="evicted")
            raise EvictedError(
                f"restart epoch {d.epoch} excluded process "
                f"{self.process_id} (survivors {d.survivors}); fencing")
        self.watchdog.disarm()
        self.log("peer_lost", step=step, process_id=self.process_id,
                 reason="stale_epoch")
        raise PeerLostError(
            [], f"coordinator epoch {d.epoch} > adopted epoch "
                f"{self.epoch} at step {step}: a new world was already "
                f"committed; re-entering through the restart path")

    # -- coordinated elastic restart ------------------------------------

    def decide_restart(self, lost: Sequence[int],
                       restore_step: int) -> RestartDecision:
        """Chief half of the protocol: shrink the world by the lost
        hosts, pick the restore **source** (peer replicas when every
        old-world host — the lost one included — advertised a pushed
        replica; the disk walk otherwise), and commit the decision
        survivors will poll. ``restore_step`` is the disk candidate
        (newest checkpoint); a peer-sourced decision restores at the
        replica step instead. Raises ``PeerLostError`` (unrecoverable
        by world-shrink) when the survivor set would fall under
        ``min_hosts``."""
        survivors = [p for p in self._survivors if p not in set(lost)]
        if len(survivors) < self.min_hosts:
            raise PeerLostError(
                sorted(lost),
                f"only {len(survivors)} survivor(s) left, below "
                f"min_hosts={self.min_hosts}; halting")
        source, step = self._choose_restore_source(restore_step)
        return self.coordinator.record(RestartDecision(
            epoch=self.epoch + 1, world_size=len(survivors),
            restore_step=step, survivors=survivors, source=source))

    def _choose_restore_source(self, disk_step: int):
        """Peer-vs-disk restore choice, from the heartbeat record: the
        newest replica step every old-world host advertised (a lost
        host's last beat persists in the store). Viable = every host
        pushed at least once; the restore step is the MINIMUM advertised
        replica step, the newest one every replica set can serve. The
        choice is logged as a ``peer_replica`` ``decide`` record with
        the staleness (beats ahead of the replica step) telemetry_report
        surfaces."""
        if self.peer_store is None or not self.peer_store.enabled:
            return "disk", disk_step
        beats = self.store.read_all()
        steps = []
        for pid in self._survivors:
            if pid == self.process_id:
                steps.append(self.peer_store.replica_step)
                continue
            beat = beats.get(pid)
            extra = beat.extra if beat is not None else None
            steps.append(int((extra or {}).get("replica_step", -1)))
        peer_step = min(steps) if steps else -1
        beat_step = max(
            [b.step for p, b in beats.items() if p in self._survivors]
            + [self._step])
        ok = peer_step >= 0
        self.log("peer_replica", op="decide",
                 step=peer_step if ok else disk_step, owner=None,
                 bytes=None, secs=None, ok=ok, error=None,
                 staleness=max(beat_step - peer_step, 0) if ok else None)
        if not ok:
            return "disk", disk_step
        return "peer", peer_step

    def await_restart(self, timeout_s: float) -> RestartDecision:
        """Non-chief half: poll for the chief's decision; fence if it
        excludes this process."""
        d = self.coordinator.await_decision(self.epoch + 1, timeout_s)
        if self.process_id not in d.survivors:
            self.log("peer_lost", step=d.restore_step,
                     process_id=self.process_id, reason="evicted")
            raise EvictedError(
                f"restart epoch {d.epoch} excluded process "
                f"{self.process_id}; fencing")
        return d

    def adopt(self, decision: RestartDecision) -> None:
        """Enter the new world: the decision's survivor set (smaller on
        a shrink, larger on an expand), next epoch, dead bookkeeping
        cleared (the dead are no longer expected — and a rejoined host
        must stop counting as a corpse). A peer-sourced decision is
        staged for the next attempt's restore seam
        (:meth:`take_peer_restore`); the replica ring re-forms over the
        new world."""
        old_world = list(self._survivors)
        self.epoch = decision.epoch
        self._survivors = list(decision.survivors)
        self.watchdog.dead_peers.clear()
        self._phase = "restart"
        if self.peer_store is not None:
            if getattr(decision, "source", "disk") == "peer":
                new = set(decision.survivors)
                lost = [p for p in old_world if p not in new]
                world = sorted(set(old_world) | new)
                self._pending_peer_restore = (decision, world, lost)
            self.peer_store.set_world(list(decision.survivors))

    def take_peer_restore(self):
        """One-shot handoff to the restore seam: the staged
        ``(decision, old_world, lost)`` of an adopted peer-sourced
        decision, or None. Consuming clears it — a disk fallback must
        not replay the peer attempt on the attempt after."""
        pending = self._pending_peer_restore
        self._pending_peer_restore = None
        return pending

    # -- coordinated elastic scale-UP (expand) ---------------------------

    def rejoin_candidates(self) -> List[int]:
        """Process ids OUTSIDE the current survivor set with a FRESH
        ``rejoin``-phase beat — hosts asking to be let back in (or
        brand-new hosts announcing themselves). Read-only; any seat may
        query it (the fault injector's ``host_return`` drill polls it
        to make the 2→1→2 CPU sim deterministic)."""
        out = []
        now = time.time()
        for pid, beat in self.store.read_all().items():
            if pid == self.process_id or pid in self._survivors:
                continue
            if beat.phase == "rejoin" \
                    and beat.age_s(now) <= self.peer_dead_after_s:
                out.append(pid)
        return sorted(out)

    def _maybe_raise_rejoin(self, step: int) -> None:
        """Chief-side expand trigger, rate-limited to the heartbeat
        cadence: a fresh rejoin announcement raises
        :class:`PeerRejoinError` so the supervisor coordinates the
        expand. Off unless ``elastic_expand`` — the PR-4 shrink-only
        behavior (returning hosts stay fenced) is the default."""
        if not self.elastic_expand or not self.is_chief:
            return
        now = time.time()
        if now - self._last_rejoin_scan < self.heartbeat_interval_s:
            return
        self._last_rejoin_scan = now
        joiners = self.rejoin_candidates()
        if not joiners:
            return
        self.watchdog.disarm()
        for pid in joiners:
            self.log("host_rejoin", step=step, process_id=pid,
                     epoch=self.epoch)
        raise PeerRejoinError(
            joiners, f"process(es) {joiners} announced rejoin at step "
                     f"{step}; coordinating elastic expand")

    def decide_expand(self, joiners: Sequence[int],
                      restore_step: int) -> RestartDecision:
        """Chief half of the expand protocol: grow the survivor set by
        the announced joiners and commit the monotone-epoch decision
        (atomic rename, same file the shrink path uses). The joiners
        poll it via :meth:`await_inclusion`; surviving non-chiefs
        observe the newer epoch at their next seam check and re-enter
        through the clean ``peer_lost`` path."""
        survivors = sorted(set(self._survivors) | set(joiners))
        return self.coordinator.record(RestartDecision(
            epoch=self.epoch + 1, world_size=len(survivors),
            restore_step=restore_step, survivors=survivors,
            kind="expand"))

    def request_rejoin(self) -> None:
        """Returning-host half: adopt the world that excluded us as the
        current truth (so :meth:`await_inclusion` waits for a STRICTLY
        newer epoch), clear the stall/death bookkeeping a previous life
        may have left, and start announcing with ``rejoin``-phase beats
        (one published immediately; the background publisher keeps them
        flowing)."""
        d = self.coordinator.read()
        if d is not None and d.epoch > self.epoch:
            self.epoch = d.epoch
            self._survivors = list(d.survivors)
        self.watchdog.dead_peers.clear()
        self.watchdog.disarm()
        self._stalled = False
        self._phase = "rejoin"
        self.store.publish(self._step, "rejoin",
                           extra=self._beat_extra())

    def await_inclusion(self, timeout_s: float,
                        poll_s: float = 0.05) -> RestartDecision:
        """Block until a decision at a NEWER epoch includes this
        process. A chief that never answers within ``timeout_s`` is a
        refused (or coordinator-lost) rejoin: raise ``PeerLostError``
        so the caller can fence cleanly instead of polling forever."""
        deadline = time.time() + timeout_s
        attempt = 0
        while True:
            d = self.coordinator.read()
            if d is not None and d.epoch > self.epoch \
                    and self.process_id in d.survivors:
                return d
            if time.time() > deadline:
                raise PeerLostError(
                    [], f"no expand decision including process "
                        f"{self.process_id} at epoch > {self.epoch} "
                        f"within {timeout_s:.1f}s — rejoin refused or "
                        f"coordinator lost")
            # Same bounded-backoff poll as await_decision: a waiting
            # joiner must not hammer the shared decision file.
            attempt += 1
            time.sleep(backoff.delay_s(poll_s, poll_s * 10.0, attempt))

    # -- lifecycle -------------------------------------------------------

    def close(self) -> None:
        self._stop.set()
        self.watchdog.stop()
        if self.peer_store is not None:
            self.peer_store.close()
        self._publisher.join(timeout=2.0)
        self.watchdog.join(timeout=2.0)
        if self.net_server is not None:
            self.net_server.stop()
