"""Distribution layer: device meshes, SPMD step compilation, collectives,
multi-host bootstrap.

This subsystem replaces the reference's entire distributed runtime — the
gRPC ``ClusterSpec``/``Server`` parameter-server cluster, device placement
via ``replica_device_setter``, and the per-step parameter/gradient RPCs
(``cifar10cnn.py:184-196`` and the implicit graph partitioning under every
``session.run``). The TPU-native design has no server processes at all: one
pjit-compiled SPMD step runs on every chip, the batch is sharded over the
``data`` mesh axis, and gradient aggregation is a ``psum`` all-reduce
compiled into the step and scheduled on ICI by XLA.

The one deliberate semantic change from the reference: updates are
**synchronous** (async staleness was an artifact of the PS architecture, not
a capability). See SURVEY.md §2.3.
"""

from dml_cnn_cifar10_tpu.parallel.mesh import (  # noqa: F401
    build_mesh,
    batch_sharding,
    replicated,
    shard_batch,
)
from dml_cnn_cifar10_tpu.parallel.step import (  # noqa: F401
    TrainState,
    make_train_step,
    make_eval_step,
    init_train_state,
)
