"""Parameter/optimizer partition rules: an ordered regex → PartitionSpec
engine over ``/``-joined pytree paths.

The reference has no tensor parallelism (SURVEY §2.3 — async PS data
parallelism is its only strategy), but this framework treats the mesh
layout as first-class: each model family declares how its parameter
pytree is laid out as an ordered table of ``(regex, PartitionSpec)``
rules (the ``match_partition_rules`` idiom), the engine matches each
leaf's ``/``-joined path against the table first-match-wins, and the
jitted step (``parallel/step.py``) feeds the resulting specs to ``jit
in_shardings``/``out_shardings`` so GSPMD keeps the weights resident
shard-wise and inserts the matching collectives (all-gather for
column-parallel outputs consumed replicated, psum for row-parallel
partial sums) on ICI. ``--partition_rules`` swaps the model's table for
a user one (same grammar, :func:`parse_partition_rules`);
:func:`explain_partition_rules` renders the which-rule-matched-which-
param report, and strict mode errors on any leaf no rule covers.

Layout follows the Megatron recipe, expressed as GSPMD annotations instead
of hand-written collectives:

- **column-parallel** (shard the output features): the first matmul of a
  pair — ViT ``qkv`` / ``mlp1``, CNN ``full1``. Bias is sharded the same
  way; the activation between the pair stays sharded, no comm.
- **row-parallel** (shard the input features): the second matmul — ViT
  ``proj`` / ``mlp2``, CNN ``full2``. Each shard holds a partial sum;
  GSPMD compiles the ``psum`` over ``model``. Bias replicated.

ResNets stay replicated on ``model`` (conv-heavy, CIFAR-scale: dp is the
right layout; the table is one catch-all ``P()`` rule). Anything not
matched by a rule is replicated — correctness never depends on a rule
firing, only layout efficiency does.

Rule specs are RIGHT-aligned by default: a spec shorter than the leaf's
rank pads leading ``None``s, so ``P("model")`` means "shard the trailing
dim" for a 2-D kernel and for its stacked 3-D ``[depth, ...]`` twin
alike. ``align="left"`` (the ``^`` prefix in the CLI grammar) anchors at
the LEADING axis instead — the pipeline table uses it to shard stacked
block leaves over ``pipe``.

ViT attention note: the fused qkv projection is stored heads-major
(``models/vit.py``), so column-sharding ``qkv`` shards *whole heads* when
``model`` divides ``vit_heads`` and the [B,S,H,hd] attention tensors
propagate head-sharded through the kernel with zero resharding.

Optimizer-state sharding (``--optimizer_sharding zero1``, arxiv
2004.13336): :func:`state_pspecs` layers a ``data``-axis sharding over
the per-param optimizer moments ONLY (params keep the model rule) — the
weight-update tail of the step then runs 1/N per replica; see
``docs/SHARDING.md``.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Any, List, Optional, Sequence, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


@dataclasses.dataclass(frozen=True)
class PartitionRule:
    """One ``(regex, spec)`` entry of an ordered rule table.

    ``pattern`` is matched with ``re.search`` against the leaf's
    ``/``-joined tree path; ``spec`` aligns to the leaf rank per
    ``align`` (right: pad leading ``None`` — the trailing-dims
    convention that covers stacked ``[depth, ...]`` leaves for free;
    left: anchor at the leading axis, used by the pipeline table)."""

    pattern: str
    spec: P
    align: str = "right"

    def matches(self, path: str) -> bool:
        return re.search(self.pattern, path) is not None


Rules = Sequence[PartitionRule]


def _aligned_spec(rule: PartitionRule, path: str, ndim: int) -> P:
    entries = tuple(rule.spec)
    if len(entries) > ndim:
        raise ValueError(
            f"partition rule {rule.pattern!r} names {len(entries)} dims "
            f"but leaf {path!r} has rank {ndim}")
    if rule.align == "left" or not entries:
        return rule.spec
    return P(*([None] * (ndim - len(entries)) + list(entries)))


def match_partition_rules(rules: Rules, tree: Any,
                          strict: bool = False) -> Any:
    """Pytree of ``PartitionSpec`` for ``tree`` (arrays or
    ShapeDtypeStructs) from an ordered rule table, first match wins.

    Scalars never partition (rank-0 leaves return ``P()`` without
    consuming a rule — the standard ``match_partition_rules``
    convention). An unmatched leaf replicates, unless ``strict`` — then
    every unmatched path is collected and raised at once, so a user
    table with a typo'd regex fails loudly instead of silently
    replicating half the model."""
    unmatched: List[str] = []

    def spec_for(kp, leaf):
        path = _path_str(kp)
        if leaf.ndim == 0:
            return P()
        for rule in rules:
            if rule.matches(path):
                return _aligned_spec(rule, path, leaf.ndim)
        unmatched.append(path)
        return P()

    specs = jax.tree_util.tree_map_with_path(spec_for, tree)
    if strict and unmatched:
        raise ValueError(
            f"strict partition matching: no rule matched "
            f"{len(unmatched)} leaf path(s): {unmatched}")
    return specs


def explain_partition_rules(rules: Rules, tree: Any) -> List[dict]:
    """The which-rule-matched-which-param report, as data: one row per
    leaf with ``path``, ``shape``, the matching ``rule`` pattern (or
    ``<scalar>`` / ``<unmatched>``), and the resulting ``spec``."""
    rows = []

    def note(kp, leaf):
        path = _path_str(kp)
        if leaf.ndim == 0:
            rows.append({"path": path, "shape": tuple(leaf.shape),
                         "rule": "<scalar>", "spec": P()})
            return P()
        for rule in rules:
            if rule.matches(path):
                spec = _aligned_spec(rule, path, leaf.ndim)
                rows.append({"path": path, "shape": tuple(leaf.shape),
                             "rule": rule.pattern, "spec": spec})
                return spec
        rows.append({"path": path, "shape": tuple(leaf.shape),
                     "rule": "<unmatched>", "spec": P()})
        return P()

    jax.tree_util.tree_map_with_path(note, tree)
    return rows


def format_partition_report(rows: List[dict]) -> str:
    """Render :func:`explain_partition_rules` rows as a printable
    table (the ``--partition_report`` output)."""
    if not rows:
        return "(no leaves)"
    wp = max(len(r["path"]) for r in rows)
    wr = max(len(r["rule"]) for r in rows)
    lines = [f"{'param':<{wp}}  {'shape':<18} {'rule':<{wr}}  spec"]
    for r in rows:
        lines.append(f"{r['path']:<{wp}}  "
                     f"{str(r['shape']):<18} {r['rule']:<{wr}}  "
                     f"{r['spec']}")
    return "\n".join(lines)


def parse_partition_rules(text: Optional[str]) -> Optional[Tuple[
        PartitionRule, ...]]:
    """``--partition_rules`` grammar → rule table (None passes through).

    Rules are ``;``-separated ``regex=spec`` pairs, ordered. A spec is
    comma-separated per-dim axis entries, right-aligned to each matched
    leaf: an axis name (``model``, ``data``, ...), ``-``/``*``/empty
    for an unsharded dim, or ``a+b`` for a multi-axis dim. An empty
    spec or the word ``replicated`` is ``P()``; a ``^`` prefix
    left-aligns the spec (leading-axis anchor, e.g. pipeline stages).

    Example: ``"full1/(kernel|bias)$=model; full2/kernel$=model,-; .*="``
    reproduces the CNN table.
    """
    if not text:
        return None
    rules = []
    for i, chunk in enumerate(t for t in text.split(";") if t.strip()):
        pattern, sep, spec_text = chunk.partition("=")
        if not sep or not pattern.strip():
            raise ValueError(
                f"--partition_rules entry {i} ({chunk.strip()!r}) must "
                f"be 'regex=spec' (spec may be empty for replicated)")
        pattern = pattern.strip()
        spec_text = spec_text.strip()
        align = "right"
        if spec_text.startswith("^"):
            align = "left"
            spec_text = spec_text[1:].strip()
        if not spec_text or spec_text == "replicated":
            spec = P()
        else:
            entries = []
            for ent in spec_text.split(","):
                ent = ent.strip()
                if ent in ("", "-", "*"):
                    entries.append(None)
                elif "+" in ent:
                    entries.append(tuple(a.strip()
                                         for a in ent.split("+")))
                else:
                    entries.append(ent)
            spec = P(*entries)
        try:
            re.compile(pattern)
        except re.error as e:
            raise ValueError(
                f"--partition_rules entry {i}: bad regex "
                f"{pattern!r}: {e}")
        rules.append(PartitionRule(pattern, spec, align=align))
    return tuple(rules)


# ---------------------------------------------------------------------------
# Per-model default tables. First match wins; every table ends in a
# catch-all so the defaults never trip strict mode.
# ---------------------------------------------------------------------------

#: full1 2304→384 column-parallel, full2 384→192 row-parallel (the wide
#: FC pair of the reference model, cifar10cnn.py:130-139); convs and the
#: 192→10 head are small — replicated.
CNN_RULES = (
    PartitionRule(r"full1/(kernel|bias)$", P("model")),
    PartitionRule(r"full2/kernel$", P("model", None)),
    PartitionRule(r".*", P()),
)

#: Megatron pairing: qkv/mlp1 column-parallel (bias rides along),
#: proj/mlp2 row-parallel (bias replicated). Right alignment covers the
#: stacked [depth, ...] block leaves with the same two rules.
VIT_RULES = (
    PartitionRule(r"(qkv|mlp1)/(kernel|bias)$", P("model")),
    PartitionRule(r"(proj|mlp2)/kernel$", P("model", None)),
    PartitionRule(r".*", P()),
)

#: Expert parallelism: expert-major MoE weights shard their E dim
#: (w [.., E, D, H], b [.., E, H]) over ``model`` (ops/moe.py); the
#: router gate stays replicated; attention follows the dense ViT rules.
VIT_MOE_RULES = (
    PartitionRule(r"moe/(w1|w2)$", P("model", None, None)),
    PartitionRule(r"moe/(b1|b2)$", P("model", None)),
    PartitionRule(r"moe/gate", P()),
) + VIT_RULES

#: Pipelined stack: each stage owns depth/P contiguous layers — the
#: stacked [depth, ...] leaves shard their LEADING axis over ``pipe``
#: (left-aligned). Tensor-parallel specs are dropped (shard_map stages
#: would need hand-written collectives; parallel/pipeline.py docstring).
VIT_PIPE_RULES = (
    PartitionRule(r"^blocks/", P("pipe"), align="left"),
    PartitionRule(r".*", P()),
)

REPLICATED_RULES = (PartitionRule(r".*", P()),)

_RULES = {
    "cnn": CNN_RULES,
    "resnet18": REPLICATED_RULES,
    "resnet50": REPLICATED_RULES,
    "vit_tiny": VIT_RULES,
    "vit_moe": VIT_MOE_RULES,
}

_PIPE_RULES = {
    "vit_tiny": VIT_PIPE_RULES,
}


def rule_for(model_name: str, pipe: bool = False) -> Rules:
    """The model's default rule table (pipeline table when ``pipe``)."""
    if pipe:
        if model_name not in _PIPE_RULES:
            raise ValueError(
                f"pipeline parallelism is not supported for {model_name!r} "
                f"(supported: {sorted(_PIPE_RULES)})")
        return _PIPE_RULES[model_name]
    return _RULES.get(model_name, REPLICATED_RULES)


def _add_fsdp(spec: P, shape, data_size: int) -> P:
    """ZeRO/FSDP layout: additionally shard the largest still-unsharded dim
    divisible by the ``data``-axis size over ``data``.

    Per-leaf greedy choice keeps every rule composable: tensor-parallel
    (``model``) and pipeline (``pipe``) dims are left alone, and a leaf with
    no evenly divisible free dim stays as the base rule says (correctness
    never depends on the fsdp spec firing — GSPMD all-gathers whatever is
    sharded before compute and reduce-scatters the matching grads).
    """
    if data_size <= 1 or not shape:
        return spec
    entries = list(spec) + [None] * (len(shape) - len(spec))
    best = -1
    for i, (dim, e) in enumerate(zip(shape, entries)):
        if e is None and dim % data_size == 0:
            if best < 0 or dim > shape[best]:
                best = i
    if best < 0:
        return spec
    entries[best] = "data"
    return P(*entries)


def _path_str(key_path) -> str:
    parts = []
    for k in key_path:
        if hasattr(k, "key"):
            parts.append(str(k.key))
        elif hasattr(k, "idx"):
            parts.append(str(k.idx))
        else:
            parts.append(str(k))
    return "/".join(parts)


def param_pspecs(model_name: str, params: Any, pipe: bool = False,
                 fsdp_data: int = 0, rules: Optional[Rules] = None,
                 strict: bool = False) -> Any:
    """Pytree of ``PartitionSpec`` matching ``params`` (arrays or
    ShapeDtypeStructs). ``fsdp_data > 1`` layers the ZeRO/FSDP ``data``-axis
    sharding on top of the rule table; ``rules`` (a ``--partition_rules``
    table) overrides the model's default one; ``strict`` errors on
    unmatched leaves instead of replicating them."""
    table = rules if rules is not None else rule_for(model_name, pipe=pipe)
    specs = match_partition_rules(table, params, strict=strict)
    if not fsdp_data:
        return specs
    return jax.tree.map(
        lambda spec, leaf: _add_fsdp(spec, leaf.shape, fsdp_data),
        specs, params, is_leaf=lambda x: isinstance(x, P))


#: Optimizer-state entries that mirror the param tree leaf-for-leaf and
#: therefore take the per-param partition specs (everything else in
#: ``opt`` — scalar step, adafactor's factored stats, BN EMA — stays
#: replicated). ZERO1_KEYS is the subset ``--optimizer_sharding zero1``
#: additionally shards over ``data``: the per-param moments plus the
#: eval-time EMA (state memory, not forward-pass weights); the
#: async-staleness ring serves the FORWARD pass and must stay whole.
PARAM_SHAPED_OPT_KEYS = ("momentum", "mu", "nu", "ema", "stale")
ZERO1_KEYS = ("momentum", "mu", "nu", "ema")


def state_pspecs(model_name: str, state: Any, pipe: bool = False,
                 fsdp_data: int = 0, zero1_data: int = 0,
                 rules: Optional[Rules] = None,
                 strict: bool = False) -> Any:
    """Specs for a full ``TrainState``: params by model rule, per-param
    optimizer moments (SGD momentum, AdamW mu/nu) mirror the params (same
    tree paths), scalar step + BN state replicated. With ``fsdp_data > 1``
    params AND moments are sharded over ``data`` (ZeRO-3: the dominant
    state memory scales 1/|data|; BN state stays replicated — it is
    pmean'd cross-replica, not per-shard). With ``zero1_data > 1`` ONLY
    the optimizer moments (+ EMA) shard over ``data`` (ZeRO-1, arxiv
    2004.13336): params stay in their model layout for the forward, each
    replica owns 1/N of the update state, and the step's reduce-scatter /
    sharded-update / all-gather schedule follows from these specs alone."""
    # "stale" (the async-staleness ring) carries a leading [S] axis; the
    # rules index from the trailing dims, so the same per-param specs
    # apply — the extra leading dim just stays unsharded.
    # Adafactor's stats ("vr"/"vc"/"v") fall to the replicated default
    # DELIBERATELY: vr/vc are O(n+m) per matrix (sub-linear — sharding
    # them buys no meaningful memory and their reduced ranks don't fit
    # the per-param trailing-dim rules), and "v" holds full accumulators
    # only for 1-D leaves (biases/BN — already tiny).
    def opt_specs(k, v):
        if k not in PARAM_SHAPED_OPT_KEYS:
            return jax.tree.map(lambda _: P(), v)
        data = max(fsdp_data, zero1_data if k in ZERO1_KEYS else 0)
        return param_pspecs(model_name, v, pipe=pipe, fsdp_data=data,
                            rules=rules, strict=strict)

    opt = {k: opt_specs(k, v) for k, v in state.opt.items()}
    return type(state)(
        params=param_pspecs(model_name, state.params, pipe=pipe,
                            fsdp_data=fsdp_data, rules=rules,
                            strict=strict),
        opt=opt,
        model_state=jax.tree.map(lambda _: P(), state.model_state),
    )


def state_shardings(mesh: Mesh, model_name: str, state: Any,
                    fsdp: bool = False, zero1: bool = False,
                    rules: Optional[Rules] = None,
                    strict: bool = False) -> Any:
    """``state_pspecs`` bound to a mesh → pytree of ``NamedSharding``.

    A mesh with a nontrivial ``pipe`` axis selects the pipeline layout
    (stage-sharded layer stacks) instead of the tensor-parallel one.
    ``fsdp=True`` additionally shards params + optimizer moments over the
    ``data`` axis (ZeRO-3); GSPMD compiles the all-gather before compute
    and the reduce-scatter of gradients in place of the plain all-reduce.
    ``zero1=True`` shards ONLY the optimizer moments (+ EMA) over
    ``data`` — the ZeRO-1 layout ``--optimizer_sharding`` builds on."""
    pipe = mesh.shape.get("pipe", 1) > 1
    fsdp_data = mesh.shape["data"] if fsdp else 0
    zero1_data = mesh.shape["data"] if zero1 else 0
    return jax.tree.map(lambda spec: NamedSharding(mesh, spec),
                        state_pspecs(model_name, state, pipe=pipe,
                                     fsdp_data=fsdp_data,
                                     zero1_data=zero1_data,
                                     rules=rules, strict=strict),
                        is_leaf=lambda x: isinstance(x, P))


def specs_name_axis(tree: Any, axis: str) -> bool:
    """True iff any ``NamedSharding``/``PartitionSpec`` leaf in ``tree``
    names ``axis`` with >1 devices — e.g. detects an FSDP (``data``-axis)
    parameter layout from the sharding tree alone, so step builders don't
    need a separate flag."""
    leaves = jax.tree.leaves(
        tree, is_leaf=lambda x: isinstance(x, (NamedSharding, P)))
    for leaf in leaves:
        if isinstance(leaf, NamedSharding):
            if leaf.mesh.shape.get(axis, 1) <= 1:
                continue
            spec = leaf.spec
        elif isinstance(leaf, P):
            spec = leaf
        else:
            continue
        if any(axis in (p if isinstance(p, tuple) else (p,))
               for p in spec if p is not None):
            return True
    return False


def assert_some_leaf_sharded(state: Any, axis: str = "model") -> bool:
    """True iff at least one leaf is actually partitioned over ``axis``
    (spec names the axis AND the axis has >1 devices, i.e. the leaf really
    has multiple distinct shards) — used by tests and the driver dry run to
    prove tp is real, not declared."""
    for leaf in jax.tree.leaves(state):
        sharding = getattr(leaf, "sharding", None)
        if sharding is None or not isinstance(sharding, NamedSharding):
            continue
        if sharding.mesh.shape.get(axis, 1) <= 1:
            continue
        if any(axis in (p if isinstance(p, tuple) else (p,))
               for p in sharding.spec if p is not None):
            return True
    return False
