"""Tensor-parallel parameter sharding rules (the ``model`` mesh axis).

The reference has no tensor parallelism (SURVEY §2.3 — async PS data
parallelism is its only strategy), but this framework treats the ``model``
axis as first-class: each model family declares how its parameter pytree is
laid out over the mesh, and the jitted step (``parallel/step.py``) feeds
those specs to ``jit in_shardings``/``out_shardings`` so GSPMD keeps the
weights resident shard-wise and inserts the matching collectives
(all-gather for column-parallel outputs consumed replicated, psum for
row-parallel partial sums) on ICI.

Layout follows the Megatron recipe, expressed as GSPMD annotations instead
of hand-written collectives:

- **column-parallel** (shard the output features): the first matmul of a
  pair — ViT ``qkv`` / ``mlp1``, CNN ``full1``. Bias is sharded the same
  way; the activation between the pair stays sharded, no comm.
- **row-parallel** (shard the input features): the second matmul — ViT
  ``proj`` / ``mlp2``, CNN ``full2``. Each shard holds a partial sum;
  GSPMD compiles the ``psum`` over ``model``. Bias replicated.

ResNets stay replicated on ``model`` (conv-heavy, CIFAR-scale: dp is the
right layout; rules return ``P()`` for every leaf). Anything not matched by
a rule is replicated — correctness never depends on a rule firing, only
layout efficiency does.

ViT attention note: the fused qkv projection is stored heads-major
(``models/vit.py``), so column-sharding ``qkv`` shards *whole heads* when
``model`` divides ``vit_heads`` and the [B,S,H,hd] attention tensors
propagate head-sharded through the kernel with zero resharding.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

Rule = Callable[[str, int], P]


def _col(ndim: int) -> P:
    """Shard the trailing (output-feature) dim over ``model``."""
    return P(*([None] * (ndim - 1) + ["model"]))


def _row(ndim: int) -> P:
    """Shard the second-to-last (input-feature) dim over ``model``."""
    return P(*([None] * (ndim - 2) + ["model", None]))


def _replicated(path: str, ndim: int) -> P:
    del path, ndim
    return P()


def _cnn_rule(path: str, ndim: int) -> P:
    # full1 2304→384 column-parallel, full2 384→192 row-parallel
    # (the wide FC pair of the reference model, cifar10cnn.py:130-139);
    # convs and the 192→10 head are small — replicated.
    if path.endswith(("full1/kernel", "full1/bias")):
        return _col(ndim)
    if path.endswith("full2/kernel"):
        return _row(ndim)
    return P()


def _vit_rule(path: str, ndim: int) -> P:
    # Stacked block leaves carry a leading [depth] axis; _col/_row index
    # from the trailing dims so the same rule covers stacked and unstacked.
    if path.endswith(("qkv/kernel", "qkv/bias", "mlp1/kernel", "mlp1/bias")):
        return _col(ndim)
    if path.endswith(("proj/kernel", "mlp2/kernel")):
        return _row(ndim)
    return P()


def _expert(ndim: int, offset: int) -> P:
    """Shard the expert dim (``offset`` positions from the trailing end:
    w [.., E, D, H] → 3, b [.., E, H] → 2) over ``model``."""
    spec = [None] * ndim
    spec[ndim - offset] = "model"
    return P(*spec)


def _vit_moe_rule(path: str, ndim: int) -> P:
    # Expert parallelism: expert-major MoE weights shard their E dim over
    # ``model`` (ops/moe.py); the router gate stays replicated. Attention
    # follows the dense ViT rules.
    if path.endswith(("moe/w1", "moe/w2")):
        return _expert(ndim, 3)
    if path.endswith(("moe/b1", "moe/b2")):
        return _expert(ndim, 2)
    if "moe/gate" in path:
        return P()
    return _vit_rule(path, ndim)


def _vit_pipe_rule(path: str, ndim: int) -> P:
    # Pipelined stack: each stage owns depth/P contiguous layers — the
    # stacked [depth, ...] leaves shard their LEADING axis over ``pipe``.
    # Tensor-parallel specs are dropped (shard_map stages would need
    # hand-written collectives; parallel/pipeline.py docstring).
    if path.startswith("blocks/"):
        return P("pipe")
    return P()


_RULES = {
    "cnn": _cnn_rule,
    "resnet18": _replicated,
    "resnet50": _replicated,
    "vit_tiny": _vit_rule,
    "vit_moe": _vit_moe_rule,
}

_PIPE_RULES = {
    "vit_tiny": _vit_pipe_rule,
}


def rule_for(model_name: str, pipe: bool = False) -> Rule:
    if pipe:
        if model_name not in _PIPE_RULES:
            raise ValueError(
                f"pipeline parallelism is not supported for {model_name!r} "
                f"(supported: {sorted(_PIPE_RULES)})")
        return _PIPE_RULES[model_name]
    return _RULES.get(model_name, _replicated)


def _add_fsdp(spec: P, shape, data_size: int) -> P:
    """ZeRO/FSDP layout: additionally shard the largest still-unsharded dim
    divisible by the ``data``-axis size over ``data``.

    Per-leaf greedy choice keeps every rule composable: tensor-parallel
    (``model``) and pipeline (``pipe``) dims are left alone, and a leaf with
    no evenly divisible free dim stays as the base rule says (correctness
    never depends on the fsdp spec firing — GSPMD all-gathers whatever is
    sharded before compute and reduce-scatters the matching grads).
    """
    if data_size <= 1 or not shape:
        return spec
    entries = list(spec) + [None] * (len(shape) - len(spec))
    best = -1
    for i, (dim, e) in enumerate(zip(shape, entries)):
        if e is None and dim % data_size == 0:
            if best < 0 or dim > shape[best]:
                best = i
    if best < 0:
        return spec
    entries[best] = "data"
    return P(*entries)


def _path_str(key_path) -> str:
    parts = []
    for k in key_path:
        if hasattr(k, "key"):
            parts.append(str(k.key))
        elif hasattr(k, "idx"):
            parts.append(str(k.idx))
        else:
            parts.append(str(k))
    return "/".join(parts)


def param_pspecs(model_name: str, params: Any, pipe: bool = False,
                 fsdp_data: int = 0) -> Any:
    """Pytree of ``PartitionSpec`` matching ``params`` (arrays or
    ShapeDtypeStructs). ``fsdp_data > 1`` layers the ZeRO/FSDP ``data``-axis
    sharding on top of the model's tensor/pipeline rule."""
    rule = rule_for(model_name, pipe=pipe)

    def spec_for(kp, leaf):
        spec = rule(_path_str(kp), leaf.ndim)
        return _add_fsdp(spec, leaf.shape, fsdp_data)

    return jax.tree_util.tree_map_with_path(spec_for, params)


def state_pspecs(model_name: str, state: Any, pipe: bool = False,
                 fsdp_data: int = 0) -> Any:
    """Specs for a full ``TrainState``: params by model rule, per-param
    optimizer moments (SGD momentum, AdamW mu/nu) mirror the params (same
    tree paths), scalar step + BN state replicated. With ``fsdp_data > 1``
    params AND moments are sharded over ``data`` (ZeRO-3: the dominant
    state memory scales 1/|data|; BN state stays replicated — it is
    pmean'd cross-replica, not per-shard)."""
    # "stale" (the async-staleness ring) carries a leading [S] axis; the
    # rules index from the trailing dims, so the same per-param specs
    # apply — the extra leading dim just stays unsharded.
    # Adafactor's stats ("vr"/"vc"/"v") fall to the replicated default
    # DELIBERATELY: vr/vc are O(n+m) per matrix (sub-linear — sharding
    # them buys no meaningful memory and their reduced ranks don't fit
    # the per-param trailing-dim rules), and "v" holds full accumulators
    # only for 1-D leaves (biases/BN — already tiny).
    opt = {k: (param_pspecs(model_name, v, pipe=pipe, fsdp_data=fsdp_data)
               if k in ("momentum", "mu", "nu", "ema", "stale")
               else jax.tree.map(lambda _: P(), v))
           for k, v in state.opt.items()}
    return type(state)(
        params=param_pspecs(model_name, state.params, pipe=pipe,
                            fsdp_data=fsdp_data),
        opt=opt,
        model_state=jax.tree.map(lambda _: P(), state.model_state),
    )


def state_shardings(mesh: Mesh, model_name: str, state: Any,
                    fsdp: bool = False) -> Any:
    """``state_pspecs`` bound to a mesh → pytree of ``NamedSharding``.

    A mesh with a nontrivial ``pipe`` axis selects the pipeline layout
    (stage-sharded layer stacks) instead of the tensor-parallel one.
    ``fsdp=True`` additionally shards params + optimizer moments over the
    ``data`` axis (ZeRO-3); GSPMD compiles the all-gather before compute
    and the reduce-scatter of gradients in place of the plain all-reduce."""
    pipe = mesh.shape.get("pipe", 1) > 1
    fsdp_data = mesh.shape["data"] if fsdp else 0
    return jax.tree.map(lambda spec: NamedSharding(mesh, spec),
                        state_pspecs(model_name, state, pipe=pipe,
                                     fsdp_data=fsdp_data),
                        is_leaf=lambda x: isinstance(x, P))


def specs_name_axis(tree: Any, axis: str) -> bool:
    """True iff any ``NamedSharding``/``PartitionSpec`` leaf in ``tree``
    names ``axis`` with >1 devices — e.g. detects an FSDP (``data``-axis)
    parameter layout from the sharding tree alone, so step builders don't
    need a separate flag."""
    leaves = jax.tree.leaves(
        tree, is_leaf=lambda x: isinstance(x, (NamedSharding, P)))
    for leaf in leaves:
        if isinstance(leaf, NamedSharding):
            if leaf.mesh.shape.get(axis, 1) <= 1:
                continue
            spec = leaf.spec
        elif isinstance(leaf, P):
            spec = leaf
        else:
            continue
        if any(axis in (p if isinstance(p, tuple) else (p,))
               for p in spec if p is not None):
            return True
    return False


def assert_some_leaf_sharded(state: Any, axis: str = "model") -> bool:
    """True iff at least one leaf is actually partitioned over ``axis``
    (spec names the axis AND the axis has >1 devices, i.e. the leaf really
    has multiple distinct shards) — used by tests and the driver dry run to
    prove tp is real, not declared."""
    for leaf in jax.tree.leaves(state):
        sharding = getattr(leaf, "sharding", None)
        if sharding is None or not isinstance(sharding, NamedSharding):
            continue
        if sharding.mesh.shape.get(axis, 1) <= 1:
            continue
        if any(axis in (p if isinstance(p, tuple) else (p,))
               for p in sharding.spec if p is not None):
            return True
    return False
