"""ResNet-18/50 — the "deeper conv stack" rungs of the config ladder.

No reference counterpart (the reference model is the 5-layer CNN,
``cifar10cnn.py:94-147``); these are the BASELINE.json ladder configs
"ResNet-18 on CIFAR-10 (deeper conv stack, BatchNorm psum)" and
"ResNet-50 on ImageNet-1k". Design notes:

- Functional pytrees like :mod:`~dml_cnn_cifar10_tpu.models.cnn`; BatchNorm
  running stats live in a parallel ``state`` pytree (the framework's
  ``model_state``) so the train step stays pure.
- Cross-replica BN (SURVEY §2.3): batch stats are global means — automatic
  under jit auto-partitioning, explicit ``lax.pmean`` via ``axis_name``
  under the shard_map step. See :func:`ops.layers.batch_norm`.
- Stem adapts to input size: CIFAR-scale inputs (≤64 px) use the 3×3/s1
  stem with no maxpool; larger (ImageNet) inputs use 7×7/s2 + 3×3/s2
  maxpool.
- All convs are bias-free (BN's offset absorbs the bias); final BN of each
  residual branch is gamma-zero-initialized so blocks start as identity —
  standard large-batch trick, keeps the big-LR parity regime stable.
- ``cfg.resnet_norm="nf"`` swaps every BN for scaled weight
  standardization (per-kernel fan-in standardize + learnable gain —
  weight bytes only) + per-conv biases + a SkipInit residual scalar
  (init 0 — identity start, like the gamma-zero BN). The round-4
  roofline measured 76.5% of the ResNet-50 step bandwidth-bound with
  BN's stats/normalize passes among the top byte movers; nf removes
  every activation-sized stats read/write. Different training semantics
  (the NFNet line of work shows the class reaches BN-level accuracy
  with care); benched in BASELINE.md as the byte-reduction rung.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from dml_cnn_cifar10_tpu.config import DataConfig, ModelConfig
from dml_cnn_cifar10_tpu.ops import layers as L

Params = Dict[str, Any]
State = Dict[str, Any]

# depth → (blocks per stage, block kind)
STAGES = {
    18: ((2, 2, 2, 2), "basic"),
    34: ((3, 4, 6, 3), "basic"),
    50: ((3, 4, 6, 3), "bottleneck"),
}
STAGE_WIDTHS = (64, 128, 256, 512)
BOTTLENECK_EXPANSION = 4


def _conv_init(key, shape, dtype):
    return L.he_normal_init(key, shape, dtype)


def _init_basic_block(key, cin: int, width: int, stride: int, dtype):
    ks = jax.random.split(key, 3)
    p: Params = {}
    p["conv1"] = _conv_init(ks[0], (3, 3, cin, width), dtype)
    p["bn1"] = L.bn_init(width, dtype)
    p["conv2"] = _conv_init(ks[1], (3, 3, width, width), dtype)
    p["bn2"] = L.bn_init(width, dtype)
    p["bn2"]["scale"] = jnp.zeros_like(p["bn2"]["scale"])  # identity start
    if stride != 1 or cin != width:
        p["proj"] = _conv_init(ks[2], (1, 1, cin, width), dtype)
        p["proj_bn"] = L.bn_init(width, dtype)
    return p, width


def _init_bottleneck_block(key, cin: int, width: int, stride: int, dtype):
    cout = width * BOTTLENECK_EXPANSION
    ks = jax.random.split(key, 4)
    p: Params = {}
    p["conv1"] = _conv_init(ks[0], (1, 1, cin, width), dtype)
    p["bn1"] = L.bn_init(width, dtype)
    p["conv2"] = _conv_init(ks[1], (3, 3, width, width), dtype)
    p["bn2"] = L.bn_init(width, dtype)
    p["conv3"] = _conv_init(ks[2], (1, 1, width, cout), dtype)
    p["bn3"] = L.bn_init(cout, dtype)
    p["bn3"]["scale"] = jnp.zeros_like(p["bn3"]["scale"])  # identity start
    if stride != 1 or cin != cout:
        p["proj"] = _conv_init(ks[3], (1, 1, cin, cout), dtype)
        p["proj_bn"] = L.bn_init(cout, dtype)
    return p, cout


def _init_nf_basic_block(key, cin: int, width: int, stride: int, dtype):
    ks = jax.random.split(key, 3)
    p: Params = {
        "conv1": _conv_init(ks[0], (3, 3, cin, width), dtype),
        "g1": jnp.ones((width,), dtype), "c1": jnp.zeros((width,), dtype),
        "conv2": _conv_init(ks[1], (3, 3, width, width), dtype),
        "g2": jnp.ones((width,), dtype), "c2": jnp.zeros((width,), dtype),
        # SkipInit: the residual branch enters at 0 — blocks start as
        # identity, the NF analog of the gamma-zero BN init above.
        "skip_gain": jnp.zeros((), dtype),
    }
    if stride != 1 or cin != width:
        p["proj"] = _conv_init(ks[2], (1, 1, cin, width), dtype)
        p["gp"] = jnp.ones((width,), dtype)
        p["cp"] = jnp.zeros((width,), dtype)
    return p, width


def _init_nf_bottleneck_block(key, cin: int, width: int, stride: int,
                              dtype):
    cout = width * BOTTLENECK_EXPANSION
    ks = jax.random.split(key, 4)
    p: Params = {
        "conv1": _conv_init(ks[0], (1, 1, cin, width), dtype),
        "g1": jnp.ones((width,), dtype), "c1": jnp.zeros((width,), dtype),
        "conv2": _conv_init(ks[1], (3, 3, width, width), dtype),
        "g2": jnp.ones((width,), dtype), "c2": jnp.zeros((width,), dtype),
        "conv3": _conv_init(ks[2], (1, 1, width, cout), dtype),
        "g3": jnp.ones((cout,), dtype), "c3": jnp.zeros((cout,), dtype),
        "skip_gain": jnp.zeros((), dtype),
    }
    if stride != 1 or cin != cout:
        p["proj"] = _conv_init(ks[3], (1, 1, cin, cout), dtype)
        p["gp"] = jnp.ones((cout,), dtype)
        p["cp"] = jnp.zeros((cout,), dtype)
    return p, cout


def init_params(key: jax.Array, cfg: ModelConfig, data: DataConfig,
                depth: int = 18) -> Params:
    if depth not in STAGES:
        raise ValueError(f"unsupported resnet depth {depth}; have "
                         f"{sorted(STAGES)}")
    blocks, kind = STAGES[depth]
    dtype = jnp.dtype(cfg.dtype)
    imagenet_stem = min(data.crop_height, data.crop_width) > 64
    nf = cfg.resnet_norm == "nf"
    if cfg.resnet_norm not in ("bn", "nf"):
        raise ValueError(
            f"resnet_norm must be 'bn' or 'nf', got {cfg.resnet_norm!r}")
    if nf:
        init_block = (_init_nf_bottleneck_block if kind == "bottleneck"
                      else _init_nf_basic_block)
    else:
        init_block = (_init_bottleneck_block if kind == "bottleneck"
                      else _init_basic_block)

    keys = jax.random.split(key, 2 + sum(blocks))
    ki = iter(range(len(keys)))

    p: Params = {}
    if imagenet_stem and cfg.resnet_s2d:
        # Space-to-depth stem (BASELINE.md round-4): 4x4/1 conv over the
        # 2x2-folded input — same function class as 7x7/2 on the raw
        # image (zero-pad 7x7 to 8x8, fold into 4x4 x 4C), trained
        # directly in the folded parameterization as MLPerf does.
        stem_shape = (4, 4, 4 * data.num_channels, 64)
    else:
        stem_k = (7, 7) if imagenet_stem else (3, 3)
        stem_shape = (*stem_k, data.num_channels, 64)
    p["stem"] = {"conv": _conv_init(keys[next(ki)], stem_shape, dtype)}
    if nf:
        p["stem"]["g"] = jnp.ones((64,), dtype)
        p["stem"]["c"] = jnp.zeros((64,), dtype)
    else:
        p["stem"]["bn"] = L.bn_init(64, dtype)

    cin = 64
    for si, (n, width) in enumerate(zip(blocks, STAGE_WIDTHS)):
        stage: List[Params] = []
        for bi in range(n):
            stride = 2 if (bi == 0 and si > 0) else 1
            bp, cin = init_block(keys[next(ki)], cin, width, stride, dtype)
            stage.append(bp)
        p[f"stage{si + 1}"] = stage

    p["fc"] = {
        "kernel": L.he_normal_init(keys[next(ki)], (cin, cfg.num_classes),
                                   dtype),
        "bias": jnp.zeros((cfg.num_classes,), dtype),
    }
    return p


def init_state(params: Params) -> State:
    """Derive the running-stat pytree from the param pytree: every dict with
    ``scale``/``offset`` keys is a BN layer and gets ``mean``/``var``."""

    def walk(node):
        if isinstance(node, dict):
            if set(node) == {"scale", "offset"}:
                return {"mean": jnp.zeros(node["scale"].shape, jnp.float32),
                        "var": jnp.ones(node["scale"].shape, jnp.float32)}
            return {k: walk(v) for k, v in node.items()}
        if isinstance(node, (list, tuple)):
            return type(node)(walk(v) for v in node)
        return None  # non-BN leaf: no state

    return walk(params)


def _bn(x, p, s, cfg: ModelConfig, train: bool, axis_name):
    return L.batch_norm(x, p, s, train, cfg.bn_momentum, cfg.bn_eps,
                        axis_name)


def _basic_block(x, p, s, stride, cfg, train, axis_name):
    ns: State = {}
    h = L.conv2d(x, p["conv1"], stride=stride)
    h, ns["bn1"] = _bn(h, p["bn1"], s["bn1"], cfg, train, axis_name)
    h = jax.nn.relu(h)
    h = L.conv2d(h, p["conv2"])
    h, ns["bn2"] = _bn(h, p["bn2"], s["bn2"], cfg, train, axis_name)
    if "proj" in p:
        x = L.conv2d(x, p["proj"], stride=stride)
        x, ns["proj_bn"] = _bn(x, p["proj_bn"], s["proj_bn"], cfg, train,
                               axis_name)
    ns["conv1"] = ns["conv2"] = None
    if "proj" in p:
        ns["proj"] = None
    return jax.nn.relu(x + h), ns


def _bottleneck_block(x, p, s, stride, cfg, train, axis_name):
    ns: State = {}
    h = L.conv2d(x, p["conv1"])
    h, ns["bn1"] = _bn(h, p["bn1"], s["bn1"], cfg, train, axis_name)
    h = jax.nn.relu(h)
    h = L.conv2d(h, p["conv2"], stride=stride)
    h, ns["bn2"] = _bn(h, p["bn2"], s["bn2"], cfg, train, axis_name)
    h = jax.nn.relu(h)
    h = L.conv2d(h, p["conv3"])
    h, ns["bn3"] = _bn(h, p["bn3"], s["bn3"], cfg, train, axis_name)
    if "proj" in p:
        x = L.conv2d(x, p["proj"], stride=stride)
        x, ns["proj_bn"] = _bn(x, p["proj_bn"], s["proj_bn"], cfg, train,
                               axis_name)
    ns["conv1"] = ns["conv2"] = ns["conv3"] = None
    if "proj" in p:
        ns["proj"] = None
    return jax.nn.relu(x + h), ns


def _ws_conv(w, gain, eps: float = 1e-4):
    """Scaled weight standardization (NF-ResNet recipe): standardize the
    kernel over its (kh, kw, cin) fan-in and scale by a learnable
    per-output-channel gain. Touches only WEIGHT bytes — the activation
    tensor never takes the extra stats read/write BatchNorm forces,
    which is the whole point of the nf rung (round-4 roofline: 76.5% of
    ResNet-50 step time bandwidth-bound)."""
    mu = jnp.mean(w, axis=(0, 1, 2), keepdims=True)
    var = jnp.var(w, axis=(0, 1, 2), keepdims=True)
    fan_in = w.shape[0] * w.shape[1] * w.shape[2]
    return (w - mu) * lax.rsqrt(var * fan_in + eps) * gain


def _nf_basic_block(x, p, s, stride, cfg, train, axis_name):
    del s, train, axis_name  # stateless — no running stats
    h = jax.nn.relu(L.conv2d(x, _ws_conv(p["conv1"], p["g1"]),
                             stride=stride) + p["c1"])
    h = L.conv2d(h, _ws_conv(p["conv2"], p["g2"])) + p["c2"]
    if "proj" in p:
        x = L.conv2d(x, _ws_conv(p["proj"], p["gp"]),
                     stride=stride) + p["cp"]
    ns = {k: None for k in p}
    return jax.nn.relu(x + p["skip_gain"] * h), ns


def _nf_bottleneck_block(x, p, s, stride, cfg, train, axis_name):
    del s, train, axis_name
    h = jax.nn.relu(L.conv2d(x, _ws_conv(p["conv1"], p["g1"])) + p["c1"])
    h = jax.nn.relu(L.conv2d(h, _ws_conv(p["conv2"], p["g2"]),
                             stride=stride) + p["c2"])
    h = L.conv2d(h, _ws_conv(p["conv3"], p["g3"])) + p["c3"]
    if "proj" in p:
        x = L.conv2d(x, _ws_conv(p["proj"], p["gp"]),
                     stride=stride) + p["cp"]
    ns = {k: None for k in p}
    return jax.nn.relu(x + p["skip_gain"] * h), ns


def apply(params: Params, state: State, images: jax.Array, cfg: ModelConfig,
          train: bool = True, axis_name: Optional[str] = None
          ) -> Tuple[jax.Array, State]:
    """NHWC images → (logits [B, K], new running-stat state)."""
    cdt = jnp.dtype(cfg.compute_dtype)
    x = images.astype(cdt)
    p = jax.tree.map(lambda a: a.astype(cdt), params)

    stem_kh = p["stem"]["conv"].shape[0]
    imagenet_stem = stem_kh == 7
    s2d_stem = stem_kh == 4
    nf = "g" in p["stem"]                      # static pytree property
    if nf:
        block = (_nf_bottleneck_block if "conv3" in p["stage1"][0]
                 else _nf_basic_block)
    else:
        block = (_bottleneck_block if "bn3" in p["stage1"][0]
                 else _basic_block)
    if cfg.remat:
        # Recompute each residual block's activations in the backward
        # pass — the same O(1)-in-depth activation-memory lever the ViT
        # stack has (models/vit.py), decisive at ImageNet geometry.
        # Statics ride in a closure: ModelConfig is unhashable, so
        # jax.checkpoint static_argnums is not an option.
        inner = block

        def block(x, bp, s, stride, cfg, train, axis_name):
            return jax.checkpoint(
                lambda xx, pp, ss: inner(xx, pp, ss, stride, cfg, train,
                                         axis_name))(x, bp, s)

    # Mirror init_state's structure exactly: a treedef change between step 1
    # and step 2 would silently retrigger compilation.
    new_state: State = {"fc": {"kernel": None, "bias": None}}
    stem_w = (_ws_conv(p["stem"]["conv"], p["stem"]["g"]) if nf
              else p["stem"]["conv"])
    if s2d_stem:
        # Space-to-depth: [B,2h,2w,C] -> [B,h,w,4C] (2x2 phases into
        # channels), then the stride-1 4x4 conv with explicit padding
        # (1,2): the 7x7/2 SAME conv (XLA pad lo=2) reads raw rows
        # 2i-2..2i+4 for output i, which fold to rows i-1..i+2 — a 7x7
        # kernel embeds as ws[m,n,(a,b,c)] = w7[2m+a-... w8[2m+a] with
        # w8[0:7]=w7, w8[7]=0 (tests/test_resnet.py pins the fold).
        b_, hh, ww, c_ = x.shape
        x = x.reshape(b_, hh // 2, 2, ww // 2, 2, c_)
        x = jnp.transpose(x, (0, 1, 3, 2, 4, 5)).reshape(
            b_, hh // 2, ww // 2, 4 * c_)
        x = lax.conv_general_dilated(
            x, stem_w, window_strides=(1, 1),
            padding=((1, 2), (1, 2)),
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
    else:
        x = L.conv2d(x, stem_w, stride=2 if imagenet_stem else 1)
    if nf:
        x = x + p["stem"]["c"]
        new_state["stem"] = {"conv": None, "g": None, "c": None}
    else:
        x, stem_bn = _bn(x, p["stem"]["bn"], state["stem"]["bn"], cfg,
                         train, axis_name)
        new_state["stem"] = {"conv": None, "bn": stem_bn}
    x = jax.nn.relu(x)
    if imagenet_stem or s2d_stem:
        x = L.max_pool(x, window=3, stride=2)

    for si in range(1, 5):
        key = f"stage{si}"
        if key not in p:
            break
        stage_state = []
        for bi, bp in enumerate(p[key]):
            stride = 2 if (bi == 0 and si > 1) else 1
            x, bs = block(x, bp, state[key][bi], stride, cfg, train,
                          axis_name)
            stage_state.append(bs)
        new_state[key] = stage_state

    x = jnp.mean(x, axis=(1, 2))  # global average pool
    logits = L.dense(x, p["fc"]["kernel"], p["fc"]["bias"])
    if cfg.logit_relu:
        # Faithful-mode switch shared with the reference CNN
        # (cifar10cnn.py:145); fixed_config turns it off.
        logits = jax.nn.relu(logits)
    return logits.astype(jnp.float32), new_state


# Shared implementation: models.param_count
from dml_cnn_cifar10_tpu.models import param_count  # noqa: E402,F401
