"""Model registry: name → (init, apply, has_state)."""

from __future__ import annotations

from typing import Callable, NamedTuple


class ModelDef(NamedTuple):
    init: Callable          # (key, model_cfg, data_cfg) -> params
    apply: Callable         # stateless: (params, images, cfg, train) -> logits
                            # stateful: (params, state, images, cfg, train)
                            #           -> (logits, new_state)
    init_state: Callable    # (params) -> mutable state pytree ({} if none)
    has_state: bool
    # apply accepts a ``mesh=`` kwarg and uses it for sequence-parallel
    # (ring-attention) routing when the mesh's ``seq`` axis is >1.
    wants_mesh: bool = False
    # apply returns ``(logits, aux_loss)``; the step adds
    # ``model_cfg.moe_aux_coef * aux_loss`` to the training loss.
    has_aux: bool = False
    # Conv-family models support spatial partitioning: the image H dim
    # shards over the ``seq`` mesh axis (GSPMD inserts conv/pool halo
    # exchanges). ViTs use ``seq`` for token/sequence parallelism instead.
    spatial: bool = False
    # Models that lax.scan their layer stack report ~1/depth of their
    # FLOPs to XLA cost analysis (the scan body is counted once). This
    # optional hook — (model_cfg, data_cfg, microbatch) -> (depth,
    # bf_counted, bf_true) — gives the loop the per-block numbers to
    # correct the TFLOP/s metric (vit.block_flops_probe).
    stack_probe: Callable | None = None


def _cnn() -> ModelDef:
    from dml_cnn_cifar10_tpu.models import cnn
    return ModelDef(cnn.init_params, cnn.apply, lambda p: {}, False,
                    spatial=True)


def _resnet(depth: int) -> Callable[[], ModelDef]:
    def make() -> ModelDef:
        from dml_cnn_cifar10_tpu.models import resnet
        return ModelDef(
            lambda k, m, d: resnet.init_params(k, m, d, depth=depth),
            resnet.apply,
            resnet.init_state,
            True,
            spatial=True,
        )
    return make


def _vit() -> ModelDef:
    from dml_cnn_cifar10_tpu.models import vit

    def init(key, model_cfg, data_cfg):
        if model_cfg.moe_experts:
            raise ValueError(
                "vit_tiny is the dense ViT; moe_experts > 0 needs model "
                "name 'vit_moe' (its aux loss and expert sharding rules)")
        return vit.init_params(key, model_cfg, data_cfg)

    return ModelDef(init, vit.apply, lambda p: {}, False, wants_mesh=True,
                    stack_probe=vit.block_flops_probe)


def _vit_moe() -> ModelDef:
    from dml_cnn_cifar10_tpu.models import vit

    def init(key, model_cfg, data_cfg):
        if model_cfg.moe_experts < 2:
            raise ValueError(
                "vit_moe needs moe_experts >= 2 "
                f"(got {model_cfg.moe_experts}); set ModelConfig.moe_experts")
        return vit.init_params(key, model_cfg, data_cfg)

    return ModelDef(init, vit.apply_with_aux, lambda p: {}, False,
                    wants_mesh=True, has_aux=True,
                    stack_probe=vit.block_flops_probe)


MODELS = {
    "cnn": _cnn,
    "resnet18": _resnet(18),
    "resnet50": _resnet(50),
    "vit_tiny": _vit,
    "vit_moe": _vit_moe,
}


def get_model(name: str) -> ModelDef:
    if name not in MODELS:
        raise ValueError(f"unknown model {name!r}; have {sorted(MODELS)}")
    try:
        return MODELS[name]()
    except ImportError as e:
        raise NotImplementedError(
            f"model {name!r} is registered but its module is not built yet "
            f"({e}); available today: cnn") from e
