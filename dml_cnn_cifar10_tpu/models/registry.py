"""Model registry: name → (init, apply, has_state)."""

from __future__ import annotations

from typing import Callable, NamedTuple


class ModelDef(NamedTuple):
    init: Callable          # (key, model_cfg, data_cfg) -> params
    apply: Callable         # stateless: (params, images, cfg, train) -> logits
                            # stateful: (params, state, images, cfg, train)
                            #           -> (logits, new_state)
    init_state: Callable    # (params) -> mutable state pytree ({} if none)
    has_state: bool
    # apply accepts a ``mesh=`` kwarg and uses it for sequence-parallel
    # (ring-attention) routing when the mesh's ``seq`` axis is >1.
    wants_mesh: bool = False


def _cnn() -> ModelDef:
    from dml_cnn_cifar10_tpu.models import cnn
    return ModelDef(cnn.init_params, cnn.apply, lambda p: {}, False)


def _resnet(depth: int) -> Callable[[], ModelDef]:
    def make() -> ModelDef:
        from dml_cnn_cifar10_tpu.models import resnet
        return ModelDef(
            lambda k, m, d: resnet.init_params(k, m, d, depth=depth),
            resnet.apply,
            resnet.init_state,
            True,
        )
    return make


def _vit() -> ModelDef:
    from dml_cnn_cifar10_tpu.models import vit
    return ModelDef(vit.init_params, vit.apply, lambda p: {}, False,
                    wants_mesh=True)


MODELS = {
    "cnn": _cnn,
    "resnet18": _resnet(18),
    "resnet50": _resnet(50),
    "vit_tiny": _vit,
}


def get_model(name: str) -> ModelDef:
    if name not in MODELS:
        raise ValueError(f"unknown model {name!r}; have {sorted(MODELS)}")
    try:
        return MODELS[name]()
    except ImportError as e:
        raise NotImplementedError(
            f"model {name!r} is registered but its module is not built yet "
            f"({e}); available today: cnn") from e
