"""The reference 5-layer CNN (2 conv + 3 FC), as a functional JAX model.

Exact architecture from ``create_cnn`` (``cifar10cnn.py:94-147``):

  conv1 5×5×C→64 s1 SAME + bias + ReLU   (:105-110)
  maxpool 3×3 s2 SAME                    (:113)
  conv2 5×5×64→64 s1 SAME + bias + ReLU  (:116-121)
  maxpool 3×3 s2 SAME                    (:123)
  flatten                                (:126-127)
  FC →384 + ReLU                         (:130-133)
  FC 384→192 + ReLU                      (:136-139)
  FC 192→num_classes (+ReLU in faithful mode — the reference clamps its
  logits at 0, ``:145``; ``ModelConfig.logit_relu`` controls this)

Init: truncated normal σ=0.05 for weights (``:97-98``), constant 0.1 for
biases (``:100-101``). Parameters live in a flat dict pytree; the weight
sharing the reference gets from ``tf.get_variable`` reuse (``:204-210``)
falls out of functional purity — the same pytree is passed to the train and
eval applications.

For CIFAR-100 the only change is ``num_classes=100`` (the "head swap"
config); for bigger inputs the flatten dim is derived from the config, not
hardcoded to 2304.
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp

from dml_cnn_cifar10_tpu.config import DataConfig, ModelConfig
from dml_cnn_cifar10_tpu.ops import layers as L

Params = Dict[str, Any]


def init_params(key: jax.Array, cfg: ModelConfig, data: DataConfig) -> Params:
    dtype = jnp.dtype(cfg.dtype)
    h, w = L.pooled_hw(data.crop_height, data.crop_width, n_pools=2)
    flat = h * w * 64
    ks = jax.random.split(key, 5)
    tn = lambda k, shape: L.truncated_normal_init(k, shape, cfg.init_stddev,
                                                  dtype)
    bias = lambda shape: L.bias_init(shape, cfg.bias_init, dtype)
    return {
        "conv1": {"kernel": tn(ks[0], (5, 5, data.num_channels, 64)),
                  "bias": bias((64,))},
        "conv2": {"kernel": tn(ks[1], (5, 5, 64, 64)), "bias": bias((64,))},
        "full1": {"kernel": tn(ks[2], (flat, 384)), "bias": bias((384,))},
        "full2": {"kernel": tn(ks[3], (384, 192)), "bias": bias((192,))},
        "full3": {"kernel": tn(ks[4], (192, cfg.num_classes)),
                  "bias": bias((cfg.num_classes,))},
    }


def apply(params: Params, images: jax.Array, cfg: ModelConfig,
          train: bool = True) -> jax.Array:
    """Forward pass: NHWC images → logits [B, num_classes].

    ``train`` is accepted for registry uniformity (this model has no
    BatchNorm/dropout, ``cifar10cnn.py:94-147``).
    """
    del train
    cdt = jnp.dtype(cfg.compute_dtype)
    x = images.astype(cdt)
    p = jax.tree.map(lambda a: a.astype(cdt), params)

    x = jax.nn.relu(L.conv2d(x, p["conv1"]["kernel"]) + p["conv1"]["bias"])
    x = L.max_pool(x)
    x = jax.nn.relu(L.conv2d(x, p["conv2"]["kernel"]) + p["conv2"]["bias"])
    x = L.max_pool(x)
    x = x.reshape(x.shape[0], -1)
    x = jax.nn.relu(L.dense(x, p["full1"]["kernel"], p["full1"]["bias"]))
    x = jax.nn.relu(L.dense(x, p["full2"]["kernel"], p["full2"]["bias"]))
    logits = L.dense(x, p["full3"]["kernel"], p["full3"]["bias"])
    if cfg.logit_relu:  # faithful: reference ReLUs its logits (:145)
        logits = jax.nn.relu(logits)
    return logits.astype(jnp.float32)


# Shared implementation: models.param_count
from dml_cnn_cifar10_tpu.models import param_count  # noqa: E402,F401
