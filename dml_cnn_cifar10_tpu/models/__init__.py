"""Model zoo.

``cnn`` is the reference architecture at parity (``cifar10cnn.py:94-147``);
``resnet18``/``resnet50`` and ``vit_tiny`` are the BASELINE.json config-ladder
models. All models share one functional interface:

  init_params(key, model_cfg, data_cfg) -> params pytree
  apply(params, images, model_cfg, train=...) -> logits      (stateless), or
  apply(params, state, images, model_cfg, train=...) -> (logits, new_state)
  (stateful models, e.g. BatchNorm running stats — see registry.has_state)
"""

import jax

from dml_cnn_cifar10_tpu.models.registry import get_model, MODELS  # noqa: F401


def param_count(params) -> int:
    """Total parameter count of a params pytree (shared by all models)."""
    return sum(int(a.size) for a in jax.tree.leaves(params))
