"""ViT-Tiny — the attention rung of the config ladder.

No reference counterpart (SURVEY §2.3: the reference has no attention and
fixed 24×24 inputs); this is the BASELINE.json config "ViT-Tiny/16 on
CIFAR-10 (patch-embed + attention via Pallas)", sized by ``ModelConfig``:
``patch_size=4`` (24×24 → 6×6 = 36 patches), ``vit_dim=192``,
``vit_depth=12``, ``vit_heads=3`` — the standard ViT-Ti geometry.

Architecture: conv patch embed → +cls token → learned positional embedding
→ ``depth`` pre-LN transformer blocks (MHA + 4× GELU MLP) → final LN →
linear head on the cls token. Attention goes through
:func:`ops.attention.dispatch_attention` (Pallas flash kernel at long
sequence lengths, fused XLA softmax-attention at ViT-on-CIFAR lengths).

Functional pytrees like the other models; stateless (LayerNorm has no
running stats), so the registry wires it like the CNN. The transformer
stack is a ``lax.scan`` over stacked per-layer params: one compiled block
body regardless of depth (compile time stays flat as depth grows — XLA
sees a loop, not 12 inlined copies).
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
from jax import lax

from dml_cnn_cifar10_tpu.config import DataConfig, ModelConfig
from dml_cnn_cifar10_tpu.ops import attention as attn
from dml_cnn_cifar10_tpu.ops import layers as L

Params = Dict[str, Any]
MLP_RATIO = 4


def _ln_init(dim: int, dtype):
    return {"scale": jnp.ones((dim,), dtype), "bias": jnp.zeros((dim,), dtype)}


def layer_norm(x: jax.Array, p, eps: float = 1e-6) -> jax.Array:
    mean = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x - mean), axis=-1, keepdims=True)
    return (x - mean) * lax.rsqrt(var + eps) * p["scale"] + p["bias"]


def _init_block(key, dim: int, dtype) -> Params:
    ks = jax.random.split(key, 4)
    hidden = dim * MLP_RATIO
    return {
        "ln1": _ln_init(dim, dtype),
        # fused qkv: one [dim, 3*dim] matmul keeps the MXU busy vs 3 skinny
        # matmuls. Output features are HEADS-MAJOR ([head][q|k|v][hd]) so
        # column-sharding over the ``model`` mesh axis splits whole heads
        # (parallel/shardings.py) and the attention tensors stay
        # head-sharded with no resharding.
        "qkv": {"kernel": L.he_normal_init(ks[0], (dim, 3 * dim), dtype),
                "bias": jnp.zeros((3 * dim,), dtype)},
        "proj": {"kernel": L.he_normal_init(ks[1], (dim, dim), dtype),
                 "bias": jnp.zeros((dim,), dtype)},
        "ln2": _ln_init(dim, dtype),
        "mlp1": {"kernel": L.he_normal_init(ks[2], (dim, hidden), dtype),
                 "bias": jnp.zeros((hidden,), dtype)},
        "mlp2": {"kernel": L.he_normal_init(ks[3], (hidden, dim), dtype),
                 "bias": jnp.zeros((dim,), dtype)},
    }


def init_params(key: jax.Array, cfg: ModelConfig, data: DataConfig) -> Params:
    dtype = jnp.dtype(cfg.dtype)
    dim, depth = cfg.vit_dim, cfg.vit_depth
    ph = data.crop_height // cfg.patch_size
    pw = data.crop_width // cfg.patch_size
    if ph * cfg.patch_size != data.crop_height or \
       pw * cfg.patch_size != data.crop_width:
        raise ValueError(
            f"input {data.crop_height}x{data.crop_width} not divisible by "
            f"patch_size={cfg.patch_size}")
    seq = ph * pw + 1  # +cls

    ks = jax.random.split(key, depth + 4)
    # One stacked pytree for all blocks: leaves get a leading [depth] axis,
    # consumed by lax.scan in apply().
    blocks = [_init_block(ks[i], dim, dtype) for i in range(depth)]
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *blocks)

    return {
        "patch": {"kernel": L.he_normal_init(
                      ks[depth],
                      (cfg.patch_size, cfg.patch_size, data.num_channels,
                       dim), dtype),
                  "bias": jnp.zeros((dim,), dtype)},
        "cls": jnp.zeros((1, 1, dim), dtype),
        "pos": 0.02 * jax.random.normal(ks[depth + 1], (1, seq, dim), dtype),
        "blocks": stacked,
        "ln_f": _ln_init(dim, dtype),
        "head": {"kernel": 0.01 * jax.random.normal(
                     ks[depth + 2], (dim, cfg.num_classes), dtype),
                 "bias": jnp.zeros((cfg.num_classes,), dtype)},
    }


def _block(x: jax.Array, p: Params, heads: int, use_pallas: bool
           ) -> jax.Array:
    b, s, dim = x.shape
    h = layer_norm(x, p["ln1"])
    qkv = L.dense(h, p["qkv"]["kernel"], p["qkv"]["bias"])
    qkv = qkv.reshape(b, s, heads, 3, dim // heads)  # heads-major
    q, k, v = qkv[:, :, :, 0], qkv[:, :, :, 1], qkv[:, :, :, 2]
    o = attn.dispatch_attention(q, k, v, use_pallas=use_pallas)
    x = x + L.dense(o.reshape(b, s, dim), p["proj"]["kernel"],
                    p["proj"]["bias"])
    h = layer_norm(x, p["ln2"])
    h = jax.nn.gelu(L.dense(h, p["mlp1"]["kernel"], p["mlp1"]["bias"]))
    return x + L.dense(h, p["mlp2"]["kernel"], p["mlp2"]["bias"])


def apply(params: Params, images: jax.Array, cfg: ModelConfig,
          train: bool = True) -> jax.Array:
    """NHWC images → logits [B, num_classes]."""
    del train  # no dropout in the ladder config
    cdt = jnp.dtype(cfg.compute_dtype)
    p = jax.tree.map(lambda a: a.astype(cdt), params)
    x = images.astype(cdt)

    # Patch embed: stride=patch conv == per-patch linear, one MXU matmul.
    x = L.conv2d(x, p["patch"]["kernel"], stride=cfg.patch_size,
                 padding="VALID") + p["patch"]["bias"]
    b = x.shape[0]
    x = x.reshape(b, -1, cfg.vit_dim)
    cls = jnp.broadcast_to(p["cls"], (b, 1, cfg.vit_dim))
    x = jnp.concatenate([cls, x], axis=1) + p["pos"]

    def body(carry, bp):
        return _block(carry, bp, cfg.vit_heads,
                      cfg.use_pallas_attention), None

    x, _ = lax.scan(body, x, p["blocks"])
    x = layer_norm(x, p["ln_f"])
    logits = L.dense(x[:, 0], p["head"]["kernel"], p["head"]["bias"])
    if cfg.logit_relu:
        # Shared faithful-mode switch (cifar10cnn.py:145); fixed mode off.
        logits = jax.nn.relu(logits)
    return logits.astype(jnp.float32)


# Shared implementation: models.param_count
from dml_cnn_cifar10_tpu.models import param_count  # noqa: E402,F401
