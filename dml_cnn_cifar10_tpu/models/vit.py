"""ViT-Tiny — the attention rung of the config ladder.

No reference counterpart (SURVEY §2.3: the reference has no attention and
fixed 24×24 inputs); this is the BASELINE.json config "ViT-Tiny/16 on
CIFAR-10 (patch-embed + attention via Pallas)", sized by ``ModelConfig``:
``patch_size=4`` (24×24 → 6×6 = 36 patches), ``vit_dim=192``,
``vit_depth=12``, ``vit_heads=3`` — the standard ViT-Ti geometry.

Architecture: conv patch embed → +cls token → learned positional embedding
→ ``depth`` pre-LN transformer blocks (MHA + 4× GELU MLP) → final LN →
linear head on the cls token. Attention goes through
:func:`ops.attention.dispatch_attention` (Pallas flash kernel at long
sequence lengths, fused XLA softmax-attention at ViT-on-CIFAR lengths).

Functional pytrees like the other models; stateless (LayerNorm has no
running stats), so the registry wires it like the CNN. The transformer
stack is a ``lax.scan`` over stacked per-layer params: one compiled block
body regardless of depth (compile time stays flat as depth grows — XLA
sees a loop, not 12 inlined copies).
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from dml_cnn_cifar10_tpu.config import DataConfig, ModelConfig
from dml_cnn_cifar10_tpu.ops import attention as attn
from dml_cnn_cifar10_tpu.ops import layers as L
from dml_cnn_cifar10_tpu.ops import moe as moe_ops

Params = Dict[str, Any]
MLP_RATIO = 4


def _ln_init(dim: int, dtype):
    return {"scale": jnp.ones((dim,), dtype), "bias": jnp.zeros((dim,), dtype)}


def layer_norm(x: jax.Array, p, eps: float = 1e-6) -> jax.Array:
    mean = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x - mean), axis=-1, keepdims=True)
    return (x - mean) * lax.rsqrt(var + eps) * p["scale"] + p["bias"]


def _init_block(key, dim: int, dtype, moe_experts: int = 0) -> Params:
    ks = jax.random.split(key, 4)
    hidden = dim * MLP_RATIO
    block = {
        "ln1": _ln_init(dim, dtype),
        # fused qkv: one [dim, 3*dim] matmul keeps the MXU busy vs 3 skinny
        # matmuls. Output features are HEADS-MAJOR ([head][q|k|v][hd]) so
        # column-sharding over the ``model`` mesh axis splits whole heads
        # (parallel/shardings.py) and the attention tensors stay
        # head-sharded with no resharding.
        "qkv": {"kernel": L.he_normal_init(ks[0], (dim, 3 * dim), dtype),
                "bias": jnp.zeros((3 * dim,), dtype)},
        "proj": {"kernel": L.he_normal_init(ks[1], (dim, dim), dtype),
                 "bias": jnp.zeros((dim,), dtype)},
        "ln2": _ln_init(dim, dtype),
    }
    if moe_experts:
        block["moe"] = moe_ops.init_moe_params(ks[2], dim, hidden,
                                               moe_experts, dtype)
    else:
        block["mlp1"] = {"kernel": L.he_normal_init(ks[2], (dim, hidden),
                                                    dtype),
                         "bias": jnp.zeros((hidden,), dtype)}
        block["mlp2"] = {"kernel": L.he_normal_init(ks[3], (hidden, dim),
                                                    dtype),
                         "bias": jnp.zeros((dim,), dtype)}
    return block


def init_params(key: jax.Array, cfg: ModelConfig, data: DataConfig) -> Params:
    dtype = jnp.dtype(cfg.dtype)
    dim, depth = cfg.vit_dim, cfg.vit_depth
    ph = data.crop_height // cfg.patch_size
    pw = data.crop_width // cfg.patch_size
    if ph * cfg.patch_size != data.crop_height or \
       pw * cfg.patch_size != data.crop_width:
        raise ValueError(
            f"input {data.crop_height}x{data.crop_width} not divisible by "
            f"patch_size={cfg.patch_size}")
    seq = ph * pw + (1 if cfg.pool == "cls" else 0)

    ks = jax.random.split(key, depth + 4)
    # One stacked pytree for all blocks: leaves get a leading [depth] axis,
    # consumed by lax.scan in apply().
    blocks = [_init_block(ks[i], dim, dtype, moe_experts=cfg.moe_experts)
              for i in range(depth)]
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *blocks)

    params = {
        "patch": {"kernel": L.he_normal_init(
                      ks[depth],
                      (cfg.patch_size, cfg.patch_size, data.num_channels,
                       dim), dtype),
                  "bias": jnp.zeros((dim,), dtype)},
        "pos": 0.02 * jax.random.normal(ks[depth + 1], (1, seq, dim), dtype),
        "blocks": stacked,
        "ln_f": _ln_init(dim, dtype),
        "head": {"kernel": 0.01 * jax.random.normal(
                     ks[depth + 2], (dim, cfg.num_classes), dtype),
                 "bias": jnp.zeros((cfg.num_classes,), dtype)},
    }
    if cfg.pool == "cls":
        params["cls"] = jnp.zeros((1, 1, dim), dtype)
    elif cfg.pool != "mean":
        raise ValueError(f"pool must be 'cls' or 'mean', got {cfg.pool!r}")
    return params


def _block(x: jax.Array, p: Params, heads: int, use_pallas: bool,
           capacity_factor: float, mesh=None, sp_mode: str = "ring",
           moe_top_k: int = 1, causal: bool = False, window=None,
           moe_dispatch: str = "einsum"):
    """One transformer block → ``(x, aux)`` — ``aux`` is the MoE router
    stats dict (ops/moe.py) for MoE blocks, scalar 0.0 for dense MLPs."""
    b, s, dim = x.shape
    h = layer_norm(x, p["ln1"])
    qkv = L.dense(h, p["qkv"]["kernel"], p["qkv"]["bias"])
    qkv = qkv.reshape(b, s, heads, 3, dim // heads)  # heads-major
    q, k, v = qkv[:, :, :, 0], qkv[:, :, :, 1], qkv[:, :, :, 2]
    if mesh is not None:
        # Sequence-parallel path over the ``seq`` mesh axis. Two strategies
        # with the same sharded-activation contract:
        # - "ring": each device holds S/seq tokens, K/V shards walk the
        #   ring over ICI (parallel/ring_attention.py);
        # - "ulysses": all-to-all re-partitions seq→heads, full-sequence
        #   attention on a head slice, all-to-all back
        #   (parallel/ulysses.py; needs heads % seq_axis == 0).
        if sp_mode == "ulysses":
            from dml_cnn_cifar10_tpu.parallel import ulysses
            o = ulysses.ulysses_attention(q, k, v, mesh,
                                          use_pallas=use_pallas,
                                          causal=causal, window=window)
        elif sp_mode == "ring":
            from dml_cnn_cifar10_tpu.parallel import ring_attention as ring
            o = ring.ring_attention(q, k, v, mesh, use_pallas=use_pallas,
                                    causal=causal, window=window)
        else:
            raise ValueError(f"unknown sp_mode {sp_mode!r}")
    else:
        o = attn.dispatch_attention(q, k, v, use_pallas=use_pallas,
                                    causal=causal, window=window)
    x = x + L.dense(o.reshape(b, s, dim), p["proj"]["kernel"],
                    p["proj"]["bias"])
    h = layer_norm(x, p["ln2"])
    if "moe" in p:
        y, stats = moe_ops.moe_mlp(h, p["moe"], capacity_factor,
                                   top_k=moe_top_k,
                                   dispatch=moe_dispatch)
        return x + y, stats
    h = jax.nn.gelu(L.dense(h, p["mlp1"]["kernel"], p["mlp1"]["bias"]))
    return x + L.dense(h, p["mlp2"]["kernel"], p["mlp2"]["bias"]), \
        jnp.zeros((), jnp.float32)


def apply(params: Params, images: jax.Array, cfg: ModelConfig,
          train: bool = True, mesh=None) -> jax.Array:
    """NHWC images → logits [B, num_classes] (dense-MLP models)."""
    return apply_with_aux(params, images, cfg, train=train, mesh=mesh)[0]


def apply_with_aux(params: Params, images: jax.Array, cfg: ModelConfig,
                   train: bool = True, mesh=None):
    """NHWC images → ``(logits [B, num_classes], aux)``.

    For MoE stacks ``aux`` is the router-stats dict accumulated over
    blocks: ``aux_loss`` summed (the caller scales it into the loss),
    ``dropped_frac`` / ``expert_load`` depth-averaged — the numbers the
    Trainer metrics stream publishes. For dense MLPs ``aux`` is the
    scalar 0.0. ``mesh`` with a ``seq`` axis >1 switches attention to the
    ring (sequence-parallel) kernel and keeps token activations sharded
    [data, seq] between blocks; requires ``pool='mean'`` (no cls token) and
    a token count divisible by the ``seq`` axis.
    """
    del train  # no dropout in the ladder config
    seq_parallel = mesh is not None and mesh.shape.get("seq", 1) > 1
    pipe_parallel = mesh is not None and mesh.shape.get("pipe", 1) > 1
    if seq_parallel and pipe_parallel:
        raise ValueError(
            "seq and pipe parallelism cannot both be active in one stack "
            "(ring attention's shard_map cannot nest inside the pipeline's)")
    if pipe_parallel and mesh.shape.get("model", 1) > 1:
        raise ValueError(
            "pipe and model (tensor) parallelism cannot combine: the "
            "pipeline stage body is a shard_map, so tensor-parallel matmuls "
            "inside it would need hand-written collectives "
            "(parallel/pipeline.py). Use pipe x data, or model x data.")
    if pipe_parallel and cfg.moe_experts:
        raise ValueError(
            "pipe parallelism does not compose with MoE (expert dispatch "
            "inside a pipeline stage would need hand-written all-to-all)")
    cdt = jnp.dtype(cfg.compute_dtype)
    p = jax.tree.map(lambda a: a.astype(cdt), params)
    x = images.astype(cdt)

    # Patch embed: stride=patch conv == per-patch linear, one MXU matmul.
    x = L.conv2d(x, p["patch"]["kernel"], stride=cfg.patch_size,
                 padding="VALID") + p["patch"]["bias"]
    b = x.shape[0]
    x = x.reshape(b, -1, cfg.vit_dim)
    if cfg.pool == "cls":
        cls = jnp.broadcast_to(p["cls"], (b, 1, cfg.vit_dim))
        x = jnp.concatenate([cls, x], axis=1)
    x = x + p["pos"]

    if seq_parallel:
        if cfg.pool != "mean":
            raise ValueError(
                "sequence parallelism needs pool='mean' (a cls token breaks "
                "even seq sharding)")
        if x.shape[1] % mesh.shape["seq"]:
            raise ValueError(
                f"{x.shape[1]} tokens not divisible by seq axis "
                f"{mesh.shape['seq']}")
        x = lax.with_sharding_constraint(
            x, NamedSharding(mesh, P("data", "seq", None)))

    attn_mesh = mesh if seq_parallel else None

    aux = jnp.zeros((), jnp.float32)
    if pipe_parallel:
        from dml_cnn_cifar10_tpu.parallel import pipeline

        def stage_fn(h, bp):
            return _block(h, bp, cfg.vit_heads, cfg.use_pallas_attention,
                          cfg.moe_capacity_factor, causal=cfg.attn_causal,
                          window=cfg.attn_window)[0]

        if cfg.remat:
            # Same memory lever inside each pipeline stage body.
            stage_fn = jax.checkpoint(stage_fn)
        x = pipeline.pipeline_blocks(
            x, p["blocks"], stage_fn, mesh,
            num_microbatches=cfg.pipe_microbatches or None,
            schedule=cfg.pipe_schedule)
    else:
        def block_fn(h, bp):
            return _block(h, bp, cfg.vit_heads,
                          cfg.use_pallas_attention,
                          cfg.moe_capacity_factor, mesh=attn_mesh,
                          sp_mode=cfg.sp_mode,
                          moe_top_k=cfg.moe_top_k,
                          causal=cfg.attn_causal, window=cfg.attn_window,
                          moe_dispatch=cfg.moe_dispatch)

        if cfg.remat:
            # Recompute block activations in backward: scan(checkpoint)
            # keeps live activation memory O(1) in depth — deep stacks and
            # long sequences stop being HBM-bound (traded for ~1 extra
            # forward of FLOPs, cheap on the MXU).
            block_fn = jax.checkpoint(block_fn)

        if cfg.moe_experts:
            # Zero-stats carry matching ops/moe.py's dict (the stacked
            # block params are structurally uniform, so every scan tick
            # adds the same pytree).
            aux = {"aux_loss": aux,
                   "dropped_frac": jnp.zeros((), jnp.float32),
                   "expert_load": jnp.zeros((cfg.moe_experts,),
                                            jnp.float32)}

        def body(carry, bp):
            h, aux_sum = carry
            h, block_aux = block_fn(h, bp)
            return (h, jax.tree.map(jnp.add, aux_sum, block_aux)), None

        (x, aux), _ = lax.scan(body, (x, aux), p["blocks"])
        if cfg.moe_experts:
            depth = jax.tree.leaves(p["blocks"])[0].shape[0]
            aux = {"aux_loss": aux["aux_loss"],
                   "dropped_frac": aux["dropped_frac"] / depth,
                   "expert_load": aux["expert_load"] / depth}
    x = layer_norm(x, p["ln_f"])
    pooled = jnp.mean(x, axis=1) if cfg.pool == "mean" else x[:, 0]
    logits = L.dense(pooled, p["head"]["kernel"], p["head"]["bias"])
    if cfg.logit_relu:
        # Shared faithful-mode switch (cifar10cnn.py:145); fixed mode off.
        logits = jax.nn.relu(logits)
    return logits.astype(jnp.float32), aux


def block_flops_probe(model_cfg: ModelConfig, data_cfg: DataConfig,
                      batch_size: int):
    """Measured fwd+bwd FLOPs of ONE transformer block at this config's
    [B, S, dim] geometry → ``(depth, bf_counted, bf_true)``.

    XLA's cost analysis counts a ``lax.scan`` body ONCE, so the step
    probe undercounts the ViT's depth-scanned stack by ~depth (round-2
    verdict weak #4); the loop corrects with these numbers
    (train/loop.py). Two measurements because Pallas kernels are opaque
    custom calls with zero reported FLOPs:

    - ``bf_counted`` — the block as the step actually runs it (Pallas
      attention counts as 0), i.e. what one scan-body copy contributes
      to the step's reported total;
    - ``bf_true`` — the same block with the dense XLA attention, whose
      matmul FLOPs cost analysis does count: the honest per-block cost
      (dense and flash do the same attention math).

    Geometry matches training: remat mirrors ``apply``'s
    scan(checkpoint(block)) so the recompute FLOPs are included;
    ``batch_size`` should be the PER-CHIP microbatch (batch / grad_accum
    / data-axis size — the loop passes this) so the numbers match the
    step probe's per-device accounting. The probe models the plain
    dispatch_attention path only: under sequence/tensor/pipeline
    partitioning (ring/Ulysses attention, sharded experts) one
    unsharded block does NOT equal the per-chip share, so the loop
    skips the correction there and labels the metric
    ``uncorrected_model_parallel`` instead. MoE blocks probe unsharded
    (same caveat).
    """
    from dml_cnn_cifar10_tpu.utils.profiling import compiled_flops

    dim = model_cfg.vit_dim
    ph = data_cfg.crop_height // model_cfg.patch_size
    pw = data_cfg.crop_width // model_cfg.patch_size
    seq = ph * pw + (1 if model_cfg.pool == "cls" else 0)
    cdt = jnp.dtype(model_cfg.compute_dtype)

    bp_abs = jax.eval_shape(
        lambda: _init_block(jax.random.PRNGKey(0), dim, cdt,
                            moe_experts=model_cfg.moe_experts))
    x_abs = jax.ShapeDtypeStruct((batch_size, seq, dim), cdt)

    def measure(use_pallas: bool):
        def block_fn(x, bp):
            return _block(x, bp, model_cfg.vit_heads, use_pallas,
                          model_cfg.moe_capacity_factor,
                          moe_top_k=model_cfg.moe_top_k,
                          moe_dispatch=model_cfg.moe_dispatch)[0]

        if model_cfg.remat:
            block_fn = jax.checkpoint(block_fn)

        def loss_fn(x, bp):
            return jnp.sum(block_fn(x, bp).astype(jnp.float32))

        return compiled_flops(jax.jit(jax.grad(loss_fn, argnums=(0, 1))),
                              (x_abs, bp_abs))

    pallas_active = model_cfg.use_pallas_attention and seq >= 128
    bf_true = measure(False)
    bf_counted = measure(True) if pallas_active else bf_true
    return model_cfg.vit_depth, bf_counted, bf_true


# Shared implementation: models.param_count
from dml_cnn_cifar10_tpu.models import param_count  # noqa: E402,F401
